"""Llama-3-8B-shaped FSDP measurement (BASELINE.md north star
"Llama-3-8B FSDP MFU").

Two artifacts, written to BENCH_LLAMA8B.json:

1. `proxy_mfu` (runs on the real chip): a single v5e chip cannot hold the full
   8B train state, so the per-layer cost is measured directly — the exact 8B
   layer geometry (hidden 4096, mlp 14336, 32q/8kv heads, flash attention,
   remat policy "selective": save attention-side tensors, recompute the wide
   gate/up matmuls — ~100 MB/layer saved activations at b1/s2048, the
   memory/speed point that fits an fsdp=8 v5e pod) at depths 1 and 2. Per-layer
   step cost = t2 - t1; depth-independent cost (embed + fused-CE head, measured
   at a reduced vocab) scales linearly with vocab to 128256. Projected
   full-model step time = fixed*scale + 32*per_layer; MFU uses the true 8B
   parameter count. A secondary `upper_bound` row records the same measurement
   under dots_saveable (save every matmul output — faster, but its activation
   footprint only suits chips with more HBM headroom). Assumptions are
   recorded in the JSON.

2. `fsdp8_memory` (virtual 8-device mesh, subprocess): the FULL 8B config
   (32 layers, 128256 vocab) jitted over an fsdp=8 mesh and AOT-compiled —
   XLA's memory analysis certifies per-device residency (the dryrun path's
   memory-feasibility check, without needing 8 real chips or 80 GB of host
   RAM to materialize the state).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

LLAMA8B = dict(
    vocab_size=128256, hidden=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    mlp_dim=14336, max_seq=8192, tie_embeddings=False,
)


def true_param_count() -> int:
    h, mlp, v, L = 4096, 14336, 128256, 32
    head_dim = h // 32
    attn = h * (32 * head_dim) + 2 * h * (8 * head_dim) + (32 * head_dim) * h
    mlp_p = 3 * h * mlp
    norms = 2 * h
    return L * (attn + mlp_p + norms) + 2 * v * h + h  # embed + lm_head + final norm


def measure_step(n_layers: int, vocab: int, batch: int, seq: int, iters: int = 8,
                 remat_policy: str = "selective"):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import ModelConfig, Transformer
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.spmd import build_train_step, init_state

    cfg = ModelConfig(
        vocab_size=vocab, hidden=4096, n_layers=n_layers, n_heads=32,
        n_kv_heads=8, mlp_dim=14336, max_seq=seq, remat=True,
        remat_policy=remat_policy, scan_layers=True,
        attention="flash" if jax.default_backend() == "tpu" else "reference",
    )
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"dp": 1})
    opt = optax.adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)
    state, _ = init_state(model, cfg, opt, mesh, sample_shape=(batch, seq))
    step_fn, shard = build_train_step(model, opt, mesh, with_grad_norm=False)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0, vocab)
    data = {"tokens": jax.device_put(tokens, shard["tokens"]),
            "targets": jax.device_put(tokens, shard["targets"])}
    with mesh:
        state, m = step_fn(state, data)
        _ = float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step_fn(state, data)
        _ = float(m["loss"])
        return (time.perf_counter() - t0) / iters


def _project(t1, t2, batch, seq, vocab):
    from bench import peak_flops_per_chip

    per_layer = max(t2 - t1, 1e-9)
    fixed = max(t1 - per_layer, 0.0)
    # The depth-independent cost is dominated by the fused-CE head (linear in
    # vocab); scale it from the measured vocab to the real one.
    fixed_full = fixed * (LLAMA8B["vocab_size"] / vocab)
    t_full = fixed_full + 32 * per_layer
    n_params = true_param_count()
    attn_flops = 12 * 32 * 4096 * seq  # per token, causal-averaged
    flops_per_token = 6 * n_params + attn_flops
    tokens_per_sec = batch * seq / t_full
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()
    return {
        "projected_step_s": round(t_full, 4),
        "projected_tokens_per_s": round(tokens_per_sec, 1),
        "mfu": round(mfu, 4),
        "measured": {
            "t_1layer_s": round(t1, 4), "t_2layer_s": round(t2, 4),
            "per_layer_s": round(per_layer, 5), "fixed_s": round(fixed, 4),
            "batch": batch, "seq": seq, "proxy_vocab": vocab,
        },
    }


def proxy_mfu():
    import jax

    on_tpu = jax.default_backend() == "tpu"
    # Depths 1 and 2: a 4-layer probe (~1B params + f32 adam) overflows a
    # 16 GiB v5e; the 2-vs-1 delta isolates the same per-layer cost.
    batch, seq, vocab = (2, 2048, 16384) if on_tpu else (1, 128, 1024)
    n_params = true_param_count()
    rows = {}
    for name, policy, b in (("primary", "selective", batch),
                            ("batch1", "selective", 1),
                            ("upper_bound_dots", "dots", batch)):
        t1 = measure_step(1, vocab, b, seq, remat_policy=policy)
        t2 = measure_step(2, vocab, b, seq, remat_policy=policy)
        rows[name] = _project(t1, t2, b, seq, vocab)
        rows[name]["remat_policy"] = policy
    out = {
        "metric": "llama8b_proxy_mfu_per_chip",
        **rows["primary"],
        "rows": rows,
        "assumptions": [
            "exact 8B layer geometry; per-layer cost from 2-vs-1 layer delta",
            "depth-independent cost scaled linearly in vocab (fused-CE head)",
            f"true 8B param count {n_params:,} used for FLOPs",
            "primary row: remat_policy=selective (saves post-rope q/k/v, attn "
            "out, o/down projections, pre-MLP norm; recomputes the wide "
            "gate/up matmuls) — ~100 MB/layer saved activations at b1/s2048, "
            "sized for an fsdp=8 v5e pod; upper_bound_dots saves every matmul "
            "output (~330 MB/layer) and needs more HBM headroom per chip",
            "per-chip batch 2 (primary): at pod scale this is global batch 16 "
            "over fsdp=8",
        ],
    }
    return out


_FSDP8_CHILD = "_LLAMA8B_FSDP8_CHILD"


def fsdp8_memory():
    """AOT-compile the full 8B train step over an fsdp=8 virtual mesh."""
    if not os.environ.get(_FSDP8_CHILD):
        env = dict(os.environ)
        env[_FSDP8_CHILD] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8").strip()
        env["PALLAS_AXON_POOL_IPS"] = ""
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "fsdp8"],
            env=env, capture_output=True, text=True, timeout=3600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return {"metric": "llama8b_fsdp8_memory", "ok": False,
                    "error": proc.stderr[-800:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import ModelConfig, Transformer
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.spmd import (
        TrainState,
        build_train_step,
        state_shardings,
    )

    cfg = ModelConfig(remat=True, remat_policy="selective", scan_layers=True,
                      attention="reference", **LLAMA8B)
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"fsdp": 8})
    opt = optax.adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)
    batch, seq = 8, 4096
    shardings = state_shardings(model, cfg, opt, mesh, None, (batch, seq))
    # Abstract state: shapes/dtypes via eval_shape — nothing materializes.
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)

    def make(rng):
        variables = model.init(rng, jnp.zeros((batch, seq), jnp.int32))
        params = mesh_lib.unbox(variables["params"])
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    state_avals = jax.eval_shape(make, jax.random.PRNGKey(0))
    state_avals = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        state_avals, shardings,
    )
    step_fn, batch_shardings = build_train_step(model, opt, mesh,
                                                with_grad_norm=False)
    batch_avals = {
        "tokens": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                       sharding=batch_shardings["tokens"]),
        "targets": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                        sharding=batch_shardings["targets"]),
    }
    with mesh:
        compiled = step_fn.lower(state_avals, batch_avals).compile()
    mem = compiled.memory_analysis()
    gib = 1 << 30
    out = {
        "metric": "llama8b_fsdp8_memory",
        "ok": True,
        "mesh": "fsdp=8",
        "batch": batch, "seq": seq,
        "per_device_gib": {
            "arguments": round(mem.argument_size_in_bytes / gib, 2),
            "outputs": round(mem.output_size_in_bytes / gib, 2),
            "temp_cpu_backend_upper_bound": round(
                mem.temp_size_in_bytes / gib, 2
            ),
        },
        # The real feasibility signal: the SHARDED train state (params f32 +
        # adam mu bf16/nu f32) resident per device. 10 GiB/chip of state
        # leaves ~6 GiB of a v5e for activations under remat.
        "sharded_state_fits_v5e_16gib": mem.argument_size_in_bytes < 16 * gib,
        "note": "AOT compile of the FULL 8B config over 8 virtual devices "
                "certifies the fsdp sharding end to end; `arguments` is the "
                "per-device resident train state. The temp figure is the CPU "
                "backend's buffer plan — an upper bound that lacks the TPU "
                "compiler's scheduling/fusion, not a TPU HBM prediction.",
    }
    print(json.dumps(out))
    return out


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "all"
    if mode == "fsdp8" and os.environ.get(_FSDP8_CHILD):
        fsdp8_memory()
        return
    results = {"bench": "llama8b"}
    if os.path.exists("BENCH_LLAMA8B.json"):
        # Partial reruns (proxy-only / fsdp8-only) merge over prior results.
        with open("BENCH_LLAMA8B.json") as f:
            results.update(json.load(f))
    import jax

    results["backend"] = jax.default_backend()
    results["device"] = str(jax.devices()[0].device_kind)
    if mode in ("all", "proxy"):
        results["proxy_mfu"] = proxy_mfu()
    if mode in ("all", "fsdp8"):
        results["fsdp8_memory"] = fsdp8_memory()
    with open("BENCH_LLAMA8B.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
