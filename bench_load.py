"""Open-loop load harness: p50/p99 TTFT/TPOT and goodput-under-SLO per
arrival rate (ROADMAP item 1's measurement half; docs/observability.md).

Unlike bench_serve.py's closed-loop rows (submit N, wait for N), this
harness is OPEN-LOOP: arrivals follow a Poisson process whose rate does NOT
slow down when the engine falls behind — the shape real traffic has, and the
only shape that exposes queueing collapse (a closed loop self-throttles and
hides it). Per arrival rate it drives:

- **Poisson arrivals**: exponential inter-arrival gaps at `rate_rps`,
  submitted on schedule regardless of completions. An admission rejection
  (`EngineOverloadedError`) counts as shed load — an SLO miss, not an
  excuse.
- **Heavy-tailed lengths**: lognormal prompt and output token counts
  (clipped to the engine budget) — the long-prompt tail is what chunked
  prefill exists for; a fixed-length bench never exercises it.
- **Traffic mixes**: `base` (every prompt unique), `shared_prefix` (70% of
  requests share a whole-block system-prompt prefix, the prefix-cache +
  cache-aware regime), and `multi_tenant` (three tenants, WFQ weights
  2:1:1, per-tenant percentiles reported).

Per request the CLIENT measures TTFT (submit -> first token), mean TPOT
(inter-token gaps), and e2e; goodput-under-SLO counts completions meeting
BOTH `llm_slo_ttft_s` and `llm_slo_tpot_s` (scaled for this host via
--slo-ttft/--slo-tpot). The engine's own flight-recorder/SLO plane runs
concurrently and its counters are reported alongside, so the harness also
validates the observability path under load.

Writes BENCH_LOAD.json: one row per (arrival_rate, mix) + environment
metadata. This is the signal surface ROADMAP item 1's control loops (DP
replica count, WFQ weights, P:D ratio) will close against.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from typing import List, Optional


def _pctl(values: List[float], q: float) -> float:
    xs = sorted(values)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[idx]


def build_engine(**kw):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine

    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    max_seq = kw.pop("max_seq", 1024 if on_tpu else 256)
    if "prefix_cache" not in kw:
        # Tiered prefix cache (docs/kvcache.md): the shared_prefix mix then
        # reports its per-tier hit breakdown (device/host/disk).
        import tempfile

        from ray_tpu._private.config import CONFIG
        from ray_tpu.llm.kvcache import TieredPrefixCacheManager

        kw["prefix_cache"] = TieredPrefixCacheManager(
            CONFIG.llm_kv_block_size, CONFIG.llm_prefix_cache_bytes,
            name="bench-load", device_bytes=8 << 20,
            spill_dir=tempfile.mkdtemp(prefix="bench_load_spill_"),
        )
    engine = DecodeEngine(cfg, params, num_slots=kw.pop("slots", 8),
                          max_seq=max_seq, seed=0, **kw)
    return engine, cfg, model_id, on_tpu


class _Arrival:
    """One open-loop request's client-side measurement state."""

    __slots__ = ("t_submit", "token_times", "done", "rejected", "tenant")

    def __init__(self, tenant: str = ""):
        self.t_submit: Optional[float] = None
        self.token_times: List[float] = []
        self.done = threading.Event()
        self.rejected = False
        self.tenant = tenant

    def ttft(self) -> Optional[float]:
        if self.t_submit is None or not self.token_times:
            return None
        return self.token_times[0] - self.t_submit

    def tpot(self) -> Optional[float]:
        gaps = [b - a for a, b in zip(self.token_times, self.token_times[1:])]
        return (sum(gaps) / len(gaps)) if gaps else None


def _lengths(rng, n: int, *, mean_log: float, sigma: float, lo: int, hi: int):
    """Heavy-tailed token counts: lognormal, clipped to the engine budget."""
    raw = rng.lognormal(mean=mean_log, sigma=sigma, size=n)
    return [int(min(hi, max(lo, round(x)))) for x in raw]


def run_load(engine, cfg, *, rate_rps: float, n_requests: int, mix: str,
             slo_ttft_s: float, slo_tpot_s: float, seed: int = 0,
             max_seq: int = 256) -> dict:
    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError

    rng = np.random.default_rng(seed)
    # Heavy-tailed prompt/output lengths: median ~20-token prompts with a
    # tail out to the sequence budget; outputs median ~12 tokens.
    budget = max_seq // 2
    prompt_lens = _lengths(rng, n_requests, mean_log=3.0, sigma=0.8,
                           lo=4, hi=budget)
    out_lens = _lengths(rng, n_requests, mean_log=2.5, sigma=0.7,
                        lo=2, hi=budget // 2)
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)

    bs = CONFIG.llm_kv_block_size
    shared = rng.integers(0, cfg.vocab_size, 4 * bs).tolist()
    tenants = ["gold", "silver", "bronze"]

    def make_request(i: int):
        tenant = ""
        if mix == "multi_tenant":
            tenant = tenants[int(rng.integers(len(tenants)))]
        if mix == "shared_prefix" and rng.random() < 0.7:
            tail = rng.integers(
                0, cfg.vocab_size, max(1, prompt_lens[i] - len(shared))
            ).tolist()
            prompt = shared + tail
        else:
            prompt = rng.integers(0, cfg.vocab_size, prompt_lens[i]).tolist()
        return prompt[: budget], out_lens[i], tenant

    # Pre-build prompts so the submit loop does no numpy work on-clock.
    requests = [make_request(i) for i in range(n_requests)]
    arrivals = [_Arrival(tenant=tenant) for _p, _o, tenant in requests]

    def cb_for(a: _Arrival):
        def cb(token: int, finished: bool):
            a.token_times.append(time.perf_counter())
            if finished:
                a.done.set()
        return cb

    t_start = time.perf_counter()
    next_t = t_start
    for i, (prompt, max_tokens, tenant) in enumerate(requests):
        next_t += gaps[i]
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule, not the engine, paces
        a = arrivals[i]
        a.t_submit = time.perf_counter()
        try:
            engine.submit(
                prompt, SamplingParams(max_tokens=max_tokens), cb_for(a),
                tenant=tenant or "",
            )
        except EngineOverloadedError:
            a.rejected = True  # shed load: an SLO miss by definition
            a.done.set()
    for a in arrivals:
        a.done.wait(timeout=600)
    elapsed = time.perf_counter() - t_start

    ttfts = [a.ttft() for a in arrivals if a.ttft() is not None]
    tpots = [a.tpot() for a in arrivals if a.tpot() is not None]
    good = sum(
        1 for a in arrivals
        if not a.rejected and a.ttft() is not None
        and a.ttft() <= slo_ttft_s
        and (a.tpot() is None or a.tpot() <= slo_tpot_s)
    )
    rejected = sum(1 for a in arrivals if a.rejected)
    row = {
        "metric": "open_loop_load",
        "mix": mix,
        "arrival_rate_rps": rate_rps,
        "requests": n_requests,
        "rejected": rejected,
        "elapsed_s": round(elapsed, 3),
        "throughput_rps": round(n_requests / elapsed, 2),
        "ttft_p50_s": round(_pctl(ttfts, 0.5), 4),
        "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
        "tpot_p50_s": round(_pctl(tpots, 0.5), 4),
        "tpot_p99_s": round(_pctl(tpots, 0.99), 4),
        "slo": {"ttft_s": slo_ttft_s, "tpot_s": slo_tpot_s},
        "goodput_rps": round(good / elapsed, 2),
        "goodput_fraction": round(good / n_requests, 3),
    }
    if mix == "multi_tenant":
        per_tenant = {}
        for t in tenants:
            sub = [a for a in arrivals if a.tenant == t]
            t_ttfts = [a.ttft() for a in sub if a.ttft() is not None]
            per_tenant[t] = {
                "requests": len(sub),
                "ttft_p50_s": round(_pctl(t_ttfts, 0.5), 4),
                "ttft_p99_s": round(_pctl(t_ttfts, 0.99), 4),
            }
        row["tenants"] = per_tenant
    if mix == "shared_prefix":
        stats = engine.prefix_cache_stats()
        if stats:
            row["cache_hit_rate"] = round(stats.get("hit_rate", 0.0), 3)
            tiers = stats.get("tiers")
            if tiers:
                # Tiered cache (docs/kvcache.md): which tier served the
                # shared-prefix hits, plus spill/promotion traffic.
                row["tier_hits"] = {
                    t: tiers[f"hits_{t}"] for t in ("device", "host", "disk")
                }
                row["tier_traffic"] = {
                    "spills": tiers["spills"],
                    "promotions_host": tiers["promotions_host"],
                    "promotions_device": tiers["promotions_device"],
                }
    return row


def main():
    import jax

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rates", type=float, nargs="+", default=None,
                        help="arrival rates (req/s) for the base mix sweep")
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--slo-ttft", type=float, default=None)
    parser.add_argument("--slo-tpot", type=float, default=None)
    args = parser.parse_args()

    engine, cfg, model_id, on_tpu = build_engine(
        slots=8, tenant_weights={"gold": 2.0, "silver": 1.0, "bronze": 1.0},
    )
    max_seq = engine.T
    # CPU-host test-tiny SLOs: scaled to the tiny model's actual latency
    # envelope so goodput is a real discriminator (a real deployment sets
    # llm_slo_ttft_s/llm_slo_tpot_s for its hardware).
    slo_ttft = args.slo_ttft if args.slo_ttft is not None else (
        0.5 if on_tpu else 0.1)
    slo_tpot = args.slo_tpot if args.slo_tpot is not None else 0.05
    # The sweep's top rate must push past the knee: percentiles that never
    # degrade prove the harness isn't discriminating, not that the engine
    # is fast. On this host the tiny engine sustains ~200 req/s, so the top
    # rate drives it into queueing collapse (goodput fraction falls, the
    # admission cap starts shedding) while the lower rates stay inside SLO.
    rates = args.rates or ([2.0, 8.0, 24.0] if on_tpu else [8.0, 48.0, 384.0])

    results = []
    try:
        # Warm every compiled bucket off-clock (prefill buckets across the
        # lognormal tail + decode/multi-step programs).
        import numpy as np

        from ray_tpu.llm import SamplingParams

        rng = np.random.default_rng(7)
        for n in (8, 32, 64, 120):
            done = threading.Event()
            engine.submit(
                rng.integers(0, cfg.vocab_size, min(n, max_seq // 2)).tolist(),
                SamplingParams(max_tokens=8),
                lambda t, f: done.set() if f else None,
            )
            assert done.wait(600)

        for rate in rates:
            results.append(run_load(
                engine, cfg, rate_rps=rate, n_requests=args.requests,
                mix="base", slo_ttft_s=slo_ttft, slo_tpot_s=slo_tpot,
                seed=int(rate * 10), max_seq=max_seq,
            ))
            print(json.dumps(results[-1]))
        mid = rates[len(rates) // 2]
        for mix in ("shared_prefix", "multi_tenant"):
            results.append(run_load(
                engine, cfg, rate_rps=mid, n_requests=args.requests, mix=mix,
                slo_ttft_s=slo_ttft, slo_tpot_s=slo_tpot, seed=99,
                max_seq=max_seq,
            ))
            print(json.dumps(results[-1]))
        # The engine-side observability plane saw the same traffic: its
        # recorder/SLO counters ride along as the cross-check row.
        rec = engine.recorder_stats()
        results.append({
            "metric": "recorder_crosscheck",
            "recorder": {k: rec[k] for k in
                         ("started", "finished", "rejected", "dropped")},
            "slo_burn_rate_overall": round(
                engine._serve_metrics.burn_rate(""), 2),
        })
    finally:
        engine.shutdown()

    out = {
        "bench": "open_loop_load",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "model": model_id,
        "results": results,
    }
    with open("BENCH_LOAD.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
