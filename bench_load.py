"""Open-loop load harness: p50/p99 TTFT/TPOT and goodput-under-SLO per
arrival rate (ROADMAP item 1's measurement half; docs/observability.md).

Unlike bench_serve.py's closed-loop rows (submit N, wait for N), this
harness is OPEN-LOOP: arrivals follow a Poisson process whose rate does NOT
slow down when the engine falls behind — the shape real traffic has, and the
only shape that exposes queueing collapse (a closed loop self-throttles and
hides it). Per arrival rate it drives:

- **Poisson arrivals**: exponential inter-arrival gaps at `rate_rps`,
  submitted on schedule regardless of completions. An admission rejection
  (`EngineOverloadedError`) counts as shed load — an SLO miss, not an
  excuse.
- **Heavy-tailed lengths**: lognormal prompt and output token counts
  (clipped to the engine budget) — the long-prompt tail is what chunked
  prefill exists for; a fixed-length bench never exercises it.
- **Traffic mixes**: `base` (every prompt unique), `shared_prefix` (70% of
  requests share a whole-block system-prompt prefix, the prefix-cache +
  cache-aware regime), and `multi_tenant` (three tenants, WFQ weights
  2:1:1, per-tenant percentiles reported).

Per request the CLIENT measures TTFT (submit -> first token), mean TPOT
(inter-token gaps), and e2e; goodput-under-SLO counts completions meeting
BOTH `llm_slo_ttft_s` and `llm_slo_tpot_s` (scaled for this host via
--slo-ttft/--slo-tpot). The engine's own flight-recorder/SLO plane runs
concurrently and its counters are reported alongside, so the harness also
validates the observability path under load.

Writes BENCH_LOAD.json: one row per (arrival_rate, mix) + environment
metadata. This is the signal surface ROADMAP item 1's control loops (DP
replica count, WFQ weights, P:D ratio) will close against.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from typing import List, Optional


def _pctl(values: List[float], q: float) -> float:
    xs = sorted(values)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[idx]


def build_engine(**kw):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine

    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    max_seq = kw.pop("max_seq", 1024 if on_tpu else 256)
    if "prefix_cache" not in kw:
        # Tiered prefix cache (docs/kvcache.md): the shared_prefix mix then
        # reports its per-tier hit breakdown (device/host/disk).
        import tempfile

        from ray_tpu._private.config import CONFIG
        from ray_tpu.llm.kvcache import TieredPrefixCacheManager

        kw["prefix_cache"] = TieredPrefixCacheManager(
            CONFIG.llm_kv_block_size, CONFIG.llm_prefix_cache_bytes,
            name="bench-load", device_bytes=8 << 20,
            spill_dir=tempfile.mkdtemp(prefix="bench_load_spill_"),
        )
    engine = DecodeEngine(cfg, params, num_slots=kw.pop("slots", 8),
                          max_seq=max_seq, seed=0, **kw)
    return engine, cfg, model_id, on_tpu


class _Arrival:
    """One open-loop request's client-side measurement state."""

    __slots__ = ("t_submit", "token_times", "done", "rejected", "tenant")

    def __init__(self, tenant: str = ""):
        self.t_submit: Optional[float] = None
        self.token_times: List[float] = []
        self.done = threading.Event()
        self.rejected = False
        self.tenant = tenant

    def ttft(self) -> Optional[float]:
        if self.t_submit is None or not self.token_times:
            return None
        return self.token_times[0] - self.t_submit

    def tpot(self) -> Optional[float]:
        gaps = [b - a for a, b in zip(self.token_times, self.token_times[1:])]
        return (sum(gaps) / len(gaps)) if gaps else None


def _lengths(rng, n: int, *, mean_log: float, sigma: float, lo: int, hi: int):
    """Heavy-tailed token counts: lognormal, clipped to the engine budget."""
    raw = rng.lognormal(mean=mean_log, sigma=sigma, size=n)
    return [int(min(hi, max(lo, round(x)))) for x in raw]


def run_load(engine, cfg, *, rate_rps: float, n_requests: int, mix: str,
             slo_ttft_s: float, slo_tpot_s: float, seed: int = 0,
             max_seq: int = 256) -> dict:
    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError

    rng = np.random.default_rng(seed)
    # Heavy-tailed prompt/output lengths: median ~20-token prompts with a
    # tail out to the sequence budget; outputs median ~12 tokens.
    budget = max_seq // 2
    prompt_lens = _lengths(rng, n_requests, mean_log=3.0, sigma=0.8,
                           lo=4, hi=budget)
    out_lens = _lengths(rng, n_requests, mean_log=2.5, sigma=0.7,
                        lo=2, hi=budget // 2)
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)

    bs = CONFIG.llm_kv_block_size
    shared = rng.integers(0, cfg.vocab_size, 4 * bs).tolist()
    tenants = ["gold", "silver", "bronze"]

    def make_request(i: int):
        tenant = ""
        if mix == "multi_tenant":
            tenant = tenants[int(rng.integers(len(tenants)))]
        if mix == "shared_prefix" and rng.random() < 0.7:
            tail = rng.integers(
                0, cfg.vocab_size, max(1, prompt_lens[i] - len(shared))
            ).tolist()
            prompt = shared + tail
        else:
            prompt = rng.integers(0, cfg.vocab_size, prompt_lens[i]).tolist()
        return prompt[: budget], out_lens[i], tenant

    # Pre-build prompts so the submit loop does no numpy work on-clock.
    requests = [make_request(i) for i in range(n_requests)]
    arrivals = [_Arrival(tenant=tenant) for _p, _o, tenant in requests]

    def cb_for(a: _Arrival):
        def cb(token: int, finished: bool):
            a.token_times.append(time.perf_counter())
            if finished:
                a.done.set()
        return cb

    t_start = time.perf_counter()
    next_t = t_start
    for i, (prompt, max_tokens, tenant) in enumerate(requests):
        next_t += gaps[i]
        delay = next_t - time.perf_counter()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule, not the engine, paces
        a = arrivals[i]
        a.t_submit = time.perf_counter()
        try:
            engine.submit(
                prompt, SamplingParams(max_tokens=max_tokens), cb_for(a),
                tenant=tenant or "",
            )
        except EngineOverloadedError:
            a.rejected = True  # shed load: an SLO miss by definition
            a.done.set()
    for a in arrivals:
        a.done.wait(timeout=600)
    elapsed = time.perf_counter() - t_start

    ttfts = [a.ttft() for a in arrivals if a.ttft() is not None]
    tpots = [a.tpot() for a in arrivals if a.tpot() is not None]
    good = sum(
        1 for a in arrivals
        if not a.rejected and a.ttft() is not None
        and a.ttft() <= slo_ttft_s
        and (a.tpot() is None or a.tpot() <= slo_tpot_s)
    )
    rejected = sum(1 for a in arrivals if a.rejected)
    row = {
        "metric": "open_loop_load",
        "mix": mix,
        "arrival_rate_rps": rate_rps,
        "requests": n_requests,
        "rejected": rejected,
        "elapsed_s": round(elapsed, 3),
        "throughput_rps": round(n_requests / elapsed, 2),
        "ttft_p50_s": round(_pctl(ttfts, 0.5), 4),
        "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
        "tpot_p50_s": round(_pctl(tpots, 0.5), 4),
        "tpot_p99_s": round(_pctl(tpots, 0.99), 4),
        "slo": {"ttft_s": slo_ttft_s, "tpot_s": slo_tpot_s},
        "goodput_rps": round(good / elapsed, 2),
        "goodput_fraction": round(good / n_requests, 3),
    }
    if mix == "multi_tenant":
        per_tenant = {}
        for t in tenants:
            sub = [a for a in arrivals if a.tenant == t]
            t_ttfts = [a.ttft() for a in sub if a.ttft() is not None]
            per_tenant[t] = {
                "requests": len(sub),
                "ttft_p50_s": round(_pctl(t_ttfts, 0.5), 4),
                "ttft_p99_s": round(_pctl(t_ttfts, 0.99), 4),
            }
        row["tenants"] = per_tenant
    if mix == "shared_prefix":
        stats = engine.prefix_cache_stats()
        if stats:
            row["cache_hit_rate"] = round(stats.get("hit_rate", 0.0), 3)
            tiers = stats.get("tiers")
            if tiers:
                # Tiered cache (docs/kvcache.md): which tier served the
                # shared-prefix hits, plus spill/promotion traffic.
                row["tier_hits"] = {
                    t: tiers[f"hits_{t}"] for t in ("device", "host", "disk")
                }
                row["tier_traffic"] = {
                    "spills": tiers["spills"],
                    "promotions_host": tiers["promotions_host"],
                    "promotions_device": tiers["promotions_device"],
                }
    return row


class _PacedReplica:
    """One DP replica with an explicit admission-rate budget.

    On a one-core CI host every in-process engine shares the same CPU, so
    raw engine throughput cannot model per-replica capacity (N engines are
    still one core of compute, and building an engine mid-run starves the
    live one). The pacer caps each replica at `rps` admissions per second —
    the stand-in for one TPU host's serving capacity — while the REAL
    engine underneath still produces tokens, queue depth, and SLO burn for
    the control law to read. TTFT is measured from arrival, so admission
    queueing in an overloaded replica shows up as the SLO breach it is.
    """

    def __init__(self, engine, rps: float, cap: int = 64):
        self.engine = engine
        self._gap = 1.0 / rps
        self._cap = cap
        self._q: List = []
        self._cv = threading.Condition()
        self._stop = False
        self._th = threading.Thread(target=self._drain, daemon=True)
        self._th.start()

    def submit(self, prompt, params, cb):
        from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError

        with self._cv:
            if len(self._q) >= self._cap:
                raise EngineOverloadedError(
                    f"replica admission queue at capacity ({self._cap})")
            self._q.append((prompt, params, cb))
            self._cv.notify()

    def queue_depth(self) -> int:
        with self._cv:
            pending = len(self._q)
        return pending + self.engine._sched.queue_depth()

    def ongoing(self) -> int:
        st = self.engine._sched.stats()
        return st.get("running", 0) + st.get("prefilling", 0)

    def burn(self) -> float:
        return self.engine._serve_metrics.burn_rate("")

    def _drain(self):
        free_at = time.perf_counter()
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(0.1)
                if not self._q:
                    return  # stopped AND fully drained
                prompt, params, cb = self._q.pop(0)
            delay = free_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                self.engine.submit(prompt, params, cb)
            except Exception:
                cb(-1, True)  # surfaces as a rejection, not a lost request
            free_at = max(free_at, time.perf_counter()) + self._gap

    def close(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._th.join(timeout=120)
        self.engine.shutdown()


def run_autopilot_ab(cfg, params, *, base_rps: float, surge_rps: float,
                     phase_requests, slo_ttft_s: float, slo_tpot_s: float,
                     autopilot: bool, max_seq: int, seed: int = 0) -> dict:
    """One arm of the autopilot A/B (docs/autoscale.md): an in-process DP
    replica pool under a rate-STEP schedule (base -> 3x surge -> base ->
    quiet). The closed-loop arm drives the pool's size with the real
    `replica_law` off the replicas' own queue/burn signals — the same law
    the serve controller ticks — while the static arm holds one replica.
    Replicas are paced `_PacedReplica`s over a warm standby pool built
    off-clock (see its docstring for why), with a fixed activation delay
    per scale-up standing in for provisioning. The row records
    goodput-under-SLO, TTFT p50/p99, and the replica count over time."""
    import numpy as np

    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm._engine import DecodeEngine
    from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError
    from ray_tpu.serve.autopilot import ReplicaBounds
    from ray_tpu.serve.autopilot._laws import new_replica_state, replica_law

    rng = np.random.default_rng(seed)

    def new_engine(i: int) -> DecodeEngine:
        # Two slots per replica: small enough that the surge genuinely
        # overloads ONE replica (the regime the autopilot exists for) while
        # three absorb it.
        e = DecodeEngine(cfg, params, num_slots=2, max_seq=max_seq, seed=i)
        # Warm-start analog of the serve path's mmap + prefix bootstrap:
        # compile the arrival-sized buckets before the replica is routed.
        # Own rng: this runs on the control thread concurrently with the
        # submit loop's draws.
        wrng = np.random.default_rng(1000 + i)
        for n in (8, 32, max_seq // 4):
            done = threading.Event()
            e.submit(wrng.integers(0, cfg.vocab_size, n).tolist(),
                     SamplingParams(max_tokens=4),
                     lambda t, f, _d=done: _d.set() if f else None)
            done.wait(600)
        return e

    max_replicas = 3
    replica_rps = 1.5 * base_rps
    activation_delay_s = 1.0
    # Warm standby pool, built OFF-CLOCK (the static arm only needs one).
    replicas = [_PacedReplica(new_engine(i), replica_rps)
                for i in range(max_replicas if autopilot else 1)]
    pool = replicas[:1]
    lock = threading.Lock()
    bounds = ReplicaBounds(
        min_replicas=1, max_replicas=max_replicas, burn_high=1.0,
        queue_high=8.0, sustain_ticks=2, upscale_cooldown_s=0.5,
        downscale_cooldown_s=1.0, cold_start_guard_s=0.0)
    law_state = new_replica_state(1)
    t0 = time.perf_counter()
    series: List[List[float]] = [[0.0, 1]]
    stop = threading.Event()

    def control_loop():
        while not stop.wait(0.25):
            with lock:
                live = list(pool)
            queued = sum(r.queue_depth() for r in live)
            ongoing = sum(r.ongoing() for r in live)
            burn = max((r.burn() for r in live), default=0.0)
            fired = replica_law(
                state=law_state, replicas=len(live), queued=queued,
                ongoing=ongoing, burn=burn, bounds=bounds,
                now=time.perf_counter())
            if fired is None:
                continue
            target = fired[0]
            if target > len(live):
                time.sleep(activation_delay_s)  # provisioning stand-in
            with lock:
                # Activation routes new arrivals to standby replicas;
                # deactivation is drain-and-retire (a demoted replica keeps
                # serving its admitted queue, it just stops receiving).
                pool[:] = replicas[:target]
                series.append([round(time.perf_counter() - t0, 2),
                               len(pool)])

    controller = None
    if autopilot:
        controller = threading.Thread(target=control_loop, daemon=True)
        controller.start()

    phases = [(base_rps, phase_requests[0]), (surge_rps, phase_requests[1]),
              (base_rps, phase_requests[2])]
    n_total = sum(n for _r, n in phases)
    prompt_lens = _lengths(rng, n_total, mean_log=2.5, sigma=0.6, lo=4,
                           hi=max_seq // 4)
    arrivals = [_Arrival() for _ in range(n_total)]

    def cb_for(a: _Arrival):
        def cb(token: int, finished: bool):
            if token < 0:  # pacer-surfaced late rejection
                a.rejected = True
                a.done.set()
                return
            a.token_times.append(time.perf_counter())
            if finished:
                a.done.set()
        return cb

    i = 0
    next_t = time.perf_counter()
    for rate, n in phases:
        gaps = rng.exponential(1.0 / rate, size=n)
        for g in gaps:
            next_t += g
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            a = arrivals[i]
            prompt = rng.integers(0, cfg.vocab_size, prompt_lens[i]).tolist()
            a.t_submit = time.perf_counter()
            with lock:
                # Least-queued routing across the live pool (the DP router's
                # balanced pick, collapsed to in-process form).
                target = min(pool, key=lambda r: r.queue_depth())
            try:
                target.submit(prompt, SamplingParams(max_tokens=48),
                              cb_for(a))
            except EngineOverloadedError:
                a.rejected = True
                a.done.set()
            i += 1
    for a in arrivals:
        a.done.wait(timeout=600)
    # Quiet tail: the closed loop must also scale back DOWN once idle.
    if autopilot:
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            with lock:
                if len(pool) == 1:
                    break
            time.sleep(0.25)
    stop.set()
    if controller is not None:
        controller.join(timeout=10)

    ttfts = [a.ttft() for a in arrivals if a.ttft() is not None]
    good = sum(
        1 for a in arrivals
        if not a.rejected and a.ttft() is not None
        and a.ttft() <= slo_ttft_s
        and (a.tpot() is None or a.tpot() <= slo_tpot_s)
    )
    with lock:
        series.append([round(time.perf_counter() - t0, 2), len(pool)])
        pool.clear()
    for r in replicas:
        r.close()
    counts = [n for _t, n in series]
    return {
        "metric": "autopilot_ab",
        "arm": "autopilot" if autopilot else "static",
        "schedule": {"base_rps": base_rps, "surge_rps": surge_rps,
                     "phase_requests": list(phase_requests),
                     "replica_rps": replica_rps,
                     "activation_delay_s": activation_delay_s},
        "requests": n_total,
        "rejected": sum(1 for a in arrivals if a.rejected),
        "slo": {"ttft_s": slo_ttft_s, "tpot_s": slo_tpot_s},
        "goodput_fraction": round(good / n_total, 3),
        "ttft_p50_s": round(_pctl(ttfts, 0.5), 4),
        "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
        "replicas_over_time": series,
        "scaled_up": max(counts) > 1,
        "scaled_back_down": max(counts) > 1 and counts[-1] == 1,
    }


def run_autopilot_ab_suite(args) -> List[dict]:
    """Both arms on one loaded model; the A/B contract is autopilot goodput
    >= static goodput under the same rate-step schedule, having scaled up
    AND back down."""
    import jax

    from ray_tpu.llm import LLMConfig, load_model

    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    max_seq = 1024 if on_tpu else 256
    slo_ttft = args.slo_ttft if args.slo_ttft is not None else (
        0.5 if on_tpu else 0.25)
    slo_tpot = args.slo_tpot if args.slo_tpot is not None else 0.05
    base = args.ab_base_rps or (4.0 if on_tpu else 10.0)
    surge = args.ab_surge_rps or 3.0 * base
    # Duration-based phases: the surge window must dwarf an engine cold
    # start (~5s build+warm on CPU) or scaling up can never pay off before
    # the step ends. ~3s base, ~20s surge, ~5s base.
    durations = (3.0, 20.0, 5.0)
    phase_requests = tuple(
        max(4, int(r * d))
        for r, d in zip((base, surge, base), durations))
    rows = []
    for autopilot in (False, True):
        rows.append(run_autopilot_ab(
            cfg, params, base_rps=base, surge_rps=surge,
            phase_requests=phase_requests, slo_ttft_s=slo_ttft,
            slo_tpot_s=slo_tpot, autopilot=autopilot, max_seq=max_seq,
            seed=11))
        print(json.dumps(rows[-1]))
    return rows


def main():
    import jax

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rates", type=float, nargs="+", default=None,
                        help="arrival rates (req/s) for the base mix sweep")
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--slo-ttft", type=float, default=None)
    parser.add_argument("--slo-tpot", type=float, default=None)
    parser.add_argument("--autopilot-ab", action="store_true",
                        help="run the static-vs-closed-loop A/B under a "
                             "rate-step schedule and append the rows to "
                             "BENCH_LOAD.json (docs/autoscale.md)")
    parser.add_argument("--ab-base-rps", type=float, default=None)
    parser.add_argument("--ab-surge-rps", type=float, default=None)
    args = parser.parse_args()

    if args.autopilot_ab:
        rows = run_autopilot_ab_suite(args)
        try:
            with open("BENCH_LOAD.json") as f:
                out = json.load(f)
        except (OSError, ValueError):
            out = {"bench": "open_loop_load", "results": []}
        out["results"] = [r for r in out.get("results", [])
                          if r.get("metric") != "autopilot_ab"] + rows
        with open("BENCH_LOAD.json", "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps(rows))
        return

    engine, cfg, model_id, on_tpu = build_engine(
        slots=8, tenant_weights={"gold": 2.0, "silver": 1.0, "bronze": 1.0},
    )
    max_seq = engine.T
    # CPU-host test-tiny SLOs: scaled to the tiny model's actual latency
    # envelope so goodput is a real discriminator (a real deployment sets
    # llm_slo_ttft_s/llm_slo_tpot_s for its hardware).
    slo_ttft = args.slo_ttft if args.slo_ttft is not None else (
        0.5 if on_tpu else 0.1)
    slo_tpot = args.slo_tpot if args.slo_tpot is not None else 0.05
    # The sweep's top rate must push past the knee: percentiles that never
    # degrade prove the harness isn't discriminating, not that the engine
    # is fast. On this host the tiny engine sustains ~200 req/s, so the top
    # rate drives it into queueing collapse (goodput fraction falls, the
    # admission cap starts shedding) while the lower rates stay inside SLO.
    rates = args.rates or ([2.0, 8.0, 24.0] if on_tpu else [8.0, 48.0, 384.0])

    results = []
    try:
        # Warm every compiled bucket off-clock (prefill buckets across the
        # lognormal tail + decode/multi-step programs).
        import numpy as np

        from ray_tpu.llm import SamplingParams

        rng = np.random.default_rng(7)
        for n in (8, 32, 64, 120):
            done = threading.Event()
            engine.submit(
                rng.integers(0, cfg.vocab_size, min(n, max_seq // 2)).tolist(),
                SamplingParams(max_tokens=8),
                lambda t, f: done.set() if f else None,
            )
            assert done.wait(600)

        for rate in rates:
            results.append(run_load(
                engine, cfg, rate_rps=rate, n_requests=args.requests,
                mix="base", slo_ttft_s=slo_ttft, slo_tpot_s=slo_tpot,
                seed=int(rate * 10), max_seq=max_seq,
            ))
            print(json.dumps(results[-1]))
        mid = rates[len(rates) // 2]
        for mix in ("shared_prefix", "multi_tenant"):
            results.append(run_load(
                engine, cfg, rate_rps=mid, n_requests=args.requests, mix=mix,
                slo_ttft_s=slo_ttft, slo_tpot_s=slo_tpot, seed=99,
                max_seq=max_seq,
            ))
            print(json.dumps(results[-1]))
        # The engine-side observability plane saw the same traffic: its
        # recorder/SLO counters ride along as the cross-check row.
        rec = engine.recorder_stats()
        results.append({
            "metric": "recorder_crosscheck",
            "recorder": {k: rec[k] for k in
                         ("started", "finished", "rejected", "dropped")},
            "slo_burn_rate_overall": round(
                engine._serve_metrics.burn_rate(""), 2),
        })
    finally:
        engine.shutdown()

    out = {
        "bench": "open_loop_load",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "model": model_id,
        "results": results,
    }
    with open("BENCH_LOAD.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
