"""Benchmark: sharded checkpoint save/restore (docs/checkpoint.md).

Emits BENCH_CKPT.json in the BENCH_* shape: the step-loop BLOCKED time per
save for the sync vs async paths (the number the CheckFreq split is supposed
to shrink), end-to-end persist time, and restore time both onto the saved
layout and resharded onto a transposed mesh.

Methodology: the "train step" is a jitted matmul chain long enough to dwarf
dispatch noise; blocked time is (step+save loop wall) - (step-only loop wall)
over the same number of iterations, so fixed per-call dispatch cost cancels
(see docs/perf.md on why single-shot timings lie on this backend).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time


def _tree(mesh, dtype, n_layers: int, width: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("a", None))
    key = jax.random.PRNGKey(0)
    tree = {}
    for i in range(n_layers):
        key, sub = jax.random.split(key)
        tree[f"layer_{i}"] = {
            "kernel": jax.device_put(
                jax.random.normal(sub, (width, width), dtype), sh),
            "bias": jax.device_put(jnp.zeros((width,), dtype),
                                   NamedSharding(mesh, P("a"))),
        }
    return tree


def _step_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        for _ in range(8):
            x = jnp.tanh(x @ x)
        return x

    return step


def _timed_loop(step, x, iters, save=None):
    import jax

    t0 = time.perf_counter()
    for i in range(iters):
        x = step(x)
        if save is not None:
            save(i)
    jax.block_until_ready(x)
    return time.perf_counter() - t0


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu import checkpoint as ckpt

    n_dev = len(jax.devices())
    mesh_axis = n_dev if n_dev in (2, 4, 8) else 1
    mesh = Mesh(np.array(jax.devices()[:mesh_axis]).reshape(mesh_axis), ("a",))
    on_tpu = jax.default_backend() == "tpu"
    n_layers, width = (8, 2048) if on_tpu else (8, 512)
    tree = _tree(mesh, jnp.float32, n_layers, width)
    tree_bytes = sum(int(np.prod(v.shape)) * 4
                     for layer in tree.values() for v in layer.values())
    step = _step_fn()
    x0 = jnp.ones((width, width), jnp.float32)
    jax.block_until_ready(step(x0))  # compile + warm
    iters = 10
    base = tempfile.mkdtemp(prefix="bench_ckpt_")
    results = []
    try:
        base_wall = _timed_loop(step, x0, iters)

        # Sync save every step: the loop eats snapshot + IO + commit.
        w = ckpt.AsyncCheckpointWriter(inflight=2)
        sync_wall = _timed_loop(
            step, x0, iters,
            save=lambda i: w.save_sync(os.path.join(base, f"s{i}"), tree))
        # Async save every step: the loop eats snapshot + enqueue only.
        async_wall = _timed_loop(
            step, x0, iters,
            save=lambda i: w.save(os.path.join(base, f"a{i}"), tree))
        drain_t0 = time.perf_counter()
        w.wait_until_finished()
        drain_s = time.perf_counter() - drain_t0
        w.shutdown()

        sync_blocked = (sync_wall - base_wall) / iters
        async_blocked = (async_wall - base_wall) / iters
        results.append({
            "metric": "ckpt_step_blocked_ms_sync",
            "value": round(sync_blocked * 1e3, 2),
            "tree_mb": round(tree_bytes / 1e6, 1), "iters": iters,
        })
        results.append({
            "metric": "ckpt_step_blocked_ms_async",
            "value": round(async_blocked * 1e3, 2),
            "tree_mb": round(tree_bytes / 1e6, 1), "iters": iters,
            "speedup_vs_sync": round(sync_blocked / max(async_blocked, 1e-9), 2),
            "drain_s_after_loop": round(drain_s, 3),
        })

        path = os.path.join(base, "s0")
        t0 = time.perf_counter()
        host = ckpt.restore(path)
        restore_host_s = time.perf_counter() - t0
        del host
        # Transposed layout for the matrices, replicated vectors — a genuine
        # reshard of every 2-D leaf relative to the saved P("a", None).
        reshard = {
            key: NamedSharding(mesh,
                               P(None, "a") if key.endswith("kernel") else P())
            for key in ckpt.load_manifest(path)["leaves"]
        }
        t0 = time.perf_counter()
        out = ckpt.restore(path, shardings=reshard)
        jax.block_until_ready(out)
        restore_reshard_s = time.perf_counter() - t0
        results.append({
            "metric": "ckpt_restore_host_s", "value": round(restore_host_s, 3),
            "tree_mb": round(tree_bytes / 1e6, 1),
        })
        results.append({
            "metric": "ckpt_restore_reshard_s",
            "value": round(restore_reshard_s, 3),
            "tree_mb": round(tree_bytes / 1e6, 1),
            "note": "axis transposed vs saved layout",
        })
    finally:
        shutil.rmtree(base, ignore_errors=True)

    out = {
        "bench": "checkpoint",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "results": results,
    }
    with open("BENCH_CKPT.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
