"""PD KV-transfer benchmark: chunked tensor stream vs the host-pickle blob.

Measures the make-or-break cost of prefill/decode disaggregation (DistServe /
Mooncake: the KV handoff must be pipelined and copy-free) at realistic prefix
sizes, across REAL actor processes on one node:

- host_pickle: the seed-shape path — device -> host -> cloudpickle -> ONE
  RPC frame -> unpickle -> host -> device. The monolithic blob every copy of
  which is serial.
- object_plane: the pre-round-11 device_objects path — one full-tensor host
  materialization through the shared-memory object store.
- chunked_stream: the round-11 DeviceChannel path (docs/device_channels.md):
  raw chunk frames through a shm ring, D2H / wire / assembly pipelined at
  `llm_channel_chunk_bytes` granularity, no pickling of tensor bytes.

Per mode: transfer_s (descriptor resolution + payload to a host/continuous
buffer on the consumer) and attach_s (staging the prefix into device memory,
`block_until_ready` — the decode-side `_attach_kv` feed). Writes
BENCH_PD.json. Acceptance (ISSUE 8): chunked_stream total <= 0.5x host_pickle
total at >= 16 MB.
"""

from __future__ import annotations

import json
import time

KV_SHAPES = {
    # [L, 2, P, Hkv, D] float32; row cost L*2*Hkv*D*4 = 4096 B/token.
    "4MB": (4, 2, 1024, 2, 64),
    "16MB": (4, 2, 4096, 2, 64),
    "64MB": (4, 2, 16384, 2, 64),
}


def main():
    import numpy as np

    import ray_tpu

    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
    )

    @ray_tpu.remote
    class Prefill:
        """Owns the pinned KV prefixes (the prefill replica role)."""

        def pin(self, shape):
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.experimental import device_objects as dev

            rng = np.random.default_rng(0)
            kv = rng.standard_normal(shape).astype(np.float32)
            return dev.put(jnp.asarray(kv))

        def open_blob_channel(self, ref):
            """The host-pickle baseline's wire: one cloudpickled blob pushed
            through an RpcChannel (device->host->pickle->one RPC frame)."""
            import pickle
            import threading

            import cloudpickle
            import numpy as np

            from ray_tpu._private.worker import global_worker
            from ray_tpu.experimental import device_objects as dev
            from ray_tpu.experimental.channel import RpcChannel

            w = global_worker()
            ch = RpcChannel(num_readers=1, num_slots=2,
                            owner=("actor", w.actor_id))

            def pump():
                arr = dev.get(ref)  # owner-local: zero transfer
                blob = cloudpickle.dumps(
                    np.asarray(arr), protocol=pickle.HIGHEST_PROTOCOL
                )
                ch.write_bytes(blob, timeout=120.0)
                ch.drain(timeout=120.0)
                ch.destroy()

            threading.Thread(target=pump, daemon=True).start()
            return ch

    @ray_tpu.remote
    class Decode:
        """Pulls + attaches (the decode replica role); timings measured HERE,
        inside the consuming process."""

        def measure(self, owner, ref, mode):
            import cloudpickle
            import jax.numpy as jnp

            import ray_tpu as rt
            from ray_tpu.experimental import device_objects as dev

            t0 = time.perf_counter()
            if mode == "host_pickle":
                ch = rt.get(owner.open_blob_channel.remote(ref))
                kv = cloudpickle.loads(ch.read_bytes(timeout=120.0))
            elif mode == "object_plane":
                kv = dev.get(ref, _legacy=True)
            elif mode == "chunked_stream":
                # Direct stream call: get() itself gates small payloads onto
                # the blob path (devobj_stream_min_bytes); the bench measures
                # the raw stream at every size to show WHERE the gate sits.
                kv = dev._stream_fetch(ref, to_device=False)
            else:
                raise ValueError(mode)
            t1 = time.perf_counter()
            dev_kv = jnp.asarray(kv)
            dev_kv.block_until_ready()
            t2 = time.perf_counter()
            assert dev_kv.shape == ref.shape
            return {"transfer_s": t1 - t0, "attach_s": t2 - t1,
                    "total_s": t2 - t0}

    prefill, decode = Prefill.remote(), Decode.remote()
    results = []
    for label, shape in KV_SHAPES.items():
        ref = ray_tpu.get(prefill.pin.remote(shape), timeout=300)
        nbytes = int(np.prod(shape)) * 4
        row = {"metric": "pd_kv_transfer_attach", "prefix": label,
               "prefix_tokens": shape[2], "kv_bytes": nbytes}
        for mode in ("host_pickle", "object_plane", "chunked_stream"):
            best = None
            for _ in range(3):
                t = ray_tpu.get(
                    decode.measure.remote(prefill, ref, mode), timeout=600
                )
                if best is None or t["total_s"] < best["total_s"]:
                    best = t
            row[mode] = {k: round(v, 4) for k, v in best.items()}
        row["speedup_vs_host_pickle"] = round(
            row["host_pickle"]["total_s"] / row["chunked_stream"]["total_s"], 2
        )
        row["speedup_vs_object_plane"] = round(
            row["object_plane"]["total_s"] / row["chunked_stream"]["total_s"], 2
        )
        results.append(row)
        print(json.dumps(row))

    import jax

    from ray_tpu._private.config import CONFIG

    out = {
        "bench": "pd_kv_transfer",
        "backend": jax.default_backend(),
        "chunk_bytes": CONFIG.llm_channel_chunk_bytes,
        "stream_slots": CONFIG.devobj_stream_slots,
        "results": results,
        "stream_min_bytes": CONFIG.devobj_stream_min_bytes,
        "note": "same-node actor pair; chunked_stream rides the shm "
                "DeviceChannel ring (docs/device_channels.md), host_pickle "
                "is the seed-shape monolithic cloudpickle blob over one RPC "
                "frame, object_plane the pre-round-11 device_objects blob; "
                "production get() takes the blob below devobj_stream_min_"
                "bytes (stream setup only amortizes on multi-MB tensors)",
    }
    with open("BENCH_PD.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
