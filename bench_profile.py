"""Component-level profile of the flagship single-chip train step.

Decomposes bench.py's gpt2-125m step (batch 8, seq 1024, bf16, flash
attention) into its pipeline stages and measures each in isolation on the
real chip, so docs/perf.md can account for every millisecond between the
MXU-peak floor and the measured step.

Methodology: each component body is repeated N times inside ONE jitted
lax.scan (true data dependence through the carry) and the call syncs on a
scalar device_get — per-call dispatch latency (milliseconds on the axon
remote-dispatch tunnel, enough to swamp a 1 ms kernel measured call-by-call)
is paid once per N, not once per iteration. bench.py's own number uses
host-side chaining; the two agree at step granularity (~100 ms >> dispatch).

Usage: python bench_profile.py [component ...]
Components: step grad fwd opt attn attnbwd mlp head embed
"""

from __future__ import annotations

import functools
import json
import sys
import time


def scan_time(body, init, *, iters=16, warm=1, reps=3):
    """Per-iteration time of `body` via TWO-POINT scan timing.

    body: carry -> carry (pure). Runs jit(scan(body)) at two lengths (iters
    and 4*iters) and reports (t_long - t_short) / (3*iters): the fixed
    per-call cost — dispatch, the tunnel's sync round-trip, argument refresh —
    cancels in the subtraction. Single-length timing on the axon backend
    over-reports a 0.3 ms kernel as ~7 ms (measured: the per-call fixed cost
    is tens of ms); bench.py survives it only because its per-call payload is
    20 full steps. Syncs via device_get of a scalar folded from the carry —
    block_until_ready alone under-measures here.
    """
    import jax
    import jax.numpy as jnp

    def make(length):
        @jax.jit
        def run(init):
            def step(carry, _):
                return body(carry), ()

            final, _ = jax.lax.scan(step, init, None, length=length)
            # Fold ONE element of EVERY leaf into the sync scalar: anything
            # less and XLA dead-code-eliminates the parts of the chain that
            # don't reach the scalar (a step counter as first leaf once made
            # the whole train chain disappear and "measure" 0 ms).
            return sum(
                jnp.sum(leaf.astype(jnp.float32).ravel()[:1])
                for leaf in jax.tree_util.tree_leaves(final)
            )

        return run

    short, long_ = make(iters), make(4 * iters)
    for _ in range(warm):
        _ = float(short(init))
        _ = float(long_(init))
    pers = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _ = float(short(init))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        _ = float(long_(init))
        t_long = time.perf_counter() - t0
        pers.append((t_long - t_short) / (3 * iters))
    pers.sort()
    return max(pers[len(pers) // 2], 1e-9)  # median: robust to host-load spikes


def dispatch_overhead():
    """One near-empty jitted call, synced: the per-call floor."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def tiny(x):
        return x + 1.0

    x = jnp.zeros(())
    _ = float(tiny(x))
    t0 = time.perf_counter()
    for _ in range(5):
        x = tiny(x)
    _ = float(x)
    return (time.perf_counter() - t0) / 5


def build():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import Transformer, get_config
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.spmd import build_train_step, init_state

    on_tpu = jax.default_backend() == "tpu"
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    cfg = get_config("gpt2-125m", remat=False, max_seq=seq,
                     attention="flash" if on_tpu else "reference")
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"dp": 1})
    opt = optax.adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)
    state, _ = init_state(model, cfg, opt, mesh, sample_shape=(batch, seq))
    step_fn, shard = build_train_step(model, opt, mesh, with_grad_norm=False,
                                      donate=False)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size)
    data = {"tokens": jax.device_put(tokens, shard["tokens"]),
            "targets": jax.device_put(tokens, shard["targets"])}
    return model, cfg, opt, mesh, state, step_fn, data, batch, seq


def main():
    import jax
    import jax.numpy as jnp
    import optax

    want = set(sys.argv[1:]) or {
        "step", "grad", "fwd", "opt", "attn", "attnbwd", "mlp", "head", "embed"
    }
    model, cfg, opt, mesh, state, step_fn, data, B, S = build()
    H, E, D = cfg.n_heads, cfg.hidden, cfg.head_dim
    res = {"batch": B, "seq": S}
    res["dispatch_ms"] = 1e3 * dispatch_overhead()

    from ray_tpu.models.transformer import cross_entropy_loss

    def loss_of(params):
        logits = model.apply({"params": params}, data["tokens"])
        return cross_entropy_loss(logits, data["targets"])

    with mesh:
        if "step" in want:
            res["full_step_ms"] = 1e3 * scan_time(
                lambda st: step_fn(st, data)[0], state, iters=3)

        if "grad" in want:
            def grad_body(params):
                _, g = jax.value_and_grad(loss_of)(params)
                # Chain: params' = params + 0*g keeps true dependence without
                # drifting the values.
                return jax.tree.map(lambda p, gg: p + 0.0 * gg.astype(p.dtype),
                                    params, g)

            res["value_and_grad_ms"] = 1e3 * scan_time(
                grad_body, state.params, iters=8)

        if "fwd" in want:
            def loss_of_tokens(params, tokens):
                logits = model.apply({"params": params}, tokens)
                return cross_entropy_loss(logits, data["targets"])

            def fwd_body(carry):
                # Tokens must evolve with the carry or XLA hoists the whole
                # forward out of the scan as loop-invariant (measured 0.06 ms).
                tokens, acc = carry
                loss = loss_of_tokens(state.params, tokens)
                nxt = (tokens + loss.astype(jnp.int32) + 1) % cfg.vocab_size
                return nxt, acc + loss

            res["forward_loss_ms"] = 1e3 * scan_time(
                fwd_body, (data["tokens"], jnp.zeros(())), iters=6)

        if "opt" in want:
            _, grads = jax.jit(jax.value_and_grad(loss_of))(state.params)

            def opt_body(carry):
                params, opt_state = carry
                updates, new_opt = opt.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), new_opt

            res["optimizer_ms"] = 1e3 * scan_time(
                opt_body, (state.params, state.opt_state), iters=8)

        if "attn" in want or "attnbwd" in want:
            from ray_tpu.ops.attention import flash_attention
            k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
            q = jax.random.normal(k1, (B, S, H, D), jnp.bfloat16)
            k = jax.random.normal(k2, (B, S, H, D), jnp.bfloat16)
            v = jax.random.normal(k3, (B, S, H, D), jnp.bfloat16)

        if "attn" in want:
            def attn_body(q):
                return flash_attention(q, k, v, True)

            t = scan_time(attn_body, q, iters=24)
            res["attn_fwd_ms_x12"] = 12e3 * t
            attn_fwd_flops = 2 * 2 * B * H * S * S * D / 2  # causal half
            res["attn_fwd_tflops"] = attn_fwd_flops / t / 1e12

        if "attnbwd" in want:
            def attn_loss(q):
                return jnp.sum(flash_attention(q, k, v, True)
                               .astype(jnp.float32))

            def attnbwd_body(q):
                g = jax.grad(attn_loss)(q)
                return q + 0.0 * g.astype(q.dtype)

            t = scan_time(attnbwd_body, q, iters=16)
            res["attn_fwdbwd_ms_x12"] = 12e3 * t

        if "attnbhsd" in want:
            # Transpose-free layout: same kernel, operands already [B,H,S,D].
            from ray_tpu.ops.attention import flash_attention_bhsd

            k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
            qh = jax.random.normal(k1, (B, H, S, D), jnp.bfloat16)
            kh = jax.random.normal(k2, (B, H, S, D), jnp.bfloat16)
            vh = jax.random.normal(k3, (B, H, S, D), jnp.bfloat16)

            def bhsd_body(qh):
                return flash_attention_bhsd(qh, kh, vh, True)

            t = scan_time(bhsd_body, qh, iters=24)
            res["attnbhsd_fwd_ms_x12"] = 12e3 * t

            def bhsd_loss(qh):
                return jnp.sum(flash_attention_bhsd(qh, kh, vh, True)
                               .astype(jnp.float32))

            def bhsd_bwd_body(qh):
                g = jax.grad(bhsd_loss)(qh)
                return qh + 0.0 * g.astype(qh.dtype)

            t = scan_time(bhsd_bwd_body, qh, iters=16)
            res["attnbhsd_fwdbwd_ms_x12"] = 12e3 * t

        if "attnlib" in want:
            # The jax-shipped tuned TPU flash kernel (public pallas ops), as a
            # candidate replacement for ops/attention.py's custom kernel.
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as lib_fa,
            )
            import math as _math

            k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
            qh = jax.random.normal(k1, (B, H, S, D), jnp.bfloat16)
            kh = jax.random.normal(k2, (B, H, S, D), jnp.bfloat16)
            vh = jax.random.normal(k3, (B, H, S, D), jnp.bfloat16)
            sc = 1.0 / _math.sqrt(D)

            def lib_body(qh):
                return lib_fa(qh, kh, vh, causal=True, sm_scale=sc)

            t = scan_time(lib_body, qh, iters=24)
            res["attnlib_fwd_ms_x12"] = 12e3 * t
            res["attnlib_fwd_tflops"] = (2 * 2 * B * H * S * S * D / 2) / t / 1e12

            def lib_loss(qh):
                return jnp.sum(lib_fa(qh, kh, vh, causal=True, sm_scale=sc)
                               .astype(jnp.float32))

            def lib_bwd_body(qh):
                g = jax.grad(lib_loss)(qh)
                return qh + 0.0 * g.astype(qh.dtype)

            t = scan_time(lib_bwd_body, qh, iters=16)
            res["attnlib_fwdbwd_ms_x12"] = 12e3 * t

        if "mlp" in want:
            # The per-layer dense matmuls (q,k,v,o + gate,up,down) as one
            # chained program: achievable MXU efficiency at model shapes.
            x = jax.random.normal(jax.random.PRNGKey(2), (B * S, E), jnp.bfloat16)
            wq = jax.random.normal(jax.random.PRNGKey(3), (E, E), jnp.bfloat16)
            wg = jax.random.normal(jax.random.PRNGKey(4), (E, cfg.mlp_dim), jnp.bfloat16)
            wd = jax.random.normal(jax.random.PRNGKey(5), (cfg.mlp_dim, E), jnp.bfloat16)

            def mlp_body(x):
                mm = lambda a, b: jax.lax.dot(  # noqa: E731
                    a, b, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
                for _ in range(4):  # q k v o
                    x = mm(x, wq)
                g = mm(x, wg)
                u = mm(x, wg)
                return mm((g * u).astype(jnp.bfloat16), wd)

            t = scan_time(mlp_body, x, iters=24)
            flops = 2 * B * S * (4 * E * E + 3 * E * cfg.mlp_dim)
            res["dense_matmuls_ms_x12"] = 12e3 * t
            res["dense_matmul_tflops"] = flops / t / 1e12

        if "head" in want:
            hidden0 = jax.random.normal(jax.random.PRNGKey(6), (B, S, E),
                                        jnp.bfloat16)
            table0 = jax.random.normal(jax.random.PRNGKey(7),
                                       (cfg.vocab_size, E), jnp.float32)

            def head_loss(hidden, table):
                logits = jax.lax.dot_general(
                    hidden, table.astype(jnp.bfloat16),
                    (((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return cross_entropy_loss(logits, data["targets"])

            def head_body(carry):
                hidden, table = carry
                gh, gt = jax.grad(head_loss, argnums=(0, 1))(hidden, table)
                return hidden + 0.0 * gh.astype(hidden.dtype), \
                    table + 0.0 * gt.astype(table.dtype)

            res["head_ce_fwdbwd_ms"] = 1e3 * scan_time(
                head_body, (hidden0, table0), iters=8)

        if "embed" in want:
            table0 = jax.random.normal(jax.random.PRNGKey(8),
                                       (cfg.vocab_size, E), jnp.float32)

            def embed_body(carry):
                table, acc = carry
                x = table[data["tokens"]].astype(jnp.bfloat16)
                return table, acc + jnp.sum(x.astype(jnp.float32))

            res["embed_gather_ms"] = 1e3 * scan_time(
                embed_body, (table0, jnp.zeros(())), iters=16)

    # Roofline context.
    import bench
    peak = bench.peak_flops_per_chip()
    n_params = cfg.num_params()
    attn_flops = 12 * cfg.n_layers * cfg.hidden * S
    step_flops = (6 * n_params + attn_flops) * B * S
    res["model_flops_per_step_T"] = round(step_flops / 1e12, 3)
    res["mxu_floor_ms"] = round(1e3 * step_flops / peak, 2)
    for k, v in list(res.items()):
        if isinstance(v, float):
            res[k] = round(v, 3)
    print(json.dumps(res, indent=1))

    # Artifact, same convention as BENCH_SERVE.json: environment metadata +
    # one row per measured component so docs/perf.md can link a committed
    # snapshot instead of a pasted blob.
    context_keys = ("batch", "seq", "model_flops_per_step_T", "mxu_floor_ms")
    rows = [
        {"component": k, "per_iteration_ms": v}
        for k, v in res.items()
        if k not in context_keys and not k.endswith("_tflops")
    ]
    for k, v in res.items():
        if k.endswith("_tflops"):
            base = k[: -len("_tflops")]
            for row in rows:
                if row["component"].startswith(base):
                    row["tflops"] = v
    out = {
        "bench": "train_step_profile",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "context": {k: res[k] for k in context_keys if k in res},
        "methodology": (
            "two-point scan timing: each component repeated inside one "
            "jitted lax.scan at lengths N and 4N, per-iteration ms = "
            "(t_long - t_short) / 3N so the fixed per-call cost (dispatch, "
            "sync round-trip) cancels; median of 3 reps; synced via "
            "device_get of a scalar folded from every carry leaf"
        ),
        "results": rows,
    }
    with open("BENCH_PROFILE.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
