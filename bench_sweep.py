"""Experiment sweep for the single-chip train step (writes incremental results)."""

import sys
import time

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.transformer import Transformer, get_config
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.spmd import build_train_step, init_state


def run(tag, batch=8, seq=1024, fused=None, chunk=None, attention="flash",
        remat=False, iters=10, **cfg_over):
    t_start = time.time()
    try:
        cfg = get_config("gpt2-125m", remat=remat, max_seq=seq,
                         attention=attention, **cfg_over)
        model = Transformer(cfg)
        mesh = mesh_lib.create_mesh({"dp": 1})
        opt = optax.adamw(3e-4, weight_decay=0.01)
        state, _ = init_state(model, cfg, opt, mesh, sample_shape=(batch, seq))
        kwargs = {}
        if fused is not None:
            kwargs["fused_ce"] = fused
        if chunk is not None:
            import ray_tpu.models.transformer as tmod
            orig = tmod.fused_cross_entropy_loss

            def patched(h, t, tg, m=None, **kw):
                kw["chunk"] = chunk
                return orig(h, t, tg, m, **kw)

            tmod.fused_cross_entropy_loss = patched
        step_fn, shard = build_train_step(model, opt, mesh, **kwargs)
        if chunk is not None:
            tmod.fused_cross_entropy_loss = orig
        tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                    cfg.vocab_size)
        data = {"tokens": jax.device_put(tokens, shard["tokens"]),
                "targets": jax.device_put(tokens, shard["targets"])}
        with mesh:
            state, m = step_fn(state, data)
            _ = float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(iters):
                state, m = step_fn(state, data)
            _ = float(m["loss"])
            dt = (time.perf_counter() - t0) / iters
        msg = (f"{tag}: {dt*1e3:.1f} ms/step, {batch*seq/dt:.0f} tok/s "
               f"(compile+run {time.time()-t_start:.0f}s)")
    except Exception as e:  # noqa: BLE001
        msg = f"{tag}: FAILED {type(e).__name__}: {str(e)[:160]}"
    print(msg, flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "1"):
        run("plain-b8", fused=False)
        run("fused-c512-b8", fused=True, chunk=512)
        run("fused-c1024-b8", fused=True, chunk=1024)
    if which in ("all", "2"):
        run("plain-b8-refattn", fused=False, attention="reference")
        run("fused-c1024-b16", fused=True, chunk=1024, batch=16)
        run("plain-b4", fused=False, batch=4)
