"""Experiment sweep for the single-chip train step (writes incremental results)."""

import sys
import time

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.transformer import Transformer, get_config
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.spmd import build_train_step, init_state


def run(tag, batch=8, seq=1024, fused=None, chunk=None, attention="flash",
        remat=False, iters=10, grad_norm=False, env=None, **cfg_over):
    import os
    t_start = time.time()
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = str(v)
    try:
        cfg = get_config("gpt2-125m", remat=remat, max_seq=seq,
                         attention=attention, **cfg_over)
        model = Transformer(cfg)
        mesh = mesh_lib.create_mesh({"dp": 1})
        # Match bench.py exactly: bf16 first moment, no grad-norm pass.
        opt = optax.adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)
        state, _ = init_state(model, cfg, opt, mesh, sample_shape=(batch, seq))
        kwargs = {}
        if fused is not None:
            kwargs["fused_ce"] = fused
        if chunk is not None:
            import ray_tpu.models.transformer as tmod
            orig = tmod.fused_cross_entropy_loss

            def patched(h, t, tg, m=None, **kw):
                kw["chunk"] = chunk
                return orig(h, t, tg, m, **kw)

            tmod.fused_cross_entropy_loss = patched
        step_fn, shard = build_train_step(model, opt, mesh,
                                          with_grad_norm=grad_norm, **kwargs)
        if chunk is not None:
            tmod.fused_cross_entropy_loss = orig
        tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                    cfg.vocab_size)
        data = {"tokens": jax.device_put(tokens, shard["tokens"]),
                "targets": jax.device_put(tokens, shard["targets"])}
        with mesh:
            state, m = step_fn(state, data)
            _ = float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(iters):
                state, m = step_fn(state, data)
            _ = float(m["loss"])
            dt = (time.perf_counter() - t0) / iters
        msg = (f"{tag}: {dt*1e3:.1f} ms/step, {batch*seq/dt:.0f} tok/s "
               f"(compile+run {time.time()-t_start:.0f}s)")
    except Exception as e:  # noqa: BLE001
        msg = f"{tag}: FAILED {type(e).__name__}: {str(e)[:160]}"
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print(msg, flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "1"):
        run("plain-b8", fused=False)
        run("fused-c512-b8", fused=True, chunk=512)
        run("fused-c1024-b8", fused=True, chunk=1024)
    if which in ("all", "2"):
        run("plain-b8-refattn", fused=False, attention="reference")
        run("fused-c1024-b16", fused=True, chunk=1024, batch=16)
        run("plain-b4", fused=False, batch=4)
    if which == "r5a":
        run("plain-b8", fused=False)
        run("fused-c1024-b8", fused=True, chunk=1024)
        run("plain-b16", fused=False, batch=16)
        run("fused-c1024-b16", fused=True, chunk=1024, batch=16)
        run("fused-c1024-b32", fused=True, chunk=1024, batch=32)
    if which == "r5b":
        run("fused-c2048-b16", fused=True, chunk=2048, batch=16)
        run("fused-c512-b16", fused=True, chunk=512, batch=16)
        run("plain-b32", fused=False, batch=32)
        run("fused-c1024-b64", fused=True, chunk=1024, batch=64)
    if which == "r5c":
        run("unrolled-b8", fused=False, scan_layers=False)
        run("refattn-b8", fused=False, attention="reference")
        run("flash-bq256-bk512", fused=False,
            env={"RAY_TPU_FLASH_BQ": 256, "RAY_TPU_FLASH_BK": 512,
                 "RAY_TPU_FLASH_BWD_BQ": 256, "RAY_TPU_FLASH_BWD_BK": 512})
        run("flash-bq1024-bk512", fused=False,
            env={"RAY_TPU_FLASH_BQ": 1024, "RAY_TPU_FLASH_BK": 512,
                 "RAY_TPU_FLASH_BWD_BQ": 1024, "RAY_TPU_FLASH_BWD_BK": 512})
        run("xla-bwd-b8", fused=False, env={"RAY_TPU_FLASH_BWD": "xla"})
    if which == "r5e":
        # In-graph ablations: replace one component with a near-free stand-in
        # and diff against baseline — locates where the full value_and_grad's
        # time actually goes (isolated microbenches under-count fusion costs).
        import jax as _jax
        import jax.numpy as _jnp

        import ray_tpu.models.transformer as _tmod

        run("ablate-none", fused=False)
        orig_flash = _tmod.flash_attention
        _tmod.flash_attention = lambda q, k, v, causal=True, scale=None: v
        run("ablate-attn", fused=False)
        _tmod.flash_attention = orig_flash
        # The rotation is applied via _rope_apply (angles are hoisted out of
        # the layers); patching _rope alone would ablate nothing.
        orig_rope_apply = _tmod._rope_apply
        _tmod._rope_apply = lambda x, cos, sin: x
        run("ablate-rope", fused=False)
        _tmod._rope_apply = orig_rope_apply

        class _CheapNorm(_tmod.RMSNorm):
            @_tmod.nn.compact
            def __call__(self, x):
                scale = self.param(
                    "scale",
                    _tmod.nn.with_logical_partitioning(
                        _tmod.nn.initializers.ones_init(), ("embed",)),
                    (x.shape[-1],), self.param_dtype)
                return x * scale.astype(x.dtype)

        orig_norm = _tmod.RMSNorm
        _tmod.RMSNorm = _CheapNorm
        run("ablate-norm", fused=False)
        _tmod.RMSNorm = orig_norm
    if which == "r5f":
        import jax.numpy as _jnp

        import ray_tpu.models.transformer as _tmod

        run("ablate-none2", fused=False)
        # Head+CE ablation: fused=True makes apply() return hidden (the head
        # matmul never runs); patching fused_cross_entropy_loss to a cheap
        # mean removes the entire head+CE cost from the graph.
        orig_fce = _tmod.fused_cross_entropy_loss
        _tmod.fused_cross_entropy_loss = (
            lambda hidden, table, targets, mask=None, **kw:
            _jnp.mean(hidden.astype(_jnp.float32)) + 0.0 * _jnp.sum(table[0, 0])
        )
        run("ablate-head", fused=True)
        _tmod.fused_cross_entropy_loss = orig_fce
        run("best-blocks", fused=False,
            env={"RAY_TPU_FLASH_BQ": 256, "RAY_TPU_FLASH_BK": 1024})
    if which == "r5d":
        run("unrolled-refattn-b8", fused=False, scan_layers=False,
            attention="reference")
        run("unrolled-xla-bwd-b8", fused=False, scan_layers=False,
            env={"RAY_TPU_FLASH_BWD": "xla"})
        run("remat-b8", fused=False, remat=True)
        run("fusedqkv-b8", fused=False, fused_qkv=True)
