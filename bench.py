"""Benchmark: flagship-model training throughput on the available TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: gpt2-125m causal-LM training tokens/sec on one chip (bf16, flash attention,
adamw, remat off at this size). vs_baseline is measured model-FLOPs utilization (MFU)
divided by 0.40 — the MFU a tuned A100 torch/FSDP stack typically reaches on GPT-2-class
models (the reference framework's GPU training path; BASELINE.md north-star row
"FSDP->shard_map MFU vs A100 FSDP"). vs_baseline >= 1.0 means we match that bar.

Timing methodology: the train state is threaded through consecutive steps (step N+1
consumes step N's output), so the measured wall time covers real execution; a final
device_get syncs the chain. This matters on remote-dispatch backends where
block_until_ready alone under-measures.
"""

from __future__ import annotations

import json
import time


def peak_flops_per_chip() -> float:
    """bf16 peak for the local chip generation."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import Transformer, get_config
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.spmd import build_train_step, init_state

    on_tpu = jax.default_backend() == "tpu"
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    cfg = get_config("gpt2-125m", remat=False, max_seq=seq,
                     attention="flash" if on_tpu else "reference")
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"dp": 1})  # single chip; dp>1 when more are visible
    # First-moment state in bf16 (mu_dtype): halves one optimizer-state stream's
    # HBM traffic; nu and params stay f32 (standard practice, e.g. T5X).
    optimizer = optax.adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)

    state, _ = init_state(model, cfg, optimizer, mesh, sample_shape=(batch, seq))
    step_fn, batch_shardings = build_train_step(
        model, optimizer, mesh, with_grad_norm=False
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab_size)
    data = {
        "tokens": jax.device_put(tokens, batch_shardings["tokens"]),
        "targets": jax.device_put(tokens, batch_shardings["targets"]),
    }

    with mesh:
        state, metrics = step_fn(state, data)  # compile + warm
        _ = float(metrics["loss"])
        iters = 20 if on_tpu else 3
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step_fn(state, data)
        _ = float(metrics["loss"])  # sync the chain
        dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / dt
    n_params = cfg.num_params()
    # Training FLOPs/token ~= 6N (fwd 2N + bwd 4N); attention term added explicitly.
    attn_flops = 12 * cfg.n_layers * cfg.hidden * seq  # per token, causal-averaged
    flops_per_token = 6 * n_params + attn_flops
    mfu = tokens_per_sec * flops_per_token / peak_flops_per_chip()
    vs_baseline = mfu / 0.40 if on_tpu else 0.0

    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 3),
        "extra": {
            "mfu": round(mfu, 4),
            "step_ms": round(dt * 1e3, 2),
            "batch": batch,
            "seq": seq,
            "params_m": round(n_params / 1e6, 1),
            "backend": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    main()
