"""Serving benchmark: TTFT + decode throughput of the TPU decode engine.

Measures the BASELINE.md "Serve LLM tokens/s + TTFT" north star directly on the
continuous-batching engine (`ray_tpu/llm/_engine.py`) — no cluster in the
measurement path, so the numbers are the engine's own ceiling:

- TTFT: submit -> first token on a warm engine (compiled prefill bucket),
  single request, empty batch (the latency-bound regime).
- decode tokens/s at concurrency 1/2/4/8: all requests in flight together
  through the slot scheduler; total generated tokens / wall time.
- speculative decoding on/off at concurrency 1 (self-draft upper bound: the
  draft IS the target, so every proposal verifies — measures the dispatch
  mechanics' best case, reference vllm spec_decode).
- prefix-cache warm vs cold TTFT on a repeated-prefix workload (shared
  system prompt + unique tails): a warm hit attaches cached KV blocks and
  prefills suffix-only (docs/kvcache.md), so warm TTFT must sit strictly
  below cold; hit-rate and prefill-bucket columns verify the mechanism.

Writes BENCH_SERVE.json: a list of measurement dicts + environment metadata.
"""

from __future__ import annotations

import json
import threading
import time


def build_engine(spec: bool = False, slots: int = 8):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine

    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    max_seq = 1024 if on_tpu else 128
    spec_config = None
    if spec:
        spec_config = {"draft_cfg": cfg, "draft_params": params,
                       "num_spec_tokens": 6}
    engine = DecodeEngine(
        cfg, params, num_slots=slots, max_seq=max_seq, seed=0,
        spec_config=spec_config,
    )
    return engine, cfg, model_id, on_tpu


def run_requests(engine, vocab: int, n: int, prompt_len: int, max_tokens: int):
    """Submit n concurrent requests; returns (ttft_first_req_s, tokens/s, total)."""
    from ray_tpu.llm._engine import SamplingParams

    import numpy as np

    rng = np.random.default_rng(0)
    done = [threading.Event() for _ in range(n)]
    first_token_t = [None] * n
    counts = [0] * n
    t0 = time.perf_counter()

    def cb_for(i):
        def cb(token, finished):
            if first_token_t[i] is None:
                first_token_t[i] = time.perf_counter() - t0
            counts[i] += 1
            if finished:
                done[i].set()

        return cb

    for i in range(n):
        prompt = rng.integers(0, vocab, prompt_len).tolist()
        engine.submit(prompt, SamplingParams(max_tokens=max_tokens), cb_for(i))
    for ev in done:
        if not ev.wait(timeout=600):
            raise TimeoutError("generation did not finish")
    elapsed = time.perf_counter() - t0
    total = sum(counts)
    return first_token_t[0], total / elapsed, total


def bench_prefix_cache(prompt_len: int):
    """Warm vs cold TTFT for a shared-prefix workload (docs/kvcache.md).

    Requests share a 5-block system-prompt prefix and differ in an 8-token
    tail. The first request prefills everything (cold); later ones attach the
    cached prefix and prefill only the tail's bucket (warm). Programs are
    warmed on a DIFFERENT prefix first so both measurements exclude compile
    time; `last_prefill` proves the warm request really prefilled
    suffix-only.
    """
    import time as _time

    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.kvcache import PrefixCacheManager

    engine, cfg, model_id, _on_tpu = build_engine(spec=False, slots=4)
    bs = CONFIG.llm_kv_block_size
    shared_len, tail_len = 5 * bs, 8
    rng = np.random.default_rng(1)

    def request(prefix, seed):
        tail = np.random.default_rng(seed).integers(0, cfg.vocab_size, tail_len)
        prompt = prefix + tail.tolist()
        done = threading.Event()
        ttft = [None]
        t0 = _time.perf_counter()

        def cb(token, finished):
            if ttft[0] is None:
                ttft[0] = _time.perf_counter() - t0
            if finished:
                done.set()

        engine.submit(prompt, SamplingParams(max_tokens=2), cb)
        assert done.wait(timeout=600)
        return ttft[0]

    try:
        # Compile warm-up on a throwaway prefix: first call compiles the cold
        # bucket, second the attach + suffix-bucket programs.
        warm_prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        request(warm_prefix, 100)
        request(warm_prefix, 101)

        prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        ttft_cold = request(prefix, 0)
        cold = dict(engine.last_prefill)
        warm_ttfts = []
        for i in range(1, 4):
            warm_ttfts.append(request(prefix, i))
        warm = dict(engine.last_prefill)
        stats = engine.prefix_cache_stats()
        assert warm["offset"] == shared_len and cold["offset"] == 0, (cold, warm)
        assert warm["bucket"] < cold["bucket"], (cold, warm)
        return [
            {
                "metric": "ttft_prefix_cold_s", "value": round(ttft_cold, 4),
                "prompt_len": shared_len + tail_len,
                "prefill_bucket": cold["bucket"], "model": model_id,
            },
            {
                "metric": "ttft_prefix_warm_s",
                "value": round(min(warm_ttfts), 4),
                "prompt_len": shared_len + tail_len,
                "prefill_bucket": warm["bucket"],
                "prefill_offset": warm["offset"],
                "cache_hit_rate": round(stats["hit_rate"], 3),
                "cache_hit_tokens": stats["hit_tokens"],
                "model": model_id,
                "note": "shared 5-block prefix attached from cache; "
                        "suffix-only prefill",
            },
        ]
    finally:
        engine.shutdown()


def main():
    import jax

    results = []
    engine, cfg, model_id, on_tpu = build_engine(spec=False, slots=8)
    prompt_len, max_tokens = (128, 64) if on_tpu else (16, 16)

    # Warm every compiled program off-clock: prefill bucket, batched decode,
    # and every multi-step chunk bucket the measured budget will use
    # (8/4/2/1 for max_tokens=64).
    run_requests(engine, cfg.vocab_size, 2, prompt_len, max_tokens)

    # TTFT: warm single request into an empty engine.
    ttfts = []
    for _ in range(3):
        ttft, _, _ = run_requests(engine, cfg.vocab_size, 1, prompt_len, 2)
        ttfts.append(ttft)
    results.append({
        "metric": "ttft_warm_s", "value": round(min(ttfts), 4),
        "prompt_len": prompt_len, "model": model_id,
    })

    # Decode throughput vs concurrency (continuous batching).
    for conc in (1, 2, 4, 8):
        _, tps, total = run_requests(
            engine, cfg.vocab_size, conc, prompt_len, max_tokens
        )
        results.append({
            "metric": "decode_tokens_per_s", "concurrency": conc,
            "value": round(tps, 1), "tokens": total, "model": model_id,
        })
    engine.shutdown()

    # Speculative decoding (self-draft upper bound), concurrency 1.
    engine_spec, cfg_s, _, _ = build_engine(spec=True, slots=8)
    run_requests(engine_spec, cfg_s.vocab_size, 1, prompt_len, max_tokens)  # warm
    _, tps_spec, _ = run_requests(
        engine_spec, cfg_s.vocab_size, 1, prompt_len, max_tokens
    )
    engine_spec.shutdown()
    base = next(r["value"] for r in results
                if r["metric"] == "decode_tokens_per_s" and r["concurrency"] == 1)
    results.append({
        "metric": "decode_tokens_per_s_specdecode", "concurrency": 1,
        "value": round(tps_spec, 1), "speedup_vs_plain": round(tps_spec / base, 2),
        "model": model_id, "note": "self-draft k=6: all-accept upper bound",
    })

    results.extend(bench_prefix_cache(prompt_len))

    out = {
        "bench": "serve_engine",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "results": results,
    }
    with open("BENCH_SERVE.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
