"""Serving benchmark: TTFT + decode throughput of the TPU decode engine.

Measures the BASELINE.md "Serve LLM tokens/s + TTFT" north star directly on the
continuous-batching engine (`ray_tpu/llm/_engine.py`) — no cluster in the
measurement path, so the numbers are the engine's own ceiling:

- TTFT: submit -> first token on a warm engine (compiled prefill bucket),
  single request, empty batch (the latency-bound regime).
- decode tokens/s at concurrency 1/2/4/8: all requests in flight together
  through the slot scheduler; total generated tokens / wall time.
- mixed traffic (docs/scheduler.md): long prompts injected into 4 live
  decode streams, with the iteration-level scheduler's chunked prefill ON
  (token budget) vs OFF (legacy whole-prompt admission) — measures injected
  TTFT p50/p99 and the decode streams' inter-token stall (TPOT p99 / max)
  during the injection window. Chunked prefill must bound the stall.
- speculative decoding at concurrency 1 on a repeated-traffic workload
  (ngram/REST retrieval draft, docs/scheduler.md): reports tokens/s,
  speedup vs the plain engine on the SAME workload, and the measured
  acceptance rate (realistic: the first pass misses, repeats hit).
- prefix-cache warm vs cold TTFT on a repeated-prefix workload (shared
  system prompt + unique tails): a warm hit attaches cached KV blocks and
  prefills suffix-only (docs/kvcache.md), so warm TTFT must sit strictly
  below cold; hit-rate and prefill-bucket columns verify the mechanism.

Writes BENCH_SERVE.json: a list of measurement dicts + environment metadata.
"""

from __future__ import annotations

import json
import threading
import time


def build_engine(spec: bool = False, slots: int = 8, **kw):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine

    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    max_seq = kw.pop("max_seq", 1024 if on_tpu else 128)
    spec_config = kw.pop("spec_config", None)
    if spec and spec_config is None:
        spec_config = {"draft_cfg": cfg, "draft_params": params,
                       "num_spec_tokens": 6}
    engine = DecodeEngine(
        cfg, params, num_slots=slots, max_seq=max_seq, seed=0,
        spec_config=spec_config, **kw,
    )
    return engine, cfg, model_id, on_tpu


def run_requests(engine, vocab: int, n: int, prompt_len: int, max_tokens: int):
    """Submit n concurrent requests; returns (ttft_first_req_s, tokens/s, total)."""
    from ray_tpu.llm._engine import SamplingParams

    import numpy as np

    rng = np.random.default_rng(0)
    done = [threading.Event() for _ in range(n)]
    first_token_t = [None] * n
    counts = [0] * n
    t0 = time.perf_counter()

    def cb_for(i):
        def cb(token, finished):
            if first_token_t[i] is None:
                first_token_t[i] = time.perf_counter() - t0
            counts[i] += 1
            if finished:
                done[i].set()

        return cb

    for i in range(n):
        prompt = rng.integers(0, vocab, prompt_len).tolist()
        engine.submit(prompt, SamplingParams(max_tokens=max_tokens), cb_for(i))
    for ev in done:
        if not ev.wait(timeout=600):
            raise TimeoutError("generation did not finish")
    elapsed = time.perf_counter() - t0
    total = sum(counts)
    return first_token_t[0], total / elapsed, total


def _pctl(values, q):
    xs = sorted(values)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[idx]


def bench_mixed_traffic(token_budget: int, on_tpu: bool):
    """Inject long prefills into live decode streams and measure the damage.

    4 background streams decode steadily; once they are flowing, 4 long
    prompts are submitted together (concurrency 4 prefill + 4 decode).
    Reported: injected-request TTFT p50/p99, and the background streams'
    inter-token gap (TPOT) p99/max during the injection window. With
    token_budget=0 every prefill runs whole-prompt before decode resumes
    (the request-at-a-time cliff); with a budget the scheduler interleaves
    bucketed chunks with decode, bounding the stall (docs/scheduler.md).
    """
    import numpy as np

    from ray_tpu.llm import SamplingParams

    max_seq = 1024 if on_tpu else 512
    long_len = 768 if on_tpu else 384
    engine, cfg, model_id, _ = build_engine(
        slots=8, max_seq=max_seq, token_budget=token_budget,
        prefix_cache=False,
    )
    rng = np.random.default_rng(0)
    try:
        # Warm every program off-clock: the long-prompt chunk/whole buckets
        # and the decode/multi-step programs.
        warm_done = threading.Event()
        engine.submit(
            rng.integers(0, cfg.vocab_size, long_len).tolist(),
            SamplingParams(max_tokens=16),
            lambda t, fin: warm_done.set() if fin else None,
        )
        assert warm_done.wait(600)

        n_streams, n_inject = 4, 4
        stream_times = [[] for _ in range(n_streams)]
        stream_done = [threading.Event() for _ in range(n_streams)]

        def stream_cb(i):
            def cb(tok, fin):
                stream_times[i].append(time.perf_counter())
                if fin:
                    stream_done[i].set()
            return cb

        for i in range(n_streams):
            engine.submit(
                rng.integers(0, cfg.vocab_size, 16).tolist(),
                SamplingParams(max_tokens=160), stream_cb(i),
            )
        while min(len(t) for t in stream_times) < 8:  # streams flowing
            time.sleep(0.001)

        inject_t0 = time.perf_counter()
        ttfts = [None] * n_inject
        inject_done = [threading.Event() for _ in range(n_inject)]

        def inject_cb(i):
            def cb(tok, fin):
                if ttfts[i] is None:
                    ttfts[i] = time.perf_counter() - inject_t0
                if fin:
                    inject_done[i].set()
            return cb

        for i in range(n_inject):
            engine.submit(
                rng.integers(0, cfg.vocab_size, long_len).tolist(),
                SamplingParams(max_tokens=2), inject_cb(i),
            )
        for ev in inject_done:
            assert ev.wait(600)
        window_end = time.perf_counter()
        for ev in stream_done:
            assert ev.wait(600)

        gaps = []
        for times in stream_times:
            in_window = [t for t in times if inject_t0 <= t <= window_end]
            gaps.extend(b - a for a, b in zip(in_window, in_window[1:]))
        stats = engine.scheduler_stats()
        return {
            "metric": "mixed_traffic",
            "token_budget": token_budget,
            "prefill_concurrency": n_inject,
            "decode_concurrency": n_streams,
            "long_prompt_len": long_len,
            "ttft_p50_s": round(_pctl(ttfts, 0.5), 4),
            "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
            "decode_tpot_p99_s": round(_pctl(gaps, 0.99), 4),
            "decode_stall_max_s": round(max(gaps), 4) if gaps else 0.0,
            "prefill_chunks": stats["prefill_chunks"],
            "interleaved_iterations": stats["interleaved_iterations"],
            "model": model_id,
        }
    finally:
        engine.shutdown()


def bench_spec_decode(on_tpu: bool):
    """Speculative decoding on a repeated-traffic workload (concurrency 1).

    The ngram/REST retrieval draft proposes continuations remembered from
    earlier requests; greedy decode is deterministic, so repeats verify at
    high (but NOT all-accept — the first pass misses) acceptance with ZERO
    draft FLOPs, and one batched verify emits up to k+1 tokens per
    dispatch. The plain engine runs the SAME two-pass workload with its
    multi-step decode fully engaged — this is the honest baseline the old
    self-draft bench lost to (speedup 0.85)."""
    import numpy as np

    from ray_tpu.llm import SamplingParams

    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, 256, 32).tolist() for _ in range(4)]
    max_tokens = 64

    def run_pass(engine):
        total, t0 = 0, time.perf_counter()
        for p in prompts:
            done = threading.Event()
            count = [0]

            def cb(tok, fin):
                count[0] += 1
                if fin:
                    done.set()

            engine.submit(p, SamplingParams(max_tokens=max_tokens), cb)
            assert done.wait(600)
            total += count[0]
        return total, time.perf_counter() - t0

    results = {}
    model_id = None
    for mode in ("plain", "spec"):
        kw = {"prefix_cache": False}
        if mode == "spec":
            kw["spec_config"] = {"method": "ngram", "num_spec_tokens": 32}
        engine, _cfg, model_id, _ = build_engine(slots=4, **kw)
        try:
            run_pass(engine)                  # warm + build the draft store
            total, elapsed = run_pass(engine)  # measured: repeated traffic
            results[mode] = total / elapsed
            if mode == "spec":
                spec_stats = engine.scheduler_stats()["spec"]
        finally:
            engine.shutdown()
    return {
        "metric": "decode_tokens_per_s_specdecode",
        "concurrency": 1,
        "value": round(results["spec"], 1),
        "plain_tokens_per_s": round(results["plain"], 1),
        "speedup_vs_plain": round(results["spec"] / results["plain"], 2),
        "acceptance_rate": round(spec_stats["accept_rate"], 3),
        "spec_rounds": spec_stats["rounds"],
        "model": model_id,
        "note": "ngram/REST retrieval draft k=32, repeated-traffic workload "
                "(2 passes x 4 prompts; acceptance includes the cold pass); "
                "plain baseline runs multi-step decode on the same workload",
    }


def bench_prefix_cache(prompt_len: int):
    """Warm vs cold TTFT for a shared-prefix workload (docs/kvcache.md).

    Requests share a 5-block system-prompt prefix and differ in an 8-token
    tail. The first request prefills everything (cold); later ones attach the
    cached prefix and prefill only the tail's bucket (warm). Programs are
    warmed on a DIFFERENT prefix first so both measurements exclude compile
    time; `last_prefill` proves the warm request really prefilled
    suffix-only.
    """
    import time as _time

    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.kvcache import PrefixCacheManager

    engine, cfg, model_id, _on_tpu = build_engine(spec=False, slots=4)
    bs = CONFIG.llm_kv_block_size
    shared_len, tail_len = 5 * bs, 8
    rng = np.random.default_rng(1)

    def request(prefix, seed):
        tail = np.random.default_rng(seed).integers(0, cfg.vocab_size, tail_len)
        prompt = prefix + tail.tolist()
        done = threading.Event()
        ttft = [None]
        t0 = _time.perf_counter()

        def cb(token, finished):
            if ttft[0] is None:
                ttft[0] = _time.perf_counter() - t0
            if finished:
                done.set()

        engine.submit(prompt, SamplingParams(max_tokens=2), cb)
        assert done.wait(timeout=600)
        return ttft[0]

    try:
        # Compile warm-up on a throwaway prefix: first call compiles the cold
        # bucket, second the attach + suffix-bucket programs.
        warm_prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        request(warm_prefix, 100)
        request(warm_prefix, 101)

        prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        ttft_cold = request(prefix, 0)
        cold = dict(engine.last_prefill)
        warm_ttfts = []
        for i in range(1, 4):
            warm_ttfts.append(request(prefix, i))
        warm = dict(engine.last_prefill)
        stats = engine.prefix_cache_stats()
        assert warm["offset"] == shared_len and cold["offset"] == 0, (cold, warm)
        assert warm["bucket"] < cold["bucket"], (cold, warm)
        return [
            {
                "metric": "ttft_prefix_cold_s", "value": round(ttft_cold, 4),
                "prompt_len": shared_len + tail_len,
                "prefill_bucket": cold["bucket"], "model": model_id,
            },
            {
                "metric": "ttft_prefix_warm_s",
                "value": round(min(warm_ttfts), 4),
                "prompt_len": shared_len + tail_len,
                "prefill_bucket": warm["bucket"],
                "prefill_offset": warm["offset"],
                "cache_hit_rate": round(stats["hit_rate"], 3),
                "cache_hit_tokens": stats["hit_tokens"],
                "model": model_id,
                "note": "shared 5-block prefix attached from cache; "
                        "suffix-only prefill",
            },
        ]
    finally:
        engine.shutdown()


def bench_tier_sweep():
    """TTFT by serving tier of the hierarchical KV store (docs/kvcache.md):
    cold (full prefill) vs host-warm (attach from the host pool) vs
    device-warm (attach a device-resident hot-tier prefix, zero H2D) vs
    disk-warm (promote a spilled chain back through the host pool first).
    The engine's `last_attach` proves which tier actually served each row."""
    import tempfile
    import time as _time

    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.kvcache import TieredPrefixCacheManager

    import jax

    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine

    bs = CONFIG.llm_kv_block_size
    shared_len, tail_len = 5 * bs, 8
    on_tpu = jax.default_backend() == "tpu"
    model_id = "gpt2-125m" if on_tpu else "test-tiny"
    cfg, params = load_model(LLMConfig(model_id=model_id))
    block_bytes = (cfg.n_layers * 2 * bs * cfg.n_kv_heads * cfg.head_dim
                   * np.dtype(cfg.dtype).itemsize)
    # Capacity of exactly one 5-block chain: inserting a second chain
    # evicts (spills) the first, which is how we stage the disk-warm case.
    spill_dir = tempfile.mkdtemp(prefix="bench_kv_spill_")
    mgr = TieredPrefixCacheManager(
        bs, 5 * block_bytes, name="bench-tier",
        device_bytes=8 * block_bytes, spill_dir=spill_dir,
    )
    engine = DecodeEngine(cfg, params, num_slots=4,
                          max_seq=1024 if on_tpu else 256, seed=0,
                          prefix_cache=mgr)
    rng = np.random.default_rng(1)

    def request(prefix, seed):
        tail = np.random.default_rng(seed).integers(0, cfg.vocab_size, tail_len)
        prompt = prefix + tail.tolist()
        done = threading.Event()
        ttft = [None]
        t0 = _time.perf_counter()

        def cb(token, finished):
            if ttft[0] is None:
                ttft[0] = _time.perf_counter() - t0
            if finished:
                done.set()

        engine.submit(prompt, SamplingParams(max_tokens=2), cb)
        assert done.wait(timeout=600)
        return ttft[0]

    def wait_spills(n):
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            if mgr.stats()["tiers"]["spills"] >= n:
                return
            _time.sleep(0.05)
        raise TimeoutError("spill worker never drained")

    try:
        warm_prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        request(warm_prefix, 100)  # compile cold bucket
        request(warm_prefix, 101)  # compile attach + suffix bucket
        other = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        request(other, 102)  # evicts warm_prefix; its chain spills

        prefix = rng.integers(0, cfg.vocab_size, shared_len).tolist()
        ttft_cold = request(prefix, 0)
        ttft_host = request(prefix, 1)
        assert engine.last_attach["tier"] == "host", engine.last_attach
        ttft_device = request(prefix, 2)
        assert engine.last_attach["tier"] == "device", engine.last_attach
        request(other, 3)          # evict prefix's chain -> disk
        wait_spills(5)
        ttft_disk = request(prefix, 4)
        assert engine.last_attach["tier"] == "disk", engine.last_attach
        tiers = mgr.stats()["tiers"]
        rows = []
        for tier, value in (("cold", ttft_cold), ("host", ttft_host),
                            ("device", ttft_device), ("disk", ttft_disk)):
            rows.append({
                "metric": f"ttft_tier_{tier}_s", "value": round(value, 4),
                "prompt_len": shared_len + tail_len, "model": model_id,
                "cached_blocks": 0 if tier == "cold" else 5,
            })
        rows[-1]["note"] = (
            f"tiered cache (docs/kvcache.md): device attach is zero-H2D, "
            f"disk promotes through the host pool; "
            f"spills={tiers['spills']} promotions_host="
            f"{tiers['promotions_host']} promotions_device="
            f"{tiers['promotions_device']}"
        )
        return rows
    finally:
        engine.shutdown()


def bench_multicast_fanout():
    """One prefill feeding N decode readers (docs/device_channels.md):
    multicast 1->4 over ONE ring (each payload chunk staged once) vs 4
    point-to-point streams (staged 4x). Reports writer wall time and the
    staged-chunk counters that prove the single D2H pass."""
    import time as _time

    import numpy as np

    from ray_tpu.experimental import tensor_transport as _tt
    from ray_tpu.experimental.device_channel import (
        DeviceChannel, MulticastDeviceChannel,
    )

    payload = np.random.default_rng(0).standard_normal(
        (2, 1 << 20)).astype(np.float32)  # 8 MiB, a PD-prefix-sized tensor
    fanout = 4

    def run_multicast():
        mc = MulticastDeviceChannel.create(fanout, num_slots=8)
        threads = []
        for i in range(fanout):
            def reader(i=i):
                with mc.subscribe(i) as sub:
                    sub.recv(timeout=120)
            threads.append(threading.Thread(target=reader))
            threads[-1].start()
        t0 = _time.perf_counter()
        mc.send(payload, timeout=120)
        mc.drain(timeout=120)
        wall = _time.perf_counter() - t0
        for t in threads:
            t.join(120)
        mc.close()
        mc.destroy()
        return wall

    def run_p2p():
        t_total = 0.0
        for _ in range(fanout):
            ch = DeviceChannel.create(same_node=True, num_slots=8)
            t = threading.Thread(target=lambda: ch.recv(timeout=120))
            t.start()
            t0 = _time.perf_counter()
            ch.send(payload, timeout=120)
            ch.drain(timeout=120)
            t_total += _time.perf_counter() - t0
            t.join(120)
            ch.close()
            ch.destroy()
        return t_total

    before = _tt.transport_stats()["stream_chunks_staged"]
    mc_wall = min(run_multicast() for _ in range(3))
    mc_staged = (_tt.transport_stats()["stream_chunks_staged"] - before) // 3
    before = _tt.transport_stats()["stream_chunks_staged"]
    p2p_wall = min(run_p2p() for _ in range(3))
    p2p_staged = (_tt.transport_stats()["stream_chunks_staged"] - before) // 3
    return {
        "metric": "multicast_fanout_1_to_4",
        "payload_mb": round(payload.nbytes / 2**20, 1),
        "multicast_writer_s": round(mc_wall, 4),
        "p2p_x4_writer_s": round(p2p_wall, 4),
        "multicast_chunks_staged": mc_staged,
        "p2p_chunks_staged": p2p_staged,
        "speedup_vs_p2p": round(p2p_wall / max(mc_wall, 1e-9), 2),
        "note": "one staged (D2H) pass fanned to 4 subscribers over one "
                "ring vs 4 point-to-point streams re-staging the payload",
    }


def bench_remote_fetch_crossover():
    """Cluster prefix plane (docs/kvcache.md): fetching a peer replica's
    cached prefix over the DeviceChannel stream vs recomputing it locally.
    Reports both legs for the standard 5-block prefix; the crossover moves
    toward fetch as model size grows (prefill FLOPs scale with params, the
    fetch only with KV bytes)."""
    import asyncio
    import time as _time

    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import LLMConfig, LLMServer

    bs = CONFIG.llm_kv_block_size
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
    )
    try:
        cfg_obj = LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128)
        s1, s2 = LLMServer(cfg_obj), LLMServer(cfg_obj)
        rng = np.random.default_rng(5)
        toks = list(map(int, rng.integers(0, 64, 5 * bs + 4)))
        warmup = list(map(int, rng.integers(0, 64, 5 * bs + 4)))

        async def run():
            # Warm every compiled program off-clock on BOTH replicas (cold
            # bucket, then attach + suffix bucket via the repeat).
            for srv in (s1, s2):
                await srv.generate(warmup, max_tokens=1)
                await srv.generate(warmup, max_tokens=1)
            await s1.generate(toks, max_tokens=2)   # S1 computes + caches
            # recompute leg: S2 cold TTFT
            r = await s2.generate(list(reversed(toks)), max_tokens=1)
            recompute_s = r["ttft_s"]
            # fetch leg: export S1 -> stream -> import S2 -> warm TTFT
            t0 = _time.perf_counter()
            desc = await s1.export_prefix(toks)
            inserted = await s2.import_prefix(desc, toks)
            fetch_s = _time.perf_counter() - t0
            warm = await s2.generate(toks, max_tokens=1)
            return recompute_s, fetch_s, warm["ttft_s"], inserted

        recompute_s, fetch_s, warm_ttft, inserted = asyncio.run(run())
        out = {
            "metric": "remote_fetch_vs_recompute",
            "prefix_blocks": 5, "blocks_fetched": inserted,
            "recompute_ttft_s": round(recompute_s, 4),
            "fetch_s": round(fetch_s, 4),
            "post_fetch_warm_ttft_s": round(warm_ttft, 4),
            "model": "test-tiny",
            "note": "fetch = export lease + DeviceChannel stream + import; "
                    "crossover favors fetch as prefill FLOPs grow with "
                    "model size while fetch cost scales only with KV bytes",
        }
        asyncio.run(s1.shutdown())
        asyncio.run(s2.shutdown())
        return out
    finally:
        ray_tpu.shutdown()


def bench_adapter_churn(on_tpu: bool):
    """Multi-tenant LoRA paging (docs/multitenancy.md): 32 registered
    adapters served through an 8-slot HBM budget, with a zipf-ish mix (a hot
    working set inside the budget + a cold tail beyond it), vs the
    always-resident upper bound (table holds all 32).

    Reported: cache hit rate, TTFT p50/p99 under churn, TTFT p50 of the
    WARM subset (adapter resident at submit) — the acceptance bar is
    warm-adapter TTFT ~= resident-engine TTFT (paging costs the cold tail
    its page-in, never the warm path)."""
    import numpy as np

    from ray_tpu.llm import SamplingParams

    n_adapters, n_slots = 32, 8
    rng = np.random.default_rng(2)

    def build(paged: bool):
        cfg_extra = {"cache_slots": n_slots} if paged else {}
        engine, cfg, model_id, _ = build_engine(
            slots=4, prefix_cache=False,
            lora_config={"max_loras": n_adapters, "rank": 4, **cfg_extra},
        )
        for i in range(n_adapters):
            r = np.random.default_rng(1000 + i)
            engine.add_lora(f"a{i}", {0: {
                "q_A": r.normal(size=(cfg.hidden, 4)).astype(np.float32),
                "q_B": r.normal(size=(4, cfg.n_heads * cfg.head_dim)).astype(np.float32),
            }}, alpha=8.0)
        return engine, cfg, model_id

    # Traffic: 70% on a hot set of 6 adapters (fits the 8-slot budget),
    # 30% uniform over the cold tail — the shape a real tenant fleet has.
    hot = [f"a{i}" for i in range(6)]
    cold = [f"a{i}" for i in range(6, n_adapters)]
    names = [
        (hot[rng.integers(len(hot))] if rng.random() < 0.7
         else cold[rng.integers(len(cold))])
        for _ in range(120)
    ]

    def run(engine, cfg, classify=None):
        prompt = rng.integers(0, cfg.vocab_size, 12).tolist()
        # warm the compiled programs + the hot set off-clock
        for name in hot:
            done = threading.Event()
            engine.submit(prompt, SamplingParams(max_tokens=2),
                          lambda t, f: done.set() if f else None, lora=name)
            assert done.wait(600)
        ttfts, warm_ttfts = [], []
        for name in names:
            resident = classify(name) if classify else True
            done = threading.Event()
            ttft = [None]
            t0 = time.perf_counter()

            def cb(tok, fin):
                if ttft[0] is None:
                    ttft[0] = time.perf_counter() - t0
                if fin:
                    done.set()

            engine.submit(prompt, SamplingParams(max_tokens=2), cb, lora=name)
            assert done.wait(600)
            ttfts.append(ttft[0])
            if resident:
                warm_ttfts.append(ttft[0])
        return ttfts, warm_ttfts

    resident_engine, cfg, model_id = build(paged=False)
    try:
        res_ttfts, _ = run(resident_engine, cfg)
    finally:
        resident_engine.shutdown()
    paged_engine, cfg, model_id = build(paged=True)
    try:
        adapters = paged_engine._adapters
        ttfts, warm_ttfts = run(
            paged_engine, cfg,
            classify=lambda n: adapters.is_resident(adapters.uid_of(n)),
        )
        stats = paged_engine.adapter_stats()
    finally:
        paged_engine.shutdown()
    return {
        "metric": "adapter_churn_ttft",
        "adapters": n_adapters, "cache_slots": n_slots,
        "requests": len(names),
        "cache_hit_rate": round(stats["hit_rate"], 3),
        "evictions": stats["evictions"],
        "page_ins": stats["page_ins"],
        "ttft_p50_s": round(_pctl(ttfts, 0.5), 4),
        "ttft_p99_s": round(_pctl(ttfts, 0.99), 4),
        "ttft_warm_p50_s": round(_pctl(warm_ttfts, 0.5), 4),
        "ttft_resident_p50_s": round(_pctl(res_ttfts, 0.5), 4),
        "ttft_resident_p99_s": round(_pctl(res_ttfts, 0.99), 4),
        "model": model_id,
        "note": "32 adapters on an 8-slot HBM budget, 70% traffic on a "
                "6-adapter hot set; warm-adapter TTFT vs the always-resident "
                "upper bound is the paging-overhead bar",
    }


def bench_wfq_fairness(on_tpu: bool):
    """Weighted-fair admission under saturation vs the FIFO control
    (docs/multitenancy.md): three tenants (weights 2:1:1) keep the queue
    full; the light tenant's flood arrives LAST, so FIFO serves it nothing
    inside the measurement window while WFQ holds every tenant's
    decode-token share within 10% of its weight."""
    import numpy as np

    from ray_tpu.llm import SamplingParams

    weights = {"gold": 2.0, "silver": 1.0, "bronze": 1.0}
    target = {"gold": 0.5, "silver": 0.25, "bronze": 0.25}

    def run(wfq: bool):
        engine, cfg, model_id, _ = build_engine(
            slots=2, prefix_cache=False, wfq=wfq,
            tenant_weights=weights if wfq else None, tenant_quota=0,
        )
        rng = np.random.default_rng(3)
        counts = {t: 0 for t in weights}
        finished = []
        lock = threading.Lock()
        try:
            # warm off-clock
            done = threading.Event()
            engine.submit([1, 2, 3], SamplingParams(max_tokens=2),
                          lambda t, f: done.set() if f else None)
            assert done.wait(600)
            # gold+silver flood first; bronze arrives behind them (the FIFO
            # killer ordering)
            for tenant in ("gold", "silver", "bronze"):
                for _ in range(25):
                    def cb(tok, fin, _t=tenant):
                        with lock:
                            counts[_t] += 1
                        if fin:
                            finished.append(_t)

                    engine.submit(
                        rng.integers(0, cfg.vocab_size, 8).tolist(),
                        SamplingParams(max_tokens=4), cb, tenant=tenant,
                    )
            deadline = time.perf_counter() + 600
            while len(finished) < 40 and time.perf_counter() < deadline:
                time.sleep(0.01)
            with lock:
                total = sum(counts.values()) or 1
                shares = {t: round(c / total, 3) for t, c in counts.items()}
            return shares, model_id
        finally:
            engine.shutdown()

    wfq_shares, model_id = run(True)
    fifo_shares, _ = run(False)
    return {
        "metric": "wfq_fairness",
        "weights": {t: w for t, w in weights.items()},
        "target_share": target,
        "wfq_share": wfq_shares,
        "fifo_share": fifo_shares,
        "max_weight_error_wfq": round(
            max(abs(wfq_shares[t] - target[t]) for t in weights), 3),
        "light_tenant_share_fifo": fifo_shares["bronze"],
        "model": model_id,
        "note": "3 saturated tenants, 2 slots; shares measured over the "
                "first ~40 completions (queues still full). FIFO serves "
                "arrival order, starving the late light tenant; WFQ tracks "
                "the configured weights",
    }


def bench_tp_sweep(on_tpu: bool):
    """Tensor-parallel decode sweep (docs/serving_tp.md): decode tokens/s and
    per-chip HBM high-water vs TP degree on the forced multi-device mesh,
    plus a model-larger-than-one-chip configuration — a parameter+KV
    footprint exceeding a single device's budget that only the sharded
    plane can serve, with throughput scaling reported vs TP=1."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.llm._engine import DecodeEngine
    from ray_tpu.llm.tp import per_device_bytes
    from ray_tpu.models.transformer import Transformer, get_config

    if on_tpu:
        cfg = get_config("gpt2-125m", scan_layers=False, remat=False)
        max_seq, prompt_len, max_tokens = 1024, 128, 64
    else:
        # kv_heads=4 so every sweep degree divides the KV axis; a deeper KV
        # budget (max_seq) makes the pool a real fraction of the footprint.
        cfg = get_config("test-tiny", scan_layers=False, remat=False,
                         n_kv_heads=4)
        max_seq, prompt_len, max_tokens = 512, 16, 16
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    degrees = [d for d in (1, 2, 4) if d <= len(jax.devices())]
    rows = []
    per_chip = {}
    tps_by_degree = {}
    for tp in degrees:
        engine = DecodeEngine(cfg, params, num_slots=8, max_seq=max_seq,
                              seed=0, tp=tp)
        try:
            run_requests(engine, cfg.vocab_size, 4, prompt_len, max_tokens)  # warm
            _, tps, total = run_requests(
                engine, cfg.vocab_size, 4, prompt_len, max_tokens
            )
            chip = per_device_bytes(engine.params) + per_device_bytes(
                engine._caches
            )
        finally:
            engine.shutdown()
        per_chip[tp] = chip
        tps_by_degree[tp] = tps
        row = {
            "metric": "tp_decode_sweep", "tp": tp,
            "decode_tokens_per_s": round(tps, 1), "tokens": total,
            "per_chip_bytes": int(chip),
            "speedup_vs_tp1": round(tps / tps_by_degree[degrees[0]], 2),
            "model": "gpt2-125m" if on_tpu else "test-tiny-kv4",
            "max_seq": max_seq,
        }
        if not on_tpu and tp > 1:
            row["note"] = (
                "CPU artifact: the 'mesh' is 8 virtual host devices on one "
                "CPU, so GSPMD collectives cost wall-clock they repay only "
                "on real ICI; the load-bearing columns here are per_chip_"
                "bytes (the 1/tp footprint) and token-identity (tests)"
            )
        rows.append(row)
    # Model-larger-than-one-chip: a synthetic per-chip budget strictly
    # between the TP=max per-chip footprint and the TP=1 footprint — the
    # unsharded engine cannot exist under it, the sharded one serves.
    tp_hi = degrees[-1]
    budget = int((per_chip[1] + per_chip[tp_hi]) // 2)
    rows.append({
        "metric": "tp_model_exceeds_one_chip",
        "chip_budget_bytes": budget,
        "per_chip_bytes_tp1": int(per_chip[1]),
        f"per_chip_bytes_tp{tp_hi}": int(per_chip[tp_hi]),
        "fits_one_chip": per_chip[1] <= budget,
        f"fits_tp{tp_hi}": per_chip[tp_hi] <= budget,
        f"decode_tokens_per_s_tp{tp_hi}": round(tps_by_degree[tp_hi], 1),
        "throughput_vs_tp1": round(
            tps_by_degree[tp_hi] / tps_by_degree[degrees[0]], 2
        ),
        "note": "footprint = params + per-slot KV pool per device; the "
                "budget sits between the sharded and unsharded footprints, "
                "so only the TP mesh serves this configuration",
    })
    return rows


def bench_pd_ttft():
    """PD-disaggregated TTFT through the real serve app: prefill replica ->
    KV handoff (descriptor + pull over the round-11 device-channel plane,
    docs/device_channels.md) -> decode replica's first token. max_tokens=1,
    so latency_s IS the disaggregated TTFT."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.pd_disagg import build_pd_openai_app

    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
    )
    try:
        app = build_pd_openai_app(
            LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128),
            num_prefill=1, num_decode=1,
        )
        handle = serve.run(app, name="bench_pd_app", route_prefix=None)
        handle.generate.remote("warm up the compiled buckets",
                               max_tokens=2).result(timeout_s=600)
        ttfts, prefills = [], []
        for _ in range(5):
            r = handle.generate.remote(
                "hello world benchmark prompt", max_tokens=1
            ).result(timeout_s=600)
            ttfts.append(r["latency_s"])
            prefills.append(r["prefill_s"])
        serve.delete("bench_pd_app")
        return {
            "metric": "pd_ttft_s", "value": round(min(ttfts), 4),
            "prefill_s": round(min(prefills), 4), "max_tokens": 1,
            "model": "test-tiny",
            "note": "prefill replica -> KV descriptor + pull "
                    "(blob/stream gated by devobj_stream_min_bytes) -> "
                    "decode first token, across real replica actors",
        }
    finally:
        ray_tpu.shutdown()


def bench_stream_ttft_vs_blocking(on_tpu: bool):
    """Round 22 (docs/generation.md): the TokenStream subscription vs the
    raw-callback blocking path on the SAME engine and prompt — streaming is
    a host-side relay, so its TTFT must sit on top of blocking TTFT."""
    import numpy as np

    from ray_tpu.llm._engine import SamplingParams

    engine, cfg, model_id, _ = build_engine(spec=False, slots=4)
    prompt_len, max_tokens = (128, 32) if on_tpu else (16, 16)
    rng = np.random.default_rng(7)
    try:
        run_requests(engine, cfg.vocab_size, 2, prompt_len, 4)  # warm
        blocking, streaming = [], []
        for _ in range(5):
            prompt = rng.integers(0, cfg.vocab_size, prompt_len).tolist()
            first = [None]
            done = threading.Event()
            t0 = time.perf_counter()

            def cb(tok, fin, first=first, done=done, t0=t0):
                if first[0] is None:
                    first[0] = time.perf_counter() - t0
                if fin:
                    done.set()

            engine.submit(prompt, SamplingParams(max_tokens=max_tokens), cb)
            done.wait(600)
            blocking.append(first[0])

            t0 = time.perf_counter()
            stream = engine.open_stream(
                prompt, SamplingParams(max_tokens=max_tokens))
            ttft = None
            for _tok in stream:
                if ttft is None:
                    ttft = time.perf_counter() - t0
            streaming.append(ttft)
        return {
            "metric": "stream_ttft_vs_blocking",
            "value": round(min(streaming), 4),
            "blocking_ttft_s": round(min(blocking), 4),
            "stream_over_blocking": round(min(streaming) / max(min(blocking), 1e-9), 3),
            "model": model_id,
        }
    finally:
        engine.shutdown()


def bench_guided_decode_overhead(on_tpu: bool):
    """Round 22 (docs/generation.md): decode throughput with an
    allow-everything constraint vs unconstrained — isolates the per-step
    host cost of the mask add + DFA advance (the mask changes no tokens)."""
    import numpy as np

    from ray_tpu.llm import ByteTokenizer
    from ray_tpu.llm._engine import SamplingParams
    from ray_tpu.llm.generate import compile_constraint

    engine, cfg, model_id, _ = build_engine(spec=False, slots=4)
    prompt_len, max_tokens = (128, 64) if on_tpu else (16, 32)
    n = 4
    rng = np.random.default_rng(11)
    constraint = compile_constraint("(.|\n)*", ByteTokenizer(), cfg.vocab_size)
    try:
        run_requests(engine, cfg.vocab_size, 2, prompt_len, max_tokens)  # warm
        results = {}
        for mode in ("plain", "guided"):
            done = [threading.Event() for _ in range(n)]
            counts = [0] * n
            t0 = time.perf_counter()

            def cb_for(i):
                def cb(token, finished):
                    counts[i] += 1
                    if finished:
                        done[i].set()

                return cb

            for i in range(n):
                prompt = rng.integers(0, 256, prompt_len).tolist()
                engine.submit(
                    prompt, SamplingParams(max_tokens=max_tokens), cb_for(i),
                    constraint=constraint if mode == "guided" else None,
                )
            for ev in done:
                ev.wait(600)
            results[mode] = sum(counts) / (time.perf_counter() - t0)
        return {
            "metric": "guided_decode_overhead",
            "value": round(results["guided"], 1),
            "plain_tokens_per_s": round(results["plain"], 1),
            "guided_over_plain": round(results["guided"] / results["plain"], 3),
            "model": model_id,
        }
    finally:
        engine.shutdown()


def bench_batch_coexistence(on_tpu: bool):
    """Round 22 (docs/generation.md): online TTFT p50/p99 with a deep
    floor-weight batch-tenant backlog queued vs a no-batch baseline — the
    number the batch-admission policy exists to protect."""
    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm._engine import SamplingParams

    engine, cfg, model_id, _ = build_engine(spec=False, slots=4)
    prompt_len = 128 if on_tpu else 16
    rng = np.random.default_rng(13)

    def timed_online(n):
        ttfts, dones = [], []
        for _ in range(n):
            first = [None]
            done = threading.Event()
            t0 = time.perf_counter()

            def cb(tok, fin, first=first, done=done, t0=t0):
                if first[0] is None and tok >= 0:
                    first[0] = time.perf_counter() - t0
                if fin:
                    done.set()

            engine.submit(
                rng.integers(0, cfg.vocab_size, prompt_len).tolist(),
                SamplingParams(max_tokens=8), cb, tenant="online")
            dones.append((done, first))
            time.sleep(0.02)
        for done, first in dones:
            done.wait(600)
            ttfts.append(first[0])
        return ttfts

    try:
        run_requests(engine, cfg.vocab_size, 2, prompt_len, 8)  # warm
        base = timed_online(8)
        batch_done = [threading.Event() for _ in range(16)]
        for i in range(16):
            engine.submit(
                rng.integers(0, cfg.vocab_size, prompt_len).tolist(),
                SamplingParams(max_tokens=24),
                lambda t, f, ev=batch_done[i]: ev.set() if f else None,
                tenant=CONFIG.llm_batch_tenant)
        loaded = timed_online(8)
        for ev in batch_done:
            ev.wait(600)
        return {
            "metric": "batch_coexistence",
            "value": round(_pctl(loaded, 0.99), 4),
            "online_ttft_p50_s": round(_pctl(loaded, 0.5), 4),
            "baseline_ttft_p99_s": round(_pctl(base, 0.99), 4),
            "loaded_over_baseline_p99": round(
                _pctl(loaded, 0.99) / max(_pctl(base, 0.99), 1e-9), 2),
            "batch_backlog_rows": 16,
            "model": model_id,
        }
    finally:
        engine.shutdown()


def main():
    import jax

    results = []
    engine, cfg, model_id, on_tpu = build_engine(spec=False, slots=8)
    prompt_len, max_tokens = (128, 64) if on_tpu else (16, 16)

    # Warm every compiled program off-clock: prefill bucket, batched decode,
    # and every multi-step chunk bucket the measured budget will use
    # (8/4/2/1 for max_tokens=64).
    run_requests(engine, cfg.vocab_size, 2, prompt_len, max_tokens)

    # TTFT: warm single request into an empty engine.
    ttfts = []
    for _ in range(3):
        ttft, _, _ = run_requests(engine, cfg.vocab_size, 1, prompt_len, 2)
        ttfts.append(ttft)
    results.append({
        "metric": "ttft_warm_s", "value": round(min(ttfts), 4),
        "prompt_len": prompt_len, "model": model_id,
    })

    # Decode throughput vs concurrency (continuous batching).
    for conc in (1, 2, 4, 8):
        _, tps, total = run_requests(
            engine, cfg.vocab_size, conc, prompt_len, max_tokens
        )
        results.append({
            "metric": "decode_tokens_per_s", "concurrency": conc,
            "value": round(tps, 1), "tokens": total, "model": model_id,
        })
    engine.shutdown()

    # Mixed traffic: chunked prefill (scheduler token budget) vs legacy
    # whole-prompt admission — the TTFT/TPOT interference A/B.
    from ray_tpu._private.config import CONFIG

    results.append(bench_mixed_traffic(0, on_tpu))
    results.append(bench_mixed_traffic(CONFIG.llm_sched_token_budget, on_tpu))

    # Speculative decoding on repeated traffic (ngram/REST draft).
    results.append(bench_spec_decode(on_tpu))

    results.extend(bench_prefix_cache(prompt_len))

    # Hierarchical KV store (round 17, docs/kvcache.md): per-tier TTFT,
    # multicast fanout vs point-to-point, and the cross-replica
    # fetch-vs-recompute crossover.
    results.extend(bench_tier_sweep())
    results.append(bench_multicast_fanout())

    # Multi-tenant serving plane (round 13, docs/multitenancy.md):
    # adapter-churn paging overhead + WFQ-vs-FIFO fairness under saturation.
    results.append(bench_adapter_churn(on_tpu))
    results.append(bench_wfq_fairness(on_tpu))

    # Tensor-parallel decode sweep + model-larger-than-one-chip (round 15,
    # docs/serving_tp.md).
    results.extend(bench_tp_sweep(on_tpu))

    # Generation modes (round 22, docs/generation.md): streaming TTFT tax,
    # guided-mask host overhead, and online TTFT under a batch backlog.
    results.append(bench_stream_ttft_vs_blocking(on_tpu))
    results.append(bench_guided_decode_overhead(on_tpu))
    results.append(bench_batch_coexistence(on_tpu))

    # PD disaggregation TTFT across real replica actors (round 11).
    results.append(bench_pd_ttft())

    # Cluster prefix plane: fetch a peer's cached prefix vs recompute
    # (round 17; needs its own cluster, so it runs after bench_pd_ttft's).
    results.append(bench_remote_fetch_crossover())

    out = {
        "bench": "serve_engine",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "results": results,
    }
    with open("BENCH_SERVE.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
