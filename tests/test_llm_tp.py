"""Tensor-parallel sharded serving (docs/serving_tp.md): mesh-sharded decode
plane over the forced multi-device CPU harness.

The contract under test: greedy output is TOKEN-IDENTICAL across TP=1/2/4
mesh shapes (same prompts, same seeds) with zero mid-serve recompiles —
including speculative-verify, adapter-paging churn, and a PD-disaggregated
handoff between a TP prefill replica and a TP decode replica — and a
retired TP replica provably frees every mesh-resident shard (leaksan).
The token-identity sweep runs through the subprocess-spawned multi-device
group (conftest.run_multi_device_subprocess), so it holds even when the
parent interpreter's jax initialized under different XLA flags.
"""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

import jax

NUM_DEVICES = len(jax.devices())
needs_mesh = pytest.mark.skipif(
    NUM_DEVICES < 4,
    reason="TP tests need the 8-virtual-device CPU harness "
           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
)

_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


def _model(n_kv_heads=None, seed=0):
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    kw = {"scan_layers": False, "remat": False}
    if n_kv_heads is not None:
        kw["n_kv_heads"] = n_kv_heads
    cfg = get_config("test-tiny", **kw)
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _generate(engine, prompt, n=10, lora=""):
    from ray_tpu.llm import SamplingParams

    acc, done = [], threading.Event()

    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(max_tokens=n), cb, lora=lora)
    assert done.wait(240), acc
    return acc


# -- token identity across mesh shapes (subprocess-spawned group) -------------

_SWEEP_SNIPPET = r"""
import json, threading
import numpy as np
import jax, jax.numpy as jnp
from ray_tpu.models.transformer import Transformer, get_config
from ray_tpu.llm._engine import DecodeEngine, SamplingParams

cfg = get_config("test-tiny", scan_layers=False, remat=False, n_kv_heads=4)
model = Transformer(cfg)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
prompts = [[5, 9, 17, 3], [8, 2, 44, 7, 19, 21, 6], [5, 9, 17, 3]]

def generate(engine, prompt, n=12):
    acc, done = [], threading.Event()
    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()
    engine.submit(prompt, SamplingParams(max_tokens=n), cb)
    assert done.wait(240)
    return acc

def program_count(e):
    n = len(e._jit_prefill) + len(e._jit_spec_verify)
    for prog in (e._jit_decode, e._jit_decode_multi):
        try:
            n += prog._cache_size()
        except Exception:
            pass
    return n

out = {"devices": len(jax.devices()), "tokens": {}, "programs_flat": {}}
for tp in (1, 2, 4):
    eng = DecodeEngine(cfg, params, num_slots=2, max_seq=64, tp=tp,
                       spec_config={"method": "ngram", "num_spec_tokens": 4})
    warm = [generate(eng, p) for p in prompts]   # warmup compiles everything
    n0 = program_count(eng)
    again = [generate(eng, p) for p in prompts]  # steady state: zero compiles
    n1 = program_count(eng)
    assert warm == again, (tp, warm, again)
    out["tokens"][str(tp)] = warm
    out["programs_flat"][str(tp)] = (n0 == n1, n0, n1)
    spec = eng.scheduler_stats().get("spec", {})
    out.setdefault("spec_rounds", {})[str(tp)] = spec.get("rounds", 0)
    eng.shutdown()
print("RESULT " + json.dumps(out))
"""


def test_greedy_token_identity_across_tp_meshes(multi_device_run):
    """TP=1/2/4 greedy output bitwise token-identical, spec-verify included,
    program caches flat after warmup (zero mid-serve recompiles) — on the
    subprocess-spawned 8-device CPU group, i.e. CI without TPUs."""
    out = multi_device_run(_SWEEP_SNIPPET, timeout=900)
    assert out["devices"] >= 8, out["devices"]
    assert out["tokens"]["1"] == out["tokens"]["2"] == out["tokens"]["4"], out
    for tp, (flat, n0, n1) in out["programs_flat"].items():
        assert flat, f"tp={tp}: program cache grew {n0} -> {n1} after warmup"
    # The spec phase really ran (the identity claim covers the verify path).
    assert all(r > 0 for r in out["spec_rounds"].values()), out["spec_rounds"]


# -- sharding plan ------------------------------------------------------------

@needs_mesh
def test_decode_plane_is_mesh_sharded():
    """Params, per-slot KV pool, and program-cache keys all carry the mesh:
    the q/gate projections shard their output dims, o/down their input dims,
    the KV pool its kv-head axis — per-device bytes drop accordingly."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.llm._engine import DecodeEngine
    from ray_tpu.llm.tp import per_device_bytes

    cfg, params = _model(n_kv_heads=4)
    eng = DecodeEngine(cfg, params, num_slots=2, max_seq=64, tp=4)
    try:
        p = eng.params
        assert p["layer_0"]["attn"]["q"]["kernel"].sharding.spec == P(None, "tp", None)
        assert p["layer_0"]["attn"]["o"]["kernel"].sharding.spec == P("tp", None, None)
        assert p["layer_0"]["mlp"]["gate"]["kernel"].sharding.spec == P(None, "tp")
        assert p["layer_0"]["mlp"]["down"]["kernel"].sharding.spec == P("tp", None)
        assert p["embedding"].sharding.spec == P("tp", None)
        # norms replicate
        assert p["final_norm"]["scale"].sharding.spec == P()
        ck, _cv = eng._caches[0]
        assert ck.sharding.spec == P(None, None, "tp", None)
        # HBM accounting: the sharded plane puts ~1/tp of params+KV per chip.
        total = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(eng.params)
        ) + sum(ck.nbytes + cv.nbytes for ck, cv in eng._caches)
        per_dev = per_device_bytes(eng.params) + per_device_bytes(eng._caches)
        assert per_dev < total / 2, (per_dev, total)
        # Program-cache keys carry the mesh signature: a different sharding
        # regime can never silently alias an existing program.
        _generate(eng, [5, 9, 17], n=2)
        assert all(
            isinstance(k, tuple) and k[0][0] == "mesh"
            for k in eng._jit_prefill
        ), list(eng._jit_prefill)
    finally:
        eng.shutdown()


# -- adapter paging churn under TP -------------------------------------------

@needs_mesh
def test_adapter_paging_churn_token_identical_across_tp():
    """LoRA adapter tables shard with the model and the AdapterCache paging
    path stays token-identical: 6 adapters churning through 2 device slots
    on a TP=2 engine emit exactly what the TP=1 engine emits."""
    from ray_tpu.llm._engine import DecodeEngine

    cfg, params = _model(n_kv_heads=4)
    rng = np.random.default_rng(7)
    r = 4

    def adapter(scale):
        return {0: {
            "q_A": rng.normal(size=(cfg.hidden, r)).astype(np.float32) * scale,
            "q_B": rng.normal(size=(r, cfg.n_heads * cfg.head_dim)).astype(np.float32),
            "v_A": rng.normal(size=(cfg.hidden, r)).astype(np.float32) * scale,
            "v_B": rng.normal(size=(r, cfg.n_kv_heads * cfg.head_dim)).astype(np.float32),
        }}

    weights = {f"a{i}": adapter(1.0 + i) for i in range(6)}
    prompt = [7, 21, 3, 9]
    outs = {}
    stats = {}
    for tp in (1, 2):
        eng = DecodeEngine(
            cfg, params, num_slots=2, max_seq=64, tp=tp,
            lora_config={"max_loras": 8, "rank": r, "cache_slots": 2},
        )
        try:
            for name, w in weights.items():
                eng.add_lora(name, w, alpha=4.0)
            # Two churn passes: every adapter pages in, out, and back in.
            outs[tp] = [
                _generate(eng, prompt, n=6, lora=name)
                for _ in range(2) for name in weights
            ]
            stats[tp] = eng.adapter_stats()
        finally:
            eng.shutdown()
    assert outs[1] == outs[2], (outs[1][:2], outs[2][:2])
    # Distinct adapters really produce distinct generations (not a no-op).
    assert len({tuple(o) for o in outs[2][:6]}) > 1
    # The churn actually paged: evictions happened on both engines alike.
    assert stats[2]["evictions"] > 0 and stats[2]["install_programs"] in (1, None)


# -- PD disaggregation: TP prefill replica -> TP decode replica ---------------

@needs_mesh
def test_pd_handoff_tp_prefill_to_tp_decode_engine_level():
    """prefill_detached on a TP mesh keeps the KV prefix mesh-resident
    (sharded jax Array — no host gather), and a TP decode engine continues
    it to exactly the monolithic TP=1 output."""
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm._engine import DecodeEngine

    cfg, params = _model(n_kv_heads=4)
    prompt = [8, 2, 44, 7, 19, 21, 6]
    mono = DecodeEngine(cfg, params, num_slots=1, max_seq=64)
    pre = DecodeEngine(cfg, params, num_slots=1, max_seq=64, tp=2,
                       decode_loop=False)
    dec = DecodeEngine(cfg, params, num_slots=2, max_seq=64, tp=2)
    try:
        expect = _generate(mono, prompt, n=8)
        first_logits, kv, plen = pre.prefill_detached(prompt)
        assert isinstance(kv, jax.Array), type(kv)  # stayed device-resident
        assert len(kv.sharding.device_set) == 2, kv.sharding
        acc, done = [], threading.Event()
        dec.submit_prefilled(
            kv, plen, first_logits, SamplingParams(max_tokens=8),
            lambda t, f: (acc.append(t), done.set() if f else None),
            token_ids=prompt,
        )
        assert done.wait(240)
        assert acc == expect, (acc, expect)
    finally:
        mono.shutdown()
        pre.shutdown()
        dec.shutdown()


@needs_mesh
def test_sharded_kv_streams_per_shard_over_device_channel():
    """The PD transport half: a mesh-sharded array streams as per-shard
    frames (each shard's bytes leave its own device — the plan has one entry
    per shard, no global gather) and the consumer can reassemble either
    host-side or straight onto ITS mesh layout per-shard."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.experimental.device_channel import DeviceChannel, _shard_plan
    from ray_tpu.llm.tp import build_tp_mesh

    mesh = build_tp_mesh(4)
    ns = NamedSharding(mesh, P(None, None, None, "tp", None))
    x = np.arange(2 * 2 * 6 * 4 * 3, dtype=np.float32).reshape(2, 2, 6, 4, 3)
    xs = jax.device_put(x, ns)
    plan = _shard_plan(xs)
    assert plan is not None and len(plan) == 4  # one frame group per shard

    ch = DeviceChannel.create(same_node=True, chunk_bytes=96)
    try:
        t = threading.Thread(target=lambda: ch.send(xs, timeout=60))
        t.start()
        got = ch.recv(timeout=60)
        t.join(timeout=60)
        np.testing.assert_array_equal(got, x)
    finally:
        ch.destroy()

    # Matching target layout: per-shard device staging, no host assembly of
    # the whole array, sharding preserved end to end.
    ch2 = DeviceChannel.create(same_node=True, chunk_bytes=96)
    try:
        t = threading.Thread(target=lambda: ch2.send(xs, timeout=60))
        t.start()
        got_dev = ch2.recv_device(timeout=60, sharding=ns)
        t.join(timeout=60)
        assert got_dev.sharding == ns
        np.testing.assert_array_equal(np.asarray(got_dev), x)
    finally:
        ch2.destroy()

    # Mismatched layout (a TP=2 consumer of a TP=4 producer) still lands
    # correctly — one explicit resharding copy, never corruption.
    ns2 = NamedSharding(build_tp_mesh(2), P(None, None, None, "tp", None))
    ch3 = DeviceChannel.create(same_node=True, chunk_bytes=96)
    try:
        t = threading.Thread(target=lambda: ch3.send(xs, timeout=60))
        t.start()
        got2 = ch3.recv_device(timeout=60, sharding=ns2)
        t.join(timeout=60)
        assert got2.sharding == ns2
        np.testing.assert_array_equal(np.asarray(got2), x)
    finally:
        ch3.destroy()


# -- checkpoint restore straight to mesh layout -------------------------------

@needs_mesh
def test_from_sharded_checkpoint_restores_to_mesh_layout(tmp_path):
    """from_sharded_checkpoint hands LAYOUTS to the resharding restore: TP
    leaves arrive already mesh-sharded, TP=1 leaves arrive device-resident
    (no intermediate host pytree), and generation matches the host-loaded
    engine token for token."""
    from jax.sharding import PartitionSpec as P, SingleDeviceSharding

    from ray_tpu import checkpoint as ckpt
    from ray_tpu.llm._engine import DecodeEngine

    cfg, params = _model(n_kv_heads=4)
    path = str(tmp_path / "w")
    ckpt.save(path, {"params": params})

    ref = DecodeEngine(cfg, params, num_slots=2, max_seq=64)
    eng4 = DecodeEngine.from_sharded_checkpoint(
        cfg, path, tp=4, num_slots=2, max_seq=64)
    eng1 = DecodeEngine.from_sharded_checkpoint(
        cfg, path, num_slots=2, max_seq=64)
    try:
        q4 = eng4.params["layer_0"]["attn"]["q"]["kernel"]
        assert q4.sharding.spec == P(None, "tp", None), q4.sharding
        q1 = eng1.params["layer_0"]["attn"]["q"]["kernel"]
        assert isinstance(q1, jax.Array)
        assert isinstance(q1.sharding, SingleDeviceSharding), q1.sharding
        prompt = [5, 9, 17, 3]
        expect = _generate(ref, prompt, n=8)
        assert _generate(eng4, prompt, n=8) == expect
        assert _generate(eng1, prompt, n=8) == expect
    finally:
        ref.shutdown()
        eng4.shutdown()
        eng1.shutdown()


# -- device-memory ledger: per-shard attribution ------------------------------

_LEDGER_SNIPPET = r"""
import json
import jax, jax.numpy as jnp
from ray_tpu.models.transformer import Transformer, get_config
from ray_tpu.llm._engine import DecodeEngine
from ray_tpu.util import xprof

cfg = get_config("test-tiny", scan_layers=False, remat=False, n_kv_heads=4)
model = Transformer(cfg)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
eng = DecodeEngine(cfg, params, num_slots=2, max_seq=64, tp=2)
rep = xprof.device_memory_report()
row = rep["owners"][eng._xprof_owner]
out = {
    "pool_bytes": eng._kv_pool.total_bytes,
    "kv_slots": row["components"]["kv_slots"],
    "per_device": row.get("per_device", {}),
    "tracked_total": rep["tracked_bytes_total"],
}
eng.shutdown()
out["owners_after"] = [o for o in xprof.device_memory_report()["owners"]
                       if o.startswith("engine-")]
print("RESULT " + json.dumps(out))
"""


def test_device_memory_report_attributes_tp2_shards(multi_device_run):
    """The ledger's TP contract: on a TP=2 mesh, device_memory_report()
    attributes the engine's KV bytes per DEVICE (shard shape metadata only —
    per_device_byte_map never pulls), the per-device rows sum exactly to the
    pool's tracked total, split evenly across the mesh, and the owner row
    vanishes on shutdown."""
    out = multi_device_run(_LEDGER_SNIPPET, timeout=600)
    assert out["pool_bytes"] > 0
    assert out["kv_slots"] == out["pool_bytes"]
    assert out["tracked_total"] >= out["pool_bytes"]
    per_device = {k: int(v) for k, v in out["per_device"].items()}
    assert len(per_device) == 2, per_device      # exactly the TP=2 mesh
    assert sum(per_device.values()) == out["pool_bytes"], per_device
    lo, hi = sorted(per_device.values())
    assert lo == hi, per_device                  # heads shard evenly
    assert out["owners_after"] == []             # shutdown unregisters


# -- drain-and-retire frees every shard ---------------------------------------

@needs_mesh
def test_tp_shutdown_frees_every_shard():
    """leaksan: a TP engine registers its mesh-resident allocations
    (kv_shard_pool + tp_param_shards) and shutdown — the PR 9
    prepare_shutdown path every serve replica funnels through — balances the
    books exactly. The suite-wide leaksan_guard enforces the same invariant
    on every other test here."""
    from ray_tpu.devtools import leaksan
    from ray_tpu.llm._engine import DecodeEngine

    leaksan.enable()
    cfg, params = _model(n_kv_heads=4)
    before = leaksan.live_counts()
    eng = DecodeEngine(cfg, params, num_slots=2, max_seq=64, tp=2)
    during = leaksan.live_counts()
    assert during.get("kv_shard_pool", 0) == before.get("kv_shard_pool", 0) + 1
    assert during.get("tp_param_shards", 0) == before.get("tp_param_shards", 0) + 1
    eng.shutdown()
    eng.shutdown()  # idempotent: the second release must not go negative
    after = leaksan.live_counts()
    assert after.get("kv_shard_pool", 0) == before.get("kv_shard_pool", 0)
    assert after.get("tp_param_shards", 0) == before.get("tp_param_shards", 0)


# -- DP x TP serve composition ------------------------------------------------

@pytest.fixture(scope="module")
def tpu_cluster():
    """Single node advertising TPU:4 — room for a dp=2 x tp=2 fleet."""
    ray_tpu.init(num_cpus=4, num_tpus=4, worker_env=_WORKER_ENV)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps(request):
    yield
    if "tpu_cluster" in request.fixturenames:
        for app in list(serve.status()):
            serve.delete(app)


@needs_mesh
def test_dp_tp_replicas_compose(tpu_cluster):
    """DP x TP: dp_size=2 replicas, each a TP=2 mesh engine whose device
    gang is reserved atomically ({"TPU": 2} per replica). Both ranks serve,
    greedy output is identical across ranks, and the fleet consumes exactly
    the cluster's 4 chips."""
    from ray_tpu.llm import LLMConfig, replica_resources
    from ray_tpu.llm.dp_serve import build_dp_openai_app

    config = LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128, tp=2,
                       accelerator_resources={"TPU": 1})
    assert replica_resources(config) == {"TPU": 2.0}
    app = build_dp_openai_app(config, dp_size=2)
    handle = serve.run(app, name="dp-tp-llm", route_prefix=None, _timeout_s=300)

    ranks = handle.ranks.remote().result(timeout_s=120)
    assert sorted(ranks.values()) == [0, 1], ranks
    rs = [handle.generate.remote(f"req {i}", max_tokens=4) for i in range(10)]
    outs = [r.result(timeout_s=300) for r in rs]
    assert {o["dp_rank"] for o in outs} == {0, 1}
    a = handle.generate.remote("same prompt", max_tokens=6).result(timeout_s=120)
    b = handle.generate.remote("same prompt", max_tokens=6).result(timeout_s=120)
    assert a["token_ids"] == b["token_ids"]
    serve.delete("dp-tp-llm")


@needs_mesh
def test_pd_disagg_app_tp_replicas(tpu_cluster):
    """PD disaggregation with TP on both sides: a TP=2 prefill replica hands
    its mesh-sharded KV to a TP=2 decode replica and the end-to-end output
    matches a plain single-device LLM server's greedy output."""
    from ray_tpu.llm import LLMConfig, build_llm_deployment
    from ray_tpu.llm.pd_disagg import build_pd_openai_app

    config = LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128, tp=2)
    app = build_pd_openai_app(config, num_prefill=1, num_decode=1)
    handle = serve.run(app, name="pd-tp", route_prefix=None, _timeout_s=300)
    resp = handle.generate.remote("hello world", max_tokens=8).result(
        timeout_s=300)
    assert len(resp["token_ids"]) == 8

    ref_app = serve.run(
        build_llm_deployment(
            LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128)),
        name="pd-tp-ref", route_prefix=None, _timeout_s=300)
    ref = ref_app.generate.remote("hello world", max_tokens=8).result(
        timeout_s=300)
    assert resp["token_ids"] == ref["token_ids"], (resp, ref)
    serve.delete("pd-tp")
    serve.delete("pd-tp-ref")


@needs_mesh
def test_reserve_tp_slice_placement_group(tpu_cluster):
    """cluster_utils.reserve_tp_slice gang-reserves one bundle per replica:
    a 2 x TPU:2 fleet fits TPU:4 and actors schedule into their bundles; an
    oversized fleet is refused loudly instead of wedging half-acquired."""
    from ray_tpu.cluster_utils import reserve_tp_slice
    from ray_tpu.util.placement_group import remove_placement_group

    pg = reserve_tp_slice(2, resource="TPU", replicas=2)
    try:
        assert len(pg.bundles) == 2

        @ray_tpu.remote(num_cpus=0, num_tpus=2, placement_group=pg,
                        placement_group_bundle_index=0)
        class Rep:
            def ping(self):
                return "ok"

        rep = Rep.remote()
        assert ray_tpu.get(rep.ping.remote(), timeout=60) == "ok"
        del rep
    finally:
        remove_placement_group(pg)

    with pytest.raises(TimeoutError):
        reserve_tp_slice(8, resource="TPU", replicas=2, ready_timeout_s=3.0)


# -- tiered hot tier on a mesh (docs/kvcache.md) -------------------------------

_TIER_SNIPPET = r"""
import json, threading
import numpy as np
import jax, jax.numpy as jnp
from ray_tpu.models.transformer import Transformer, get_config
from ray_tpu.llm._engine import DecodeEngine, SamplingParams

cfg = get_config("test-tiny", scan_layers=False, remat=False, n_kv_heads=4)
model = Transformer(cfg)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
rng = np.random.default_rng(13)
prompt = list(map(int, rng.integers(0, cfg.vocab_size, 40))) + [3, 1]

def generate(engine, p, n=8):
    acc, done = [], threading.Event()
    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()
    engine.submit(p, SamplingParams(max_tokens=n), cb)
    assert done.wait(240)
    return acc

ref_eng = DecodeEngine(cfg, params, num_slots=2, max_seq=128, tp=1,
                       prefix_cache=False)
# RAY_TPU_LLM_KV_DEVICE_BYTES (env) makes this engine build the TIERED cache
# with its hot tier sharded over the tp=2 mesh via kv_prefix_sharding.
eng = DecodeEngine(cfg, params, num_slots=2, max_seq=128, tp=2)
ref = generate(ref_eng, prompt)
cold = generate(eng, prompt)
warm_host = generate(eng, prompt)   # host tier; promotes to device
warm_dev = generate(eng, prompt)    # device tier: mesh-resident, zero H2D
mgr = eng._prefix_cache
shard_degrees = [
    len(dev.sharding.device_set) for dev, _nb in mgr._device._blocks.values()
]
out = {
    "ref": ref, "cold": cold, "host": warm_host, "dev": warm_dev,
    "tier": eng.last_attach["tier"], "shard_degrees": shard_degrees,
    "tiers": eng.prefix_cache_stats()["tiers"],
}
eng.shutdown()
ref_eng.shutdown()
print("RESULT " + json.dumps(out))
"""


def test_tiered_hot_tier_is_mesh_resident_tp2(multi_device_run):
    """TP=2 engine with the flag-driven tiered cache: device-warm greedy
    output is token-identical to a TP=1 cache-disabled reference, the warm
    attach reports tier=device, and every hot-tier block is SHARDED over
    the 2-device mesh (kv_prefix_sharding) — mesh-resident, so the attach
    pays zero host->device copies (docs/kvcache.md)."""
    out = multi_device_run(
        _TIER_SNIPPET,
        env_extra={"RAY_TPU_LLM_KV_DEVICE_BYTES": str(32 << 20)},
    )
    assert out["ref"] == out["cold"] == out["host"] == out["dev"], out
    assert out["tier"] == "device", out["tier"]
    assert out["shard_degrees"] and all(d == 2 for d in out["shard_degrees"])
    assert out["tiers"]["hits_device"] >= 1
