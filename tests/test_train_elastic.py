"""Elastic Train scaling: restarts resize the world to live cluster capacity.

Parity: reference python/ray/train/v2/_internal/execution/scaling_policy/ —
lost node -> continue at N-1 from checkpoint; capacity back -> scale up again.
"""

import time

import ray_tpu
from ray_tpu import train
from ray_tpu.train import DataParallelTrainer, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train._internal.failure_policy import ElasticScalingPolicy

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


def test_elastic_policy_math():
    class _Fake:
        num_workers = 4

        @property
        def _resources_per_worker_not_none(self):
            return {"trainslot": 1.0}

    policy = ElasticScalingPolicy(_Fake(), min_workers=2)
    # First attempt always tries the configured size.
    assert policy.world_size_for_attempt(0) == 4

    import ray_tpu as rt

    real_nodes = rt.nodes

    def fake_nodes(avail_counts):
        return [
            {"alive": True, "resources_total": {"trainslot": float(c)}}
            for c in avail_counts
        ]

    try:
        # Capacity for 1 -> clamped up to min_workers.
        rt.nodes = lambda: fake_nodes([1])
        assert policy.world_size_for_attempt(1) == 2
        # Capacity for 3 -> shrink to 3.
        rt.nodes = lambda: fake_nodes([1, 1, 1])
        assert policy.world_size_for_attempt(1) == 3
        # Capacity restored -> re-expand to the configured size.
        rt.nodes = lambda: fake_nodes([2, 2])
        assert policy.world_size_for_attempt(2) == 4
        # Dead nodes don't count.
        rt.nodes = lambda: [
            {"alive": False, "resources_total": {"trainslot": 8.0}}
        ] + fake_nodes([1, 1])
        assert policy.world_size_for_attempt(1) == 2
    finally:
        rt.nodes = real_nodes


def test_elastic_shrinks_on_node_loss_then_reexpands(tmp_path):
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _ENV})
    cluster.add_node(num_cpus=1, resources={"trainslot": 1.0}, env_vars=_ENV)
    n2 = cluster.add_node(num_cpus=1, resources={"trainslot": 1.0}, env_vars=_ENV)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        marker_dir = str(tmp_path)

        def loop(config):
            import os

            ctx = train.get_context()
            world = ctx.get_world_size()
            rank = ctx.get_world_rank()
            open(os.path.join(config["markers"], f"started_{world}_{rank}"), "w").write("x")
            if world == 2:
                # Full-size attempt: park until the driver kills a node out
                # from under one of us (the recovery path under test).
                time.sleep(600)
            train.report({"world": world, "rank": rank})

        trainer = DataParallelTrainer(
            loop,
            train_loop_config={"markers": marker_dir},
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1,
                resources_per_worker={"trainslot": 1.0},
            ),
            run_config=RunConfig(
                name="elastic", storage_path=str(tmp_path / "storage"),
                failure_config=FailureConfig(max_failures=3),
            ),
        )

        import threading

        result_box = {}

        def fit():
            result_box["result"] = trainer.fit()

        t = threading.Thread(target=fit)
        t.start()
        # Wait for both full-size workers to start, then take a node down.
        import os

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            started = [f for f in os.listdir(marker_dir) if f.startswith("started_2_")]
            if len(started) >= 2:
                break
            time.sleep(0.2)
        assert len([f for f in os.listdir(marker_dir) if f.startswith("started_2_")]) >= 2
        cluster.remove_node(n2)
        t.join(timeout=300)
        assert not t.is_alive(), "trainer did not finish after node loss"
        result = result_box["result"]
        assert result.error is None, result.error
        # The restarted attempt ran at the reduced world size.
        assert result.metrics["world"] == 1

        # Capacity returns: a new run expands back to the full size.
        cluster.add_node(num_cpus=1, resources={"trainslot": 1.0}, env_vars=_ENV)
        cluster.wait_for_nodes()

        def quick_loop(config):
            ctx = train.get_context()
            train.report({"world": ctx.get_world_size()})

        result2 = DataParallelTrainer(
            quick_loop,
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1,
                resources_per_worker={"trainslot": 1.0},
            ),
            run_config=RunConfig(name="elastic2",
                                 storage_path=str(tmp_path / "storage2")),
        ).fit()
        assert result2.error is None
        assert result2.metrics["world"] == 2
    finally:
        cluster.shutdown()
