"""ray_tpu.data.llm batch inference.

Shape parity with the reference suite (python/ray/llm/tests/batch/): processor
build + e2e run over a Dataset, warm-engine actor pools, continuous-batching
interleaving, chat template + tokenize/detokenize stages, HTTP processor.
"""

import json
import threading

import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data.llm import (
    EngineProcessorConfig,
    HttpRequestProcessorConfig,
    build_llm_processor,
)


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def _engine_config(**overrides):
    defaults = dict(
        model_id="test-tiny",
        batch_size=4,
        concurrency=1,
        engine_kwargs={"num_slots": 2, "max_seq": 128},
        sampling_params={"max_tokens": 6},
    )
    defaults.update(overrides)
    return EngineProcessorConfig(**defaults)


def test_processor_e2e_prompts_to_text():
    """Dataset of prompts -> generated_text, usage columns, postprocess."""
    processor = build_llm_processor(
        _engine_config(),
        preprocess=lambda row: {"prompt": f"say {row['id']}"},
        postprocess=lambda row: {"answer": row["generated_text"]},
    )
    ds = processor(rdata.range(4))
    rows = ds.take_all()
    assert len(rows) == 4
    for row in rows:
        assert row["num_generated_tokens"] == 6
        assert row["num_input_tokens"] == len(f"say {row['id']}")
        assert isinstance(row["answer"], str)
        assert row["batch_tokens_per_s"] > 0  # the tokens/sec number
        # original column carried through preprocess/postprocess
        assert "id" in row


def test_engine_pool_spans_multiple_actors():
    """concurrency=2 builds TWO warm engine actors; with more batches than
    actors both engines serve traffic (reference: data parallelism across
    vLLM engine workers)."""
    processor = build_llm_processor(
        _engine_config(batch_size=2, concurrency=2),
        preprocess=lambda row: {"prompt": f"p{row['id']}"},
    )
    rows = processor(rdata.range(8, parallelism=4)).take_all()
    assert len(rows) == 8
    pids = {row["engine_pid"] for row in rows}
    assert len(pids) == 2, f"expected 2 engine actors, saw pids {pids}"


def test_continuous_batching_interleaves_requests():
    """The engine stage must run rows through the slot scheduler CONCURRENTLY:
    with 2 slots and max_tokens 8, decode steps advance both active rows
    together, so the emission order interleaves row indices rather than
    finishing one prompt before starting the next."""
    processor = build_llm_processor(
        _engine_config(
            batch_size=4,
            sampling_params={"max_tokens": 8},
            record_emit_order=True,
        ),
        preprocess=lambda row: {"prompt": f"prompt number {row['id']}"},
    )
    rows = processor(rdata.range(4, parallelism=1)).take_all()
    order = rows[0]["emit_order"]
    assert len(order) == 4 * 8
    # Interleaving: some row's token is emitted between two tokens of another
    # row (a, b, a pattern). One-prompt-at-a-time would be strictly grouped.
    interleaved = any(
        order[i] != order[i + 1] and order[i] in order[i + 2:]
        for i in range(len(order) - 2)
    )
    assert interleaved, f"emission order was not interleaved: {order}"


def test_chat_template_and_sampling_column():
    """messages rows flow through the chat-template stage; a per-row
    sampling_params column overrides config defaults."""
    processor = build_llm_processor(
        _engine_config(apply_chat_template=True),
        preprocess=lambda row: {
            "messages": [
                {"role": "system", "content": "be brief"},
                {"role": "user", "content": f"q{row['id']}"},
            ],
            "sampling_params": {"max_tokens": 3 + row["id"] % 2},
        },
    )
    rows = processor(rdata.range(2)).take_all()
    by_id = {row["id"]: row for row in rows}
    assert by_id[0]["num_generated_tokens"] == 3
    assert by_id[1]["num_generated_tokens"] == 4
    # chat template rendered a role-prefixed prompt before tokenize
    assert "user: q0" in by_id[0]["prompt"]


def test_http_request_processor():
    """HTTP processor posts each row's payload and lands http_response
    (reference: http_request_proc.py), against a local server."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            out = json.dumps({"echo": body, "n": body.get("x", 0) * 2}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        processor = build_llm_processor(
            HttpRequestProcessorConfig(
                url=f"http://127.0.0.1:{server.server_port}/",
                batch_size=2,
                concurrency=1,
            ),
            preprocess=lambda row: {"payload": {"x": row["id"]}},
            postprocess=lambda row: {"doubled": row["http_response"]["n"]},
        )
        rows = processor(rdata.range(4)).take_all()
        assert sorted(row["doubled"] for row in rows) == [0, 2, 4, 6]
    finally:
        server.shutdown()
