"""Multi-slice (DCN) training: hybrid meshes, k-slice gang scheduling, JaxTrainer.

Reference precedent: `python/ray/_private/accelerators/tpu.py:482-547` multi-slice
gang scheduling; the hybrid mesh follows
`jax.experimental.mesh_utils.create_hybrid_device_mesh` semantics (DCN axes vary
across slice groups, ICI axes within a slice).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


def test_hybrid_mesh_layout_and_collectives():
    """dcn_axes build a slice-major mesh: the dp axis crosses fake slices, the
    ici axes stay within one, and collectives over both are correct."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.util.jax_compat import shard_map

    m = mesh_lib.create_mesh({"fsdp": 2, "tp": 2}, dcn_axes={"dp": 2})
    assert m.shape["dp"] == 2 and m.shape["fsdp"] == 2 and m.shape["tp"] == 2
    ids = np.vectorize(lambda d: d.id)(m.devices).reshape(2, 2, 2)
    # slice 0 (devices 0-3) fills dp=0; slice 1 (4-7) fills dp=1
    assert set(ids[0].flatten().tolist()) == {0, 1, 2, 3}
    assert set(ids[1].flatten().tolist()) == {4, 5, 6, 7}

    f = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "dp"), mesh=m, in_specs=P("dp"), out_specs=P()
        )
    )
    np.testing.assert_allclose(np.asarray(f(jnp.arange(2.0))), [1.0])

    # -1 absorbs the per-slice remainder, not the global one.
    m2 = mesh_lib.create_mesh({"tp": -1}, dcn_axes={"dp": 2})
    assert m2.shape["tp"] == 4 and m2.shape["dp"] == 2


def test_hybrid_mesh_rejects_bad_factorings():
    from ray_tpu.parallel import mesh as mesh_lib

    with pytest.raises(ValueError):
        mesh_lib.create_mesh({"tp": 3}, dcn_axes={"dp": 2})  # 3 doesn't divide 4
    with pytest.raises(ValueError):
        mesh_lib.create_mesh({}, dcn_axes={"dp": 3})  # 8 devices % 3 != 0


def test_scaling_config_multi_slice_bundles():
    """k slices => k slice-head bundles, one per slice's host block."""
    sc = ScalingConfig(topology="v4-16", num_slices=2)
    assert sc.num_workers == 4  # 2 hosts/slice x 2 slices
    bundles = sc.bundles()
    heads = [i for i, b in enumerate(bundles) if "TPU-v4-16-head" in b]
    assert heads == [0, 2]
    with pytest.raises(ValueError):
        ScalingConfig(num_slices=2)  # needs a topology
    with pytest.raises(ValueError):
        # an explicit worker count that under-provisions the gang must not
        # silently reserve fewer slices
        ScalingConfig(topology="v4-16", num_slices=2, num_workers=2)


def test_jax_trainer_two_fake_slices_dp_across_dcn(ray_start_cluster):
    """Two fake single-host slices (distinct slice names): the gang spans both
    (one head bundle per slice) and the loop trains data-parallel across the
    DCN tier — per-slice grads allreduced via the host collective group, every
    slice ending with identical params."""
    cluster = ray_start_cluster
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PALLAS_AXON_POOL_IPS": "",
    }
    for name in ("sliceA", "sliceB"):
        cluster.add_node(
            num_cpus=2,
            resources={"TPU": 4.0, "TPU-v4-8": 1.0, "TPU-v4-8-head": 1.0,
                       f"TPU-{name}": 1.0},
            env_vars=env,
        )
    cluster.connect()
    assert cluster.wait_for_nodes()

    def loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.parallel import mesh as mesh_lib
        from ray_tpu.util import collective

        ctx = train.get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        # Local (per-slice) mesh: fsdp x tp over this host's virtual devices.
        mesh = mesh_lib.create_mesh({"fsdp": 2, "tp": 2})
        assert mesh.shape["fsdp"] == 2

        collective.init_collective_group(world, rank, backend="host",
                                         group_name="dcn-dp")
        # Each slice sees different data; DP-across-DCN averages the grads.
        w = jnp.zeros((4,))
        data = jnp.full((4,), float(rank + 1))

        def lossf(w):
            return jnp.sum((w - data) ** 2)

        for _ in range(3):
            g = jax.grad(lossf)(w)
            g = collective.allreduce(np.asarray(g), group_name="dcn-dp",
                                     op=collective.ReduceOp.MEAN)
            w = w - 0.25 * jnp.asarray(g)
        train.report({"rank": rank, "world": world,
                      "w0": float(w[0]), "loss": float(lossf(w))})

    result = JaxTrainer(
        loop,
        jax_config=train.JaxConfig(distributed=False),
        scaling_config=ScalingConfig(topology="v4-8"),
        num_slices=2,
        run_config=RunConfig(name="dcn", storage_path="/tmp/rtpu_dcn_test"),
    ).fit()
    assert result.metrics["world"] == 2
    # grads of sum((w-d)^2) with d=1,2 average to pull w toward 1.5
    assert abs(result.metrics["w0"] - 1.5) < 0.2
