"""Config-flag runtime contract: the dynamic complement of raylint RL1004.

Static reads of unknown flags are caught at lint time; dynamic reads
(getattr with a computed name, CONFIG.get) fail LOUDLY at runtime with a
did-you-mean KeyError instead of silently running on a default. These are
the regression tests for that contract plus the RL10xx triage fixes that
reshaped the tree's cross-process surfaces.
"""

import copy

import pytest

from ray_tpu._private.config import _DEFS, CONFIG


# ---- unknown flags fail loudly with a suggestion ---------------------------

def test_unknown_attribute_raises_keyerror_with_suggestion():
    with pytest.raises(KeyError) as exc:
        CONFIG.data_block_target_byte  # typo: trailing s dropped
    msg = str(exc.value)
    assert "unknown config flag 'data_block_target_byte'" in msg
    assert "did you mean 'data_block_target_bytes'" in msg


def test_unknown_get_raises_keyerror_with_suggestion():
    with pytest.raises(KeyError) as exc:
        CONFIG.get("serve_autopilot_pd_ratio_tolerance")
    assert "did you mean 'serve_autopilot_pd_ratio_tol'" in str(exc.value)


def test_unknown_get_with_default_is_intentional():
    sentinel = object()
    assert CONFIG.get("definitely_not_a_flag", sentinel) is sentinel
    # a None default is still an explicit default, not "missing"
    assert CONFIG.get("definitely_not_a_flag", None) is None


def test_known_get_matches_attribute_read():
    assert CONFIG.get("data_output_queue_size") == \
        CONFIG.data_output_queue_size
    # the explicit default is NOT used when the flag exists
    assert CONFIG.get("data_output_queue_size", -1) == \
        CONFIG.data_output_queue_size


def test_gibberish_name_has_no_suggestion():
    with pytest.raises(KeyError) as exc:
        CONFIG.get("zzqj_xxyy_wwvv")
    assert "did you mean" not in str(exc.value)


def test_underscore_probes_keep_attributeerror():
    """Dunder probes from hasattr/copy/pickle machinery must see
    AttributeError, never KeyError — otherwise copy.copy(CONFIG) and
    friends break."""
    with pytest.raises(AttributeError):
        CONFIG.__deepcopy__
    assert copy.copy(CONFIG) is not CONFIG  # would blow up on KeyError


# ---- the RL1004 triage: every declared flag has a reader -------------------

def test_data_context_reads_the_data_flags():
    """data/context.py was rewired from a dynamic getattr helper to direct
    static reads so the lint (and this test) can see the wiring."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext()
    assert ctx.target_max_block_size == CONFIG.data_block_target_bytes
    assert ctx.output_queue_size == CONFIG.data_output_queue_size


def test_no_flag_is_unreferenced_outside_config_module():
    """The apilint registry view of the tree: every _DEFS entry has at
    least one static read somewhere (deleting 11 dead flags was part of
    this round's triage — this keeps the table honest going forward)."""
    import os

    import ray_tpu
    from ray_tpu.devtools.raylint import apilint
    from ray_tpu.devtools.raylint.core import _load_context, iter_python_files

    pkg = os.path.dirname(os.path.abspath(ray_tpu.__file__))
    ctxs = [_load_context(p)[0] for p in iter_python_files([pkg])]
    reg = apilint.build_registry([c for c in ctxs if c is not None])
    dead = set(_DEFS) - set(reg.flag_reads)
    assert dead == set(), f"declared but never read: {sorted(dead)}"
    assert set(reg.flags) == set(_DEFS)


# ---- the RL1003/RL1006 triage: surfaces reshaped by this round -------------

def test_llm_deployments_cover_their_protocol_rosters():
    """PrefillServer/DecodeServer/PDRouter/DPRouter grew the methods that
    made their rosters whole; losing one would AttributeError inside fleet
    broadcasts (and re-fire RL1003)."""
    from ray_tpu.llm.dp_serve import DPRouter
    from ray_tpu.llm.pd_disagg import DecodeServer, PDRouter, PrefillServer

    stats_surface = ("cache_stats", "scheduler_stats", "recorder_stats",
                     "capture_profile")
    for cls in (PrefillServer, DecodeServer):
        for member in stats_surface:
            assert callable(getattr(cls, member, None)), (cls, member)
        assert callable(getattr(cls, "set_tenant_weight", None)), cls
    for member in ("cache_stats", "set_tenant_weight", "capture_profile"):
        assert callable(getattr(PDRouter, member, None)), member
    # the router answers the autopilot probe AND the weight actuator
    assert callable(getattr(DPRouter, "autopilot_signals", None))
    assert callable(getattr(DPRouter, "set_tenant_weight", None))


def test_dp_router_autopilot_signals_shape():
    from ray_tpu.llm.dp_serve import DPRouter

    import asyncio

    router = object.__new__(DPRouter)
    router._fingerprints = {}
    router._routing = {"cache_routed": 3, "balanced": 1}
    out = asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
        DPRouter.autopilot_signals(router))
    assert out["role"] == "dp_router"
    # a router must never look scalable: zero queue pressure by contract
    assert out["queued"] == 0 and out["running"] == 0
    assert out["cache_routed"] == 3 and out["balanced"] == 1


def test_gcs_orphan_verbs_became_private_helpers():
    """rpc_report_object/rpc_free_object were unreachable as verbs (only
    the batch op names them); they are private helpers now so the verb
    table matches what clients can actually call."""
    from ray_tpu._private.gcs import GcsService

    assert not hasattr(GcsService, "rpc_report_object")
    assert not hasattr(GcsService, "rpc_free_object")
    assert callable(getattr(GcsService, "_report_object", None))
    assert callable(getattr(GcsService, "_free_object", None))
    assert callable(getattr(GcsService, "rpc_object_ops_batch", None))
