"""Compute-plane observatory: XLA program registry, device-memory ledger,
OOM forensics, and profiler capture (docs/observability.md "compute plane").

The registry's core contract: a warm program never counts a compile again
(`xla_recompiles_total` reads 0 across any warm run), while a planted retrace
— rebuilding a program the registry has already seen compiled — fires it.
"""

import json
import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.util import xprof


@pytest.fixture()
def reg():
    r = xprof.ProgramRegistry()
    yield r


# ---- program registry -------------------------------------------------------

def test_registry_counts_one_compile_per_program(reg):
    prog = reg.instrument("eng", ("decode",), jax.jit(lambda x: x + 1))
    for i in range(3):
        _ = prog(jnp.zeros(4))
    rep = reg.report()
    assert rep["totals"] == {
        "programs": 1, "compiles_total": 1, "recompiles_total": 0,
        "compile_s_total": pytest.approx(rep["totals"]["compile_s_total"]),
    }
    (row,) = rep["programs"]
    assert row["owner"] == "eng" and row["compiles"] == 1
    assert row["invocations"] == 3 and row["recompiles"] == 0
    assert row["compile_s"] > 0.0  # first call paid a real trace+compile


def test_planted_retrace_fires_recompile_counter(reg):
    """The adversarial shape: re-instrumenting an already-seen (owner, key) —
    what a cache eviction rebuild or a shape-retrace storm looks like at the
    registry — increments recompiles, not warmup compiles."""
    prog = reg.instrument("eng", ("prefill", 64), jax.jit(lambda x: x * 2))
    _ = prog(jnp.zeros(4))
    assert reg.recompiles_total == 0

    # Plant the retrace: the engine rebuilds the same bucket's program.
    prog2 = reg.instrument("eng", ("prefill", 64), jax.jit(lambda x: x * 2))
    _ = prog2(jnp.zeros(4))
    assert reg.recompiles_total == 1
    rep = reg.report()
    (row,) = rep["programs"]
    assert row["compiles"] == 2 and row["recompiles"] == 1
    # Warm calls after the retrace stay free.
    _ = prog2(jnp.zeros(4))
    assert reg.recompiles_total == 1


def test_note_span_and_note_exec_never_count_compiles(reg):
    reg.note_span("checkpoint", ("restore",), 1.5)
    reg.note_exec("learner", ("update", "sig"), 0.25)
    rep = reg.report()
    assert rep["totals"]["compiles_total"] == 0
    assert rep["totals"]["recompiles_total"] == 0
    by_owner = {r["owner"]: r for r in rep["programs"]}
    assert by_owner["checkpoint"]["invocations"] == 1
    assert by_owner["checkpoint"]["exec_s"] == pytest.approx(1.5)
    assert by_owner["learner"]["invocations"] == 0
    assert by_owner["learner"]["exec_s"] == pytest.approx(0.25)


def test_report_filters_by_owner_and_forget_owner(reg):
    a = reg.instrument("a", ("k",), jax.jit(lambda x: x + 1))
    b = reg.instrument("b", ("k",), jax.jit(lambda x: x - 1))
    _ = a(jnp.zeros(2))
    _ = b(jnp.zeros(2))
    assert len(reg.report(owner="a")["programs"]) == 1
    assert len(reg.report()["programs"]) == 2
    reg.forget_owner("a")
    assert reg.report(owner="a")["programs"] == []
    # totals watermarks survive the forget: no negative deltas on next report
    assert reg.report()["totals"]["programs"] == 1


def test_instrumented_program_delegates_attributes(reg):
    jitted = jax.jit(lambda x: x + 1)
    prog = reg.instrument("eng", ("k",), jitted)
    _ = prog(jnp.zeros(2))
    # the adapters stats() probe and any other jit attribute ride through
    assert prog._cache_size() == jitted._cache_size()
    assert prog.__wrapped__ is jitted


def test_unhashable_key_is_frozen(reg):
    prog = reg.instrument("eng", ["prefill", [1, 2]], jax.jit(lambda x: x))
    _ = prog(jnp.zeros(2))
    (row,) = reg.report()["programs"]
    assert row["key"] == ("prefill", (1, 2))


# ---- device-memory ledger ---------------------------------------------------

def test_memory_ledger_attributes_owner_bytes():
    xprof.register_memory_owner("san-owner", lambda: {
        "bytes": 1024, "components": {"kv": 1024},
        "per_device": {"0": 512, "1": 512},
    })
    try:
        rep = xprof.device_memory_report()
        assert rep["owners"]["san-owner"]["bytes"] == 1024
        assert rep["tracked_bytes_total"] >= 1024
        assert rep["per_device_tracked_bytes"]["0"] == 512
        assert rep["devices"], "jax.devices() must appear in the report"
        assert {"id", "platform"} <= set(rep["devices"][0])
    finally:
        xprof.unregister_memory_owner("san-owner")
    assert "san-owner" not in xprof.device_memory_report()["owners"]


def test_memory_ledger_owner_error_is_contained():
    def broken():
        raise RuntimeError("owner died")

    xprof.register_memory_owner("san-broken", broken)
    try:
        rep = xprof.device_memory_report()
        assert "owner died" in rep["owners"]["san-broken"]["error"]
    finally:
        xprof.unregister_memory_owner("san-broken")


# ---- OOM forensics ----------------------------------------------------------

def test_is_resource_exhausted_matches_xla_shapes():
    assert xprof.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 21474836480 bytes."))
    assert xprof.is_resource_exhausted(ValueError("Resource exhausted: HBM"))
    assert not xprof.is_resource_exhausted(ValueError("shape mismatch"))


def test_oom_snapshot_ranks_owners_descending():
    xprof.register_memory_owner("san-big", lambda: {"bytes": 2048})
    xprof.register_memory_owner("san-small", lambda: {"bytes": 16})
    try:
        snap = xprof.oom_snapshot()
        ranked = [r["owner"] for r in snap["ranked_owners"]
                  if r["owner"].startswith("san-")]
        assert ranked == ["san-big", "san-small"]
        assert snap["ts"] > 0
    finally:
        xprof.unregister_memory_owner("san-big")
        xprof.unregister_memory_owner("san-small")


def test_flight_recorder_keeps_first_oom_snapshot():
    from ray_tpu.llm.flight_recorder import FlightRecorder

    rec = FlightRecorder(name="san-oom", capacity=4)
    try:
        rec.note_oom({"ts": 1.0, "ranked_owners": [{"owner": "kv", "bytes": 9}]})
        rec.note_oom({"ts": 2.0, "ranked_owners": []})  # cascade: noise
        stats = rec.stats()
        assert stats["oom"] == 2
        assert stats["last_oom"]["ts"] == 1.0
    finally:
        rec.close()


# ---- profiler capture -------------------------------------------------------

def test_capture_round_trip_yields_manifest_and_files():
    log_dir = tempfile.mkdtemp(prefix="xprof_test_")
    out = xprof.capture(duration_s=0.05, log_dir=log_dir)
    assert out["log_dir"] == log_dir
    assert out["manifest"]["duration_s"] >= 0.05
    assert out["manifest"]["pid"] == os.getpid()
    # at minimum the manifest itself is gathered inline
    assert "capture_manifest.json" in out["files"]
    manifest = json.loads(out["files"]["capture_manifest.json"])
    assert manifest["log_dir"] == log_dir


def test_second_start_capture_raises_while_active():
    cap = xprof.start_capture(log_dir=tempfile.mkdtemp(prefix="xprof_test_"))
    try:
        with pytest.raises(RuntimeError):
            xprof.start_capture()
    finally:
        cap.stop_capture()
    # idempotent stop, and the slot frees for the next capture
    cap.stop_capture()
    cap2 = xprof.start_capture(log_dir=tempfile.mkdtemp(prefix="xprof_test_"))
    cap2.close()


# ---- metrics exposition (report path) ---------------------------------------

def test_registry_report_emits_metrics_deltas(reg, ray_start_isolated):
    from ray_tpu.util.metrics import render_prometheus

    prog = reg.instrument("eng", ("decode",), jax.jit(lambda x: x + 1))
    _ = prog(jnp.zeros(2))
    reg.report()  # the ONLY place counters become util.metrics series
    text = render_prometheus()
    assert "xla_compiles_total" in text
    assert "xla_recompiles_total" in text


def test_render_prometheus_alias_preserved():
    from ray_tpu.util import metrics

    assert metrics.prometheus_text is metrics.render_prometheus
