"""Flight recorder + SLO metrics plane (docs/observability.md): bounded
ring/event accounting, timing breakdowns, span export shape, SLO/goodput
classification, and leak-free shutdown (this suite runs under leaksan —
tests/conftest.py LEAKSAN_SUITES — so a stranded flight_record handle is a
test failure, not a slow leak)."""

import threading
import time

import pytest


def _tiny_engine(**kwargs):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return DecodeEngine(cfg, params, **kwargs), cfg


def _generate(engine, prompt, rid=None, **sp):
    from ray_tpu.llm import SamplingParams

    acc, done = [], threading.Event()

    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(**sp), cb, request_id=rid)
    assert done.wait(180), engine.error
    return acc


# -- recorder unit behavior ---------------------------------------------------


def test_ring_and_event_caps_bounded():
    from ray_tpu.llm import flight_recorder as fr

    rec = fr.FlightRecorder(name="unit", capacity=4)
    for i in range(10):
        r = rec.start(f"r{i}")
        r.mark("queued")
        rec.finish(r)
    stats = rec.stats()
    assert stats["ring"] == 4 and stats["finished"] == 10
    assert stats["live"] == 0
    # per-record event cap: overflow counts, never grows
    r = rec.start("big")
    for i in range(fr._MAX_EVENTS + 50):
        r.mark(f"e{i}")
    assert len(r.events) == fr._MAX_EVENTS and r.dropped_events == 50
    summary = rec.finish(r)
    assert summary["dropped_events"] == 50


def test_capacity_zero_disables():
    from ray_tpu.llm.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=0)
    assert rec.start("x") is None
    assert rec.finish(None) is None  # None-guards hold end to end
    assert rec.stats()["started"] == 0


def test_finish_idempotent_and_lookup():
    from ray_tpu.llm.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=8)
    r = rec.start("a", tenant="t1", route="cache_routed")
    r.mark("admitted", slot=0)
    r.token()
    time.sleep(0.01)
    r.token()
    s1 = rec.finish(r)
    s2 = rec.finish(r)  # second retire is a no-op, books stay balanced
    assert rec.stats()["finished"] == 1
    assert s1["tokens"] == 2 and s1["ttft_s"] is not None
    assert s1["tpot_s"] == pytest.approx(
        s1["events"] and (r.token_times[1] - r.token_times[0]), rel=0.2
    )
    assert s2["tenant"] == "t1"
    found = rec.lookup("a")
    assert found is not None and found["route"] == "cache_routed"
    assert rec.lookup("missing") is None


def test_span_export_tree_shape():
    """Span export: one root per record, phase children parented under it,
    trace ids preserved — the shape to_otlp_json/spans_to_otel consume."""
    from ray_tpu.llm.flight_recorder import FlightRecorder
    from ray_tpu.util.tracing_export import to_otlp_json

    rec = FlightRecorder(name="spans", capacity=8)
    trace = {"trace_id": "f" * 32, "span_id": "1" * 16}
    r = rec.start("req", trace=trace, tenant="t")
    r.mark("queued")
    r.span("prefill-chunk", time.time() - 0.01, time.time(), bucket=32)
    rec.finish(r)
    spans = rec.spans()
    root = next(s for s in spans if s["name"] == "llm:request")
    assert root["trace_id"] == "f" * 32
    assert root["parent_span_id"] == "1" * 16  # the serve task's span
    children = [s for s in spans if s["name"] != "llm:request"]
    assert {s["name"] for s in children} == {"llm:queued", "llm:prefill-chunk"}
    assert all(s["parent_span_id"] == root["span_id"] for s in children)
    chunk = next(s for s in children if s["name"] == "llm:prefill-chunk")
    assert chunk["attributes"]["ray_tpu.llm.bucket"] == 32
    # and the OTLP mapping accepts it wholesale
    otlp = to_otlp_json(spans)
    names = [s["name"]
             for s in otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]]
    assert "llm:request" in names and "llm:prefill-chunk" in names


def test_serve_metrics_slo_classification_and_burn():
    from ray_tpu.llm.flight_recorder import ServeMetrics

    m = ServeMetrics("unit", slo_ttft_s=0.1, slo_tpot_s=0.01,
                     error_budget=0.1)
    good = {"status": "ok", "ttft_s": 0.05, "tpot_s": 0.005, "e2e_s": 0.2,
            "tenant": ""}
    bad_ttft = {**good, "ttft_s": 0.5}
    bad_tpot = {**good, "tpot_s": 0.02}
    rejected = {**good, "status": "rejected"}
    assert m.good(good) and not m.good(bad_ttft)
    assert not m.good(bad_tpot) and not m.good(rejected)
    for s in (good, good, bad_ttft, good):
        m.record(s)
    m.flush()  # no cluster: metrics export is best-effort, window still fills
    # 1 breach in 4 over a 0.1 budget -> burn 2.5
    assert m.burn_rate("") == pytest.approx((1 / 4) / 0.1)


# -- engine integration -------------------------------------------------------


def test_engine_timing_breakdown_and_phases():
    engine, _cfg = _tiny_engine(num_slots=2, max_seq=64)
    try:
        out = _generate(engine, [1, 2, 3, 4, 5], rid="req-tb", max_tokens=6)
        assert len(out) == 6
        t = engine.request_timing("req-tb")
        assert t is not None and t["tokens"] == 6
        assert t["queue_s"] is not None and t["queue_s"] >= 0
        assert t["ttft_s"] > 0 and t["e2e_s"] >= t["ttft_s"]
        assert "prefill-chunk" in t["phases"] and "decode" in t["phases"]
        rec = engine._recorder.records()[-1]
        names = [e[0] for e in rec["events"]]
        assert names[0] == "queued" and "admitted" in names
    finally:
        engine.shutdown()


def test_engine_shutdown_drops_live_records():
    """Requests still queued/active at shutdown retire as dropped — the
    leaksan flight_record books balance (this suite's autouse guard is the
    enforcement) and counters stay exact."""
    from ray_tpu.llm import SamplingParams

    engine, _cfg = _tiny_engine(num_slots=1, max_seq=64)
    try:
        stall = threading.Event()
        first = threading.Event()

        def cb(tok, fin):
            first.set()
            stall.wait(0.01)  # slow consumer keeps the slot occupied

        engine.submit(list(range(1, 9)), SamplingParams(max_tokens=64), cb)
        # a second request that stays QUEUED behind the busy slot
        engine.submit(list(range(1, 5)), SamplingParams(max_tokens=4),
                      lambda t, f: None)
        assert first.wait(60), engine.error
    finally:
        engine.shutdown()
    stats = engine._recorder.stats()
    assert stats["live"] == 0
    assert stats["started"] == stats["finished"] + stats["dropped"] + \
        stats["rejected"]
    assert stats["dropped"] >= 1  # the queued request never got a slot


def test_overload_rejection_records():
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError

    engine, _cfg = _tiny_engine(num_slots=1, max_seq=64, max_queue_depth=1,
                                tenant_quota=0)
    try:
        started = threading.Event()

        def slow(t, f):
            started.set()
            time.sleep(0.005)

        engine.submit([1, 2, 3], SamplingParams(max_tokens=64), slow)
        assert started.wait(60), engine.error  # admitted: the queue is empty
        engine.submit([1, 2], SamplingParams(max_tokens=2),
                      lambda t, f: None)  # fills the depth-1 queue
        with pytest.raises(EngineOverloadedError):
            engine.submit([1], SamplingParams(max_tokens=2),
                          lambda t, f: None)
        assert engine._recorder.stats()["rejected"] == 1
    finally:
        engine.shutdown()


def test_spec_and_cache_phases_recorded():
    """A cache-hit + spec-decode request's record carries the cache-attach
    and spec-verify phases (the events the tuning loops read)."""
    import numpy as np

    from ray_tpu._private.config import CONFIG

    bs = CONFIG.llm_kv_block_size
    engine, cfg = _tiny_engine(
        num_slots=2, max_seq=128,
        spec_config={"method": "ngram", "num_spec_tokens": 4},
    )
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, 3 * bs).tolist()
    try:
        _generate(engine, prefix + [1, 2], rid="cold", max_tokens=12)
        _generate(engine, prefix + [3, 4], rid="warm", max_tokens=12)
        warm = engine.request_timing("warm")
        assert "cache-attach" in warm["phases"], warm["phases"]
        # repeated greedy traffic: the ngram draft proposes on the warm run
        recs = engine._recorder.records()
        phases = [e[0] for r in recs for e in r["events"]]
        assert "spec-verify" in phases or "prefill-chunk" in phases
    finally:
        engine.shutdown()
