"""Sharded async checkpointing plane (ray_tpu.checkpoint + train wiring).

The contract under test (docs/checkpoint.md): shards + specs first, manifest
last and atomic — a manifest-less dir is garbage (never resumed from, always
reaped); restore reassembles the global tree from slice offsets and
redistributes onto whatever mesh exists NOW (elastic N->M); the async writer
charges the step loop one batched snapshot, not the IO.
"""

import os
import shutil
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu import checkpoint as ckpt
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train._internal.controller import TrainController


def _mesh(shape):
    return Mesh(np.array(jax.devices()).reshape(shape), ("a", "b"))


def _sample_tree(mesh):
    """Mixed dtypes, mixed shardings, nested containers, host leaves."""
    return {
        "params": {
            "dense": {
                "kernel": jax.device_put(
                    jnp.arange(64 * 32, dtype=jnp.float32).reshape(64, 32),
                    NamedSharding(mesh, P("a", "b"))),
                "bias": jax.device_put(
                    jnp.arange(32, dtype=jnp.bfloat16),
                    NamedSharding(mesh, P("b"))),
            },
            "emb": jax.device_put(
                jnp.arange(128, dtype=jnp.int32).reshape(16, 8),
                NamedSharding(mesh, P("a", None))),
        },
        "step": np.int64(7),
        "opt": [np.ones((3, 3), np.float32),
                jax.device_put(jnp.full((8,), 2.0), NamedSharding(mesh, P()))],
    }


def _assert_tree_equal(got, want):
    jax.tree.map(
        lambda g, w: np.testing.assert_array_equal(np.asarray(g), np.asarray(w)),
        got, want,
    )


# ---- format: N-process save -> M-layout restore, bitwise ---------------------

def test_roundtrip_reshard_n_to_m(tmp_path):
    """The elastic property: save on a simulated 4-process (4,2) mesh, restore
    onto a (2,4) mesh with DIFFERENT partition specs — bitwise identical."""
    path = str(tmp_path / "c1")
    tree = _sample_tree(_mesh((4, 2)))
    for p in range(4):  # each simulated process writes only its owned slices
        ckpt.write_process_shards(path, tree, process_index=p, process_count=4)
    ckpt.commit(path, process_count=4)
    assert ckpt.is_committed(path) and not ckpt.is_partial(path)

    # Host restore preserves structure, dtypes, and bits.
    host = ckpt.restore(path)
    _assert_tree_equal(host, tree)
    assert isinstance(host["opt"], list)
    assert np.asarray(host["params"]["dense"]["bias"]).dtype == jnp.bfloat16

    # Reshard restore: new mesh shape AND transposed/changed specs.
    mesh_m = _mesh((2, 4))
    out = ckpt.restore(path, shardings={
        "params/dense/kernel": NamedSharding(mesh_m, P("b", "a")),
        "params/dense/bias": NamedSharding(mesh_m, P("a")),
        "params/emb": NamedSharding(mesh_m, P(("a", "b"))),
    })
    _assert_tree_equal(out, tree)
    k = out["params"]["dense"]["kernel"]
    assert k.sharding.spec == P("b", "a")  # actually resharded, not replicated

    # Replicated restore onto the current mesh.
    _assert_tree_equal(ckpt.restore(path, mesh=mesh_m), tree)


def test_single_process_save_is_one_call(tmp_path):
    path = str(tmp_path / "c2")
    tree = {"w": jnp.arange(12.0).reshape(3, 4), "n": np.int32(3)}
    ckpt.save(path, tree)
    assert ckpt.is_committed(path)
    _assert_tree_equal(ckpt.restore(path), tree)


def test_commit_refuses_missing_coverage(tmp_path):
    """A writer's shards missing -> commit times out (spec never appears) or,
    with a lying process_count, fails coverage — never a silent half-commit."""
    path = str(tmp_path / "c3")
    tree = _sample_tree(_mesh((4, 2)))
    ckpt.write_process_shards(path, tree, process_index=0, process_count=2)
    with pytest.raises(ckpt.CommitTimeout):
        ckpt.commit(path, process_count=2, timeout_s=0.2)
    with pytest.raises(ValueError, match="covers"):
        ckpt.commit(path, process_count=1)  # process 0's shards alone: gaps
    assert ckpt.is_partial(path)  # still garbage after both failed commits


# ---- kill-mid-save: partial dirs are never resumed, always reaped ------------

def _make_controller(storage, name, **run_kw):
    return TrainController(
        train_fn=lambda cfg: None, train_fn_config=None,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name=name, storage_path=storage, **run_kw),
    )


def test_partial_dir_ignored_on_resume_and_reaped(tmp_path):
    storage = str(tmp_path)
    exp = os.path.join(storage, "killed")
    committed = os.path.join(exp, "checkpoint_000001")
    partial = os.path.join(exp, "checkpoint_000002")
    tree = {"w": jnp.arange(8.0)}
    ckpt.save(committed, tree)
    # Simulated kill mid-save: shards of one writer landed, manifest never did.
    ckpt.write_process_shards(partial, tree, process_index=0, process_count=2)
    assert ckpt.is_partial(partial)
    with pytest.raises(FileNotFoundError):
        ckpt.restore(partial)

    c = _make_controller(storage, "killed")
    c._checkpoints.register(1, Checkpoint(committed), {"step": 1})
    c._checkpoints.register(2, Checkpoint(partial), {"step": 2})
    # Resume point skips the tracked-but-uncommitted dir.
    assert c._checkpoints.latest.path == partial
    assert c._checkpoints.latest_committed.path == committed
    # Restart-time cleanup reaps the partial (tracked or not) and keeps the
    # committed resume point.
    c._remove_orphan_checkpoints()
    assert not os.path.exists(partial)
    assert os.path.exists(committed)
    assert c._checkpoints.latest_committed.path == committed


def test_orphan_checkpoint_zero_reaped_when_nothing_tracked(tmp_path):
    """Regression: max_index defaults to 0 when nothing is tracked, so a dead
    first attempt's checkpoint_0 survived `0 > 0`. highest_tracked_index (-1)
    subsumes it: with no tracked checkpoints, EVERY leftover dir is garbage."""
    storage = str(tmp_path)
    exp = os.path.join(storage, "dead_first")
    os.makedirs(os.path.join(exp, "checkpoint_0"))
    with open(os.path.join(exp, "checkpoint_0", "model.bin"), "w") as f:
        f.write("stale")
    c = _make_controller(storage, "dead_first")
    assert c._checkpoints.max_index == 0  # the numbering offset keeps its floor
    assert c._checkpoints.highest_tracked_index == -1
    c._remove_orphan_checkpoints()
    assert not os.path.exists(os.path.join(exp, "checkpoint_0"))


def test_orphan_cleanup_keeps_tracked_and_reaps_above(tmp_path):
    storage = str(tmp_path)
    exp = os.path.join(storage, "mixed")
    for n in (1, 2, 3):
        d = os.path.join(exp, f"checkpoint_{n}")
        os.makedirs(d)
        with open(os.path.join(d, "x"), "w") as f:
            f.write("x")
    c = _make_controller(storage, "mixed")
    c._checkpoints.register(1, Checkpoint(os.path.join(exp, "checkpoint_1")), {})
    c._remove_orphan_checkpoints()
    assert os.path.exists(os.path.join(exp, "checkpoint_1"))
    assert not os.path.exists(os.path.join(exp, "checkpoint_2"))
    assert not os.path.exists(os.path.join(exp, "checkpoint_3"))


# ---- Checkpoint.to_directory: stale files must not survive -------------------

def test_to_directory_clears_stale_target(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "model.txt").write_text("new")
    target = tmp_path / "restore"
    target.mkdir()
    (target / "leftover.txt").write_text("stale")  # from a previous restore
    out = Checkpoint(str(src)).to_directory(str(target))
    assert out == str(target)
    assert (target / "model.txt").read_text() == "new"
    assert not (target / "leftover.txt").exists()  # stale file did NOT survive


# ---- CheckpointManager retention ---------------------------------------------

def _mgr_register(mgr, tmp_path, index, metrics):
    d = tmp_path / f"checkpoint_{index:06d}"
    d.mkdir(exist_ok=True)
    (d / "data").write_text(str(index))
    mgr.register(index, Checkpoint(str(d)), metrics)
    return str(d)


def test_retention_missing_score_ranks_worst(tmp_path):
    """A report without the score attribute ranks -inf: it is the eviction
    victim, not accidentally the best."""
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="score"))
    d1 = _mgr_register(mgr, tmp_path, 1, {"score": 5.0})
    d2 = _mgr_register(mgr, tmp_path, 2, {})          # score missing -> -inf
    d3 = _mgr_register(mgr, tmp_path, 3, {"score": 1.0})
    assert not os.path.exists(d2)
    assert os.path.exists(d1) and os.path.exists(d3)
    assert mgr.best.path == d1


def test_retention_never_deletes_resume_point(tmp_path):
    """The LATEST checkpoint is the resume point: it survives retention even
    when it scores worst (here: missing metric on the newest report)."""
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=1, checkpoint_score_attribute="score",
        checkpoint_score_order="max"))
    d1 = _mgr_register(mgr, tmp_path, 1, {"score": 100.0})
    d2 = _mgr_register(mgr, tmp_path, 2, {})  # newest, scoreless -> worst
    assert os.path.exists(d2), "resume point was deleted"
    assert mgr.latest.path == d2
    # Score order min: same invariant.
    mgr2 = CheckpointManager(CheckpointConfig(
        num_to_keep=1, checkpoint_score_attribute="loss",
        checkpoint_score_order="min"))
    e1 = _mgr_register(mgr2, tmp_path, 11, {"loss": 0.001})
    e2 = _mgr_register(mgr2, tmp_path, 12, {"loss": 999.0})
    # e2 (latest, worst loss) is the only over-budget victim but is protected:
    # retention backs off rather than deleting the resume point.
    assert os.path.exists(e2) and mgr2.latest.path == e2
    assert os.path.exists(e1) and mgr2.best.path == e1


# ---- async writer ------------------------------------------------------------

def test_async_writer_overlaps_write_with_step_loop(tmp_path, monkeypatch):
    """save() must return while persistence is still running: gate the
    background write on an event the 'step loop' only sets afterwards."""
    from ray_tpu.checkpoint import _format as fmt

    gate = threading.Event()
    real_write = fmt.write_snapshot

    def slow_write(*a, **kw):
        assert gate.wait(10.0)
        return real_write(*a, **kw)

    monkeypatch.setattr(fmt, "write_snapshot", slow_write)
    w = ckpt.AsyncCheckpointWriter(inflight=2)
    path = str(tmp_path / "async1")
    w.save(path, {"w": jnp.arange(16.0)})   # returns pre-persistence
    assert not ckpt.is_committed(path)      # nothing durable yet...
    gate.set()
    assert w.wait_until_finished(timeout=30.0)
    assert ckpt.is_committed(path)          # ...but committed after the barrier
    w.shutdown()


def test_async_writer_surfaces_background_errors(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where the checkpoint dir must go")
    w = ckpt.AsyncCheckpointWriter(inflight=1)
    w.save(str(blocker), {"w": jnp.arange(4.0)})  # job will fail in background
    with pytest.raises(RuntimeError, match="checkpoint save failed"):
        w.wait_until_finished(timeout=30.0)
    with pytest.raises(RuntimeError, match="previous async checkpoint"):
        w.save(str(tmp_path / "next"), {"w": jnp.arange(4.0)})
    w.shutdown()


# ---- train integration -------------------------------------------------------

@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


def test_report_sharded_state_multirank(ray_start_regular, storage):
    """Both ranks persist only their owned shards; rank 0 commits after every
    rank's spec is durable; the Result checkpoint restores bitwise."""

    def loop(config):
        ctx = train.get_context()
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("a",))
        state = {
            "w": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                NamedSharding(mesh, P("a"))),
            "step": np.int64(ctx.get_world_size()),
        }
        train.report({"rank": ctx.get_world_rank()},
                     checkpoint=ckpt.ShardedState(state))

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="sharded", storage_path=storage),
    ).fit()
    assert result.error is None
    assert result.checkpoint is not None
    path = result.checkpoint.path
    assert ckpt.is_committed(path)
    # Both ranks wrote their process specs (the commit barrier's inputs).
    assert os.path.exists(os.path.join(path, "process_0.json"))
    assert os.path.exists(os.path.join(path, "process_1.json"))
    manifest = ckpt.load_manifest(path)
    assert manifest["process_count"] == 2
    tree = result.checkpoint.to_pytree()
    np.testing.assert_array_equal(
        tree["w"], np.arange(64, dtype=np.float32).reshape(8, 8))
    assert tree["step"] == 2


def test_failure_restart_resumes_from_sharded(ray_start_regular, storage, tmp_path):
    marker = tmp_path / "fail_once"

    def loop(config):
        ctx = train.get_context()
        start = 0
        prev = train.get_checkpoint()
        if prev is not None:
            assert prev.is_sharded and prev.is_committed
            start = int(prev.to_pytree()["step"]) + 1
        for step in range(start, 4):
            train.report(
                {"step": step, "resumed_from": start},
                checkpoint=ckpt.ShardedState(
                    {"step": np.int64(step),
                     "w": jnp.full((4,), float(step))}),
            )
            if step == 1 and ctx.get_world_rank() == 0 and not marker.exists():
                marker.write_text("x")
                raise RuntimeError("injected failure")

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="restart_sharded", storage_path=storage,
                             failure_config=FailureConfig(max_failures=1)),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    assert result.metrics["resumed_from"] >= 1  # really resumed from a commit
    tree = result.checkpoint.to_pytree()
    np.testing.assert_array_equal(tree["w"], np.full((4,), 3.0))


# ---- llm warm start ----------------------------------------------------------

def test_llm_engine_warm_start_from_sharded(tmp_path):
    from ray_tpu.llm import LLMConfig, load_model
    from ray_tpu.llm._engine import DecodeEngine
    from ray_tpu.parallel.mesh import unbox

    cfg, boxed = load_model(LLMConfig(model_id="test-tiny", seed=3))
    params = unbox(boxed)  # flax partitioning boxes are stripped on save
    path = str(tmp_path / "weights")
    ckpt.save(path, {"params": boxed})

    cfg2, params2 = load_model(
        LLMConfig(model_id="test-tiny", checkpoint_path=path))
    _assert_tree_equal(params2, params)

    engine = DecodeEngine.from_sharded_checkpoint(
        cfg, path, num_slots=2, max_seq=64, decode_loop=False)
    _assert_tree_equal(engine.params, params)
    engine.shutdown()

    # A partial dir must be refused, not half-loaded.
    shutil.rmtree(path)
    ckpt.write_process_shards(path, {"params": params},
                              process_index=0, process_count=2)
    with pytest.raises(FileNotFoundError):
        load_model(LLMConfig(model_id="test-tiny", checkpoint_path=path))
