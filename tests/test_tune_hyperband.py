"""HyperBand / BOHB schedulers + searcher breadth.

Shape parity: reference python/ray/tune/tests/test_trial_scheduler.py
(HyperBand promotion/stop behavior), schedulers/hb_bohb.py coupling, and the
search adapter gating pattern of search/hyperopt.
"""

import json
import os
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train.checkpoint import Checkpoint


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def _checkpointing_trainable(config):
    """Reports score=x each iteration with a checkpoint; resumes from pauses."""
    start = 1
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "it.json")) as f:
            start = json.load(f)["iter"] + 1
    for i in range(start, 5):
        d = tempfile.mkdtemp()
        with open(os.path.join(d, "it.json"), "w") as f:
            json.dump({"iter": i}, f)
        tune.report({"score": float(config["x"])}, checkpoint=Checkpoint(d))


def test_hyperband_promotes_top_and_stops_rest():
    """4-trial bracket, eta=2, milestones 1/2/4: the barrier pauses everyone
    at each rung, promotes the top half from their checkpoints, and the best
    trial runs its full budget while demoted trials stop early."""
    grid = tune.Tuner(
        _checkpointing_trainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.HyperBandScheduler(
                metric="score", mode="max", max_t=4, reduction_factor=2
            ),
        ),
        run_config=tune.RunConfig(
            name="hb", storage_path=tempfile.mkdtemp()
        ),
    ).fit()
    assert len(grid) == 4
    by_x = {r.metrics["config"]["x"]: r.metrics for r in grid}
    # The winner (x=4) ran the full budget; iteration numbering continued
    # across pauses (checkpoint resume, not restart).
    assert by_x[4]["training_iteration"] == 4
    # Demoted trials stopped before the full budget.
    iters = sorted(m["training_iteration"] for m in by_x.values())
    assert iters[0] <= 2, iters
    assert sum(1 for i in iters if i >= 4) <= 2, iters


def test_bohb_searcher_uses_rung_observations():
    """TuneBOHB's model sees partial-budget rung results (the BOHB coupling):
    after rung feedback strongly favoring high x, post-warmup suggestions
    concentrate there."""
    space = {"x": tune.uniform(0, 1)}
    searcher = tune.TuneBOHB(space, metric="score", mode="max", n_initial=2,
                             seed=3)
    c1 = searcher.suggest("t1")
    c2 = searcher.suggest("t2")
    searcher.on_rung_result("t1", c1, c1["x"] * 10)
    searcher.on_rung_result("t2", c2, c2["x"] * 10)
    assert len(searcher._rung_obs) == 2
    # completion supersedes the rung entry
    searcher.on_trial_complete("t1", {"score": c1["x"] * 10})
    assert "t1" not in searcher._rung_obs
    # model proposals draw on both kinds of observations without error
    c3 = searcher.suggest("t3")
    assert 0 <= c3["x"] <= 1


def test_bohb_end_to_end_with_hyperband():
    grid = tune.Tuner(
        _checkpointing_trainable,
        param_space={"x": tune.uniform(0, 4)},
        tune_config=tune.TuneConfig(
            num_samples=4, metric="score", mode="max",
            search_alg=tune.TuneBOHB(
                {"x": tune.uniform(0, 4)}, metric="score", mode="max",
                n_initial=2, seed=5,
            ),
            scheduler=tune.HyperBandForBOHB(
                metric="score", mode="max", max_t=4, reduction_factor=2
            ),
        ),
        run_config=tune.RunConfig(name="bohb", storage_path=tempfile.mkdtemp()),
    ).fit()
    assert len(grid) == 4
    best = grid.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] >= 0


def test_hyperopt_adapter_gated():
    """Without the hyperopt package the adapter fails with a pointer to the
    native TPESearch (air-gapped-pod guidance), like OptunaSearch; with it,
    suggestions flow."""
    try:
        searcher = tune.HyperOptSearch(
            {"x": tune.uniform(0, 1)}, metric="score", seed=0
        )
    except ImportError as e:
        assert "TPESearch" in str(e)
        return
    cfg = searcher.suggest("t1")
    assert 0 <= cfg["x"] <= 1
    searcher.on_trial_complete("t1", {"score": cfg["x"]})
