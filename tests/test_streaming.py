"""Streaming generators: num_returns="streaming" -> ObjectRefGenerator.

Reference shapes: python/ray/tests/test_streaming_generator.py (ObjectRefStream,
task_manager.h:177 owns the stream; items consumable while the task still runs).
"""

import time

import pytest

import ray_tpu


def test_task_streaming_basic(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def countdown(n):
        for i in range(n):
            yield i * 10

    gen = countdown.remote(5)
    assert isinstance(gen, ray_tpu.ObjectRefGenerator)
    values = [ray_tpu.get(ref, timeout=60) for ref in gen]
    assert values == [0, 10, 20, 30, 40]


def test_streaming_overlaps_production(ray_start_regular):
    """The first item must be consumable well before the producer finishes."""

    @ray_tpu.remote(num_returns="streaming")
    def slow_stream():
        for i in range(4):
            yield i
            time.sleep(2.0)

    gen = slow_stream.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(gen), timeout=60)
    elapsed = time.monotonic() - t0
    assert first == 0
    # Producer takes ~8s total; item 0 arriving well before that proves
    # consumption overlaps production. The generous margin absorbs worker
    # spawn time on loaded 1-core CI hosts.
    assert elapsed < 6.0
    rest = [ray_tpu.get(r, timeout=60) for r in gen]
    assert rest == [1, 2, 3]


def test_streaming_mid_stream_error(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def broken():
        yield 1
        yield 2
        raise RuntimeError("stream broke")

    gen = broken.remote()
    assert ray_tpu.get(next(gen), timeout=60) == 1
    assert ray_tpu.get(next(gen), timeout=60) == 2
    with pytest.raises(RuntimeError, match="stream broke"):
        ray_tpu.get(next(gen), timeout=60)
    with pytest.raises(StopIteration):
        next(gen)


def test_streaming_plasma_sized_items(ray_start_regular):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def arrays():
        for i in range(3):
            yield np.full(200_000, float(i))

    total = 0.0
    for ref in arrays.remote():
        total += float(ray_tpu.get(ref, timeout=60).sum())
    assert total == 200_000.0 * (0 + 1 + 2)


def test_actor_streaming_method(ray_start_regular):
    @ray_tpu.remote
    class Streamer:
        def stream(self, n):
            for i in range(n):
                yield f"item-{i}"

    s = Streamer.remote()
    gen = s.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r, timeout=60) for r in gen] == ["item-0", "item-1", "item-2"]


def test_async_actor_streaming(ray_start_regular):
    @ray_tpu.remote
    class AsyncStreamer:
        async def agen(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 2

    a = AsyncStreamer.remote()
    gen = a.agen.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r, timeout=60) for r in gen] == [0, 2, 4, 6]


def test_streaming_bad_function_error(ray_start_regular):
    """A failure before the first yield terminates the stream with an error ref."""

    @ray_tpu.remote(num_returns="streaming")
    def bad(x):
        raise ValueError("no stream for you")
        yield x  # pragma: no cover

    gen = bad.remote(1)
    with pytest.raises(ValueError, match="no stream for you"):
        ray_tpu.get(next(gen), timeout=60)


def test_async_for_consumption(ray_start_regular):
    """async for over the generator must end with StopAsyncIteration, not the
    RuntimeError Python makes of StopIteration crossing an executor Future."""
    import asyncio

    @ray_tpu.remote(num_returns="streaming")
    def nums(n):
        for i in range(n):
            yield i

    async def consume():
        out = []
        async for ref in nums.remote(3):
            out.append(ray_tpu.get(ref, timeout=60))
        return out

    assert asyncio.run(consume()) == [0, 1, 2]


def test_actor_death_aborts_stream(ray_start_regular):
    """Killing the actor mid-stream unblocks the consumer with an error instead
    of hanging forever."""

    @ray_tpu.remote
    class Infinite:
        def stream(self):
            i = 0
            while True:
                yield i
                i += 1
                time.sleep(0.1)

    a = Infinite.remote()
    gen = a.stream.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(gen), timeout=60) == 0
    ray_tpu.kill(a)
    with pytest.raises(Exception):  # ActorDiedError / WorkerCrashedError at some index
        for _ in range(10_000):
            ray_tpu.get(next(gen), timeout=30)


def test_streaming_interleaved_with_plain_calls(ray_start_regular):
    """A streaming call between plain calls must not wedge the actor's ordered
    direct send queue (regression: a raylet-detoured streaming seq left a
    permanent hole and every later call hung)."""

    @ray_tpu.remote
    class Mixed:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def stream(self, k):
            for i in range(k):
                yield i

    a = Mixed.remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    gen = a.stream.options(num_returns="streaming").remote(3)
    # Plain calls AFTER the streaming call must still execute.
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 2
    assert [ray_tpu.get(r, timeout=60) for r in gen] == [0, 1, 2]
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 3
