"""Platform services tests: state API, ActorPool, Queue, multiprocessing Pool,
metrics, job submission.

Shape parity: reference python/ray/tests/test_state_api*.py, test_actor_pool.py,
test_queue.py, test_multiprocessing.py, test_metrics*.py, dashboard job tests.
"""

import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import state
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.metrics import Counter, Gauge, Histogram, collect_all, prometheus_text
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_state_lists():
    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.remote()
    assert ray_tpu.get(p.ping.remote()) == "pong"
    nodes = state.list_nodes()
    assert len(nodes) >= 1
    actors = state.list_actors()
    assert any(a.get("class_name") == "Pinger" for a in actors)
    # task events reach the GCS on a flush interval: poll briefly
    deadline = time.time() + 15
    while time.time() < deadline:
        tasks = state.list_tasks()
        if any("ping" in str(t.get("name", "")) for t in tasks):
            break
        time.sleep(0.5)
    else:
        raise AssertionError(f"no ping task event in {tasks[:5]}")
    summary = state.cluster_summary()
    assert summary["alive_nodes"] >= 1
    assert "CPU" in summary["resources_total"]


def test_state_filters_pagination_and_drilldown():
    """Comparison filters, pagination, and per-entity drill-down (parity:
    python/ray/util/state predicates + `ray get`)."""

    @ray_tpu.remote
    class Probe:
        def ping(self):
            return "pong"

    probes = [Probe.remote() for _ in range(3)]
    for p in probes:
        assert ray_tpu.get(p.ping.remote(), timeout=60) == "pong"
    alive = state.list_actors(filters=[("state", "=", "ALIVE"),
                                       ("class_name", "=", "Probe")])
    assert len(alive) >= 3
    assert state.list_actors(
        filters=[("class_name", "=", "Probe"), ("state", "!=", "ALIVE")]
    ) == []
    # pagination slices deterministically
    page1 = state.list_actors(filters=[("class_name", "=", "Probe")], limit=2)
    page2 = state.list_actors(filters=[("class_name", "=", "Probe")], limit=2,
                              offset=2)
    assert len(page1) == 2 and len(page2) >= 1
    ids = {a["actor_id"].hex() for a in page1} | {
        a["actor_id"].hex() for a in page2
    }
    assert len(ids) >= 3
    # numeric comparison op
    assert state.list_actors(filters=[("num_restarts", "<", 1)])
    # drill-down: one actor, one task's full event history
    target = alive[0]
    got = state.get_actor(target["actor_id"].hex())
    assert got is not None and got.get("class_name") == "Probe"
    deadline = time.time() + 20
    while time.time() < deadline:
        tasks = state.list_tasks(filters=[("name", "=", "ping")])
        if tasks:
            break
        time.sleep(0.5)
    assert tasks, "no ping task events"
    history = state.get_task(tasks[0]["task_id"])
    assert history and [e.get("time") for e in history] == sorted(
        e.get("time") for e in history
    )
    for p in probes:
        ray_tpu.kill(p)


def test_timeline_chrome_trace_export(tmp_path):
    """`ray_tpu timeline` capability (reference: `ray timeline` Chrome trace
    export): spans carry ph/ts/dur and the file is valid trace JSON."""
    import json

    @ray_tpu.remote
    def traced_work():
        time.sleep(0.05)
        return 1

    ray_tpu.get([traced_work.remote() for _ in range(3)], timeout=120)
    out = str(tmp_path / "trace.json")
    deadline = time.time() + 20
    while time.time() < deadline:
        events = state.timeline(out)
        spans = [e for e in events if e.get("ph") == "X"
                 and "traced_work" in str(e.get("name"))]
        if len(spans) >= 3:
            break
        time.sleep(0.5)
    assert len(spans) >= 3, events[:5]
    for span in spans:
        assert span["dur"] >= 0 and span["ts"] > 0 and "pid" in span
    loaded = json.load(open(out))  # Perfetto-loadable: plain JSON array
    assert isinstance(loaded, list) and len(loaded) == len(events)


def test_memory_summary_by_owner():
    """`ray_tpu memory` capability (reference: `ray memory`): live objects
    grouped by owner with sizes."""
    import numpy as np

    refs = [ray_tpu.put(np.zeros(300_000, np.uint8)) for _ in range(3)]
    deadline = time.time() + 20
    while time.time() < deadline:
        summary = state.memory_summary()
        big = [o for o in summary["objects"] if (o.get("size") or 0) >= 300_000]
        if len(big) >= 3:
            break
        time.sleep(0.5)
    assert len(big) >= 3, summary["objects"][:5]
    assert summary["total_bytes"] >= 900_000
    owners = {o.get("owner_worker_id") for o in big}
    assert owners and None not in owners, "objects missing owner attribution"
    top_owner = max(summary["by_owner"].items(), key=lambda kv: kv[1]["bytes"])
    assert top_owner[1]["bytes"] >= 900_000
    del refs


def test_actor_pool_ordered_and_unordered():
    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    pool = ActorPool([Sq.remote(), Sq.remote()])
    assert list(pool.map(lambda a, v: a.sq.remote(v), range(8))) == [i * i for i in range(8)]
    out = sorted(pool.map_unordered(lambda a, v: a.sq.remote(v), range(8)))
    assert out == sorted(i * i for i in range(8))


def test_queue_blocking_and_nowait():
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Exception):
        q.put(3, block=False)
    assert q.get() == 1
    assert q.qsize() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


def test_multiprocessing_pool():
    with Pool(processes=2) as pool:
        assert pool.map(_sq_for_pool, range(10)) == [i * i for i in range(10)]
        assert pool.starmap(_add_for_pool, [(1, 2), (3, 4)]) == [3, 7]
        r = pool.apply_async(_sq_for_pool, (6,))
        assert r.get(timeout=60) == 36
        assert sorted(pool.imap_unordered(_sq_for_pool, range(6), chunksize=2)) == [
            i * i for i in range(6)
        ]


def _sq_for_pool(x):
    return x * x


def _add_for_pool(a, b):
    return a + b


def test_metrics_roundtrip():
    c = Counter("test_requests_total", "test counter", ("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("test_inflight", "gauge")
    g.set(7)
    h = Histogram("test_latency", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)
    for m in (c, g, h):
        m.flush()
    all_metrics = collect_all()
    names = {m["name"] for m in all_metrics}
    assert {"test_requests_total", "test_inflight", "test_latency"} <= names
    text = prometheus_text()
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert "test_inflight 7" in text


def test_job_submission_end_to_end(tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "job.py"
    script.write_text("print('hello from job'); import sys; sys.exit(0)\n")
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    status = client.wait_until_status(job_id, timeout=120)
    assert status == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_reported(tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "bad.py"
    script.write_text("raise SystemExit(3)\n")
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    assert client.wait_until_status(job_id, timeout=120) == JobStatus.FAILED


def test_job_attaches_to_cluster(tmp_path):
    """The entrypoint can init against the running cluster and use actors."""
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "attach.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"  # picks up RAY_TPU_ADDRESS + RAY_TPU_RAYLET_PORT
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x + 1\n"
        "assert ray_tpu.get(f.remote(41)) == 42\n"
        "print('attached ok')\n"
    )
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    status = client.wait_until_status(job_id, timeout=120)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "attached ok" in logs


def test_actor_pool_survives_task_errors():
    @ray_tpu.remote
    class Flaky:
        def f(self, x):
            if x == 2:
                raise ValueError("flaky")
            return x

    pool = ActorPool([Flaky.remote()])
    for v in range(4):
        pool.submit(lambda a, v: a.f.remote(v), v)
    results = []
    errors = 0
    while pool.has_next():
        try:
            results.append(pool.get_next(timeout=60))
        except ValueError:
            errors += 1
    assert errors == 1 and results == [0, 1, 3]  # actor returned after the error


def test_queue_batches_atomic():
    q = Queue(maxsize=3)
    q.put_nowait_batch([1, 2])
    with pytest.raises(Exception):
        q.put_nowait_batch([3, 4])  # would exceed maxsize: nothing inserted
    assert q.qsize() == 2
    with pytest.raises(Empty):
        q.get_nowait_batch(3)  # only 2 present: nothing popped
    assert q.get_nowait_batch(2) == [1, 2]
    q.shutdown()


def test_pool_initializer_runs_for_map():
    with Pool(processes=2, initializer=_set_flag_for_pool, initargs=(5,)) as pool:
        assert pool.map(_read_flag_for_pool, range(4)) == [5] * 4


def _set_flag_for_pool(v):
    import builtins

    builtins._rtpu_pool_flag = v


def _read_flag_for_pool(_x):
    import builtins

    return getattr(builtins, "_rtpu_pool_flag", None)


def test_prometheus_histogram_exposition():
    h = Histogram("expo_latency", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)
    h.observe(50)
    h.flush()
    text = prometheus_text()
    assert 'expo_latency_bucket{le="1"} 1.0' in text
    assert 'expo_latency_bucket{le="10"} 2.0' in text
    assert 'expo_latency_bucket{le="+Inf"} 3.0' in text
    assert "expo_latency_count 3.0" in text
    assert "expo_latency_sum 55.5" in text


def test_chaos_actor_killer_and_recovery():
    from ray_tpu._private.test_utils import ActorKiller

    @ray_tpu.remote(max_restarts=3)
    class Victim:
        def ping(self):
            return "ok"

    actors = [Victim.remote() for _ in range(2)]
    assert all(ray_tpu.get(a.ping.remote()) == "ok" for a in actors)
    killer = ActorKiller(class_name="Victim", interval_s=0.2, max_to_kill=1, seed=0)
    killer.run()
    deadline = time.time() + 30
    while time.time() < deadline and not killer.killed:
        time.sleep(0.2)
    killed = killer.stop()
    assert len(killed) == 1
    # max_restarts>0: the killed actor comes back
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            assert all(
                ray_tpu.get(a.ping.remote(), timeout=30) == "ok" for a in actors
            )
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("killed actor did not recover")


def test_timeline_chrome_trace(tmp_path):
    import json

    @ray_tpu.remote
    def traced_task():
        time.sleep(0.05)
        return 1

    ray_tpu.get([traced_task.remote() for _ in range(3)])
    trace_file = tmp_path / "trace.json"
    deadline = time.time() + 20
    while time.time() < deadline:
        ray_tpu.timeline(str(trace_file))
        trace = json.loads(trace_file.read_text())
        if any(e["name"] == "traced_task" for e in trace):
            break
        time.sleep(0.5)
    trace = json.loads(trace_file.read_text())
    slices = [e for e in trace if e["name"] == "traced_task"]
    assert slices and all(e["ph"] == "X" and e["dur"] >= 0 for e in slices)


def test_iter_torch_batches():
    import torch

    from ray_tpu import data as rd

    ds = rd.range(64)
    batches = list(ds.iter_torch_batches(batch_size=16, dtypes={"id": torch.float32}))
    assert len(batches) == 4
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)
    assert all(b["id"].dtype == torch.float32 for b in batches)
    assert float(sum(b["id"].sum() for b in batches)) == sum(range(64))


def test_inspect_serializability():
    import threading

    from ray_tpu.util.check_serialize import inspect_serializability

    lock = threading.Lock()

    def bad_fn():
        return lock  # unpicklable closure

    ok, failures = inspect_serializability(bad_fn)
    assert not ok
    assert any("lock" in f for f in failures)
    ok2, failures2 = inspect_serializability(lambda: 42)
    assert ok2 and not failures2


def test_dashboard_endpoints():
    import json as _json
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    class Dash:
        def ping(self):
            return 1

    d = Dash.remote()
    ray_tpu.get(d.ping.remote())
    port = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(base + "/", timeout=30) as r:
            assert b"ray_tpu dashboard" in r.read()
        with urllib.request.urlopen(base + "/api/cluster", timeout=30) as r:
            summary = _json.loads(r.read())
        assert summary["alive_nodes"] >= 1
        with urllib.request.urlopen(base + "/api/actors", timeout=30) as r:
            actors = _json.loads(r.read())
        assert any(a["class_name"] == "Dash" for a in actors)
        with urllib.request.urlopen(base + "/api/nodes", timeout=30) as r:
            assert _json.loads(r.read())
        # round-3 operability surface: metrics history, prometheus, log viewer
        import time as _t

        deadline = _t.monotonic() + 30
        hist = []
        while _t.monotonic() < deadline and len(hist) < 2:
            with urllib.request.urlopen(base + "/api/metrics_history", timeout=30) as r:
                hist = _json.loads(r.read())
            _t.sleep(1.0)
        assert len(hist) >= 2, "metrics sampler produced no history"
        assert hist[-1]["cpu_total"] > 0 and "task_events_rate" in hist[-1]
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
        # the worker that ran Dash.ping has logged at least its banner by now;
        # poll briefly (the log monitor ships every 0.5s)
        @ray_tpu.remote
        def chatty():
            print("dashboard-log-viewer-probe")
            return 1

        ray_tpu.get(chatty.remote())
        deadline = _t.monotonic() + 30
        workers = []
        while _t.monotonic() < deadline:
            with urllib.request.urlopen(base + "/api/log_workers", timeout=30) as r:
                workers = _json.loads(r.read())
            if workers:
                break
            _t.sleep(0.5)
        assert workers, "no worker logs retained for the viewer"
        found = False
        for w in workers:
            with urllib.request.urlopen(
                base + f"/api/worker_log?worker={w['worker']}&limit=200", timeout=30
            ) as r:
                lines = _json.loads(r.read())
            if any("dashboard-log-viewer-probe" in ln for ln in lines):
                found = True
                break
        assert found, "probe line never reached the log viewer"
        # round-4 per-library views (reference: dashboard serve/train/data
        # modules): serve apps + proxy ports, train runs, data executions.
        from ray_tpu import data as rdata

        @serve.deployment
        def dashping(request):
            return "ok"

        serve.run(dashping.bind(), name="dash_app", route_prefix="/dashping")
        with urllib.request.urlopen(base + "/api/serve", timeout=60) as r:
            sv = _json.loads(r.read())
        assert "dash_app" in sv["apps"]
        assert sv["apps"]["dash_app"]["deployments"]["dashping"]["target"] == 1
        assert sv["proxy_ports"]
        serve.delete("dash_app")

        rdata.range(32).map_batches(lambda b: b).take_all()
        # Stats publish lands after the consumer is unblocked (off the
        # completion critical path): poll briefly.
        deadline = _t.monotonic() + 30
        executions = []
        while _t.monotonic() < deadline and not executions:
            with urllib.request.urlopen(base + "/api/data", timeout=60) as r:
                executions = _json.loads(r.read())
            _t.sleep(0.5)
        assert executions, "no data execution stats published"
        assert any(
            any("MapBatches" in op["name"] for op in ex["ops"])
            for ex in executions
        )
        with urllib.request.urlopen(base + "/api/train", timeout=60) as r:
            assert isinstance(_json.loads(r.read()), list)
    finally:
        stop_dashboard()
        serve.shutdown()
