"""Token streaming (docs/generation.md): TokenStream at the engine, SSE-shaped
generate_stream at the serve layer, and the mid-stream-disconnect cancel plane.

The contract under test: a streamed request is token-identical to its
blocking twin; closing a stream mid-flight cancels the request, frees the
slot within one scheduler iteration, and finishes the flight record as
`cancelled` (not an SLO breach); a stalled consumer is shed at the buffer
cap instead of growing host memory. This suite runs under the leaksan +
distsan autouse guards, so every path here must balance its books.
"""

import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield
    serve.shutdown()


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def _engine(tiny, **kw):
    from ray_tpu.llm import DecodeEngine

    cfg, params = tiny
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 128)
    return DecodeEngine(cfg, params, **kw)


def _wait_idle(engine, timeout=30.0):
    """Poll until the scheduler holds zero active work (cancel-to-free is
    one scheduler iteration; the poll absorbs CI timer jitter only)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = engine.scheduler_stats()
        if not st.get("running") and not st.get("prefilling"):
            return st
        time.sleep(0.05)
    raise AssertionError(f"engine never went idle: {engine.scheduler_stats()}")


def test_open_stream_tokens_match_blocking(tiny):
    from ray_tpu.llm import SamplingParams

    engine = _engine(tiny)
    try:
        acc, done = [], threading.Event()

        def cb(tok, fin):
            acc.append(tok)
            if fin:
                done.set()

        engine.submit(list(b"hi"), SamplingParams(max_tokens=8), cb)
        assert done.wait(300)
        blocking = [t for t in acc if t >= 0]

        stream = engine.open_stream(list(b"hi"), SamplingParams(max_tokens=8))
        streamed = list(stream)  # iteration closes on exhaustion
        assert streamed == blocking
    finally:
        engine.shutdown()


def test_stream_get_timeout_raises_stream_closed(tiny):
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.generate import StreamClosed

    engine = _engine(tiny)
    try:
        stream = engine.open_stream(list(b"x"), SamplingParams(max_tokens=2))
        try:
            got = []
            while True:
                tok, fin = stream.get(timeout=120)
                if tok >= 0:
                    got.append(tok)
                if fin:
                    break
            assert len(got) == 2
            with pytest.raises(StreamClosed):
                stream.get(timeout=0.05)  # drained: nothing further arrives
        finally:
            stream.close()
    finally:
        engine.shutdown()


def test_mid_stream_disconnect_cancels_and_frees_slot(tiny):
    """The disconnect path end to end at the engine: close() on a live
    stream cancels the request, the slot frees within one scheduler
    iteration, and the record retires as `cancelled`."""
    from ray_tpu.llm import SamplingParams

    engine = _engine(tiny)
    try:
        before = engine.recorder_stats()["cancelled"]
        stream = engine.open_stream(
            list(b"stream"), SamplingParams(max_tokens=120),
            request_id="disconnect-me",
        )
        tok, fin = stream.get(timeout=120)
        assert tok >= 0 and not fin  # mid-flight, provably
        stream.close()
        st = _wait_idle(engine)
        assert st["queue_depth"] == 0
        assert engine.recorder_stats()["cancelled"] == before + 1
    finally:
        engine.shutdown()


def test_stalled_consumer_shed_at_buffer_cap(tiny):
    """A consumer that never drains must not buffer without bound: past
    `buffer_cap` undelivered tokens the stream cancels its own request."""
    from ray_tpu.llm import SamplingParams

    engine = _engine(tiny)
    try:
        stream = engine.open_stream(
            list(b"y"), SamplingParams(max_tokens=120), buffer_cap=4,
        )
        try:
            assert stream._finished.wait(120)  # self-cancel finished it
            delivered = list(stream)
            assert len(delivered) < 120, "cap never shed the request"
            _wait_idle(engine)
            assert engine.recorder_stats()["cancelled"] >= 1
        finally:
            stream.close()
    finally:
        engine.shutdown()


def test_fixture_catches_planted_token_stream_leak():
    """The leaksan contract for the streaming plane: a TokenStream opened
    and never closed grows the `token_stream` kind; closing clears it."""
    from ray_tpu.devtools import leaksan
    from ray_tpu.llm.generate import TokenStream

    class _StubEngine:
        def cancel(self, rid):
            return True

    before = leaksan.snapshot()
    stream = TokenStream(_StubEngine(), "planted-stream", buffer_cap=0)
    growth = leaksan.check_growth(before, settle_s=0.2)
    assert "token_stream" in growth, growth
    stream.close()
    assert leaksan.check_growth(before, settle_s=0.2) == {}


# -- serve layer: generate_stream through a real deployment -------------------


@pytest.fixture(scope="module")
def llm_handle(_cluster):
    from ray_tpu.llm import LLMConfig, build_llm_deployment

    # max_seq is deliberately large: the disconnect test needs a request
    # whose natural completion is far beyond the cancel round-trip, so the
    # cancel is provably what retired it.
    app = build_llm_deployment(
        LLMConfig(model_id="test-tiny", num_slots=2, max_seq=4096))
    handle = serve.run(app, name="llm-stream", route_prefix=None,
                       _timeout_s=240)
    yield handle
    serve.delete("llm-stream")


def test_serve_generate_stream_matches_blocking(llm_handle):
    out = llm_handle.generate.remote("hi", max_tokens=8).result(timeout_s=240)
    gen = llm_handle.options(stream=True).generate_stream.remote(
        "hi", max_tokens=8)
    try:
        streamed = "".join(gen)
    finally:
        gen.close()
    assert streamed == out["text"]


def test_serve_stream_disconnect_cancels_replica_request(llm_handle):
    """The full disconnect chain: handle-side close() -> replica cancel
    event -> endpoint generator finally -> TokenStream.close -> engine
    cancel. The replica's engine must retire the request as `cancelled`
    and return to idle — a vanished client must not pin a decode slot."""
    before = llm_handle.recorder_stats.remote().result(timeout_s=120)["cancelled"]
    gen = llm_handle.options(stream=True).generate_stream.remote(
        "stream me", max_tokens=4000)
    first = next(iter(gen))  # provably mid-flight
    assert isinstance(first, str) and first
    gen.close()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        stats = llm_handle.recorder_stats.remote().result(timeout_s=120)
        sched = llm_handle.scheduler_stats.remote().result(timeout_s=120)
        if (stats["cancelled"] >= before + 1
                and not sched.get("running") and not sched.get("prefilling")):
            return
        time.sleep(0.25)
    raise AssertionError(
        f"disconnect never retired the request: {stats} / {sched}")
