"""Offline RL: CQL and IQL learn from a logged behavior dataset via ray_tpu.data.

Shape parity with the reference suite (rllib/algorithms/cql/tests/test_cql.py,
rllib/algorithms/iql/tests/): train on offline transitions only, then evaluate
greedy rollouts — the learned policy must beat the behavior policy that logged
the data (the whole point of conservative / implicit offline RL).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


class _OneStepBoxEnv:
    """One-step continuous env: reward = -(a - 0.5)^2, optimum at a=0.5."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._obs = np.array([0.3, -0.7], np.float32)

    def reset(self, *, seed=None, options=None):
        return self._obs, {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        return self._obs, -((a - 0.5) ** 2), True, False, {}

    def close(self):
        pass


def _behavior_dataset(n_rows: int = 3000, seed: int = 0):
    """Log a mixed behavior policy: half uniform exploration, half a noisy
    near-expert — the classic offline-RL setting where the learner must keep to
    the data support (CQL) / regress the good quantile (IQL) to beat the logger.
    Returns (ray_tpu.data Dataset, behavior mean return)."""
    rng = np.random.default_rng(seed)
    obs = np.array([0.3, -0.7], np.float32)
    n_rand = n_rows // 2
    a_rand = rng.uniform(-1, 1, size=n_rand)
    a_exp = np.clip(rng.normal(0.5, 0.2, size=n_rows - n_rand), -1, 1)
    actions = np.concatenate([a_rand, a_exp]).astype(np.float32)
    rewards = -((actions - 0.5) ** 2)
    rows = [
        {
            "obs": obs,
            "actions": np.array([a], np.float32),
            "rewards": float(r),
            "next_obs": obs,
            "dones": 1.0,
        }
        for a, r in zip(actions, rewards)
    ]
    import ray_tpu.data as rd

    return rd.from_items(rows), float(rewards.mean())


def test_offline_data_sources():
    from ray_tpu.rllib import OfflineData

    batches = [{"obs": np.zeros((4, 2))}, {"obs": np.ones((4, 2))}]
    src = OfflineData(batches, batch_size=4)
    assert src.next(1)["obs"].sum() == 0
    assert src.next(2)["obs"].sum() == 8  # round-robin

    calls = []
    src = OfflineData(lambda: calls.append(1) or {"obs": np.zeros((2, 2))}, 2)
    src.next(1)
    src.next(2)
    assert len(calls) == 2

    with pytest.raises(ValueError):
        OfflineData(None, 4)


def test_cql_beats_behavior_policy():
    """VERDICT r2 #5: CQL on the offline path, fed by ray_tpu.data."""
    from ray_tpu.rllib import CQLConfig

    ds, behavior_mean = _behavior_dataset()
    config = (
        CQLConfig()
        .environment(lambda cfg: _OneStepBoxEnv())
        .training(train_batch_size=1500, minibatch_size=256, lr=3e-3,
                  n_updates_per_iter=40, cql_alpha=1.0, cql_n_actions=4,
                  initial_alpha=0.2, model={"hiddens": (64, 64)})
        .debugging(seed=0)
    ).offline(ds)
    algo = config.build_algo()
    try:
        last = {}
        for _ in range(6):
            last = algo.train()
        assert np.isfinite(last["learner/critic_loss"])
        assert np.isfinite(last["learner/cql_penalty"])
        ev = algo.evaluate(num_episodes=5)
        # behavior logs average about -0.3; greedy CQL should be near-optimal
        assert ev["evaluation/episode_return_mean"] > behavior_mean + 0.1
        assert ev["evaluation/episode_return_mean"] > -0.1, ev
    finally:
        algo.stop()


def test_iql_beats_behavior_policy():
    """VERDICT r2 #5: IQL on the offline path, fed by ray_tpu.data."""
    from ray_tpu.rllib import IQLConfig

    ds, behavior_mean = _behavior_dataset()
    config = (
        IQLConfig()
        .environment(lambda cfg: _OneStepBoxEnv())
        .training(train_batch_size=1500, minibatch_size=256, lr=3e-3,
                  n_updates_per_iter=40, expectile=0.8, beta=3.0,
                  model={"hiddens": (64, 64)})
        .debugging(seed=0)
    ).offline(ds)
    algo = config.build_algo()
    try:
        last = {}
        for _ in range(6):
            last = algo.train()
        assert np.isfinite(last["learner/v_loss"])
        assert np.isfinite(last["learner/q_loss"])
        # expectile-regressed V sits above the dataset mean return for good states
        ev = algo.evaluate(num_episodes=5)
        assert ev["evaluation/episode_return_mean"] > behavior_mean + 0.1
        assert ev["evaluation/episode_return_mean"] > -0.1, ev
    finally:
        algo.stop()


def test_iql_checkpoint_roundtrip(tmp_path):
    """Target critics are Learner state — save/restore must carry them."""
    import jax

    from ray_tpu.rllib import IQLConfig

    ds, _ = _behavior_dataset(400)
    config = (
        IQLConfig()
        .environment(lambda cfg: _OneStepBoxEnv())
        .training(train_batch_size=400, minibatch_size=128,
                  n_updates_per_iter=4, model={"hiddens": (32,)})
        .debugging(seed=0)
    ).offline(ds)
    algo = config.build_algo()
    try:
        algo.train()
        path = algo.save_to_path(str(tmp_path / "iql"))
        algo2 = config.copy().offline(ds).build_algo()
        try:
            algo2.restore_from_path(path)
            for a, b in zip(
                jax.tree_util.tree_leaves(algo.learner_group.get_target()),
                jax.tree_util.tree_leaves(algo2.learner_group.get_target()),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        finally:
            algo2.stop()
    finally:
        algo.stop()
