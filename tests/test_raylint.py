"""raylint: each checker fires on its seeded fixture, honors suppressions,
respects the baseline — and the shipped tree is clean (the tier-1 gate)."""

import json
import os
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu.devtools.raylint import (
    Finding,
    lint_file,
    lint_paths,
    load_baseline,
    partition_baselined,
)
from ray_tpu.devtools.raylint.cli import main as raylint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "raylint_fixtures")
PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))


def _codes_by_symbol(findings):
    out = {}
    for f in findings:
        out.setdefault(f.symbol.rsplit(".", 1)[-1], set()).add(f.code)
    return out


def _fixture(name):
    return lint_file(os.path.join(FIXTURES, name))


# ---- each checker fires on seeded violations, and only there ---------------

def test_rl101_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl101.py"))
    assert found.get("bad_await_under_lock") == {"RL101"}
    assert found.get("bad_await_under_global_lock") == {"RL101"}
    for sym in ("suppressed_await_under_lock", "ok_async_lock",
                "ok_lock_released_before_await", "ok_sync_closure_under_async"):
        assert sym not in found


def test_rl102_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl102.py"))
    for sym in ("bad_sleep", "bad_queue_get", "bad_lock_acquire",
                "bad_subprocess", "bad_ray_get"):
        assert found.get(sym) == {"RL102"}, sym
    for sym in ("suppressed_sleep", "ok_awaited_get", "ok_wait_for",
                "ok_nonblocking", "ok_executor", "ok_sync_code"):
        assert sym not in found, sym


def test_rl201_fires_on_opposite_order_only():
    findings = _fixture("case_rl201.py")
    cycles = [f for f in findings if f.code == "RL201"]
    assert len(cycles) == 1
    assert "Store._alpha_lock" in cycles[0].message
    assert "Store._beta_lock" in cycles[0].message
    assert "Clean" not in cycles[0].symbol


def test_rl201_cross_file_graph(tmp_path):
    # Opposite acquisition orders living in DIFFERENT files still form a
    # cycle: the graph is per run, not per file.
    a = tmp_path / "mod_a.py"
    b = tmp_path / "mod_b.py"
    # Lock identity is class-qualified, so a class whose methods live in two
    # files (mixins, _impl splits) still composes into one graph.
    a.write_text(
        "class Pool:\n"
        "    def fwd(self):\n"
        "        with self._x_lock:\n"
        "            with self._y_lock:\n"
        "                return 1\n"
    )
    b.write_text(
        "class Pool:\n"
        "    def bwd(self):\n"
        "        with self._y_lock:\n"
        "            with self._x_lock:\n"
        "                return 2\n"
    )
    per_file = lint_file(str(a)) + lint_file(str(b))
    assert not [f for f in per_file if f.code == "RL201"]
    both = lint_paths([str(tmp_path)])
    assert [f for f in both if f.code == "RL201"]


def test_rl301_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl301.py"))
    assert found.get("bad_override") == {"RL301"}
    assert found.get("bad_deep_store") == {"RL301"}
    assert found.get("bad_module_mutation") == {"RL301"}
    assert found.get("overrides") == {"RL302"}  # BadSchema.overrides
    for sym in ("suppressed_override", "ok_copied_override",
                "ok_param_own_attr", "ok_locked_module_mutation", "OkSchema"):
        assert sym not in found, sym


def test_rl401_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl401.py"))
    assert found.get("control_loop") == {"RL401"}
    assert found.get("rpc_submit") == {"RL401"}
    for sym in ("suppressed", "ok_documented", "ok_logged",
                "ok_failure_value", "ok_teardown", "ok_plain_sync"):
        assert sym not in found, sym


def test_rl501_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl501.py"))
    for sym in ("bad_fire_and_forget", "bad_dropped_execute",
                "bad_dropped_execute_async"):
        assert found.get(sym) == {"RL501"}, sym
    for sym in ("suppressed_fire_and_forget", "ok_kept_ref", "ok_gotten"):
        assert sym not in found, sym


# ---- jaxlint family (RL6xx/RL7xx) -------------------------------------------

def test_rl601_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl601.py"))
    assert found.get("bad_jit_in_loop") == {"RL601"}
    assert found.get("bad_inline_jit") == {"RL601"}
    for sym in ("suppressed_inline", "ok_cached_call", "__init__",
                "<module>"):
        assert sym not in found, sym


def test_rl602_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl602.py"))
    assert found.get("bad_unbounded") == {"RL602"}
    for sym in ("suppressed_store", "ok_bounded"):
        assert sym not in found, sym


def test_rl603_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl603.py"))
    for sym in ("bad_sync_in_loop", "bad_item_in_loop", "_helper_pull",
                "bad_async_sync"):
        assert found.get(sym) == {"RL603"}, sym
    for sym in ("suppressed_sync", "ok_sync_after_loop", "ok_host_values"):
        assert sym not in found, sym


def test_rl604_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl604.py"))
    for sym in ("bad_list_arg", "bad_list_display", "bad_unbucketed_shape"):
        assert found.get(sym) == {"RL604"}, sym
    for sym in ("suppressed_list", "ok_bucketed", "ok_array"):
        assert sym not in found, sym


def test_rl605_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl605.py"))
    assert found.get("bad_read_after_donate") == {"RL605"}
    for sym in ("suppressed_read", "ok_rebound", "ok_undonated"):
        assert sym not in found, sym


def test_rl701_fires_and_suppresses():
    findings = _fixture("case_rl701.py")
    by_symbol = {}
    for f in findings:
        by_symbol.setdefault(f.symbol, set()).add(f.code)
    assert by_symbol.get("BadModule._forward") == {"RL701"}
    assert by_symbol.get("bad_closure_append.bad_scan_body") == {"RL701"}
    # a traced-fn check must not leak onto same-named plain methods
    assert "OkSameName.bad_scan_body" not in by_symbol
    assert "SuppressedModule._forward" not in by_symbol
    assert "ok_local_state.ok_scan_body" not in by_symbol


# ---- leaklint family (RL8xx) ------------------------------------------------

def test_rl801_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl801.py"))
    for sym in ("bad_never_released", "bad_conditional_release",
                "bad_risky_gap", "bad_discarded", "bad_pin_no_release"):
        assert found.get(sym) == {"RL801"}, sym
    for sym in ("ok_with", "ok_try_finally", "ok_returned", "ok_stored",
                "ok_passed_on", "ok_immediate_release", "ok_pin_finally",
                "grab", "drop", "suppressed_leak"):
        assert sym not in found, sym


def test_rl801_adapter_pin_fires_and_suppresses():
    """The round-13 RESOURCE_TABLE entry (AdapterCache.acquire ->
    AdapterHandle.release) flows through the same RL801 path analysis as the
    lease/pin obligations."""
    found = _codes_by_symbol(_fixture("case_rl8_adapter.py"))
    for sym in ("bad_adapter_pin_never_released", "bad_adapter_pin_conditional",
                "bad_adapter_pin_risky_gap"):
        assert found.get(sym) == {"RL801"}, sym
    for sym in ("ok_adapter_pin_with", "ok_adapter_pin_finally",
                "ok_adapter_pin_stored", "ok_adapter_pin_returned",
                "suppressed_adapter_pin"):
        assert sym not in found, sym


def test_rl801_gcs_repl_fires_and_suppresses():
    """The round-14 RESOURCE_TABLE entries (GcsCandidate.open_peer ->
    PeerLink.close, acquire_lease -> LeaseToken.release) flow through the
    same RL801 path analysis: a deposed primary stranding follower links or
    a released-but-held lease is the leak class they encode."""
    found = _codes_by_symbol(_fixture("case_rl8_gcsrepl.py"))
    for sym in ("bad_peer_link_never_closed", "bad_peer_link_conditional",
                "bad_lease_never_released", "bad_lease_risky_gap"):
        assert found.get(sym) == {"RL801"}, sym
    for sym in ("ok_peer_link_stored", "ok_peer_link_finally",
                "ok_lease_stored_for_demotion", "ok_lease_returned",
                "suppressed_peer_link"):
        assert sym not in found, sym


def test_rl801_kv_shard_pool_fires_and_suppresses():
    """The round-15 RESOURCE_TABLE entry (ShardedKVPool -> free) flows
    through the same RL801 path analysis: a TP replica retiring without
    freeing its mesh-resident KV pool strands every shard's buffer."""
    found = _codes_by_symbol(_fixture("case_rl8_tp.py"))
    for sym in ("bad_kv_pool_never_freed", "bad_kv_pool_conditional",
                "bad_kv_pool_risky_gap"):
        assert found.get(sym) == {"RL801"}, sym
    for sym in ("ok_kv_pool_finally", "ok_kv_pool_stored",
                "ok_kv_pool_returned", "suppressed_kv_pool"):
        assert sym not in found, sym


def test_rl801_kvtier_fires_and_suppresses():
    """The round-17 RESOURCE_TABLE entries (DiskSpillStore.open_spill ->
    commit/close, MulticastDeviceChannel.subscribe -> unsubscribe,
    lease_prefix -> release) flow through the same RL801 path analysis: a
    dangling spill handle, a subscription that back-pressures the multicast
    ring forever, and a fetch lease pinning its chain are the leak classes
    they encode (docs/kvcache.md)."""
    found = _codes_by_symbol(_fixture("case_rl8_kvtier.py"))
    for sym in ("bad_spill_never_closed", "bad_spill_conditional",
                "bad_spill_risky_gap", "bad_subscription_never_released",
                "bad_subscription_conditional",
                "bad_fetch_lease_never_released",
                "bad_fetch_lease_risky_gap"):
        assert found.get(sym) == {"RL801"}, (sym, found.get(sym))
    for sym in ("ok_spill_finally", "ok_spill_with", "ok_spill_returned",
                "suppressed_spill", "ok_subscription_finally",
                "ok_subscription_with", "ok_subscription_stored",
                "suppressed_subscription", "ok_fetch_lease_finally",
                "ok_fetch_lease_returned", "ok_fetch_lease_closure",
                "suppressed_fetch_lease"):
        assert sym not in found, (sym, found.get(sym))


def test_rl801_profiler_capture_fires_and_suppresses():
    """The round-18 RESOURCE_TABLE entry (xprof.start_capture ->
    ProfilerCapture.stop_capture/close) flows through the same RL801 path
    analysis: a capture never stopped keeps jax.profiler tracing for the
    rest of the process's life (docs/observability.md)."""
    found = _codes_by_symbol(_fixture("case_rl8_xprof.py"))
    for sym in ("bad_capture_never_stopped", "bad_capture_conditional",
                "bad_capture_risky_gap"):
        assert found.get(sym) == {"RL801"}, (sym, found.get(sym))
    for sym in ("ok_capture_finally", "ok_capture_close_finally",
                "ok_capture_stored", "ok_capture_returned",
                "suppressed_capture"):
        assert sym not in found, (sym, found.get(sym))


def test_rl801_autopilot_scale_op_table_row():
    """Round 20: the autopilot scale-op token (Autopilot.begin_scale_op ->
    ScaleOp.commit/abort) flows through the same RL801 path analysis: a
    dropped token leaves its decision "pending" forever and a half-applied
    replica target for the next controller restart to replay
    (docs/autoscale.md)."""
    found = _codes_by_symbol(_fixture("case_rl8_autopilot.py"))
    for sym in ("bad_scale_op_never_resolved", "bad_scale_op_conditional",
                "bad_scale_op_risky_gap"):
        assert found.get(sym) == {"RL801"}, (sym, found.get(sym))
    for sym in ("ok_scale_op_finally", "ok_scale_op_abort_finally",
                "ok_scale_op_stored", "ok_scale_op_returned",
                "suppressed_scale_op"):
        assert sym not in found, (sym, found.get(sym))


def test_rl801_generate_modes_table_rows():
    """Round 22: the engine token stream (DecodeEngine.open_stream ->
    TokenStream.close/cancel) and the guided-decoding constraint state
    (Constraint.begin -> ConstraintState.release) flow through the same
    RL801 path analysis: an unclosed stream orphans a decode slot behind a
    vanished consumer, an unreleased constraint state outlives its request
    (docs/generation.md)."""
    found = _codes_by_symbol(_fixture("case_rl8_generate.py"))
    for sym in ("bad_stream_never_closed", "bad_stream_conditional",
                "bad_stream_risky_gap", "bad_constraint_never_released",
                "bad_constraint_conditional"):
        assert found.get(sym) == {"RL801"}, (sym, found.get(sym))
    for sym in ("ok_stream_finally", "ok_stream_cancel_finally",
                "ok_stream_stored", "ok_stream_returned", "suppressed_stream",
                "ok_constraint_finally", "ok_constraint_stored",
                "suppressed_constraint"):
        assert sym not in found, (sym, found.get(sym))


def test_rl802_fires_and_suppresses():
    findings = _fixture("case_rl802.py")
    by_symbol = {}
    for f in findings:
        by_symbol.setdefault(f.symbol, set()).add(f.code)
    assert by_symbol.get("BadGcOnly.__del__") == {"RL802"}
    assert by_symbol.get("BadGcOnlyRemote.__del__") == {"RL802"}
    for sym in ("OkExplicitPath.__del__", "OkDelegatesToOwnMethod.__del__",
                "SuppressedGcOnly.__del__"):
        assert sym not in by_symbol, sym


def test_rl803_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl803.py"))
    assert found.get("bad_use_after_release") == {"RL803"}
    assert found.get("bad_double_release") == {"RL803"}
    for sym in ("ok_rebound", "ok_single_release", "suppressed_use"):
        assert sym not in found, sym


def test_rl804_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl804.py"))
    assert found.get("bad_swallowed_release") == {"RL804"}
    assert found.get("bad_cross_lock") == {"RL804"}
    for sym in ("ok_commented_swallow", "ok_narrow_swallow", "ok_same_lock",
                "ok_unlocked_release", "suppressed_cross_lock"):
        assert sym not in found, sym


def test_leaklint_silent_on_canonical_resource_shapes(tmp_path):
    # The shipped recv() shape: acquire -> try/finally release, in a loop.
    f = tmp_path / "canonical.py"
    f.write_text(
        "def recv(transport, n):\n"
        "    out = []\n"
        "    for _ in range(n):\n"
        "        view = transport.read_view()\n"
        "        try:\n"
        "            out.append(bytes(view.mv))\n"
        "        finally:\n"
        "            view.release()\n"
        "    return out\n"
    )
    assert not [x for x in lint_file(str(f)) if x.code.startswith("RL8")]


def test_jaxlint_silent_on_bucketed_jit_pattern():
    # The legitimate engine shape (bucket table + capped program cache +
    # host-native counters + one readback per dispatch) must be finding-free.
    assert _fixture("case_jax_ok.py") == []


def test_jaxlint_skips_files_without_jax(tmp_path):
    # control-plane float()/asarray idioms are out of jaxlint's scope
    f = tmp_path / "hostcode.py"
    f.write_text(
        "import numpy as np\n"
        "def tally(rows):\n"
        "    return [float(r) for r in np.asarray(rows)]\n"
    )
    assert not [x for x in lint_file(str(f)) if x.code.startswith("RL6")]


# ---- distlint family (RL9xx) ------------------------------------------------

def test_rl901_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl901.py"))
    for sym in ("bad_module_metric_inc", "bad_factory_series_observe",
                "bad_data_path_inc", "bad_dict_series_observe",
                "bad_explicit_flush", "_shared_helper"):
        assert found.get(sym) == {"RL901"}, (sym, found.get(sym))
    for sym in ("stats", "_refresh", "report", "on_request",
                "ok_contextvar_set", "ok_plain_counter", "suppressed_inc"):
        assert sym not in found, (sym, found.get(sym))


def test_rl902_fires_and_suppresses():
    findings = _fixture("case_rl902.py")
    by_symbol = {}
    for f in findings:
        by_symbol.setdefault(f.symbol, set()).add(f.code)
    assert by_symbol.get("Holder.__del__") == {"RL902"}
    assert by_symbol.get("_finalize_entry") == {"RL902"}
    assert by_symbol.get("bad_rpc_under_lock") == {"RL902"}
    assert by_symbol.get("bad_kv_verb_under_lock") == {"RL902"}
    assert by_symbol.get(
        "bad_by_name_lookup_in_del._Owner.__del__"
    ) == {"RL902"}
    assert by_symbol.get("bad_connect_under_lock") == {"RL902"}
    assert by_symbol.get("Scheduler.decode_loop") == {"RL902"}
    assert by_symbol.get("Scheduler._place") == {"RL902"}  # hot by propagation
    for sym in ("Holder.close", "Scheduler.scheduler_stats",
                "Scheduler.schedule_step", "ok_plain_method",
                "ok_copy_out_then_call", "ok_socket_connect",
                "suppressed_del_rpc._Owner.__del__"):
        assert sym not in by_symbol, (sym, by_symbol.get(sym))


def test_rl903_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl903.py"))
    for sym in ("BadFormattedInit", "BadDefaultedError", "BadDerivedError"):
        assert found.get(sym) == {"RL903"}, (sym, found.get(sym))
    for sym in ("OkReduceError", "OkVerbatimForward", "OkNoCustomInit",
                "OkPlainFormatter", "SuppressedError"):
        assert sym not in found, (sym, found.get(sym))


def test_rl904_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl904.py"))
    for sym in ("bad_lambda_reads_inside", "bad_named_callback",
                "bad_transitive_callback", "bad_partial_callback",
                "bad_executor_submit", "bad_thread_target"):
        assert found.get(sym) == {"RL904"}, (sym, found.get(sym))
    for sym in ("ok_captured_before_hop", "ok_lambda_closes_over_capture",
                "ok_plain_callback", "suppressed_read_inside",
                "_work_reads_trace", "_work_transitively", "_work_takes_ctx"):
        assert sym not in found, (sym, found.get(sym))


def test_rl905_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl905.py"))
    for sym in ("bad_await_remote_under_lock", "bad_await_gcs_under_lock",
                "bad_await_helper_under_lock", "bad_sync_helper_under_lock"):
        assert found.get(sym) == {"RL905"}, (sym, found.get(sym))
    for sym in ("ok_await_outside_lock", "ok_local_await_under_lock",
                "ok_sync_helper_outside_lock", "ok_local_helper_under_lock",
                "suppressed_await_under_lock", "_dispatch",
                "_refresh_placement"):
        assert sym not in found, (sym, found.get(sym))


def test_distlint_silent_on_report_path_shapes(tmp_path):
    # The blessed shape: data paths bump plain ints; stats() mutates the
    # gauges and does the control-plane round-trips.
    f = tmp_path / "blessed.py"
    f.write_text(
        "from ray_tpu.util.metrics import Gauge\n"
        "class Plane:\n"
        "    def __init__(self):\n"
        "        self._depth = Gauge('depth')\n"
        "        self._n = 0\n"
        "    def on_request(self):\n"
        "        self._n += 1\n"
        "    def stats(self, worker):\n"
        "        self._depth.set(float(self._n))\n"
        "        return {'kv': worker.gcs_call('kv_keys', 'ns', b'')}\n"
    )
    assert not [x for x in lint_file(str(f)) if x.code.startswith("RL9")]


# ---- baseline ---------------------------------------------------------------

def test_baseline_grandfathers_by_symbol():
    findings = _fixture("case_rl501.py")
    entries = [{"file": "case_rl501.py", "code": "RL501",
                "symbol": "bad_fire_and_forget", "reason": "test"}]
    violations, grandfathered, stale = partition_baselined(findings, entries)
    assert {f.symbol for f in grandfathered} == {"bad_fire_and_forget"}
    assert all(f.symbol != "bad_fire_and_forget" for f in violations)
    assert not stale


def test_baseline_reports_stale_entries():
    entries = [{"file": "case_rl501.py", "code": "RL999",
                "symbol": "nope", "reason": "obsolete"}]
    _v, _g, stale = partition_baselined(_fixture("case_rl501.py"), entries)
    assert stale == entries


def test_checked_in_baseline_entries_are_justified():
    for entry in load_baseline():
        assert entry.get("reason"), f"baseline entry missing reason: {entry}"
        assert "TODO" not in entry["reason"], entry


# ---- the gate: the shipped tree is clean ------------------------------------

def test_shipped_tree_has_zero_nonbaselined_findings():
    findings = lint_paths([PKG_DIR])
    violations, _grandfathered, stale = partition_baselined(
        findings, load_baseline()
    )
    assert not violations, "\n" + "\n".join(f.render() for f in violations)
    assert not stale, f"stale baseline entries: {stale}"


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(actor):\n    actor.ping.remote()\n")
    assert raylint_main([str(bad)]) == 1
    good = tmp_path / "good.py"
    good.write_text("def f(actor):\n    return actor.ping.remote()\n")
    assert raylint_main([str(good)]) == 0


def test_cli_baselined_only_exits_zero_even_when_reported(tmp_path):
    """The CI contract: exit reflects UNBASELINED findings only.
    --no-baseline widens what is reported, never what fails."""
    bad = tmp_path / "bad.py"
    bad.write_text("def f(actor):\n    actor.ping.remote()\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "bad.py", "code": "RL501", "symbol": "f", "reason": "test"}
    ]}))
    assert raylint_main([str(bad), "--baseline", str(base)]) == 0
    assert raylint_main(
        [str(bad), "--baseline", str(base), "--no-baseline"]
    ) == 0


def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(actor):\n    actor.ping.remote()\n")
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"entries": []}))
    assert raylint_main(
        [str(bad), "--baseline", str(empty), "--format", "json"]
    ) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["exit"] == 1
    assert doc["summary"] == {"violations": 1, "baselined": 0, "stale": 0}
    (v,) = doc["violations"]
    assert v["code"] == "RL501" and v["file"] == "bad.py" and v["line"] == 2
    assert v["symbol"] == "f" and v["message"]

    base = tmp_path / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "bad.py", "code": "RL501", "symbol": "f", "reason": "test"}
    ]}))
    assert raylint_main(
        [str(bad), "--baseline", str(base), "--format", "json"]
    ) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["exit"] == 0 and doc["summary"]["baselined"] == 1
    assert doc["baselined"][0]["code"] == "RL501"


def test_cli_fail_stale(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "gone.py", "code": "RL501", "symbol": "f", "reason": "old"}
    ]}))
    assert raylint_main([str(good), "--baseline", str(base)]) == 0
    assert raylint_main(
        [str(good), "--baseline", str(base), "--fail-stale"]
    ) == 1


def test_shipped_tree_clean_per_family():
    """The tier-1 gate, per family: the concurrency checkers (RL1xx-RL5xx),
    the jaxlint compute-plane checkers (RL6xx/RL7xx), the leaklint
    resource-lifetime checkers (RL8xx), the distlint distributed-contract
    checkers (RL9xx), and the apilint cross-process call-contract checkers
    (RL10xx) must EACH report zero unbaselined findings over the shipped
    package."""
    from ray_tpu.devtools.raylint.core import FAMILIES

    assert set(FAMILIES) == {"concurrency", "jax", "leak", "dist", "api"}
    findings = lint_paths([PKG_DIR])
    entries = load_baseline()
    for name, codes in FAMILIES.items():
        fam = [f for f in findings if f.code in codes]
        violations, _g, _s = partition_baselined(fam, entries)
        assert not violations, (
            name + ":\n" + "\n".join(f.render() for f in violations)
        )


def test_cli_only_and_family_filters(tmp_path):
    """`--only RL8xx` / `--family` run one lint plane in isolation: findings
    from other planes neither fail the run nor count as stale; the exit
    contract itself is unchanged."""
    mixed = tmp_path / "mixed.py"
    mixed.write_text(
        # RL501 (discarded .remote) AND RL801 (discarded read_view lease)
        "def f(actor, chan):\n"
        "    actor.ping.remote()\n"
        "    chan.read_view()\n"
    )
    assert raylint_main([str(mixed)]) == 1
    # leak plane alone: the RL501 finding does not count
    base = tmp_path / "leak_base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "mixed.py", "code": "RL801", "symbol": "f", "reason": "test"}
    ]}))
    assert raylint_main(
        [str(mixed), "--only", "RL8xx", "--baseline", str(base)]
    ) == 0
    assert raylint_main(
        [str(mixed), "--family", "leak", "--baseline", str(base)]
    ) == 0
    # concurrency plane alone: the RL801 baseline entry is not "stale"
    # for a run that never selected RL8xx
    base2 = tmp_path / "conc_base.json"
    base2.write_text(json.dumps({"entries": [
        {"file": "mixed.py", "code": "RL501", "symbol": "f", "reason": "t"},
        {"file": "mixed.py", "code": "RL801", "symbol": "f", "reason": "t"},
    ]}))
    assert raylint_main(
        [str(mixed), "--family", "concurrency", "--baseline", str(base2),
         "--fail-stale"]
    ) == 0
    # unknown pattern is a usage error (exit 2), per the documented contract
    assert raylint_main([str(mixed), "--only", "RL0xx"]) == 2
    # unknown family is a usage error too
    assert raylint_main([str(mixed), "--family", "nope"]) == 2


def test_cli_family_comma_list(tmp_path):
    """`--family a,b,...` unions the families — the one-invocation tier-1
    gate shape (`--family concurrency,jax,leak,dist`)."""
    mixed = tmp_path / "mixed.py"
    mixed.write_text(
        # RL501 (discarded .remote) AND RL901 (metric inc outside report path)
        "from ray_tpu.util.metrics import Counter\n"
        "C = Counter('c')\n"
        "def f(actor):\n"
        "    actor.ping.remote()\n"
        "    C.inc()\n"
    )
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"entries": []}))
    # each family alone sees only its own finding
    base_dist = tmp_path / "dist_base.json"
    base_dist.write_text(json.dumps({"entries": [
        {"file": "mixed.py", "code": "RL901", "symbol": "f", "reason": "t"}
    ]}))
    assert raylint_main(
        [str(mixed), "--family", "dist", "--baseline", str(base_dist)]
    ) == 0
    # the union sees both
    both = tmp_path / "both_base.json"
    both.write_text(json.dumps({"entries": [
        {"file": "mixed.py", "code": "RL901", "symbol": "f", "reason": "t"},
        {"file": "mixed.py", "code": "RL501", "symbol": "f", "reason": "t"},
    ]}))
    assert raylint_main(
        [str(mixed), "--family", "concurrency,dist", "--baseline", str(both)]
    ) == 0
    assert raylint_main(
        [str(mixed), "--family", "concurrency,dist",
         "--baseline", str(base_dist)]
    ) == 1


def test_cli_changed_lints_only_git_changed_files(tmp_path):
    """--changed scopes the run to git's changed/untracked .py files (the
    pre-commit shape); unmatched baseline entries are not stale for it."""
    import subprocess as sp

    repo = tmp_path / "repo"
    repo.mkdir()
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
           # the linter runs from inside the scratch repo: keep ray_tpu
           # importable without an install
           "PYTHONPATH": os.path.dirname(PKG_DIR)}
    sp.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    committed = repo / "committed.py"
    committed.write_text("def f(actor):\n    actor.ping.remote()\n")
    sp.run(["git", "add", "-A"], cwd=repo, check=True, env=env)
    sp.run(["git", "commit", "-qm", "seed"], cwd=repo, check=True, env=env)

    def run(*extra):
        return sp.run(
            [sys.executable, "-m", "ray_tpu.devtools.raylint", "--changed",
             "--baseline", str(repo / "nope.json"), *extra],
            cwd=repo, capture_output=True, text=True, timeout=120, env=env,
        )

    # nothing changed: the committed violation is out of scope
    assert run().returncode == 0
    # an untracked violating file IS in scope
    (repo / "fresh.py").write_text("def g(actor):\n    actor.ping.remote()\n")
    proc = run()
    assert proc.returncode == 1 and "fresh.py" in proc.stdout
    assert "committed.py" not in proc.stdout
    # a clean changed file, with a baseline covering OTHER files: not stale
    (repo / "fresh.py").write_text("x = 1\n")
    base = repo / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "elsewhere.py", "code": "RL501", "symbol": "f",
         "reason": "t"}
    ]}))
    proc = sp.run(
        [sys.executable, "-m", "ray_tpu.devtools.raylint", "--changed",
         "--baseline", str(base), "--fail-stale"],
        cwd=repo, capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_module_entrypoint_clean_tree():
    """The tier-1 gate as CI invokes it — all five families in one
    invocation: zero unbaselined findings AND zero stale baseline entries —
    a fixed-but-still-baselined finding fails loudly instead of lingering
    as a grandfather clause nobody re-earns."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.raylint",
         "--family", "concurrency,jax,leak,dist,api", "--fail-stale", PKG_DIR],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_emit_baseline(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(actor):\n    actor.ping.remote()\n")
    assert raylint_main(["--emit-baseline", str(bad)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["entries"] and doc["entries"][0]["code"] == "RL501"


def test_disable_file_directive(tmp_path):
    f = tmp_path / "all_off.py"
    f.write_text(
        "# raylint: disable-file=RL501\n"
        "def f(actor):\n    actor.ping.remote()\n"
    )
    assert not lint_file(str(f))


# ---- apilint: the RL10xx cross-process call-contract family ----------------

def test_rl1001_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl1001.py"))
    for sym in ("bad_attr_handle_typo", "bad_tracked_handle_typo",
                "bad_options_chain_typo", "bad_untracked_unknown_everywhere"):
        assert found.get(sym) == {"RL1001"}, (sym, found)
    for sym in ("ok_attr_handle", "ok_tracked_handle",
                "ok_untracked_but_known_somewhere", "ok_dynamic_class",
                "suppressed_tracked_typo"):
        assert sym not in found, sym


def test_rl1002_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl1002.py"))
    for sym in ("bad_ctor_too_many_args", "bad_ctor_missing_required",
                "bad_unknown_kwarg", "bad_positional_overflow",
                "bad_remote_function_arity"):
        assert found.get(sym) == {"RL1002"}, (sym, found)
    for sym in ("ok_ctor", "ok_generate", "ok_vararg_target",
                "ok_dynamic_call_shape", "suppressed_unknown_kwarg"):
        assert sym not in found, sym


def test_rl1003_fires_and_suppresses():
    findings = _fixture("case_rl1003.py")
    found = _codes_by_symbol(findings)
    assert found.get("PartialStats") == {"RL1003"}
    assert found.get("SignalNoActuator") == {"RL1003"}
    assert found.get("DriftedShutdown") == {"RL1003"}
    for sym in ("WholeSurface", "EngineInternal", "SuppressedPartial"):
        assert sym not in found, sym
    # the message names what's missing, so the fix is mechanical
    partial = [f for f in findings if f.symbol == "PartialStats"][0]
    assert "recorder_stats" in partial.message
    assert "capture_profile" in partial.message


def test_rl1004_fires_and_suppresses():
    findings = _fixture("case_rl1004.py")
    found = _codes_by_symbol(findings)
    assert found.get("bad_unknown_flag_read") == {"RL1004"}
    assert found.get("bad_unknown_flag_get") == {"RL1004"}
    for sym in ("ok_known_reads", "ok_get_with_default", "ok_dynamic_read",
                "suppressed_unknown_read"):
        assert sym not in found, sym
    # did-you-mean suggestion in the typo message
    typo = [f for f in findings if f.symbol == "bad_unknown_flag_read"][0]
    assert "did you mean 'llm_block_size'" in typo.message
    # dead flags anchor at their _DEFS line; the suppressed one stays quiet
    dead = [f for f in findings if f.symbol == "_DEFS"]
    assert len(dead) == 1 and "dead_flag_fires" in dead[0].message


def test_rl1005_fires_and_suppresses():
    found = _codes_by_symbol(_fixture("case_rl1005.py"))
    for sym in ("bad_lambda_arg", "bad_local_function", "bad_open_handle",
                "bad_inline_open", "bad_lock_arg"):
        assert found.get(sym) == {"RL1005"}, (sym, found)
    for sym in ("ok_module_function", "ok_plain_values",
                "ok_reassigned_handle", "suppressed_lambda"):
        assert sym not in found, sym


def test_rl1006_fires_and_suppresses():
    findings = _fixture("case_rl1006.py")
    found = _codes_by_symbol(findings)
    assert found.get("bad_unknown_verb") == {"RL1006"}
    # verb arity is the same binding contract as every cross-process call
    assert found.get("bad_verb_arity") == {"RL1002"}
    assert found.get("rpc_orphan_handler") == {"RL1006"}
    for sym in ("ok_known_verb", "ok_default_arg_verb", "ok_dynamic_verb",
                "suppressed_unknown_verb", "rpc_suppressed_orphan",
                "rpc_unrelated"):
        assert sym not in found, sym
    unknown = [f for f in findings if f.symbol == "bad_unknown_verb"][0]
    assert "did you mean 'kv_put'" in unknown.message


def test_planted_defects_produce_expected_codes(tmp_path):
    """The acceptance probe: four planted defects in a small fixture TREE
    (cross-file — the registry is tree-wide) each produce exactly the
    expected RL10xx code."""
    (tmp_path / "server.py").write_text(
        "class Server:\n"
        "    def __init__(self, model_id):\n"
        "        self.model_id = model_id\n"
        "    def generate(self, prompt, max_tokens=64):\n"
        "        return prompt\n"
        "    def cache_stats(self):\n"
        "        return {}\n"
        "    def scheduler_stats(self):\n"
        "        return {}\n"
    )
    (tmp_path / "flags.py").write_text(
        "_DEFS = {\n"
        "    'slots': (int, 4, 'decode slots'),\n"
        "}\n"
    )
    (tmp_path / "driver.py").write_text(
        "from server import Server\n"
        "from flags import _DEFS\n"
        "class CONFIG: pass\n"
        "def drive(serve):\n"
        "    serve.deployment(name='s')(Server)\n"
        "    h = Server.remote('m')\n"
        "    a = h.generate_stream.remote('hi')\n"       # typo'd method
        "    b = h.generate.remote('hi', max_token=8)\n"  # bad kwarg
        "    return a, b, CONFIG.slotz + CONFIG.slots\n"  # unknown flag read
    )
    findings = lint_paths([str(tmp_path)])
    codes_by_line = {}
    for f in findings:
        codes_by_line.setdefault((f.path.rsplit("/", 1)[-1], f.line),
                                 set()).add(f.code)
    assert codes_by_line.get(("driver.py", 7)) == {"RL1001"}
    assert codes_by_line.get(("driver.py", 8)) == {"RL1002"}
    assert codes_by_line.get(("driver.py", 9)) == {"RL1004"}
    # roster-incomplete protocol class (deployed in driver.py, defined in
    # server.py): exactly RL1003, anchored at the class definition
    assert codes_by_line.get(("server.py", 1)) == {"RL1003"}
    assert len(findings) == 4, "\n".join(f.render() for f in findings)


def test_cli_only_rl10xx_and_json_for_api_family(tmp_path, capsys):
    """`--only RL10xx` isolates the api plane; `--format json` carries its
    findings with the same schema as every other family."""
    mixed = tmp_path / "mixed.py"
    mixed.write_text(
        # RL501 (discarded .remote) AND RL1001 (typo'd tracked method)
        "class A:\n"
        "    def ping(self):\n"
        "        return 1\n"
        "def f():\n"
        "    h = A.remote()\n"
        "    h.ping.remote()\n"
        "    h.pnig.remote()\n"
    )
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"entries": []}))
    assert raylint_main(
        [str(mixed), "--baseline", str(empty), "--only", "RL10xx",
         "--format", "json"]
    ) == 1
    doc = json.loads(capsys.readouterr().out)
    assert {v["code"] for v in doc["violations"]} == {"RL1001"}
    assert raylint_main(
        [str(mixed), "--baseline", str(empty), "--family", "api",
         "--format", "json"]
    ) == 1
    doc = json.loads(capsys.readouterr().out)
    assert {v["code"] for v in doc["violations"]} == {"RL1001"}
    # the concurrency finding exists when the api filter is off
    assert raylint_main([str(mixed), "--baseline", str(empty),
                         "--family", "concurrency", "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert {v["code"] for v in doc["violations"]} == {"RL501"}


def test_cli_changed_covers_api_family(tmp_path):
    """--changed + --family api: an untracked file with a cross-process
    contract violation is caught pre-commit; the registry is built from the
    changed set (self-contained files, the fixture shape)."""
    import subprocess as sp

    repo = tmp_path / "repo"
    repo.mkdir()
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
           "PYTHONPATH": os.path.dirname(PKG_DIR)}
    sp.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    (repo / "seed.py").write_text("x = 1\n")
    sp.run(["git", "add", "-A"], cwd=repo, check=True, env=env)
    sp.run(["git", "commit", "-qm", "seed"], cwd=repo, check=True, env=env)
    (repo / "fresh.py").write_text(
        "class A:\n"
        "    def ping(self):\n"
        "        return 1\n"
        "def f():\n"
        "    h = A.remote()\n"
        "    h.pnig.remote()\n"
    )
    proc = sp.run(
        [sys.executable, "-m", "ray_tpu.devtools.raylint", "--changed",
         "--family", "api", "--baseline", str(repo / "nope.json")],
        cwd=repo, capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 1 and "RL1001" in proc.stdout, (
        proc.stdout + proc.stderr
    )
