"""Runtime environment tests: env_vars, working_dir, py_modules for tasks/actors.

Shape parity: reference python/ray/tests/test_runtime_env*.py (the env_vars/
working_dir plugins; package-installing plugins are a documented later round).
"""

import os

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_task_env_vars_applied_and_restored():
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "abc"}})
    def with_env():
        return os.environ.get("RTPU_TEST_FLAG")

    @ray_tpu.remote
    def without_env():
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(with_env.remote()) == "abc"
    # the shared worker must NOT leak the env var into other tasks
    assert ray_tpu.get(without_env.remote()) is None


def test_task_working_dir(tmp_path):
    (tmp_path / "data.txt").write_text("from working dir")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_relative():
        with open("data.txt") as f:
            return f.read()

    assert ray_tpu.get(read_relative.remote()) == "from working dir"


def test_py_modules_importable(tmp_path):
    mod_dir = tmp_path / "mods"
    mod_dir.mkdir()
    (mod_dir / "rtpu_test_mod.py").write_text("VALUE = 1234\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_module():
        import rtpu_test_mod

        return rtpu_test_mod.VALUE

    assert ray_tpu.get(use_module.remote()) == 1234


def test_actor_runtime_env_sticky():
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_ACTOR_FLAG": "sticky"}})
    class EnvActor:
        def read(self):
            return os.environ.get("RTPU_ACTOR_FLAG")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "sticky"
    assert ray_tpu.get(a.read.remote()) == "sticky"


def test_options_override_runtime_env():
    @ray_tpu.remote
    def probe():
        return os.environ.get("RTPU_OPT_FLAG")

    ref = probe.options(runtime_env={"env_vars": {"RTPU_OPT_FLAG": "via-options"}}).remote()
    assert ray_tpu.get(ref) == "via-options"


def test_invalid_runtime_env_rejected():
    @ray_tpu.remote(runtime_env={"container": {"image": "x"}})
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.remote()


def test_concurrent_tasks_do_not_observe_env(tmp_path):
    """An env-carrying task runs exclusively: parallel env-free tasks never see
    its env vars or cwd."""

    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_RACE": "yes"}})
    def env_task():
        import time

        time.sleep(0.3)
        return os.environ.get("RTPU_RACE")

    @ray_tpu.remote
    def plain_task(_i):
        import time

        time.sleep(0.05)
        return os.environ.get("RTPU_RACE")

    refs = [env_task.remote()] + [plain_task.remote(i) for i in range(8)]
    out = ray_tpu.get(refs)
    assert out[0] == "yes"
    assert all(v is None for v in out[1:])


def test_stale_py_module_evicted(tmp_path):
    v1 = tmp_path / "v1"
    v2 = tmp_path / "v2"
    v1.mkdir(); v2.mkdir()
    (v1 / "verlib.py").write_text("VERSION = 1\n")
    (v2 / "verlib.py").write_text("VERSION = 2\n")

    @ray_tpu.remote(num_cpus=4)  # force same worker by using all CPUs
    def load(path):
        import verlib

        return verlib.VERSION

    r1 = load.options(runtime_env={"py_modules": [str(v1)]}).remote(str(v1))
    assert ray_tpu.get(r1) == 1
    r2 = load.options(runtime_env={"py_modules": [str(v2)]}).remote(str(v2))
    assert ray_tpu.get(r2) == 2  # must NOT return the cached v1 module


def test_py_modules_string_rejected():
    @ray_tpu.remote(runtime_env={"py_modules": "/tmp/not-a-list"})
    def f():
        return 1

    with pytest.raises(ValueError, match="LIST"):
        f.remote()


@pytest.fixture(scope="module")
def wheel_house(tmp_path_factory):
    """A local wheel house with a tiny package — offline pip's package source."""
    import subprocess
    import sys

    src = tmp_path_factory.mktemp("demo_src")
    (src / "setup.py").write_text(
        'from setuptools import setup\n'
        'setup(name="rtpu-demo-pkg", version="1.0", py_modules=["rtpu_demo_mod"])\n'
    )
    (src / "rtpu_demo_mod.py").write_text("MAGIC = 42\n")
    wheels = tmp_path_factory.mktemp("wheels")
    subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-index", "--no-build-isolation",
         "--no-deps", str(src), "-w", str(wheels)],
        check=True, capture_output=True, timeout=180,
    )
    return wheels


def test_pip_env_task_runs_in_venv(ray_start_regular, wheel_house):
    """A task with a pip runtime_env executes in a dedicated venv worker where
    the requested package is importable (reference: runtime_env/pip.py venvs +
    env-keyed worker pools); env-free workers never see the package."""

    @ray_tpu.remote(
        runtime_env={"pip": {"packages": ["rtpu-demo-pkg"],
                             "find_links": str(wheel_house)}}
    )
    def use_pkg():
        import sys

        import rtpu_demo_mod

        return rtpu_demo_mod.MAGIC, sys.executable

    magic, exe = ray_tpu.get(use_pkg.remote(), timeout=300)
    assert magic == 42
    assert "venv_" in exe  # ran inside the cached env's interpreter

    @ray_tpu.remote
    def plain():
        try:
            import rtpu_demo_mod  # noqa: F401

            return "leaked"
        except ImportError:
            return "clean"

    assert ray_tpu.get(plain.remote(), timeout=120) == "clean"

    # Second use: the venv is cached (same interpreter path), not rebuilt.
    magic2, exe2 = ray_tpu.get(use_pkg.remote(), timeout=120)
    assert (magic2, exe2) == (magic, exe)


def test_pip_env_actor(ray_start_regular, wheel_house):
    @ray_tpu.remote(
        runtime_env={"uv": {"packages": ["rtpu-demo-pkg"],
                            "find_links": str(wheel_house)}}
    )
    class PkgActor:
        def magic(self):
            import rtpu_demo_mod

            return rtpu_demo_mod.MAGIC

    a = PkgActor.remote()
    assert ray_tpu.get(a.magic.remote(), timeout=300) == 42


def test_pip_env_install_failure_fails_task(ray_start_regular, tmp_path):
    @ray_tpu.remote(
        runtime_env={"pip": {"packages": ["definitely-not-a-real-pkg-xyz"],
                             "find_links": str(tmp_path)}}
    )
    def doomed():
        return 1

    with pytest.raises(Exception, match="pip|runtime_env|failed"):
        ray_tpu.get(doomed.remote(), timeout=300)
