"""Fault-tolerance semantics: actor restarts, init failures, task retries.

Reference shapes: python/ray/tests/test_actor_failures.py, test_failure*.py.
"""

import time

import pytest

import ray_tpu


def _wait_for(pred, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_actor_init_failure_is_fatal_and_fast(ray_start_isolated):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot construct")

        def ping(self):
            return "pong"

    t0 = time.monotonic()
    b = Broken.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=30)
    # Fatal __init__ must not burn the full 60-retry scheduling loop.
    assert time.monotonic() - t0 < 20


def test_actor_restart_after_kill(ray_start_isolated):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.count = 0

        def incr(self):
            self.count += 1
            return self.count

    p = Phoenix.remote()
    assert ray_tpu.get(p.incr.remote(), timeout=60) == 1
    ray_tpu.kill(p, no_restart=False)

    def alive_again():
        w = ray_tpu.global_worker()
        info = w.gcs_call("get_actor_info", p._actor_id, None, "")
        return info is not None and info["state"] == "ALIVE" and info["num_restarts"] >= 1

    assert _wait_for(alive_again, timeout=60)
    # State is reset (fresh __init__), calls work again.
    assert ray_tpu.get(p.incr.remote(), timeout=60) == 1


def test_kill_no_restart_overrides_max_restarts(ray_start_isolated):
    @ray_tpu.remote(max_restarts=5)
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(v, no_restart=True)

    def dead():
        w = ray_tpu.global_worker()
        info = w.gcs_call("get_actor_info", v._actor_id, None, "")
        return info is not None and info["state"] == "DEAD"

    assert _wait_for(dead, timeout=30)


def test_dropped_ref_arg_still_usable_by_task(ray_start_isolated):
    """A put() ref passed to a task and immediately dropped must stay pinned."""
    import numpy as np

    @ray_tpu.remote
    def total(arr):
        return float(arr.sum())

    ref = total.remote(ray_tpu.put(np.ones(300_000)))  # put-ref dropped immediately
    import gc

    gc.collect()
    assert ray_tpu.get(ref, timeout=60) == 300_000.0


def test_fire_and_forget_does_not_leak_store(ray_start_isolated):
    """Dropped result refs of plasma-sized returns are freed from the store."""
    import numpy as np

    @ray_tpu.remote
    def big():
        return np.ones(500_000)

    w = ray_tpu.global_worker()
    for _ in range(5):
        big.remote()  # ref dropped immediately

    @ray_tpu.remote
    def ping():
        return 1

    assert ray_tpu.get(ping.remote(), timeout=60) == 1  # cluster still healthy
