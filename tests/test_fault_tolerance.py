"""Fault-tolerance semantics: actor restarts, init failures, task retries.

Reference shapes: python/ray/tests/test_actor_failures.py, test_failure*.py.
"""

import time

import pytest

import ray_tpu


def _wait_for(pred, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_actor_init_failure_is_fatal_and_fast(ray_start_isolated):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot construct")

        def ping(self):
            return "pong"

    t0 = time.monotonic()
    b = Broken.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=30)
    # Fatal __init__ must not burn the full 60-retry scheduling loop.
    assert time.monotonic() - t0 < 20


def test_actor_restart_after_kill(ray_start_isolated):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.count = 0

        def incr(self):
            self.count += 1
            return self.count

    p = Phoenix.remote()
    assert ray_tpu.get(p.incr.remote(), timeout=60) == 1
    ray_tpu.kill(p, no_restart=False)

    def alive_again():
        w = ray_tpu.global_worker()
        info = w.gcs_call("get_actor_info", p._actor_id, None, "")
        return info is not None and info["state"] == "ALIVE" and info["num_restarts"] >= 1

    assert _wait_for(alive_again, timeout=60)
    # State is reset (fresh __init__), calls work again.
    assert ray_tpu.get(p.incr.remote(), timeout=60) == 1


def test_kill_no_restart_overrides_max_restarts(ray_start_isolated):
    @ray_tpu.remote(max_restarts=5)
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(v, no_restart=True)

    def dead():
        w = ray_tpu.global_worker()
        info = w.gcs_call("get_actor_info", v._actor_id, None, "")
        return info is not None and info["state"] == "DEAD"

    assert _wait_for(dead, timeout=30)


def test_dropped_ref_arg_still_usable_by_task(ray_start_isolated):
    """A put() ref passed to a task and immediately dropped must stay pinned."""
    import numpy as np

    @ray_tpu.remote
    def total(arr):
        return float(arr.sum())

    ref = total.remote(ray_tpu.put(np.ones(300_000)))  # put-ref dropped immediately
    import gc

    gc.collect()
    assert ray_tpu.get(ref, timeout=60) == 300_000.0


def test_fire_and_forget_does_not_leak_store(ray_start_isolated):
    """Dropped result refs of plasma-sized returns are freed from the store."""
    import numpy as np

    @ray_tpu.remote
    def big():
        return np.ones(500_000)

    w = ray_tpu.global_worker()
    for _ in range(5):
        big.remote()  # ref dropped immediately

    @ray_tpu.remote
    def ping():
        return 1

    assert ray_tpu.get(ping.remote(), timeout=60) == 1  # cluster still healthy


def test_borrower_keeps_borrowed_object_alive(ray_start_isolated):
    """An actor holding a deserialized ref reports its borrow; the owner must not
    free the object when the owner's own refs die (reference_counter.h borrowing)."""
    import gc

    import numpy as np

    @ray_tpu.remote
    class Holder:
        def hold(self, lst):
            self.ref = lst[0]  # keep the borrowed ObjectRef, not the value
            return "held"

        def fetch(self):
            return float(ray_tpu.get(self.ref).sum())

    h = Holder.remote()
    ref = ray_tpu.put(np.ones(300_000))  # plasma-sized: freed-at-owner would lose it
    assert ray_tpu.get(h.hold.remote([ref]), timeout=120) == "held"
    # The +1 borrow report travels async (actor -> raylet -> owner); wait for it so
    # the del below deterministically exercises the borrow-holds-object path.
    w = ray_tpu.global_worker()
    oid = ref.id
    assert _wait_for(lambda: w.reference_counter.num_borrows(oid) >= 1, timeout=30)
    del ref
    gc.collect()
    time.sleep(1.0)  # give a (buggy) free time to land before the borrower reads
    assert ray_tpu.get(h.fetch.remote(), timeout=120) == 300_000.0


def test_object_reconstruction_after_node_death():
    """A lost plasma object is rebuilt by re-running its producing task from
    lineage (reference: object_recovery_manager.h)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        doomed = cluster.add_node(num_cpus=1, resources={"side": 1}, env_vars=_WORKER_ENV)
        assert cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"side": 1}, num_cpus=0)
        def big():
            return np.full(300_000, 2.0)

        ref = big.remote()
        ready, _ = ray_tpu.wait([ref], timeout=120)
        assert ready  # sealed on the doomed node; never pulled locally
        cluster.remove_node(doomed)
        cluster.add_node(num_cpus=1, resources={"side": 1}, env_vars=_WORKER_ENV)
        assert cluster.wait_for_nodes()

        # Owner-path reconstruction: the driver's get finds zero live copies and
        # re-runs big() on the replacement node.
        arr = ray_tpu.get(ref, timeout=120)
        assert float(arr.sum()) == 600_000.0
    finally:
        cluster.shutdown()


def test_borrower_triggered_reconstruction():
    """A consumer task (borrower) that needs a lost object asks the owner to
    rebuild it from lineage."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        doomed = cluster.add_node(num_cpus=1, resources={"side": 1}, env_vars=_WORKER_ENV)
        assert cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"side": 1}, num_cpus=0)
        def big():
            return np.full(300_000, 2.0)

        @ray_tpu.remote(num_cpus=1)
        def consume(arr):
            return float(arr.sum())

        ref = big.remote()
        ready, _ = ray_tpu.wait([ref], timeout=120)
        assert ready
        cluster.remove_node(doomed)
        cluster.add_node(num_cpus=1, resources={"side": 1}, env_vars=_WORKER_ENV)
        assert cluster.wait_for_nodes()

        # consume runs on the head node; its get() hits "lost" as a borrower and
        # routes a reconstruct_object request to the owner (the driver).
        assert ray_tpu.get(consume.remote(ref), timeout=120) == 600_000.0
    finally:
        cluster.shutdown()


def test_dropped_result_ref_does_not_free_inflight_task_args(ray_start_isolated):
    """Dropping a task's return ref while it is still queued must not release the
    flight pin on its plasma args (regression: lineage taking over the arg pins)."""
    import numpy as np

    @ray_tpu.remote
    class Sink:
        def __init__(self):
            self.v = None

        def put(self, v):
            self.v = v

        def get(self):
            return self.v

    @ray_tpu.remote
    def use(arr, sink):
        ray_tpu.get(sink.put.remote(float(arr.sum())))

    sink = Sink.remote()
    arr_ref = ray_tpu.put(np.ones(300_000))
    use.remote(arr_ref, sink)  # return ref dropped immediately
    del arr_ref  # drop the user's own pin too: only flight/lineage pins remain
    import gc

    gc.collect()
    assert _wait_for(
        lambda: ray_tpu.get(sink.get.remote(), timeout=30) == 300_000.0, timeout=90
    )
