"""Pipeline parallelism: GPipe schedule over the pp mesh axis.

The key property: the pipelined loss AND its gradients match the unpipelined
sequential reference exactly (same layer order, same microbatch-averaged loss),
with autodiff generating the backward pipeline through reversed ppermutes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.pipeline import (
    PipelineState,
    bubble_fraction,
    build_pipeline_loss,
    build_pipeline_train_step,
    init_pipeline_state,
    sequential_reference_loss,
)

V, E, H, T = 31, 16, 32, 12
L = 8  # layers, divisible by pp


def _embed_fn(p, tokens):
    return p["table"][tokens]


def _layer_fn(p, x):
    h = jax.nn.gelu(x @ p["w1"])
    return x + h @ p["w2"]


def _head_loss_fn(p, x, targets):
    logits = x @ p["w"]
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def _make_params(rng):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    scale = 0.1
    return {
        "embed": {"table": scale * jax.random.normal(k1, (V, E))},
        "layers": {
            "w1": scale * jax.random.normal(k2, (L, E, H)),
            "w2": scale * jax.random.normal(k3, (L, H, E)),
        },
        "head": {"w": scale * jax.random.normal(k4, (E, V))},
    }


def _data(rng, batch):
    kt, kl = jax.random.split(rng)
    tokens = jax.random.randint(kt, (batch, T), 0, V)
    targets = jax.random.randint(kl, (batch, T), 0, V)
    return tokens, targets


@pytest.mark.parametrize("axes,batch,microbatches", [
    ({"pp": 4}, 8, 4),
    ({"pp": 2, "dp": 2}, 8, 2),
    ({"pp": 8}, 16, 8),
])
def test_pipeline_matches_sequential(axes, batch, microbatches):
    mesh = mesh_lib.create_mesh(axes)
    params = _make_params(jax.random.PRNGKey(0))
    tokens, targets = _data(jax.random.PRNGKey(1), batch)

    pipe_loss = build_pipeline_loss(
        _embed_fn, _layer_fn, _head_loss_fn, mesh, microbatches
    )
    ref_loss = sequential_reference_loss(_embed_fn, _layer_fn, _head_loss_fn)

    with mesh:
        lp, gp = jax.jit(jax.value_and_grad(pipe_loss))(params, tokens, targets)
    lr, gr = jax.jit(jax.value_and_grad(ref_loss))(params, tokens, targets)

    np.testing.assert_allclose(float(lp), float(lr), rtol=2e-5)
    flat_p, _ = jax.tree_util.tree_flatten(gp)
    flat_r, _ = jax.tree_util.tree_flatten(gr)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6)


def test_pipeline_train_step_learns():
    mesh = mesh_lib.create_mesh({"pp": 4})
    params = _make_params(jax.random.PRNGKey(0))
    optimizer = optax.adam(1e-2)
    state = init_pipeline_state(params, optimizer, mesh)
    step_fn, shardings = build_pipeline_train_step(
        _embed_fn, _layer_fn, _head_loss_fn, optimizer, mesh, num_microbatches=4
    )
    tokens, _ = _data(jax.random.PRNGKey(1), 8)
    targets = tokens  # learn the identity mapping: loss must drop fast
    batch = {
        "tokens": jax.device_put(tokens, shardings["tokens"]),
        "targets": jax.device_put(targets, shardings["targets"]),
    }
    with mesh:
        state, first = step_fn(state, batch)
        for _ in range(30):
            state, metrics = step_fn(state, batch)
    assert float(metrics["loss"]) < 0.5 * float(first["loss"])
    assert int(state.step) == 31


def test_bubble_fraction():
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(2, 14) == pytest.approx(1 / 15)


def test_pipeline_rejects_bad_shapes():
    mesh = mesh_lib.create_mesh({"pp": 2})
    loss = build_pipeline_loss(_embed_fn, _layer_fn, _head_loss_fn, mesh, 3)
    params = _make_params(jax.random.PRNGKey(0))
    tokens, targets = _data(jax.random.PRNGKey(1), 8)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="divisible"):
        with mesh:
            jax.jit(loss)(params, tokens, targets)

    with pytest.raises(ValueError, match="pp axis"):
        build_pipeline_loss(
            _embed_fn, _layer_fn, _head_loss_fn, mesh_lib.create_mesh({"dp": 2}), 2
        )


_OLD_JAX = not hasattr(jax, "typeof")


@pytest.mark.skipif(
    _OLD_JAX,
    reason="manual-pp + auto-tp composition needs the vma-typed shard_map partitioner (jax>=0.6); 0.4.x SPMD rejects PartitionId inside a partially-auto body",
)
@pytest.mark.parametrize("axes,specs", [
    # tp shards the layer matmuls' hidden dim and the head's vocab dim;
    # XLA inserts the tensor-parallel collectives INSIDE the pipeline
    # (manual pp + auto tp — pipeline.py round-5 composition).
    ({"pp": 2, "tp": 2}, True),
    ({"pp": 2, "dp": 2, "tp": 2}, True),
])
def test_pipeline_composes_with_tp(axes, specs):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.pipeline import place_pipeline_params

    mesh = mesh_lib.create_mesh(axes)
    params = _make_params(jax.random.PRNGKey(0))
    batch = 8
    tokens, targets = _data(jax.random.PRNGKey(1), batch)
    param_specs = {
        "layers": {"w1": P(None, "tp"), "w2": P("tp", None)},
        "head": {"w": P("tp", None)},  # contraction-dim sharding: V=31 is odd
    } if specs else None

    pipe_loss = build_pipeline_loss(
        _embed_fn, _layer_fn, _head_loss_fn, mesh, 4, param_specs=param_specs
    )
    ref_loss = sequential_reference_loss(_embed_fn, _layer_fn, _head_loss_fn)

    with mesh:
        placed = place_pipeline_params(params, mesh, param_specs=param_specs)
        # Placement really is tp-sharded (not a silent replicate).
        w1_sharding = placed["layers"]["w1"].sharding
        assert "tp" in (w1_sharding.spec[2] or ()), w1_sharding.spec
        lp, gp = jax.jit(jax.value_and_grad(pipe_loss))(placed, tokens, targets)
    lr, gr = jax.jit(jax.value_and_grad(ref_loss))(params, tokens, targets)

    np.testing.assert_allclose(float(lp), float(lr), rtol=2e-5)
    flat_p, _ = jax.tree_util.tree_flatten(gp)
    flat_r, _ = jax.tree_util.tree_flatten(gr)
    for a, b in zip(flat_p, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.skipif(
    _OLD_JAX,
    reason="manual-pp + auto-tp composition needs the vma-typed shard_map partitioner (jax>=0.6)",
)
def test_pipeline_train_step_learns_with_tp():
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.create_mesh({"pp": 2, "tp": 2})
    params = _make_params(jax.random.PRNGKey(0))
    optimizer = optax.adam(1e-2)
    param_specs = {
        "layers": {"w1": P(None, "tp"), "w2": P("tp", None)},
        "head": {"w": P("tp", None)},
    }
    state = init_pipeline_state(params, optimizer, mesh, param_specs=param_specs)
    step_fn, shardings = build_pipeline_train_step(
        _embed_fn, _layer_fn, _head_loss_fn, optimizer, mesh,
        num_microbatches=4, param_specs=param_specs,
    )
    tokens, _ = _data(jax.random.PRNGKey(1), 8)
    batch = {
        "tokens": jax.device_put(tokens, shardings["tokens"]),
        "targets": jax.device_put(tokens, shardings["targets"]),
    }
    with mesh:
        state, first = step_fn(state, batch)
        for _ in range(30):
            state, metrics = step_fn(state, batch)
    assert float(metrics["loss"]) < 0.5 * float(first["loss"])
