"""distsan: the runtime distributed-contract sanitizer catches planted
hot-path/finalizer control-plane traffic and stays zero-cost when disabled
(docs/raylint.md §distsan)."""

import threading

import pytest

from ray_tpu.devtools import distsan
from ray_tpu.util.metrics import Counter, Gauge, Histogram


@pytest.fixture(autouse=True)
def _fresh_sanitizer():
    distsan.reset()
    distsan.enable()
    yield
    distsan.reset()
    distsan.disable()


def test_metric_mutation_in_hot_path_flagged():
    """The real util.metrics hook: every mutator may flush, and a flush is
    a blocking GCS RPC — inside a tagged hot loop that's a violation even
    when this particular mutation doesn't flush."""
    c = Counter("distsan_test_counter")
    with distsan.hot_path("test-decode-loop"):
        c.inc()
    found = distsan.violations()
    assert len(found) == 1
    v = found[0]
    assert v["kind"] == "metric_mutation"
    assert v["detail"] == "distsan_test_counter"
    assert v["context"] == "hot"
    assert v["label"] == "test-decode-loop"


def test_all_three_mutators_are_hooked():
    with distsan.hot_path("loop"):
        Counter("distsan_c").inc()
        Gauge("distsan_g").set(1.0)
        Histogram("distsan_h").observe(0.5)
    kinds = [v["detail"] for v in distsan.violations()]
    assert kinds == ["distsan_c", "distsan_g", "distsan_h"]


def test_gcs_call_in_finalizer_flagged():
    with distsan.finalizer("stream-iterator"):
        distsan.note_gcs_call("kv_put")
    found = distsan.violations()
    assert len(found) == 1
    assert found[0]["kind"] == "gcs_call"
    assert found[0]["detail"] == "kv_put"
    assert found[0]["context"] == "finalizer"


def test_report_path_is_the_contract():
    with distsan.report_path("stats"):
        Counter("distsan_report_counter").inc()
        distsan.note_gcs_call("kv_put")
    assert distsan.violations() == []


def test_innermost_tag_decides():
    # A report-path flush invoked FROM a hot loop is fine (that's exactly
    # how stats() collection threads overlap the decode loop)...
    with distsan.hot_path("loop"):
        with distsan.report_path("stats"):
            distsan.note_gcs_call("kv_put")
    assert distsan.violations() == []
    # ...but a hot section entered from a report path is still hot.
    with distsan.report_path("stats"):
        with distsan.hot_path("loop"):
            distsan.note_gcs_call("kv_put")
    assert len(distsan.violations()) == 1


def test_untagged_context_not_asserted():
    # distsan only checks what is tagged: plain data-path traffic is
    # distlint's (static) territory.
    Counter("distsan_untagged").inc()
    distsan.note_gcs_call("kv_get")
    assert distsan.violations() == []


def test_disabled_records_nothing():
    distsan.disable()
    with distsan.hot_path("loop"):
        Counter("distsan_off").inc()
        distsan.note_gcs_call("kv_put")
    assert distsan.violations() == []
    distsan.enable()


def test_enable_mid_tag_stays_balanced():
    """A tag entered while disabled pushes nothing, so enabling inside its
    body must not underflow the stack on exit."""
    distsan.disable()
    with distsan.hot_path("loop"):
        distsan.enable()
        # The tag did not push: this note sees no hot context.
        distsan.note_gcs_call("kv_put")
    assert distsan.violations() == []
    with distsan.hot_path("loop"):
        distsan.note_gcs_call("kv_put")
    assert len(distsan.violations()) == 1


def test_env_var_enables(monkeypatch):
    distsan.reset()
    # Drop the programmatic override so the env decides.
    distsan._enabled_override = None
    monkeypatch.delenv("RAY_TPU_DISTSAN", raising=False)
    assert not distsan.enabled()
    monkeypatch.setenv("RAY_TPU_DISTSAN", "1")
    assert distsan.enabled()


def test_tags_are_thread_local():
    """A hot tag on one thread must not indict another thread's traffic,
    and each violation records the thread it happened on."""
    ready = threading.Event()
    release = threading.Event()

    def hot_holder():
        with distsan.hot_path("holder-loop"):
            ready.set()
            release.wait(5.0)

    t = threading.Thread(target=hot_holder, name="distsan-holder")
    t.start()
    try:
        assert ready.wait(5.0)
        distsan.note_gcs_call("kv_put")  # this thread is untagged
        assert distsan.violations() == []
    finally:
        release.set()
        t.join(5.0)

    def tagged_worker():
        with distsan.finalizer("worker-del"):
            distsan.note_gcs_call("get_actor_info")

    t2 = threading.Thread(target=tagged_worker, name="distsan-worker")
    t2.start()
    t2.join(5.0)
    found = distsan.violations()
    assert len(found) == 1
    assert found[0]["thread"] == "distsan-worker"


def test_violations_snapshot_is_a_copy():
    with distsan.hot_path("loop"):
        distsan.note_gcs_call("kv_put")
    first = distsan.violations()
    first[0]["kind"] = "mutated"
    assert distsan.violations()[0]["kind"] == "gcs_call"
