"""cgroup-v2 worker isolation.

Shape parity with the reference suite (src/ray/common/cgroup2/tests/): drive
the manager against a fake cgroupfs root (injectable via RAY_TPU_CGROUP_BASE)
— the write path is identical, only the kernel is absent — then an end-to-end
cluster test proving the raylet actually places spawned workers and caps
memory-declaring actors.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private.cgroup import CgroupV2Manager, manager_from_env


def test_manager_subtree_and_placement(tmp_path):
    base = tmp_path / "cg"
    base.mkdir()
    (base / "cgroup.subtree_control").write_text("")
    mgr = CgroupV2Manager("sess1", base=str(base),
                          total_memory=8 << 30, system_reserved=2 << 30)
    assert mgr.setup() and mgr.available
    sess = base / "ray_tpu_sess1"
    assert (sess / "system").is_dir() and (sess / "workers").is_dir()
    assert (sess / "system" / "memory.min").read_text() == str(2 << 30)
    assert (sess / "workers" / "memory.max").read_text() == str(6 << 30)
    assert (sess / "cgroup.subtree_control").read_text() == "+memory +cpu"

    assert mgr.place_system_process(111)
    assert (sess / "system" / "cgroup.procs").read_text() == "111"
    assert mgr.place_worker(222)
    # workers/ has subtree_control enabled, so pids live in the shared/ leaf
    # (cgroup-v2 no-internal-process rule), never in workers/ itself.
    assert (sess / "workers" / "shared" / "cgroup.procs").read_text() == "222"
    # declared memory -> dedicated capped sub-group
    assert mgr.place_worker(333, memory_bytes=512 << 20, cpu_weight=50)
    wd = sess / "workers" / "w_333"
    assert (wd / "memory.max").read_text() == str(512 << 20)
    assert (wd / "cpu.weight").read_text() == "50"
    assert (wd / "cgroup.procs").read_text() == "333"

    # procs files would be empty on a real kernel once the proc exits; fake
    # that before reap/teardown (rmdir requires empty dirs either way)
    (wd / "memory.max").unlink()
    (wd / "cpu.weight").unlink()
    (wd / "cgroup.procs").unlink()
    mgr.remove_worker(333)
    assert not wd.exists()
    for f in sess.rglob("*"):
        if f.is_file():
            f.unlink()
    mgr.teardown()
    assert not sess.exists()


def test_manager_unavailable_degrades(tmp_path, monkeypatch):
    mgr = CgroupV2Manager("x", base=str(tmp_path / "missing" / "deep"))
    # parent dir creatable -> setup works; point base at an unwritable path
    ro = tmp_path / "ro"
    ro.mkdir()
    ro.chmod(0o500)
    mgr2 = CgroupV2Manager("x", base=str(ro))
    if os.getuid() != 0:  # root ignores mode bits
        assert not mgr2.setup()
        assert not mgr2.place_worker(1)
    monkeypatch.setenv("RAY_TPU_CGROUP_ISOLATION", "0")
    assert manager_from_env("y") is None


@pytest.fixture
def cgroup_cluster(tmp_path, monkeypatch):
    base = tmp_path / "cgfs"
    base.mkdir()
    monkeypatch.setenv("RAY_TPU_CGROUP_BASE", str(base))
    monkeypatch.setenv("RAY_TPU_CGROUP_ISOLATION", "1")
    from tests.conftest import _WORKER_ENV

    ray_tpu.init(num_cpus=2, num_tpus=0, worker_env=_WORKER_ENV)
    yield base
    ray_tpu.shutdown()


def test_raylet_places_workers_and_caps_memory_actors(cgroup_cluster):
    base = cgroup_cluster

    @ray_tpu.remote
    def f():
        return os.getpid()

    pid = ray_tpu.get(f.remote(), timeout=120)
    sessions = [d for d in base.iterdir() if d.name.startswith("ray_tpu_")]
    assert sessions, "raylet did not create its cgroup session subtree"
    procs = sessions[0] / "workers" / "shared" / "cgroup.procs"
    assert procs.exists() and procs.read_text().strip()

    @ray_tpu.remote(memory=256 << 20)
    class Capped:
        def pid(self):
            return os.getpid()

    a = Capped.remote()
    apid = ray_tpu.get(a.pid.remote(), timeout=120)
    wd = sessions[0] / "workers" / f"w_{apid}"
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not wd.exists():
        time.sleep(0.25)
    assert wd.exists(), "memory-declaring actor got no dedicated cgroup"
    assert (wd / "memory.max").read_text() == str(256 << 20)
    assert (wd / "cgroup.procs").read_text() == str(apid)
    ray_tpu.kill(a)