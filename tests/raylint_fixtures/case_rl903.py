"""RL903 fixtures: exception classes that must survive a .remote()/RPC
pickle round-trip (the exceptions.py __reduce__ idiom made mandatory)."""


class BadFormattedInit(Exception):
    """Default pickling re-calls BadFormattedInit(formatted_message): the
    message lands in task_id and the original args are gone."""

    def __init__(self, task_id):
        self.task_id = task_id
        super().__init__(f"task {task_id} wedged")


class BadDefaultedError(Exception):
    def __init__(self, actor_id=None):
        self.actor_id = actor_id
        super().__init__(f"actor {actor_id} unavailable")


class BadDerivedError(BadFormattedInit):
    def __init__(self, task_id, node):
        self.node = node
        super().__init__(f"{task_id}@{node}")


class OkReduceError(Exception):
    def __init__(self, task_id):
        self.task_id = task_id
        super().__init__(f"task {task_id} wedged")

    def __reduce__(self):
        return type(self), (self.task_id,)


class OkVerbatimForward(Exception):
    def __init__(self, msg):
        super().__init__(msg)
        self.msg = msg


class OkNoCustomInit(Exception):
    pass


class OkPlainFormatter:
    """Formats its ctor args but is no exception class: out of scope."""

    def __init__(self, name):
        self.label = f"<{name}>"


class SuppressedError(Exception):  # raylint: disable=RL903 (fixture: never crosses a process boundary)
    def __init__(self, code):
        super().__init__(f"code {code}")
