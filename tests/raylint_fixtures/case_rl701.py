"""Seeded RL701 violations (side effects inside traced functions)."""

import jax


class BadModule:
    def __init__(self):
        self._trace_log = []
        self._jit_fwd = jax.jit(self._forward)

    def _forward(self, params, x):
        y = x @ params["w"]
        self._last = y                             # RL701: write to self
        self._trace_log.append("fwd")              # RL701: mutator on self
        return y


def bad_closure_append(xs):
    seen = []

    def bad_scan_body(carry, x):
        seen.append(x)                             # RL701: closed-over list
        return carry + x, carry

    return jax.lax.scan(bad_scan_body, 0.0, xs)


class SuppressedModule:
    def __init__(self):
        self._jit_fwd = jax.jit(self._forward)

    def _forward(self, params, x):
        self._trace_count = 1  # raylint: disable=RL701 (trace-time counter, test-only)
        return x @ params["w"]


def ok_local_state(xs):
    def ok_scan_body(carry, x):
        acc = []
        acc.append(x)                              # local list: fine
        return carry + x, carry

    return jax.lax.scan(ok_scan_body, 0.0, xs)


class OkSameName:
    """A method named like a traced nested fn elsewhere must NOT be checked."""

    def bad_scan_body(self, item):
        self._cache = item                         # plain method, not traced
        return item
