"""Seeded RL601 violations (jit constructed in hot paths)."""

import jax


def bad_jit_in_loop(fns, xs):
    out = []
    for f, x in zip(fns, xs):
        step = jax.jit(f)                          # RL601
        out.append(step(x))
    return out


def bad_inline_jit(f, x):
    return jax.jit(f)(x)                           # RL601


def suppressed_inline(f, x):
    return jax.jit(f)(x)  # raylint: disable=RL601 (one-shot init program)


_module_step = jax.jit(lambda x: x + 1)            # ok: module-level, built once


class OkEngine:
    def __init__(self, f):
        self._jit_step = jax.jit(f)                # ok: cached at init

    def ok_cached_call(self, x):
        return self._jit_step(x)
