"""RL802 fixtures: cross-process release reachable only from __del__."""


class _Assigner:
    """Defines the release(token) target so the api-family arity check
    stays quiet: this fixture seeds gc-only releases, not call-shape ones."""

    def release(self, token):
        return token


class BadGcOnly:
    def __init__(self, assigner, token):
        self._assigner = assigner
        self._token = token

    def __del__(self):
        self._assigner.release(self._token)


class BadGcOnlyRemote:
    """The actor-call hop (`.release.remote`) is still a release."""

    def __del__(self):
        try:
            self._assigner.release.remote(self._token)  # raylint: disable=RL501 (fixture: fire-and-forget is the point here)
        except Exception:
            pass  # __del__ must never raise; the release above is the point


class OkExplicitPath:
    def close(self):
        self._assigner.release(self._token)

    def __del__(self):
        self._assigner.release(self._token)


class OkDelegatesToOwnMethod:
    """`self.release()` in __del__ is the GC backstop for a public path."""

    def release(self):
        self._ring.free(self._slot)

    def __del__(self):
        self.release()


class SuppressedGcOnly:
    def __del__(self):
        # raylint: disable=RL802 (fixture: buffer-protocol lifetime IS the contract)
        self._arena.release(self._key)
