"""RL904 fixtures: trace context read on the wrong side of an executor/
thread boundary (contextvars do not cross run_in_executor)."""

import threading
from functools import partial

from ray_tpu.util import tracing


def _work_reads_trace(payload):
    ctx = tracing.current()
    return payload, ctx


def _work_transitively(payload):
    return _work_reads_trace(payload)


def _work_takes_ctx(payload, trace_ctx):
    token = tracing.activate(trace_ctx)
    try:
        return payload
    finally:
        tracing.deactivate(token)


async def bad_lambda_reads_inside(loop, payload):
    return await loop.run_in_executor(
        None, lambda: (payload, tracing.current())
    )


async def bad_named_callback(loop, payload):
    return await loop.run_in_executor(None, _work_reads_trace, payload)


async def bad_transitive_callback(loop, payload):
    return await loop.run_in_executor(None, _work_transitively, payload)


async def bad_partial_callback(loop, payload):
    return await loop.run_in_executor(
        None, partial(_work_reads_trace, payload)
    )


def bad_executor_submit(executor, payload):
    return executor.submit(_work_reads_trace, payload)


def bad_thread_target(payload):
    t = threading.Thread(target=_work_reads_trace, args=(payload,))
    t.start()
    return t


async def ok_captured_before_hop(loop, payload):
    trace_ctx = tracing.current()
    return await loop.run_in_executor(
        None, _work_takes_ctx, payload, trace_ctx
    )


async def ok_lambda_closes_over_capture(loop, payload):
    trace_ctx = tracing.current()
    return await loop.run_in_executor(
        None, lambda: _work_takes_ctx(payload, trace_ctx)
    )


async def ok_plain_callback(loop, q):
    return await loop.run_in_executor(None, q.get)


async def suppressed_read_inside(loop, payload):
    return await loop.run_in_executor(None, _work_reads_trace, payload)  # raylint: disable=RL904 (fixture: span loss accepted for this batch path)
