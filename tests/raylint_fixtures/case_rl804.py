"""RL804 fixtures: swallowed release failures and lock-mismatched release."""


def bad_swallowed_release(chan):
    view = chan.read_view()
    try:
        view.release()
    except Exception:
        pass


def ok_commented_swallow(chan):
    view = chan.read_view()
    try:
        view.release()
    except Exception:
        pass  # slot already recycled by channel close: nothing left to ack


def ok_narrow_swallow(chan):
    view = chan.read_view()
    try:
        view.release()
    except BufferError:
        raise


class LockDiscipline:
    def bad_cross_lock(self, prefix_cache, toks):
        with self._intake_lock:
            lease = prefix_cache.lookup(toks)
        with self._evict_lock:
            lease.release()

    def ok_same_lock(self, prefix_cache, toks):
        with self._state_lock:
            lease = prefix_cache.lookup(toks)
            lease.release()

    def ok_unlocked_release(self, prefix_cache, toks):
        with self._state_lock:
            lease = prefix_cache.lookup(toks)
        lease.release()

    def suppressed_cross_lock(self, prefix_cache, toks):
        with self._intake_lock:
            lease = prefix_cache.lookup(toks)
        with self._evict_lock:
            lease.release()  # raylint: disable=RL804 (fixture: evict lock is taken WITH intake lock held elsewhere)
