"""RL801 fixtures for the mesh-sharded KV pool (ShardedKVPool -> free), the
round-15 RESOURCE_TABLE entry: the fire/suppress shapes mirror
case_rl8_adapter.py so the new obligation rides the exact same path
analysis. A TP replica that drops its pool without free() strands every
shard's device buffer (docs/serving_tp.md)."""


def bad_kv_pool_never_freed(cfg, mesh):
    pool = ShardedKVPool(n_layers=cfg.n_layers, shape=(4, 64, 2, 16),
                         dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)
    return pool.take()


def bad_kv_pool_conditional(cfg, mesh, flag):
    pool = ShardedKVPool(n_layers=cfg.n_layers, shape=(4, 64, 2, 16),
                         dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)
    if flag:
        pool.free()


def bad_kv_pool_risky_gap(cfg, mesh, engine):
    pool = ShardedKVPool(n_layers=cfg.n_layers, shape=(4, 64, 2, 16),
                         dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)
    engine.run(pool.take())
    pool.free()


def ok_kv_pool_finally(cfg, mesh, engine):
    pool = ShardedKVPool(n_layers=cfg.n_layers, shape=(4, 64, 2, 16),
                         dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)
    try:
        return engine.run(pool.take())
    finally:
        pool.free()


def ok_kv_pool_stored(engine, cfg, mesh):
    engine._kv_pool = ShardedKVPool(n_layers=cfg.n_layers,
                                    shape=(4, 64, 2, 16), dtype=cfg.dtype,
                                    mesh=mesh, n_kv_heads=2)


def ok_kv_pool_returned(cfg, mesh):
    return ShardedKVPool(n_layers=cfg.n_layers, shape=(4, 64, 2, 16),
                         dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)


def suppressed_kv_pool(cfg, mesh):
    pool = ShardedKVPool(n_layers=2, shape=(4, 64, 2, 16), dtype=cfg.dtype, mesh=mesh, n_kv_heads=2)  # raylint: disable=RL801 (fixture: engine shutdown frees it)
    return pool.take()
