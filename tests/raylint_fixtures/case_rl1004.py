"""RL1004 fixtures: flag reads absent from _DEFS, and dead declared flags.

Self-contained: this file carries its own _DEFS registry plus the reads,
exactly the shape of _private/config.py + its consumers when the whole
tree is linted in one run.
"""

from typing import Any

_DEFS: dict[str, tuple[type, Any, str]] = {
    "llm_block_size": (int, 16, "KV block size"),
    "llm_slots": (int, 4, "decode slots"),
    "dead_flag_never_read": (int, 0, "nothing reads me"),  # raylint: disable=RL1004 (fixture: reserved for the next migration step)
    "dead_flag_fires": (int, 0, "nothing reads me either"),
}


class CONFIG:
    pass


def bad_unknown_flag_read():
    return CONFIG.llm_blok_size


def bad_unknown_flag_get():
    return CONFIG.get("llm_slotz")


def ok_known_reads():
    return CONFIG.llm_block_size + CONFIG.llm_slots


def ok_get_with_default(name):
    # an explicit fallback makes the unknown key intentional
    return CONFIG.get("llm_slotz", 4)


def ok_dynamic_read(name):
    return getattr(CONFIG, name)


def suppressed_unknown_read():
    return CONFIG.llm_blok_size  # raylint: disable=RL1004 (fixture: legacy alias resolved by a shim)
