"""RL801 fixtures for the round-17 tiered-KV / multicast RESOURCE_TABLE
rows: disk-spill file handles (open_spill -> commit/close), multicast
subscriptions (subscribe -> unsubscribe), and cross-replica prefix-fetch
leases (lease_prefix -> release). The fire/suppress shapes mirror
case_rl8_adapter.py so the new obligations ride the exact same path
analysis (docs/kvcache.md, docs/device_channels.md)."""


# -- disk-spill file handle ---------------------------------------------------

def bad_spill_never_closed(store, key, data):
    f = store.open_spill(key)
    f.write(data)


def bad_spill_conditional(store, key, data, flag):
    f = store.open_spill(key)
    if flag:
        f.commit()


def bad_spill_risky_gap(store, key, encoder, data):
    f = store.open_spill(key)
    f.write(encoder.encode(data))
    f.commit()


def ok_spill_finally(store, key, data):
    f = store.open_spill(key)
    try:
        f.write(data)
        f.commit()
    finally:
        f.close()


def ok_spill_with(store, key, data):
    with store.open_spill(key) as f:
        f.write(data)


def ok_spill_returned(store, key):
    return store.open_spill(key)


def suppressed_spill(store, key, data):
    f = store.open_spill(key)  # raylint: disable=RL801 (fixture: worker thread owns the commit)
    f.write(data)


# -- multicast subscription ---------------------------------------------------

def bad_subscription_never_released(group, i):
    sub = group.subscribe(i)
    return sub.recv()


def bad_subscription_conditional(group, i, flag):
    sub = group.subscribe(i)
    if flag:
        sub.unsubscribe()


def ok_subscription_finally(group, i):
    sub = group.subscribe(i)
    try:
        return sub.recv()
    finally:
        sub.unsubscribe()


def ok_subscription_with(group, i):
    with group.subscribe(i) as sub:
        return sub.recv()


def ok_subscription_stored(self, group, i):
    self._sub = group.subscribe(i)


def suppressed_subscription(group, i):
    sub = group.subscribe(i)  # raylint: disable=RL801 (fixture: the reply handler unsubscribes)
    return sub.recv()


# -- cross-replica prefix-fetch lease ----------------------------------------

def bad_fetch_lease_never_released(cache, tokens):
    lease = cache.lease_prefix(tokens)
    return lease.kv()


def bad_fetch_lease_risky_gap(cache, tokens, channel):
    lease = cache.lease_prefix(tokens)
    channel.send(lease.kv())
    lease.release()


def ok_fetch_lease_finally(cache, tokens, channel):
    lease = cache.lease_prefix(tokens)
    try:
        channel.send(lease.kv())
    finally:
        lease.release()


def ok_fetch_lease_returned(engine, tokens):
    return engine.lease_prefix(tokens)


def ok_fetch_lease_closure(cache, tokens, channel, spawn):
    lease = cache.lease_prefix(tokens)

    def pump():
        try:
            channel.send(lease.kv())
        finally:
            lease.release()

    spawn(pump)


def suppressed_fetch_lease(cache, tokens):
    lease = cache.lease_prefix(tokens)  # raylint: disable=RL801 (fixture: export registry owns it)
    return lease.kv()
