"""RL1006 fixtures: gcs_call verbs vs the rpc_* handler table.

Unknown verb strings fail at the server with an unknown-method error;
handlers no string anywhere names are unreachable API surface. Verb arity
against the handler signature is RL1002 (same binding contract as every
other cross-process call).
"""


class GcsService:
    """Handler roster (gcs-ish by class name, like the real one)."""

    async def rpc_kv_put(self, conn, key, value, overwrite=True):
        return True

    async def rpc_kv_get(self, conn, key):
        return None

    async def rpc_heartbeat(self, conn, node_id, resources=None):
        return True

    async def rpc_orphan_handler(self, conn):
        return True

    async def rpc_suppressed_orphan(self, conn):  # raylint: disable=RL1006 (fixture: reached by a client outside the scanned tree)
        return True


class RayletService:
    """rpc_-prefixed methods on a non-GCS class are not verbs."""

    async def rpc_unrelated(self, conn):
        return True


def bad_unknown_verb(worker):
    return worker.gcs_call("kv_putt", "k", b"v")


def bad_verb_arity(worker):
    return worker.gcs_call("kv_get", "k", "extra", "args")


def ok_known_verb(worker):
    return worker.gcs_call("kv_put", "k", b"v")


def ok_default_arg_verb(worker):
    return worker.gcs_call("heartbeat", "node-1")


def ok_dynamic_verb(worker, verb):
    return worker.gcs_call(verb, "k")


def suppressed_unknown_verb(worker):
    return worker.gcs_call("kv_putt", "k", b"v")  # raylint: disable=RL1006 (fixture: verb registered by a plugin at runtime)
