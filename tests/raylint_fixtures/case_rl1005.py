"""RL1005 fixtures: values that should not cross a .remote() boundary.

Lambdas and locally-defined functions cloudpickle fine — but they ship
their captured enclosing state BY VALUE, so the worker runs a silently
diverging copy. OS-backed handles (files, locks, threads) don't survive
the hop at all.
"""

import threading


def process(fn, data):
    return fn(data)


class Mapper:
    def apply(self, fn, block):
        return fn(block)


def bad_lambda_arg(data):
    return process.remote(lambda row: row * 2, data)


def bad_local_function(data):
    scale = 2

    def udf(row):
        return row * scale

    return process.remote(udf, data)


def bad_open_handle(path):
    fh = open(path)
    return process.remote(fh, None)


def bad_inline_open(path):
    return process.remote(open(path), None)


def bad_lock_arg(data):
    guard = threading.Lock()
    return process.remote(guard, data)


def ok_module_function(data):
    return process.remote(process, data)


def ok_plain_values(path, data):
    return process.remote(path, data)


def ok_reassigned_handle(path, data):
    fh = open(path)
    fh = path  # rebound to a plain value before the submission
    return process.remote(fh, data)


def suppressed_lambda(data):
    return process.remote(lambda row: row, data)  # raylint: disable=RL1005 (fixture: pure stateless closure, divergence impossible)
