"""RL1002 fixtures: cross-process call shapes that no target def binds.

Covers actor constructors, resolved handle methods, unknown kwargs,
missing required args, @remote function arity, and the *args/**kwargs
escape hatch (dynamic shapes are never checked).
"""


class Engine:
    def __init__(self, model_id, slots=4):
        self.model_id = model_id
        self.slots = slots

    def generate(self, prompt, *, max_tokens=64, temperature=0.0):
        return prompt

    def warm(self, *blobs):
        return len(blobs)


def remote(fn=None, **opts):
    return fn if fn is not None else (lambda f: f)


@remote
def score(row, scale=1.0):
    return row


def bad_ctor_too_many_args():
    return Engine.remote("m", 4, 99)


def bad_ctor_missing_required():
    return Engine.remote()


def bad_unknown_kwarg():
    h = Engine.remote("m")
    return h.generate.remote("hi", max_token=8)


def bad_positional_overflow():
    h = Engine.remote("m")
    # max_tokens is keyword-only: two positionals cannot bind
    return h.generate.remote("hi", 8)


def bad_remote_function_arity():
    return score.remote("row", 2.0, "extra")


def ok_ctor():
    return Engine.remote("m", slots=8)


def ok_generate():
    h = Engine.remote("m")
    return h.generate.remote("hi", max_tokens=8)


def ok_vararg_target():
    h = Engine.remote("m")
    return h.warm.remote(1, 2, 3, 4, 5)


def ok_dynamic_call_shape(args, kwargs):
    h = Engine.remote("m")
    return h.generate.remote(*args, **kwargs)


def suppressed_unknown_kwarg():
    h = Engine.remote("m")
    return h.generate.remote("hi", max_token=8)  # raylint: disable=RL1002 (fixture: server build injects this kwarg)
