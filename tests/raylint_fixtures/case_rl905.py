"""RL905 fixtures: cross-process calls under held locks — awaited under an
async lock, or reached interprocedurally under a sync lock."""


class _Peer:
    """Defines ping so the api-family universe check stays quiet: this
    fixture seeds calls-under-lock, not unknown-method ones."""

    def ping(self, req=None):
        return True

    def handle(self, req):
        return req


class Controller:
    async def bad_await_remote_under_lock(self, handle):
        async with self._state_lock:
            return await handle.ping.remote()

    async def bad_await_gcs_under_lock(self, worker):
        async with self._state_lock:
            return await worker.gcs_call("kv_get", "ns", b"k")

    async def bad_await_helper_under_lock(self, req):
        async with self._engine_lock:
            return await self._dispatch(req)

    async def _dispatch(self, req):
        return await self._replica.handle.remote(req)

    async def ok_await_outside_lock(self, handle):
        async with self._state_lock:
            req = self._next()
        return await handle.ping.remote(req)

    async def ok_local_await_under_lock(self, req):
        async with self._state_lock:
            return await self._validate(req)

    async def _validate(self, req):
        return req

    def _next(self):
        return 1

    async def suppressed_await_under_lock(self, handle):
        async with self._state_lock:
            return await handle.ping.remote()  # raylint: disable=RL905 (fixture: single-task lock, rpc has a 1s deadline)


def _refresh_placement(worker):
    return worker.gcs_call("get_nodes")


def bad_sync_helper_under_lock(worker, cache_lock):
    with cache_lock:
        return _refresh_placement(worker)


def ok_sync_helper_outside_lock(worker, cache_lock):
    with cache_lock:
        pass
    return _refresh_placement(worker)


def ok_local_helper_under_lock(records, cache_lock):
    with cache_lock:
        return _summarize(records)


def _summarize(records):
    return len(records)


async def _aresolve(worker, actor_id):
    return worker.gcs_call("get_actor_info", actor_id)


def ok_spawn_async_helper_under_lock(io, worker, cache_lock):
    # Building the coroutine under the lock is fine: _aresolve's body (and
    # its GCS round-trip) runs later on the io loop, lock long released.
    with cache_lock:
        io.spawn(_aresolve(worker, "a1"))


def ok_lambda_callback_under_lock(conn, worker, cache_lock):
    # The lambda body executes when the close callback FIRES, not here.
    with cache_lock:
        conn.on_close(lambda c: _refresh_placement(worker))
