"""RL801 fixtures for the replicated-GCS resources: the replication peer
link (GcsCandidate.open_peer -> PeerLink.close) and the primary lease token
(acquire_lease -> LeaseToken.release). Fire/suppress shapes mirror
case_rl801.py so the round-14 RESOURCE_TABLE rows ride the exact same path
analysis — a deposed primary that strands follower links or keeps a
released lease is precisely the leak class these rows exist to catch."""


def bad_peer_link_never_closed(candidate, addr, conn):
    link = candidate.open_peer(addr, conn)
    return link.addr


def bad_peer_link_conditional(candidate, addr, conn, flag):
    link = candidate.open_peer(addr, conn)
    if flag:
        link.close()


def bad_lease_never_released(candidate, epoch):
    lease = candidate.acquire_lease(epoch)
    return lease.epoch


def bad_lease_risky_gap(candidate, epoch, gcs):
    lease = candidate.acquire_lease(epoch)
    gcs.start_background()
    lease.release()


def ok_peer_link_stored(candidate, addr, conn, links, idx):
    links[idx] = candidate.open_peer(addr, conn)


def ok_peer_link_finally(candidate, addr, conn, batch):
    link = candidate.open_peer(addr, conn)
    try:
        return link.conn.call("repl_append", batch)
    finally:
        link.close()


def ok_lease_stored_for_demotion(candidate, epoch):
    candidate._lease = candidate.acquire_lease(epoch)


def ok_lease_returned(candidate, epoch):
    return candidate.acquire_lease(epoch)


def suppressed_peer_link(candidate, addr, conn):
    link = candidate.open_peer(addr, conn)  # raylint: disable=RL801 (fixture: demotion closes it)
    return link.addr
