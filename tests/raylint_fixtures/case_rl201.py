"""Seeded RL201 violation: two functions take the same locks in opposite
orders — the classic deadlock-by-interleaving."""

import threading


class Store:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()

    def forward(self):
        with self._alpha_lock:
            with self._beta_lock:                  # edge alpha -> beta
                return 1

    def backward(self):
        with self._beta_lock:
            with self._alpha_lock:                 # edge beta -> alpha: cycle
                return 2


class Clean:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock, self._b_lock:
            return 1

    def two(self):
        with self._a_lock:
            with self._b_lock:                     # same order: no cycle
                return 2
