"""RL801 fixtures for the profiler capture handle (xprof.start_capture ->
ProfilerCapture.stop_capture/close), the round-18 RESOURCE_TABLE entry: a
capture never stopped keeps jax.profiler tracing for the rest of the process's
life. The fire/suppress shapes mirror case_rl801.py's lease shapes so the new
obligation rides the exact same path analysis."""


def bad_capture_never_stopped(xprof):
    cap = xprof.start_capture()
    return cap.log_dir


def bad_capture_conditional(xprof, flag):
    cap = xprof.start_capture()
    if flag:
        cap.stop_capture()


def bad_capture_risky_gap(xprof, engine, prompt):
    cap = xprof.start_capture()
    engine.generate(prompt)
    cap.stop_capture()


def ok_capture_finally(xprof, engine, prompt):
    cap = xprof.start_capture()
    try:
        return engine.generate(prompt)
    finally:
        cap.stop_capture()


def ok_capture_close_finally(xprof, engine, prompt):
    cap = xprof.start_capture()
    try:
        return engine.generate(prompt)
    finally:
        cap.close()


def ok_capture_stored(replica, xprof):
    replica.active_capture = xprof.start_capture()


def ok_capture_returned(xprof):
    return xprof.start_capture()


def suppressed_capture(xprof):
    cap = xprof.start_capture()  # raylint: disable=RL801 (fixture: stop rides the stats report path)
    return cap.log_dir
