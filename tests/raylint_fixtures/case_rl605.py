"""Seeded RL605 violations (donated buffers read after the call)."""

import jax


def bad_read_after_donate(f, state, batch):
    step = jax.jit(f, donate_argnums=(0,))
    new_state, loss = step(state, batch)
    return state, loss                             # RL605


def suppressed_read(f, state, batch):
    step = jax.jit(f, donate_argnums=(0,))
    new_state, loss = step(state, batch)
    return state, loss  # raylint: disable=RL605 (aliasing proven safe in test)


def ok_rebound(f, state, batch):
    step = jax.jit(f, donate_argnums=(0,))
    state, loss = step(state, batch)
    return state, loss


def ok_undonated(f, state, batch):
    step = jax.jit(f)
    out, loss = step(state, batch)
    return state, out, loss
