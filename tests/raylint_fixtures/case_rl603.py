"""Seeded RL603 violations (host syncs in decode/train hot paths)."""

import jax
import jax.numpy as jnp
import numpy as np


class BadEngine:
    def __init__(self, f):
        self._jit_step = jax.jit(f)
        self._lens = jnp.zeros((4,), jnp.int32)

    def bad_sync_in_loop(self, state, steps):
        lens = None
        for _ in range(steps):
            state = self._jit_step(state)
            lens = np.asarray(self._lens)          # RL603
        return state, lens

    def bad_item_in_loop(self, state, steps):
        out = []
        for _ in range(steps):
            state = self._jit_step(state)
            out.append(state.item())               # RL603
        return out

    def _helper_pull(self, x):
        return float(self._jit_step(x))            # RL603 (loop-called helper)

    def bad_loop_called_helper(self, xs):
        return [self._helper_pull(x) for x in xs]

    async def bad_async_sync(self, x):
        return np.asarray(self._jit_step(x))       # RL603 (async frame)

    def suppressed_sync(self, state, steps):
        lens = None
        for _ in range(steps):
            state = self._jit_step(state)
            lens = np.asarray(self._lens)  # raylint: disable=RL603 (one batched readback per chunk)
        return state, lens

    def ok_sync_after_loop(self, state, steps):
        for _ in range(steps):
            state = self._jit_step(state)
        return np.asarray(state)                   # one readback per chunk

    def ok_host_values(self, rows):
        out = []
        for r in rows:
            out.append(float(r))                   # host floats, not device
        return out
