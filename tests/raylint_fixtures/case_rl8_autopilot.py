"""RL801 fixtures for the autopilot scale-op token (Autopilot.begin_scale_op
-> ScaleOp.commit/abort), the round-20 RESOURCE_TABLE entry: a dropped token
leaves the decision "pending" forever and a half-applied replica target that
the next controller restart replays. Fire/suppress shapes mirror
case_rl8_xprof.py so the new obligation rides the same path analysis."""


def bad_scale_op_never_resolved(autopilot, action):
    op = autopilot.begin_scale_op(action)
    return op.token


def bad_scale_op_conditional(autopilot, action, ok):
    op = autopilot.begin_scale_op(action)
    if ok:
        op.commit()


def bad_scale_op_risky_gap(autopilot, controller, action):
    op = autopilot.begin_scale_op(action)
    controller.reconcile(action.app)
    op.commit()


def ok_scale_op_finally(autopilot, controller, action):
    op = autopilot.begin_scale_op(action)
    try:
        return controller.reconcile(action.app)
    finally:
        op.commit()


def ok_scale_op_abort_finally(autopilot, controller, action):
    op = autopilot.begin_scale_op(action)
    try:
        return controller.reconcile(action.app)
    finally:
        op.abort()


def ok_scale_op_stored(controller, autopilot, action):
    controller.pending_op = autopilot.begin_scale_op(action)


def ok_scale_op_returned(autopilot, action):
    return autopilot.begin_scale_op(action)


def suppressed_scale_op(autopilot, action):
    op = autopilot.begin_scale_op(action)  # raylint: disable=RL801 (fixture: resolution rides _apply_scale_op)
    return op.token
