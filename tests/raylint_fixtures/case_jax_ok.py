"""No-false-positive fixture: the legitimate bucketed-jit engine pattern.

Mirrors DecodeEngine's discipline — bounded bucket table, capped program
cache with oldest-first eviction, host-native counters, and exactly one
device->host readback per dispatch (outside any loop). jaxlint must stay
silent on every line of this file.
"""

import jax
import jax.numpy as jnp
import numpy as np

_BUCKETS = (16, 32, 64, 128)


class BucketedEngine:
    def __init__(self, f, max_programs=8):
        self._f = f
        self._progs = {}
        self._max_programs = max_programs
        self._jit_decode = jax.jit(f)
        self._lens = np.zeros((4,), np.int32)      # host-native mirror

    def _bucket(self, n):
        for b in _BUCKETS:
            if n <= b:
                return b
        return _BUCKETS[-1]

    def _program(self, key):
        prog = self._progs.get(key)
        if prog is None:
            if len(self._progs) >= self._max_programs:
                self._progs.pop(next(iter(self._progs)))
            prog = self._progs[key] = jax.jit(self._f)
        return prog

    def prefill(self, prompt):
        bucket = self._bucket(len(prompt))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        logits = self._program(("prefill", bucket))(jnp.asarray(padded))
        self._lens[0] = len(prompt)                # host write, no device sync
        return np.asarray(logits)                  # one readback per dispatch

    def decode(self, steps):
        state = jnp.zeros((4,), jnp.float32)
        for _ in range(steps):
            state = self._jit_decode(state)
        return np.asarray(state)                   # sync once, after the loop
