"""RL1001 fixtures: .remote() call to a method the target class doesn't have.

The handle-provenance tracking (local vars, self attrs, .options() chains)
gives precise resolution; untracked handles fall back to the whole-file
method/function universe. Classes with __getattr__ opt out (dynamic surface).
"""


class Counter:
    def __init__(self, start=0):
        self.value = start

    def increment(self, by=1):
        self.value += by
        return self.value

    def read(self):
        return self.value


class Dynamic:
    """__getattr__ makes the method surface unknowable — never fires."""

    def __getattr__(self, name):
        return lambda *a: None


class Holder:
    def __init__(self):
        self._h = Counter.remote(0)

    def bad_attr_handle_typo(self):
        return self._h.incremant.remote(1)

    def ok_attr_handle(self):
        return self._h.increment.remote(1)


def bad_tracked_handle_typo():
    h = Counter.remote(0)
    return h.incremant.remote(1)


def bad_options_chain_typo():
    h = Counter.options(num_cpus=1).remote(0)
    return h.reed.remote()


def bad_untracked_unknown_everywhere(mystery):
    # weak path: no class or function anywhere in this file defines it
    return mystery.frobnicate_xyz.remote(1)


def ok_tracked_handle():
    h = Counter.remote(0)
    return h.increment.remote(by=2)


def ok_untracked_but_known_somewhere(mystery):
    # `increment` exists on Counter: an untracked handle gets the benefit
    # of the doubt
    return mystery.increment.remote(1)


def ok_dynamic_class():
    h = Dynamic.remote()
    return h.anything_at_all.remote()


def suppressed_tracked_typo():
    h = Counter.remote(0)
    return h.incremant.remote(1)  # raylint: disable=RL1001 (fixture: patched onto the class at runtime)
