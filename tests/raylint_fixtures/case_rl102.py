"""Seeded RL102 violations (blocking calls in async frames)."""

import queue
import subprocess
import threading
import time

_q: queue.Queue = queue.Queue()
_lock = threading.Lock()


async def bad_sleep():
    time.sleep(1)                                  # RL102


async def bad_queue_get():
    return _q.get()                                # RL102


async def bad_lock_acquire():
    _lock.acquire()                                # RL102


async def bad_subprocess():
    subprocess.run(["true"])                       # RL102


async def bad_ray_get(ray_tpu, ref):
    return ray_tpu.get(ref)                        # RL102


async def suppressed_sleep():
    time.sleep(1)  # raylint: disable=RL102


async def ok_awaited_get(aq):
    return await aq.get()                          # awaitable, not blocking


async def ok_wait_for(ev):
    import asyncio

    await asyncio.wait_for(ev.wait(), 1)           # coroutine factory arg


async def ok_nonblocking():
    _lock.acquire(blocking=False)
    return _q.get(block=False)


async def ok_executor(loop):
    return await loop.run_in_executor(None, _q.get)


def ok_sync_code():
    time.sleep(0)
    return _q.get()
