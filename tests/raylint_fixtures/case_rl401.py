"""Seeded RL401 violations (silently swallowed exceptions in handlers)."""


class Handlers:
    # control_loop and rpc_submit below each swallow silently: RL401.
    async def control_loop(self, conn):
        try:
            await conn.call("reconcile")
        except Exception:
            pass

    def rpc_submit(self, conn, spec):
        try:
            self._run(spec)
        except Exception:
            pass

    async def suppressed(self, conn):
        try:
            await conn.call("reconcile")
        except Exception:  # raylint: disable=RL401
            pass

    async def ok_documented(self, conn):
        try:
            await conn.call("reconcile")
        except Exception:
            pass  # peer may be mid-restart; next tick retries

    async def ok_logged(self, conn, logger):
        try:
            await conn.call("reconcile")
        except Exception as e:
            logger.warning("reconcile failed: %s", e)

    async def ok_failure_value(self, conn):
        try:
            return await conn.call("probe")
        except Exception:
            return False

    async def ok_teardown(self, conn):
        try:
            conn.close()
        except Exception:
            pass

    def ok_plain_sync(self):
        try:
            self._run(None)
        except Exception:
            pass                                   # not handler-scoped

    def _run(self, spec):
        raise NotImplementedError
