"""Seeded RL604 violations (retrace hazards at jitted-call boundaries)."""

import jax
import numpy as np

_step = jax.jit(lambda tokens: tokens)


def bad_list_arg(prompt):
    toks = list(prompt)
    return _step(toks)                             # RL604


def bad_list_display(a, b):
    return _step([a, b])                           # RL604


def bad_unbucketed_shape(prompt):
    padded = np.zeros((1, len(prompt)), np.int32)
    return _step(padded)                           # RL604


def suppressed_list(prompt):
    toks = list(prompt)
    return _step(toks)  # raylint: disable=RL604 (callers pass fixed-length tuples)


def ok_bucketed(prompt, bucket):
    padded = np.zeros((1, bucket), np.int32)
    padded[0, : len(prompt)] = prompt
    return _step(padded)


def ok_array(arr):
    return _step(np.asarray(arr))
