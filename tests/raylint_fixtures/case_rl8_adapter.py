"""RL801 fixtures for the LoRA adapter pin (AdapterCache.acquire ->
AdapterHandle.release), the round-13 RESOURCE_TABLE entry: the fire/suppress
shapes mirror case_rl801.py's lease shapes so the new obligation rides the
exact same path analysis."""


def bad_adapter_pin_never_released(adapter_cache, name):
    handle = adapter_cache.acquire(name)
    return handle.slot


def bad_adapter_pin_conditional(adapter_cache, name, flag):
    handle = adapter_cache.acquire(name)
    if flag:
        handle.release()


def bad_adapter_pin_risky_gap(adapter_cache, name, engine):
    handle = adapter_cache.acquire(name)
    engine.dispatch(handle.slot)
    handle.release()


def ok_adapter_pin_with(adapter_cache, name):
    with adapter_cache.acquire(name) as handle:
        return handle.slot


def ok_adapter_pin_finally(adapter_cache, name, engine):
    handle = adapter_cache.acquire(name)
    try:
        return engine.dispatch(handle.slot)
    finally:
        handle.release()


def ok_adapter_pin_stored(req, adapter_cache, name):
    req.adapter_handle = adapter_cache.acquire(name)


def ok_adapter_pin_returned(adapter_cache, name):
    return adapter_cache.acquire(name)


def suppressed_adapter_pin(adapter_cache, name):
    handle = adapter_cache.acquire(name)  # raylint: disable=RL801 (fixture: scheduler drain releases it)
    return handle.slot
