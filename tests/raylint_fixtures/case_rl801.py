"""RL801 fixtures: acquire not released on all paths.

The acquire names below come straight from leaklint's RESOURCE_TABLE
(`prefix_cache.lookup`, `chan.read_view`, `srv.pin`): the fixtures pin the
fire/suppress behavior of each RL801 sub-shape.
"""


def bad_never_released(prefix_cache, toks):
    lease = prefix_cache.lookup(toks)
    if lease is None:
        return 0
    return lease.matched_tokens


def bad_conditional_release(prefix_cache, toks, flag):
    lease = prefix_cache.lookup(toks)
    if flag:
        lease.release()


def bad_risky_gap(prefix_cache, toks, dst):
    lease = prefix_cache.lookup(toks)
    dst.attach(lease.kv())
    lease.release()


def bad_discarded(chan):
    chan.read_view()


def bad_pin_no_release(srv, key):
    if not srv.pin(key):
        return False
    return srv.read(0, 10)


def ok_with(prefix_cache, toks):
    with prefix_cache.lookup(toks) as lease:
        return lease.matched_tokens


def ok_try_finally(prefix_cache, toks, dst):
    lease = prefix_cache.lookup(toks)
    try:
        dst.attach(lease.kv())
    finally:
        lease.release()


def ok_returned(prefix_cache, toks):
    return prefix_cache.lookup(toks)


def ok_stored(owner, prefix_cache, toks):
    owner.lease = prefix_cache.lookup(toks)


def ok_passed_on(registry, prefix_cache, toks):
    lease = prefix_cache.lookup(toks)
    registry.adopt(lease)


def ok_immediate_release(prefix_cache, toks):
    lease = prefix_cache.lookup(toks)
    if lease is None:
        return None
    lease.release()
    return 1


def ok_pin_finally(srv, key):
    if not srv.pin(key):
        return None
    try:
        return bytes(srv.read(0, 10))
    finally:
        srv.release(key)


class OkClassManagedPin:
    """Cross-method acquire/release: the owner class releases elsewhere."""

    def grab(self, key):
        self._srv.pin(key)
        self._held.add(key)

    def drop(self, key):
        self._held.discard(key)
        self._srv.release(key)


def suppressed_leak(prefix_cache, toks):
    lease = prefix_cache.lookup(toks)  # raylint: disable=RL801 (fixture: released by the caller's registry)
    return lease.matched_tokens
