"""Seeded RL501 violations (discarded remote/execute results)."""


class _Probe:
    """Defines ping so the api-family universe check stays quiet: this
    fixture seeds dropped-ref violations, not unknown-method ones."""

    def ping(self):
        return True


def bad_fire_and_forget(actor):
    actor.ping.remote()                            # RL501


def bad_dropped_execute(dag, batch):
    dag.execute(batch)                             # RL501


async def bad_dropped_execute_async(dag, batch):
    dag.execute_async(batch)                       # RL501


def suppressed_fire_and_forget(actor):
    actor.ping.remote()  # raylint: disable=RL501 (liveness probe, errors via next call)


def ok_kept_ref(actor):
    ref = actor.ping.remote()
    return ref


def ok_gotten(ray_tpu, actor):
    return ray_tpu.get(actor.ping.remote())
