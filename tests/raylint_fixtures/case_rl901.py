"""RL901 fixtures: metric mutation outside a report path.

The metric identity proofs (ctor-assigned self attrs, module names, dict
displays, in-file factories) and the report-path roster propagation are the
precision gate: contextvar `.set()` and rllib's connector `.observe()` must
stay out of sight.
"""

from contextvars import ContextVar

from ray_tpu.util.metrics import Counter, Gauge, Histogram

REQUESTS = Counter("requests_total")
_model_id = ContextVar("model_id", default="")


def bad_module_metric_inc(n):
    REQUESTS.inc(n)


def _series():
    return {"latency": Histogram("latency_s")}


def bad_factory_series_observe(dt):
    _series()["latency"].observe(dt)


class Plane:
    def __init__(self):
        self._hits = Counter("hits_total")
        self._depth = Gauge("queue_depth")
        self._m = {"lat": Histogram("lat_s")}

    def bad_data_path_inc(self):
        self._hits.inc()

    def bad_dict_series_observe(self, dt):
        self._m["lat"].observe(dt)

    def bad_explicit_flush(self):
        self._depth.flush()

    def stats(self):
        self._depth.set(1.0)
        self._refresh()
        return {"depth": 1.0}

    def _refresh(self):
        # called ONLY from stats(): the report-path fixpoint covers it
        self._hits.inc(0.0)

    def _shared_helper(self):
        # called from report() AND from a data path: NOT report-path-only,
        # so the mutation inside it fires
        self._hits.inc()

    def on_request(self):
        self._shared_helper()

    def report(self):
        self._shared_helper()

    def ok_contextvar_set(self, mid):
        _model_id.set(mid)

    def ok_plain_counter(self):
        self.n = getattr(self, "n", 0) + 1

    def suppressed_inc(self):
        self._hits.inc()  # raylint: disable=RL901 (fixture: flushed by the caller's report tick)
