"""RL801 fixtures for the round-22 generation-modes lifetimes
(docs/generation.md): the engine token stream (DecodeEngine.open_stream ->
TokenStream.close/cancel) and the guided-decoding constraint state
(Constraint.begin -> ConstraintState.release). An unclosed stream orphans a
decode slot (plus its prefix lease and adapter pin) behind a vanished
consumer; an unreleased constraint state keeps its token-DFA walk past the
request's life. Fire/suppress shapes mirror case_rl8_autopilot.py so the new
obligations ride the same path analysis."""


def bad_stream_never_closed(engine, token_ids, sampling):
    stream = engine.open_stream(token_ids, sampling)
    return stream.request_id


def bad_stream_conditional(engine, token_ids, sampling, want_all):
    stream = engine.open_stream(token_ids, sampling)
    if want_all:
        stream.close()


def bad_stream_risky_gap(engine, proxy, token_ids, sampling):
    stream = engine.open_stream(token_ids, sampling)
    proxy.register(stream.request_id)
    stream.close()


def ok_stream_finally(engine, token_ids, sampling):
    stream = engine.open_stream(token_ids, sampling)
    try:
        return list(stream)
    finally:
        stream.close()


def ok_stream_cancel_finally(engine, token_ids, sampling):
    stream = engine.open_stream(token_ids, sampling)
    try:
        return stream.get(timeout=1.0)
    finally:
        stream.cancel()


def ok_stream_stored(server, engine, token_ids, sampling):
    server.live_stream = engine.open_stream(token_ids, sampling)


def ok_stream_returned(engine, token_ids, sampling):
    return engine.open_stream(token_ids, sampling)


def suppressed_stream(engine, token_ids, sampling):
    stream = engine.open_stream(token_ids, sampling)  # raylint: disable=RL801 (fixture: close rides the consumer's iterator finally)
    return stream.request_id


def bad_constraint_never_released(constraint, rid):
    state = constraint.begin(rid)
    return state.mask(0)


def bad_constraint_conditional(constraint, rid, accepted):
    state = constraint.begin(rid)
    if accepted:
        state.release()


def ok_constraint_finally(constraint, rid, tokens):
    state = constraint.begin(rid)
    try:
        for t in tokens:
            state.advance(t)
        return state.is_complete()
    finally:
        state.release()


def ok_constraint_stored(req, constraint, rid):
    req.constraint = constraint.begin(rid)


def suppressed_constraint(constraint, rid):
    state = constraint.begin(rid)  # raylint: disable=RL801 (fixture: release rides the scheduler's finish path)
    return state
