"""Seeded RL101 violations (await-under-lock). Never imported — lint fodder."""

import asyncio
import threading

_lock = threading.Lock()


class Plane:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._aio_lock = asyncio.Lock()

    async def bad_await_under_lock(self):          # line 14
        with self._state_lock:
            await asyncio.sleep(0)                 # RL101 (line 16)

    async def bad_await_under_global_lock(self):
        with _lock:
            await asyncio.sleep(0)                 # RL101 (line 20)

    async def suppressed_await_under_lock(self):
        with self._state_lock:
            await asyncio.sleep(0)  # raylint: disable=RL101

    async def ok_async_lock(self):
        async with self._aio_lock:
            await asyncio.sleep(0)                 # asyncio lock: fine

    async def ok_lock_released_before_await(self):
        with self._state_lock:
            x = 1
        await asyncio.sleep(x)

    def ok_sync_closure_under_async(self):
        async def outer():
            def read_one():
                with self._state_lock:
                    return 1
            return read_one
        return outer
