"""RL803 fixtures: use-after-release / double-release on a straight line."""


def bad_use_after_release(chan):
    view = chan.read_view()
    try:
        data = bytes(view.mv)
    finally:
        view.release()
    return (data, view.mv)


def bad_double_release(chan):
    view = chan.read_view()
    view.release()
    view.release()


def ok_rebound(chan):
    view = chan.read_view()
    view.release()
    view = chan.read_view()
    out = view.mv
    view.release()
    return out


def ok_single_release(chan):
    view = chan.read_view()
    out = view.mv
    view.release()
    return out


def suppressed_use(chan):
    view = chan.read_view()
    view.release()
    return view.mv  # raylint: disable=RL803 (fixture: mv was copied before release in the real code)
