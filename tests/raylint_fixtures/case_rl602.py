"""Seeded RL602 violations (unbounded jitted-program caches)."""

import jax


class BadCache:
    def __init__(self):
        self._progs = {}

    def bad_unbounded(self, f, n):
        if n not in self._progs:
            self._progs[n] = jax.jit(f)            # RL602
        return self._progs[n]


class SuppressedCache:
    def __init__(self):
        self._progs = {}

    def suppressed_store(self, f, n):
        if n not in self._progs:
            self._progs[n] = jax.jit(f)  # raylint: disable=RL602 (n drawn from a fixed enum)
        return self._progs[n]


class OkBoundedCache:
    """The legitimate pattern: explicit cap + oldest-first eviction."""

    def __init__(self, cap=8):
        self._progs = {}
        self._cap = cap

    def ok_bounded(self, f, n):
        if n not in self._progs:
            if len(self._progs) >= self._cap:
                self._progs.pop(next(iter(self._progs)))
            self._progs[n] = jax.jit(f)
        return self._progs[n]
