"""RL902 fixtures: blocking control-plane RPC in a forbidden context
(finalizer, held lock, scheduler/decode hot context)."""

import weakref


class Holder:
    def __del__(self):
        self._worker.gcs_call("kv_del", "ns", self._key)

    def close(self):
        # ok: explicit release path, not GC-timed
        self._worker.gcs_call("kv_del", "ns", self._key)


def _finalize_entry(worker, key):
    worker.gcs_call("kv_del", "ns", key)


class Registered:
    def __init__(self, worker, key):
        weakref.finalize(self, _finalize_entry, worker, key)


def bad_rpc_under_lock(worker, lock, key):
    with lock:
        return worker.gcs_call("kv_get", "ns", key)


def bad_kv_verb_under_lock(store, state_lock, key, blob):
    with state_lock:
        store.kv_put("ns", key, blob, True)


def bad_by_name_lookup_in_del(registry):
    class _Owner:
        def __del__(self):
            registry.get_actor("controller")

    return _Owner()


def bad_connect_under_lock(rpc_client, conn_cache, conn_lock, addr):
    with conn_lock:
        conn_cache[addr] = rpc_client.connect(addr)


class Scheduler:
    def decode_loop(self, worker, batches):
        for b in batches:
            worker.gcs_call("kv_put", "ns", b.key, b.blob, True)

    def schedule_step(self, worker, reqs):
        for r in reqs:
            self._place(worker, r)

    def _place(self, worker, r):
        # hot by propagation: called per schedule_step iteration
        worker.gcs_call("get_node", r.node_id)

    def scheduler_stats(self, worker):
        # ok: the report path IS allowed its control-plane round-trips,
        # even though "scheduler" is in its name
        out = {}
        for key in worker.gcs_call("kv_keys", "metrics", b""):
            out[key] = worker.gcs_call("kv_get", "metrics", key)
        return out


def ok_plain_method(worker, key):
    return worker.gcs_call("kv_get", "ns", key)


def ok_copy_out_then_call(worker, lock, key):
    with lock:
        k = bytes(key)
    return worker.gcs_call("kv_get", "ns", k)


def ok_socket_connect(sock, addr):
    # bare connect() on a non-rpc receiver is out of scope
    sock.connect(addr)


def suppressed_del_rpc(worker, key):
    class _Owner:
        def __del__(self):
            worker.gcs_call("kv_del", "ns", key)  # raylint: disable=RL902 (fixture: last-resort reap, explicit close is primary)

    return _Owner()
