"""Seeded RL301/RL302 violations (shared-state aliasing, mutable defaults)."""

from dataclasses import dataclass, field

_registry: dict = {}


def bad_override(acc):
    spec = acc.get("model")
    cfg = spec["config"]
    cfg.num_replicas = 2                           # RL301: alias into acc


def bad_deep_store(acc, value):
    acc["model"].max_ongoing = value               # RL301: deep path


def bad_module_mutation(key, value):
    _registry[key] = value                         # RL301: no lock held


def suppressed_override(acc):
    spec = acc.get("model")
    cfg = spec["config"]
    cfg.num_replicas = 2  # raylint: disable=RL301 (caller passes a copy)


def ok_copied_override(acc):
    import dataclasses

    cfg = dataclasses.replace(acc.get("model")["config"])
    cfg.num_replicas = 2
    return cfg


def ok_param_own_attr(pg):
    pg.allocations[0] = None                       # param's own structure


def ok_locked_module_mutation(key, value):
    import threading

    _reg_lock = threading.Lock()
    with _reg_lock:
        _registry[key] = value


class _Overrides(dict):
    pass


@dataclass
class BadSchema:
    name: str = "x"
    overrides: dict = field(default=_Overrides())  # RL302: shared instance


@dataclass
class OkSchema:
    name: str = "x"
    overrides: dict = field(default_factory=dict)
