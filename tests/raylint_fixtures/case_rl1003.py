"""RL1003 fixtures: duck-typed protocol rosters must be whole.

A deployed class answering any ANCHOR of a PROTOCOL_TABLE roster must
implement every member with the broadcast call shape. Non-deployed classes
(engine internals) are out of scope no matter what they implement.
"""


class PartialStats:
    """Deployed below; implements two of the llm-stats anchors but not the
    rest of the roster -> fleet stat collection AttributeErrors here."""

    def cache_stats(self):
        return {}

    def scheduler_stats(self):
        return {}


class SignalNoActuator:
    """Answers the autopilot probe without the weight actuator: the sticky
    managed set will broadcast set_tenant_weight straight into an
    AttributeError inside this replica."""

    def autopilot_signals(self):
        return {"queued": 0, "running": 0}


class DriftedShutdown:
    """Has the member but the broadcast shape (zero args) no longer binds."""

    def shutdown(self, grace_period):
        return grace_period


class WholeSurface:
    def cache_stats(self):
        return {}

    def scheduler_stats(self):
        return {}

    def recorder_stats(self):
        return {}

    def capture_profile(self, duration_s=3.0):
        return {}


class EngineInternal:
    """Not deployed: partial surface is fine off the process boundary."""

    def cache_stats(self):
        return {}


class SuppressedPartial:  # raylint: disable=RL1003 (fixture: roster completed by a mixin the linter can't see)
    def autopilot_signals(self):
        return {}


def build_app(serve):
    a = serve.deployment(name="partial")(PartialStats)
    b = serve.deployment(name="signal")(SignalNoActuator)
    c = serve.deployment(name="drifted")(DriftedShutdown)
    d = serve.deployment(name="whole")(WholeSurface)
    e = serve.deployment(name="suppressed")(SuppressedPartial)
    return a, b, c, d, e
