"""Thin-client mode (ray_tpu://), tracing propagation, usage stats.

Reference shapes: Ray Client (ray:// in util/client/), tracing_helper span
propagation through task metadata, usage_lib opt-out recording.
"""

import time

import numpy as np
import pytest

import ray_tpu


def test_thin_client_mode():
    """ray_tpu://host:port attaches with NO local daemons: the data plane rides
    RPC to the head raylet (put_bytes / read_chunk) instead of shared memory."""
    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    try:
        ctx = ray_tpu.init(address=f"ray_tpu://{cluster.address}")
        assert ctx is not None
        w = ray_tpu.global_worker()
        assert w.remote_data_plane

        @ray_tpu.remote
        def double(x):
            return x * 2

        assert ray_tpu.get(double.remote(21), timeout=120) == 42

        # Plasma-sized traffic both directions over the RPC data plane.
        big = np.arange(500_000, dtype=np.float64)
        ref = ray_tpu.put(big)
        back = ray_tpu.get(ref, timeout=120)
        np.testing.assert_array_equal(back, big)

        @ray_tpu.remote
        def make_big():
            return np.ones(400_000)

        arr = ray_tpu.get(make_big.remote(), timeout=120)
        assert float(arr.sum()) == 400_000.0

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
        assert ray_tpu.get(c.incr.remote(), timeout=120) == 2
    finally:
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        cluster.shutdown()


def test_tracing_spans_propagate(ray_start_isolated):
    """Spans flow through nested remote calls into the task-event pipeline."""
    from ray_tpu.util import tracing

    tracing.enable()
    try:

        @ray_tpu.remote
        def child(x):
            return x + 1

        @ray_tpu.remote
        def parent(x):
            return ray_tpu.get(child.remote(x)) + 10

        with tracing.trace("workflow") as root:
            assert ray_tpu.get(parent.remote(1), timeout=120) == 12
            trace_id = root["trace_id"]

        w = ray_tpu.global_worker()

        def traced_events():
            events = w.gcs_call("list_task_events", 5000)
            return [e for e in events if e.get("trace_id") == trace_id]

        deadline = time.monotonic() + 30
        by_name = {}
        while time.monotonic() < deadline:
            evs = traced_events()
            by_name = {}
            for e in evs:
                by_name.setdefault(e["name"], []).append(e)
            if "parent" in by_name and "child" in by_name:
                break
            time.sleep(0.5)
        assert "parent" in by_name and "child" in by_name, by_name.keys()
        parent_span = by_name["parent"][0]["span_id"]
        child_ev = by_name["child"][0]
        # The child's parent span is the parent TASK's span: one connected trace.
        assert child_ev["parent_span_id"] == parent_span
    finally:
        tracing.disable()


def test_otlp_span_conversion():
    """Task events -> OTLP/JSON spans: pairing, parenting, error status, and
    the proto JSON mapping (hex ids, nano strings)."""
    from ray_tpu.util.tracing_export import spans_from_task_events, to_otlp_json

    t = 1000.0
    events = [
        {"task_id": "a" * 24, "name": "parent", "state": "SUBMITTED", "time": t,
         "trace_id": "f" * 32, "span_id": "1" * 16, "worker_id": "w1"},
        {"task_id": "a" * 24, "name": "parent", "state": "RUNNING", "time": t + 0.5,
         "trace_id": "f" * 32, "span_id": "1" * 16, "worker_id": "w1"},
        {"task_id": "b" * 24, "name": "child", "state": "RUNNING", "time": t + 1,
         "trace_id": "f" * 32, "span_id": "2" * 16,
         "parent_span_id": "1" * 16, "worker_id": "w2"},
        {"task_id": "b" * 24, "name": "child", "state": "FAILED", "time": t + 2,
         "trace_id": "f" * 32, "span_id": "2" * 16,
         "parent_span_id": "1" * 16, "worker_id": "w2"},
        {"task_id": "a" * 24, "name": "parent", "state": "FINISHED", "time": t + 3,
         "trace_id": "f" * 32, "span_id": "1" * 16, "worker_id": "w1"},
        # untraced event: must not produce a span
        {"task_id": "c" * 24, "name": "plain", "state": "RUNNING", "time": t},
    ]
    spans = spans_from_task_events(events)
    assert {s["name"] for s in spans} == {"parent", "child"}
    child = next(s for s in spans if s["name"] == "child")
    assert child["parent_span_id"] == "1" * 16 and not child["ok"]
    parent = next(s for s in spans if s["name"] == "parent")
    assert parent["attributes"]["ray_tpu.submitted_s"] == t

    otlp = to_otlp_json(spans, service_name="svc")
    scope_spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(scope_spans) == 2
    oc = next(s for s in scope_spans if s["name"] == "child")
    assert oc["traceId"] == "f" * 32 and oc["parentSpanId"] == "1" * 16
    assert oc["status"]["code"] == 2  # STATUS_CODE_ERROR
    assert oc["startTimeUnixNano"] == str(int((t + 1) * 1e9))


def test_otlp_http_export_end_to_end(ray_start_isolated):
    """Traced cluster spans POST to an OTLP/HTTP collector (in-process stub)."""
    import http.server
    import json as _json
    import threading

    from ray_tpu.util import tracing
    from ray_tpu.util.tracing_export import export_otlp_http

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, _json.loads(body)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    tracing.enable()
    try:

        @ray_tpu.remote
        def traced(x):
            return x * 2

        with tracing.trace("export-test"):
            assert ray_tpu.get(traced.remote(5), timeout=120) == 10

        w = ray_tpu.global_worker()
        deadline = time.monotonic() + 30
        n = 0
        while time.monotonic() < deadline:
            n = export_otlp_http(f"http://127.0.0.1:{srv.server_port}")
            if n > 0:
                break
            time.sleep(0.5)
        assert n > 0
        path, payload = received[-1]
        assert path == "/v1/traces"
        names = [s["name"] for s in
                 payload["resourceSpans"][0]["scopeSpans"][0]["spans"]]
        assert "traced" in names
    finally:
        tracing.disable()
        srv.shutdown()


def test_usage_stats_recorded(ray_start_isolated):
    from ray_tpu import _driver_state
    from ray_tpu._private import usage_stats

    session_dir = _driver_state.get("session_dir")
    assert session_dir
    usage_stats.record_library_usage("unit-test-lib")
    stats = usage_stats.read(session_dir)
    assert stats is not None
    assert "unit-test-lib" in stats["libraries_used"]
    assert stats["cluster"].get("resources")
