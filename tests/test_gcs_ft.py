"""GCS fault tolerance: persistent store, GCS crash + restart recovery.

Reference shapes: the GCS runs as its own process (gcs_server_main.cc) over a
persistent store client (redis_store_client.h); on restart it re-learns durable
tables from storage and live state from raylet re-registration (gcs_init_data.cc).
Tests mirror python/ray/tests with external-Redis GCS restart coverage.
"""

import time

import ray_tpu
from ray_tpu._private.gcs_store import FileStoreClient


def _wait_for(pred, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_file_store_roundtrip(tmp_path):
    store = FileStoreClient(str(tmp_path / "s"))
    store.load()
    for i in range(100):
        store.put("t", f"k{i}", {"v": i})
    store.delete("t", "k0")
    store.put("kv", ("ns", b"key"), b"value")
    store.close()

    store2 = FileStoreClient(str(tmp_path / "s"))
    store2.load()
    assert store2.get("t", "k1") == {"v": 1}
    assert store2.get("t", "k0") is None
    assert store2.get("kv", ("ns", b"key")) == b"value"
    assert len(store2.keys("t")) == 99
    store2.close()


def test_file_store_compaction_shrinks_log_and_reloads(tmp_path, monkeypatch):
    """Crossing _COMPACT_THRESHOLD rewrites the append log as one snapshot
    record per LIVE key: the file actually shrinks (overwrites and deletes
    drop out), appends keep working afterwards, and a fresh load of the
    compacted store is identical to the pre-compaction contents."""
    import os

    from ray_tpu._private.config import CONFIG

    monkeypatch.setenv("RAY_TPU_GCS_STORE_COMPACT_THRESHOLD", "200")
    CONFIG._reset()
    try:
        store = FileStoreClient(str(tmp_path / "s"))
        store.load()
        path = store._path
        # 199 appends over only 10 keys: the log carries ~190 dead records.
        for i in range(199):
            store.put("t", f"k{i % 10}", {"v": i})
        pre_size = os.path.getsize(path)
        assert store._appends_since_compact == 199
        store.put("t", "k0", {"v": 999})  # 200th append crosses the threshold
        assert store._appends_since_compact == 0, "compaction never ran"
        post_size = os.path.getsize(path)
        assert post_size < pre_size // 4, (
            f"log did not shrink: {pre_size} -> {post_size}"
        )
        # Appends after compaction land in the fresh log.
        store.put("t", "k10", {"v": 1000})
        store.delete("t", "k9")
        store.close()

        # The compacted store reloads identically.
        store2 = FileStoreClient(str(tmp_path / "s"))
        store2.load()
        assert store2.get("t", "k0") == {"v": 999}
        for i in range(1, 9):
            assert store2.get("t", f"k{i}") == {"v": 190 + i}
        assert store2.get("t", "k9") is None
        assert store2.get("t", "k10") == {"v": 1000}
        assert len(store2.keys("t")) == 10
        store2.close()
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_STORE_COMPACT_THRESHOLD")
        CONFIG._reset()


def test_file_store_survives_torn_tail(tmp_path):
    store = FileStoreClient(str(tmp_path / "s"))
    store.load()
    store.put("t", "a", 1)
    store.close()
    with open(str(tmp_path / "s" / "gcs_tables.log"), "ab") as f:
        f.write(b"\x80\x05garbage-torn-record")
    store2 = FileStoreClient(str(tmp_path / "s"))
    store2.load()
    assert store2.get("t", "a") == 1
    store2.close()


def test_file_store_truncated_mid_record_recovers(tmp_path):
    """Crash-mid-append simulation: truncate the log INSIDE the last pickle
    frame (not appended garbage — a genuinely torn record). load() must
    recover every whole record, truncate the torn tail away, and later
    appends must be readable on the next load (the gcs_store truncate path)."""
    import os

    store = FileStoreClient(str(tmp_path / "s"))
    store.load()
    sizes = []
    for i in range(20):
        store.put("t", f"k{i}", {"v": i, "pad": "x" * 64})
        sizes.append(os.path.getsize(store._path))
    store.close()

    path = str(tmp_path / "s" / "gcs_tables.log")
    # Cut 7 bytes into the final record: k19's frame is torn mid-bytes.
    torn_at = sizes[-2] + 7
    with open(path, "r+b") as f:
        f.truncate(torn_at)

    store2 = FileStoreClient(str(tmp_path / "s"))
    store2.load()
    for i in range(19):
        assert store2.get("t", f"k{i}") == {"v": i, "pad": "x" * 64}, i
    assert store2.get("t", "k19") is None  # torn record is gone, not garbled
    assert os.path.getsize(path) == sizes[-2], "torn tail not truncated"
    # Appends land cleanly after the truncated tail...
    store2.put("t", "k19", {"v": 190})
    store2.put("t", "k20", {"v": 200})
    store2.close()
    # ...and are readable on the next load.
    store3 = FileStoreClient(str(tmp_path / "s"))
    store3.load()
    assert store3.get("t", "k19") == {"v": 190}
    assert store3.get("t", "k20") == {"v": 200}
    assert store3.get("t", "k0") == {"v": 0, "pad": "x" * 64}
    store3.close()


def test_file_store_close_joins_group_syncer_under_load(tmp_path):
    """close() must JOIN the group-fsync thread, not just flag it: a close
    racing the syncer's dup'd-fd fsync could fsync/close a recycled fd. Under
    a write hammer, close() returns with the syncer dead and the store
    reloads intact."""
    import threading

    for round_i in range(5):
        store = FileStoreClient(str(tmp_path / f"s{round_i}"))
        store.load()
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                try:
                    store.put("t", f"k{i % 50}", i)
                except Exception:
                    return  # store closed under us: the race being tested
                i += 1

        writers = [threading.Thread(target=hammer) for _ in range(3)]
        for t in writers:
            t.start()
        time.sleep(0.05)  # syncer windows are 10ms: several in flight
        store.close()
        assert store._syncer is None
        stop.set()
        for t in writers:
            t.join(timeout=5)
        check = FileStoreClient(str(tmp_path / f"s{round_i}"))
        check.load()
        assert check.get("t", "k0") is not None
        check.close()


def test_file_store_dir_fsync_on_first_create(tmp_path, monkeypatch):
    """Creating the log file must fsync the store DIRECTORY (a host crash
    right after cluster start could otherwise strand a dirent pointing at
    nothing); reopening an existing log must not re-pay it."""
    import os
    import stat

    synced_dirs = []
    real_fsync = os.fsync

    def spy_fsync(fd):
        if stat.S_ISDIR(os.fstat(fd).st_mode):
            synced_dirs.append(fd)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    store = FileStoreClient(str(tmp_path / "s"))
    store.load()
    assert synced_dirs, "log-file creation did not fsync the store directory"
    store.put("t", "k", 1)
    store.close()

    synced_dirs.clear()
    store2 = FileStoreClient(str(tmp_path / "s"))
    store2.load()
    assert not synced_dirs, "reopening an existing log re-fsynced the dir"
    assert store2.get("t", "k") == 1
    store2.close()


def test_store_stats_and_driver_report_path(tmp_path):
    """The store keeps plain counters (append count/seconds, log bytes,
    compactions); stats_view() snapshots them for the report path."""
    store = FileStoreClient(str(tmp_path / "s"))
    store.load()
    for i in range(10):
        store.put("t", f"k{i}", i)
    view = store.stats_view()
    assert view["appends"] == 10
    assert view["append_seconds"] > 0.0
    assert view["log_bytes"] > 0
    assert view["compactions"] == 0
    store.close()


def test_gcs_restart_cluster_keeps_working():
    """Kill the GCS mid-session; after restart the cluster resumes: named actors
    stay reachable, pre-crash KV and plasma objects survive, new tasks run."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        w = ray_tpu.global_worker()

        @ray_tpu.remote(name="counter")
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
        w.gcs_kv_put("app", b"config", b"v1")
        big = ray_tpu.put(np.ones(300_000))

        cluster.head.kill_gcs()
        time.sleep(1.0)
        cluster.head.restart_gcs()

        # Raylets re-register and re-report hosted actors + sealed objects.
        assert _wait_for(
            lambda: len([n for n in ray_tpu.nodes() if n["alive"]]) >= 1, timeout=30
        )
        # Durable KV survived via the file store.
        assert _wait_for(lambda: w.gcs_kv_get("app", b"config") == b"v1", timeout=30)
        # The actor's in-memory state survived (its process never died).
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
        # Named-actor registry restored from storage + re-report.
        h = ray_tpu.get_actor("counter")
        assert ray_tpu.get(h.incr.remote(), timeout=60) == 3
        # Object directory rebuilt from the raylet's sealed-object re-report.
        assert float(ray_tpu.get(big, timeout=60).sum()) == 300_000.0
        # New work schedules normally.

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21), timeout=60) == 42
    finally:
        cluster.shutdown()


def test_calls_retry_through_gcs_downtime():
    """A driver KV call issued while the GCS is down blocks and succeeds once the
    GCS is back (client-side buffer+retry, reference GCS client behavior)."""
    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        w = ray_tpu.global_worker()
        w.gcs_kv_put("app", b"k", b"v0")
        cluster.head.kill_gcs()

        import threading

        result = {}

        def blocked_put():
            try:
                w.gcs_kv_put("app", b"k", b"v1")
                result["ok"] = True
            except Exception as e:  # pragma: no cover - failure path
                result["err"] = e

        t = threading.Thread(target=blocked_put)
        t.start()
        time.sleep(1.5)
        cluster.head.restart_gcs()
        t.join(timeout=30)
        assert result.get("ok"), result
        assert _wait_for(lambda: w.gcs_kv_get("app", b"k") == b"v1", timeout=30)
    finally:
        cluster.shutdown()


def test_file_store_fsync_mode(tmp_path, monkeypatch):
    """RAY_TPU_GCS_STORE_FSYNC=1 syncs every append (host-crash durability,
    VERDICT weak #7); data survives reload either way."""
    import os

    from ray_tpu._private.gcs_store import FileStoreClient

    monkeypatch.setenv("RAY_TPU_GCS_STORE_FSYNC", "1")
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd), real_fsync(fd)))
    store = FileStoreClient(str(tmp_path))
    store.load()
    store.put("kv", b"a", b"1")
    assert synced, "fsync mode did not sync the append"
    monkeypatch.setenv("RAY_TPU_GCS_STORE_FSYNC", "0")
    store2 = FileStoreClient(str(tmp_path))
    assert not store2._fsync and store2._fsync_mode == "off"
    store2.load()
    assert store2.get("kv", b"a") == b"1"
    store2.close()
    # Default (unset): group-commit fsync — a background thread syncs windows
    # of appends, so host crashes lose at most one window.
    monkeypatch.delenv("RAY_TPU_GCS_STORE_FSYNC")
    store3 = FileStoreClient(str(tmp_path))
    assert store3._fsync_mode == "group" and store3._syncer is not None
    store3.load()
    synced.clear()
    for i in range(50):
        store3.put("kv", f"g{i}".encode(), b"x")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not synced:
        time.sleep(0.02)
    assert synced, "group-commit thread never fsynced the window"
    assert len(synced) < 50, "group commit should amortize, not sync per append"
    store3.close()


def test_gcs_sigkill_mid_append_recovers():
    """Crash consistency: SIGKILL the GCS while a client hammers KV writes;
    after restart every ACKed write must be present (flushed appends survive a
    process kill; the torn tail record, if any, is truncated on load).
    Matches redis_store_client.h:126 recovery semantics."""
    from ray_tpu.cluster_utils import Cluster
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 1, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        from ray_tpu._private.worker import _global_worker as w

        acked = []
        # Hammer writes; the GCS is killed from under the loop mid-stream.
        import threading

        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set() and i < 2000:
                try:
                    w.gcs_kv_put("crash", f"k{i}".encode(), str(i).encode())
                    acked.append(i)
                    i += 1
                except Exception:
                    return
            stop.set()

        t = threading.Thread(target=writer)
        t.start()
        time.sleep(1.0)  # let a few hundred ACKs land
        cluster.head.kill_gcs()  # SIGKILL, possibly mid-append
        stop.set()
        t.join(timeout=30)
        n_acked = len(acked)
        assert n_acked > 50, f"only {n_acked} writes landed before the kill"
        cluster.head.restart_gcs()
        assert _wait_for(
            lambda: w.gcs_kv_get("crash", b"k0") == b"0", timeout=30
        )
        for i in (0, n_acked // 2, n_acked - 2):
            key = f"k{i}".encode()
            assert _wait_for(
                lambda k=key, v=str(i).encode(): w.gcs_kv_get("crash", k) == v,
                timeout=10,
            ), f"ACKed write k{i} lost across SIGKILL+restart"
        # The cluster stays operational on the recovered control plane.
        @ray_tpu.remote
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=120) == "ok"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_gcs_call_backoff_deadline_and_reconnect_metric(monkeypatch):
    """gcs_call rides a short GCS outage transparently (counting the
    reconnect in gcs_reconnect_total), and surfaces ConnectionLost only after
    the configurable gcs_rpc_timeout_s deadline."""
    import threading

    import pytest

    from ray_tpu._private import rpc
    from ray_tpu._private.config import CONFIG
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import metrics as util_metrics
    from tests.conftest import _WORKER_ENV

    monkeypatch.setenv("RAY_TPU_GCS_RPC_TIMEOUT_S", "4")
    CONFIG._reset()
    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 1, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        w = ray_tpu.global_worker()
        w.gcs_kv_put("ft", b"k", b"v0")

        # (a) Outage SHORTER than the deadline: the call blocks, reconnects
        # with backoff, succeeds — and the recovery is observable.
        cluster.head.kill_gcs()
        result = {}

        def blocked_put():
            try:
                w.gcs_kv_put("ft", b"k", b"v1")
                result["ok"] = True
            except Exception as e:  # pragma: no cover - failure path
                result["err"] = e

        t = threading.Thread(target=blocked_put)
        t.start()
        time.sleep(1.0)
        cluster.head.restart_gcs()
        t.join(timeout=30)
        assert result.get("ok"), result
        assert w.gcs_kv_get("ft", b"k") == b"v1"
        names = {m["name"] for m in util_metrics.collect_all()}
        assert "gcs_reconnect_total" in names

        # (b) Outage LONGER than the deadline: typed ConnectionLost after
        # ~gcs_rpc_timeout_s, not an unbounded hang.
        cluster.head.kill_gcs()
        t0 = time.monotonic()
        with pytest.raises(rpc.ConnectionLost):
            w.gcs_kv_put("ft", b"k", b"v2")
        elapsed = time.monotonic() - t0
        assert 3.0 <= elapsed < 25.0, f"deadline not honored: {elapsed:.1f}s"
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_RPC_TIMEOUT_S")
        CONFIG._reset()
        cluster.shutdown()
