"""Serve control-plane fault tolerance: durable controller state, typed
routing errors, idempotent deploy replay, and the gcs_call backoff contract.

Reference shapes: the serve controller checkpoints to the GCS KV store and
recovers on restart (serve/_private/application_state.py checkpointing); GCS
clients retry through GCS downtime with backoff. Chaos-level coverage (SIGKILL
under live traffic) lives in tests/test_chaos.py; these are the targeted
contract tests.
"""

import threading
import time

import pytest

import ray_tpu
from tests.conftest import _WORKER_ENV


@pytest.fixture(scope="module")
def serve_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=_WORKER_ENV)
    yield
    from ray_tpu import serve

    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps(request):
    yield
    if "serve_cluster" in request.fixturenames:
        from ray_tpu import serve

        for app in list(serve.status()):
            serve.delete(app)


def test_handle_missing_app_raises_deployment_not_found(serve_cluster):
    """A handle to an app the controller does not know is a DEFINITIVE error:
    DeploymentNotFoundError, raised promptly — NOT a 30s retry loop and not a
    raw connection error (callers must distinguish 'app deleted' from
    'controller restarting')."""
    from ray_tpu import serve

    @serve.deployment
    def f(x):
        return x + 1

    serve.run(f.bind(), name="ft-exists", route_prefix=None)

    handle = serve.get_deployment_handle("Missing", app_name="no-such-app")
    t0 = time.monotonic()
    with pytest.raises(serve.DeploymentNotFoundError):
        handle.remote(1)
    assert time.monotonic() - t0 < 10.0, "definitive miss should not retry long"


def test_deleted_app_calls_raise_deployment_not_found(serve_cluster):
    """Calls racing an app deletion surface DeploymentNotFoundError (the
    replica-death resubmit path re-routes into the typed error instead of
    leaking ActorDiedError)."""
    from ray_tpu import serve

    @serve.deployment
    def g(x):
        return x * 2

    handle = serve.run(g.bind(), name="ft-deleted", route_prefix=None)
    assert handle.remote(4).result(timeout_s=60) == 8
    serve.delete("ft-deleted")
    with pytest.raises(serve.DeploymentNotFoundError):
        # The cached replica may absorb the first call as ActorDiedError; the
        # internal resubmit re-resolves through the controller and must land
        # on the typed error within the handle's retry budget.
        for _ in range(5):
            handle.remote(4).result(timeout_s=60)
            time.sleep(0.5)


def test_no_controller_raises_controller_unavailable(serve_cluster, monkeypatch):
    """With no controller at all (never started), routing retries with backoff
    up to the recovery deadline and then raises the RETRYABLE typed error."""
    from ray_tpu import serve
    from ray_tpu._private.config import CONFIG

    serve.shutdown()  # no controller, and durable state cleared
    monkeypatch.setenv("RAY_TPU_GCS_RPC_TIMEOUT_S", "2")
    CONFIG._reset()
    try:
        handle = serve.get_deployment_handle("D", app_name="nobody-home")
        t0 = time.monotonic()
        with pytest.raises(serve.ControllerUnavailableError):
            handle.remote(1)
        elapsed = time.monotonic() - t0
        assert 1.5 <= elapsed < 20.0, f"deadline not honored: {elapsed:.1f}s"
        assert issubclass(serve.ControllerUnavailableError, ConnectionError)
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_RPC_TIMEOUT_S")
        CONFIG._reset()


def test_deploy_replay_is_idempotent(serve_cluster):
    """A replayed deploy_app with identical code/config must ADOPT the live
    replicas, not double-create or restart them (mirrors the GCS
    bundle-reservation replay guard at rpc_create_placement_group)."""
    from ray_tpu import serve
    from ray_tpu.serve._common import CONTROLLER_NAME, SERVE_NAMESPACE

    @serve.deployment(num_replicas=2)
    class Idem:
        def pid(self):
            import os

            return os.getpid()

        def __call__(self, x):
            return x - 1

    handle = serve.run(Idem.bind(), name="ft-idem", route_prefix=None)
    assert handle.remote(3).result(timeout_s=60) == 2
    pid_handle = serve.DeploymentHandle("ft-idem", "Idem", "pid")
    pids_first = sorted(pid_handle.broadcast())
    assert len(pids_first) == 2

    for _ in range(2):  # replay twice: still the same two processes
        serve.run(Idem.bind(), name="ft-idem", route_prefix=None)
        assert sorted(pid_handle.broadcast()) == pids_first

    controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    info = ray_tpu.get(
        controller.get_replicas.remote("ft-idem", "Idem"), timeout=60
    )
    assert len(info["replicas"]) == 2


def test_controller_state_persists_to_kv_and_clears_on_shutdown(serve_cluster):
    """Every mutation lands in GCS KV (the recovery source of truth); an
    explicit serve.shutdown clears it so the next instance starts cold."""
    import cloudpickle

    from ray_tpu import serve
    from ray_tpu.serve._common import (
        CONTROLLER_KV_NS,
        REGISTRY_KEY,
        TARGET_STATE_KEY,
    )

    @serve.deployment
    def h(x):
        return x

    serve.run(h.bind(), name="ft-durable", route_prefix=None)
    w = ray_tpu.global_worker()
    state = w.gcs_kv_get(CONTROLLER_KV_NS, TARGET_STATE_KEY)
    registry = w.gcs_kv_get(CONTROLLER_KV_NS, REGISTRY_KEY)
    assert state is not None and registry is not None
    apps = cloudpickle.loads(state)["apps"]
    assert "ft-durable" in apps and "h" in apps["ft-durable"]
    reg = cloudpickle.loads(registry)
    assert len(reg["replicas"]["ft-durable"]["h"]) == 1

    serve.shutdown()
    assert w.gcs_kv_get(CONTROLLER_KV_NS, TARGET_STATE_KEY) is None
    assert w.gcs_kv_get(CONTROLLER_KV_NS, REGISTRY_KEY) is None
