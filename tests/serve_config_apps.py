"""Importable applications for the declarative serve-config tests.

`serve deploy` resolves `import_path: "tests.serve_config_apps:<attr>"` against
this module — a bound Application (`app`) and a builder callable
(`build_app`), matching the two target kinds the reference CLI accepts.
"""

import os

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x: int) -> int:
        return x * 2

    def pid(self) -> int:
        return os.getpid()


@serve.deployment
class Gateway:
    def __init__(self, doubler):
        self._doubler = doubler

    def __call__(self, x: int) -> int:
        return self._doubler.remote(x).result() + 1

    def pids(self) -> int:
        return os.getpid()


app = Gateway.bind(Doubler.bind())


@serve.deployment
class Echo:
    def __init__(self, prefix: str = "echo"):
        self._prefix = prefix

    def __call__(self, x) -> str:
        return f"{self._prefix}:{x}"


def build_app(args=None):
    args = args or {}
    return Echo.bind(args.get("prefix", "echo"))
