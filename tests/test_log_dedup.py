"""Driver-side log deduplication.

Shape parity: reference python/ray/_private/ray_logging LogDeduplicator tests —
identical lines spamming from many workers collapse to one line plus a
'[repeated Nx across ...]' summary; distinct lines pass through; numeric
differences don't defeat the match; the toggle disables it.
"""

import ray_tpu
from ray_tpu._private.worker import _LogDeduplicator


def test_dedup_collapses_repeats_and_summarizes(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOG_DEDUP", "1")
    d = _LogDeduplicator()
    out1 = d.ingest("(worker pid=1)", 1, ["loading shard 1 of 8"])
    assert "loading shard 1 of 8" in out1
    # same line (different numbers, different workers) within the window:
    # suppressed
    for pid in (2, 3, 4):
        assert d.ingest(f"(worker pid={pid})", pid,
                        [f"loading shard {pid} of 8"]) == ""
    # a DIFFERENT line passes through immediately
    out2 = d.ingest("(worker pid=2)", 2, ["something else entirely"])
    assert "something else entirely" in out2
    # expiry emits the summary with counts and process count
    d._seen[next(iter(d._seen))]["first_t"] -= 10  # age the first entry
    summary = d.flush_expired()
    assert "[repeated 3x across 4 process(es)" in summary


def test_dedup_disabled_passthrough(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOG_DEDUP", "0")
    d = _LogDeduplicator()
    lines = [d.ingest("(w)", 1, ["same line 1"]) for _ in range(5)]
    assert all("same line 1" in ln for ln in lines)


def test_worker_log_lines_still_reach_driver(ray_start_regular, capfd):
    """End to end: a worker print still lands on the driver's stderr exactly
    once (dedup must not eat first occurrences)."""
    import time

    @ray_tpu.remote
    def chatty():
        print("dedup-e2e-probe-line")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=120) == 1
    deadline = time.time() + 30
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().err
        if "dedup-e2e-probe-line" in seen:
            break
        time.sleep(0.5)
    assert "dedup-e2e-probe-line" in seen
