"""Object data plane: direct-arena put/get, batched bookkeeping, cross-node pulls.

Reference: `src/ray/object_manager/` (push/pull managers, object_buffer_pool
chunked transfer) and plasma client semantics. Round-3 rebuild: workers
alloc/write/seal directly in the shared arena (zero RPC on the hot path),
free eagerly on refcount-zero, and raylets pull remote objects with pipelined
parallel chunks under a budgeted pull manager.
"""

import time

import numpy as np
import pytest

import ray_tpu


def test_put_get_roundtrip_zero_rpc(ray_start_isolated):
    """Direct-arena put/get: values survive the round trip bit-exact."""
    arr = np.arange(1 << 20, dtype=np.float64)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)
    # A second get of the same ref re-reads the sealed object.
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)


def test_put_free_reuses_arena_blocks(ray_start_isolated):
    """Refcount-zero frees return blocks to the arena promptly: repeated
    put/drop cycles must not grow arena usage without bound."""
    from ray_tpu._private.worker import _global_worker

    arr = np.zeros(8 << 20, dtype=np.uint8)
    for _ in range(5):
        ray_tpu.get(ray_tpu.put(arr))
    arena = _global_worker.reader._arena(_global_worker._store_arena)
    # Let the final deferred free drain.
    deadline = time.monotonic() + 10
    target = 12 << 20  # one live block plus slack, not five
    used = None
    while time.monotonic() < deadline:
        ray_tpu.put(b"drain")  # put() drains deferred frees
        used = _global_worker.raylet_call("store_stats")["used_bytes"]
        if used < 5 * (8 << 20):
            break
        time.sleep(0.1)
    assert used is not None and used < 5 * (8 << 20), (
        f"arena holds {used} bytes after 5 put/free cycles of 8MiB"
    )
    assert arena is not None


def test_seal_then_free_within_batch_window(ray_start_isolated):
    """An object sealed and freed inside one report window must still be
    locally consistent (no phantom directory entries resurrect it)."""
    for _ in range(20):
        ref = ray_tpu.put(np.ones(1024))
        assert float(ray_tpu.get(ref).sum()) == 1024.0
        del ref  # freed almost immediately after seal


def test_cross_node_gigabyte_transfer(ray_start_cluster):
    """Move >=1 GiB node-to-node through the pull path (VERDICT r2 #1 gate)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"producer": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"producer": 1}, num_cpus=0)
    def produce(i):
        # 4 x 272MiB named pieces: > 1 GiB total crosses the wire.
        return np.full((17, 4 << 20), float(i), dtype=np.float64)

    @ray_tpu.remote(num_cpus=1)
    def consume(arr, i):
        assert arr.shape == (17, 4 << 20)
        return float(arr[0, 0]) == float(i) and float(arr[-1, -1]) == float(i)

    t0 = time.monotonic()
    total = 0
    for i in range(4):
        ref = produce.remote(i)
        assert ray_tpu.get(consume.remote(ref, i), timeout=600)
        total += 17 * (4 << 20) * 8
        del ref
    elapsed = time.monotonic() - t0
    assert total >= (1 << 30)
    # Sanity floor only (CI box is 1-core): the transfer must not be
    # pathologically slow. Bandwidth is reported for the record.
    print(f"cross-node transfer: {total / 2**30:.2f} GiB in {elapsed:.1f}s "
          f"({total / 2**30 / elapsed:.2f} GiB/s)")


def test_pull_manager_dedups_concurrent_pulls(ray_start_cluster):
    """Two tasks needing the same remote object trigger one pull, not two."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"src": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"src": 1}, num_cpus=0)
    def produce():
        return np.ones((2000, 2000))

    @ray_tpu.remote(num_cpus=1)
    def s(arr):
        return float(arr.sum())

    ref = produce.remote()
    a, b = s.remote(ref), s.remote(ref)
    assert ray_tpu.get(a, timeout=300) == ray_tpu.get(b, timeout=300) == 4e6
