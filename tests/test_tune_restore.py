"""Tuner.restore: experiment-state checkpointing + resume.

Shape parity with the reference suite (python/ray/tune/tests/test_tuner_restore.py):
a SIGKILLed driver's experiment restores from its directory, checkpointed trials
resume from their latest checkpoints (never rerun from scratch), finished trials
keep their results, searcher state (TPE observations) survives the restore.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu import tune


_DRIVER = """
import os, sys, time
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu import tune
from ray_tpu.train.checkpoint import Checkpoint

ray_tpu.init(num_cpus=2, worker_env={{"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}})

def slow_trial(config):
    import json, tempfile
    # Count every executed iteration in a file OUTSIDE the trial dir so the
    # restore test can prove checkpointed work is not redone.
    marker_dir = {markers!r}
    start = 1
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "state.json")) as f:
            start = json.load(f)["iter"] + 1
    for i in range(start, 6):
        with open(os.path.join(marker_dir, f"{{config['x']}}_{{i}}"), "a") as f:
            f.write("1")
        time.sleep(0.6)
        d = tempfile.mkdtemp()
        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump({{"iter": i}}, f)
        tune.report({{"score": float(config["x"] * 10 + i)}},
                    checkpoint=Checkpoint(d))

tune.Tuner(
    slow_trial,
    param_space={{"x": tune.grid_search([1, 2, 3, 4])}},
    tune_config=tune.TuneConfig(metric="score", mode="max",
                                max_concurrent_trials=2),
    run_config=tune.RunConfig(name="restore_exp", storage_path={storage!r}),
).fit()
print("DRIVER_DONE")
"""


def test_killed_driver_experiment_restores(ray_start_regular, tmp_path):
    """Kill the driver mid-sweep; Tuner.restore completes the grid without
    rerunning checkpointed iterations."""
    storage = str(tmp_path / "storage")
    markers = str(tmp_path / "markers")
    os.makedirs(storage)
    os.makedirs(markers)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _DRIVER.format(repo=repo, storage=storage, markers=markers)
    # Own session/process group: the kill below takes out the driver AND its
    # cluster daemons + trial actors in one shot (host-death semantics) —
    # surviving orphan actors would keep executing iterations and taint the
    # exactly-once assertion.
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        cwd=str(tmp_path),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    exp_dir = os.path.join(storage, "restore_exp")
    state_file = os.path.join(exp_dir, "experiment_state.pkl")
    # Wait until real progress exists: a snapshot AND >= 3 checkpointed
    # iterations, then SIGKILL the driver (no cleanup, no final snapshot).
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if os.path.isfile(state_file) and len(os.listdir(markers)) >= 3:
            break
        if proc.poll() is not None:
            out = proc.stdout.read().decode()
            pytest.fail(f"driver exited early:\n{out}")
        time.sleep(0.3)
    else:
        proc.kill()
        pytest.fail("driver made no restorable progress in time")
    os.killpg(proc.pid, signal.SIGKILL)
    proc.wait()
    time.sleep(1.0)  # let the object-store arena/socket teardown settle

    # What the snapshot knew at kill time: per-trial checkpointed iteration.
    import json
    import pickle

    with open(state_file, "rb") as f:
        snap = pickle.load(f)
    ckpt_iter = {}  # x value -> iteration covered by the snapshotted checkpoint
    for ts in snap["trials"]:
        path = ts.get("latest_checkpoint")
        if path and not os.path.isabs(path):
            path = os.path.join(exp_dir, path)  # stored experiment-relative
        if path and os.path.isfile(os.path.join(path, "state.json")):
            with open(os.path.join(path, "state.json")) as f:
                ckpt_iter[ts["config"]["x"]] = json.load(f)["iter"]
    assert ckpt_iter, "snapshot recorded no trial checkpoints before the kill"

    assert tune.Tuner.can_restore(exp_dir)
    tuner = tune.Tuner.restore(exp_dir)
    grid = tuner.fit()
    assert len(grid) == 4
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores == [15.0, 25.0, 35.0, 45.0], scores  # every trial reached iter 5

    # Checkpoint-resume semantics (at-least-once PAST the checkpoint, never
    # from scratch): every iteration covered by a trial's snapshotted
    # checkpoint executed exactly once across both driver lives — the restore
    # resumed AFTER it, not from iteration 1.
    for marker in os.listdir(markers):
        x, it = (int(v) for v in marker.split("_"))
        count = len(open(os.path.join(markers, marker)).read())
        if it <= ckpt_iter.get(x, 0):
            assert count == 1, (
                f"trial x={x} reran checkpointed iteration {it} "
                f"(snapshot covered up to {ckpt_iter[x]})"
            )
        else:
            assert count <= 2, f"iteration {marker} executed {count} times"


def test_restore_preserves_tpe_searcher_state(ray_start_regular, tmp_path):
    """The searcher's observation history survives a snapshot/restore cycle:
    after restoring, the TPE searcher continues from its recorded trials
    instead of restarting its initialization phase."""
    import pickle

    from ray_tpu.tune.search import TPESearch

    def objective(config):
        tune.report({"score": float(config["x"])})

    space = {"x": tune.uniform(0, 1)}
    searcher = TPESearch(space, metric="score", mode="max", n_initial=2, seed=7)
    tune.Tuner(
        objective,
        param_space=space,
        tune_config=tune.TuneConfig(num_samples=3, metric="score", mode="max",
                                    search_alg=searcher),
        run_config=tune.RunConfig(name="tpe_state", storage_path=str(tmp_path)),
    ).fit()
    exp_dir = os.path.join(str(tmp_path), "tpe_state")
    with open(os.path.join(exp_dir, "experiment_state.pkl"), "rb") as f:
        state = pickle.load(f)
    restored = pickle.loads(state["searcher"])
    # The snapshotted searcher carries all completed observations.
    assert len(restored._observed) >= 3
    # And a full restore cycle keeps completed trials completed: fit() after
    # restore returns immediately with the same 3 results.
    tuner = tune.Tuner.restore(exp_dir)
    grid = tuner.fit()
    assert len(grid) == 3


def test_restore_restart_errored(ray_start_regular, tmp_path):
    """restart_errored=True reruns failed trials on restore (reference:
    Tuner.restore(restart_errored=True))."""
    flag = tmp_path / "fail_once"
    flag.write_text("fail")

    def flaky(config):
        if config["x"] == 2 and flag.read_text() == "fail":
            raise RuntimeError("boom")
        tune.report({"score": float(config["x"])})

    grid1 = tune.Tuner(
        flaky,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=tune.RunConfig(name="flaky_exp", storage_path=str(tmp_path)),
    ).fit()
    errs = [r for r in grid1 if r.error is not None]
    assert len(errs) == 1
    flag.write_text("ok")
    exp_dir = os.path.join(str(tmp_path), "flaky_exp")
    grid2 = tune.Tuner.restore(exp_dir, restart_errored=True).fit()
    assert all(r.error is None for r in grid2)
    assert sorted(r.metrics["score"] for r in grid2) == [1.0, 2.0, 3.0]
