"""Unit tests for the SLO autopilot (docs/autoscale.md): pure control laws
with fake clocks, the Autopilot tick state machine, ScaleOp commit/abort
bookkeeping, persistence round-trips (restart must not flap), and the
DPRouter's retire/bootstrap hooks.

This file runs under BOTH conftest sanitizer guards: distsan (the tick is a
hot path — law math must not touch metrics; metric flushes belong to the
stats() report path) and leaksan (every ScaleOp token must resolve to
commit/abort).
"""

import asyncio
from collections import OrderedDict, deque

import pytest

from ray_tpu.serve.autopilot import (
    Autopilot,
    DecisionLog,
    DeploymentObservation,
    ReplicaBounds,
    ScaleAction,
    WeightAction,
    WeightBounds,
    aggregate_signals,
    pd_law,
    replica_law,
    wake_law,
    weight_law,
)
from ray_tpu.serve.autopilot._laws import (
    new_pd_state,
    new_replica_state,
    new_weight_state,
)


B = ReplicaBounds(min_replicas=1, max_replicas=4, burn_high=1.0,
                  queue_high=8.0, sustain_ticks=2, upscale_cooldown_s=5.0,
                  downscale_cooldown_s=30.0, cold_start_guard_s=60.0)
WB = WeightBounds(step=0.25, floor=0.25, ceiling=8.0, deadband=0.25,
                  sustain_ticks=2, cooldown_s=5.0)


# --- replica law -----------------------------------------------------------
def test_replica_law_upscale_needs_sustained_pressure():
    st = new_replica_state(1)
    assert replica_law(state=st, replicas=1, queued=20, ongoing=2, burn=0.0,
                       bounds=B, now=100.0) is None  # first hot tick
    fired = replica_law(state=st, replicas=1, queued=20, ongoing=2, burn=0.0,
                        bounds=B, now=101.0)
    assert fired is not None
    target, rule, detail = fired
    assert rule == "replica_up"
    # Queue-proportional step: 20 queued / 8 per-replica-high -> 3 replicas.
    assert target == 3
    assert st["target"] == 3 and detail["from"] == 1


def test_replica_law_burn_alone_triggers_and_cooldown_blocks():
    st = new_replica_state(1)
    for now in (100.0, 101.0):
        fired = replica_law(state=st, replicas=1, queued=0, ongoing=1,
                            burn=2.0, bounds=B, now=now)
    assert fired is not None and fired[0] == 2
    # Still burning, sustain satisfied again — but inside the 5s cooldown.
    for now in (102.0, 103.0):
        assert replica_law(state=st, replicas=2, queued=0, ongoing=1,
                           burn=2.0, bounds=B, now=now) is None
    fired = replica_law(state=st, replicas=2, queued=0, ongoing=1, burn=2.0,
                        bounds=B, now=107.0)
    assert fired is not None and fired[0] == 3


def test_replica_law_capped_at_max():
    st = new_replica_state(4)
    for now in (0.0, 1.0, 2.0):
        assert replica_law(state=st, replicas=4, queued=500, ongoing=8,
                           burn=5.0, bounds=B, now=now) is None
    assert st["target"] == 4


def test_replica_law_downscale_sustained_idle():
    st = new_replica_state(3)
    st["last_down_t"] = 0.0
    fired = None
    for i in range(2 * B.sustain_ticks):
        fired = replica_law(state=st, replicas=3, queued=0, ongoing=0,
                            burn=0.0, bounds=B, now=100.0 + i)
    assert fired is not None
    assert fired[:2] == (2, "replica_down")
    # One step at a time: next fire needs the downscale cooldown again.
    for i in range(2 * B.sustain_ticks):
        fired = replica_law(state=st, replicas=2, queued=0, ongoing=0,
                            burn=0.0, bounds=B, now=110.0 + i)
    assert fired is None
    assert st["target"] == 2


def test_replica_law_scale_to_zero_blocked_by_cold_start_guard():
    b0 = ReplicaBounds(min_replicas=0, max_replicas=4, sustain_ticks=1,
                       downscale_cooldown_s=0.0, cold_start_guard_s=60.0)
    st = new_replica_state(1)
    st["woken_t"] = 100.0
    for i in range(4):  # inside the guard window: floor is raised to 1
        assert replica_law(state=st, replicas=1, queued=0, ongoing=0,
                           burn=0.0, bounds=b0, now=101.0 + i) is None
    fired = replica_law(state=st, replicas=1, queued=0, ongoing=0, burn=0.0,
                        bounds=b0, now=200.0)  # guard expired
    assert fired is not None and fired[0] == 0


def test_wake_law_zero_to_one_and_noop_when_up():
    b0 = ReplicaBounds(min_replicas=0)
    st = new_replica_state(0)
    fired = wake_law(state=st, bounds=b0, now=50.0)
    assert fired == (1, "cold_start_wake", {"from": 0})
    assert st["woken_t"] == 50.0
    assert wake_law(state=st, bounds=b0, now=51.0) is None


# --- weight law ------------------------------------------------------------
def test_weight_law_boost_decay_and_bounds():
    st = new_weight_state()
    st["last_t"] = -100.0
    assert weight_law(state=st, burn=3.0, bounds=WB, now=0.0) is None
    fired = weight_law(state=st, burn=3.0, bounds=WB, now=1.0)
    assert fired is not None
    w, rule, _ = fired
    assert rule == "weight_up" and w == pytest.approx(1.25)
    # Healthy again: decays back toward 1.0 after 2x sustain, never below.
    for i in range(2 * WB.sustain_ticks):
        fired = weight_law(state=st, burn=0.0, bounds=WB, now=10.0 + i)
    assert fired is not None and fired[1] == "weight_decay"
    assert fired[0] == pytest.approx(1.0)
    # At 1.0 and healthy: no further decay (floor of the decay path).
    for i in range(4 * WB.sustain_ticks):
        assert weight_law(state=st, burn=0.0, bounds=WB, now=30.0 + i) is None


def test_weight_law_ceiling():
    st = new_weight_state(8.0)
    st["last_t"] = -100.0
    for i in range(4):
        assert weight_law(state=st, burn=5.0, bounds=WB, now=float(i)) is None
    assert st["weight"] == 8.0


def test_weight_law_deadband_is_quiet():
    st = new_weight_state()
    st["last_t"] = -100.0
    for i in range(6):
        assert weight_law(state=st, burn=1.0, bounds=WB, now=float(i)) is None


# --- pd law ----------------------------------------------------------------
def test_pd_law_shifts_toward_pressured_phase_conserving_total():
    st = new_pd_state()
    kw = dict(ratio_tol=2.0, sustain_ticks=2, cooldown_s=0.0)
    assert pd_law(state=st, ttft_pressure=3.0, tpot_pressure=0.5,
                  prefill_replicas=1, decode_replicas=3, now=0.0, **kw) is None
    fired = pd_law(state=st, ttft_pressure=3.0, tpot_pressure=0.5,
                   prefill_replicas=1, decode_replicas=3, now=1.0, **kw)
    assert fired is not None
    p, d, rule, _ = fired
    assert (p, d, rule) == (2, 2, "pd_shift_prefill")

    st = new_pd_state()
    for now in (0.0, 1.0):
        fired = pd_law(state=st, ttft_pressure=0.2, tpot_pressure=2.0,
                       prefill_replicas=3, decode_replicas=1, now=now, **kw)
    assert fired is not None and fired[:3] == (2, 2, "pd_shift_decode")


def test_pd_law_never_empties_a_pool():
    st = new_pd_state()
    kw = dict(ratio_tol=2.0, sustain_ticks=1, cooldown_s=0.0)
    assert pd_law(state=st, ttft_pressure=9.0, tpot_pressure=0.1,
                  prefill_replicas=3, decode_replicas=1, now=0.0, **kw) is None
    assert pd_law(state=st, ttft_pressure=0.1, tpot_pressure=9.0,
                  prefill_replicas=1, decode_replicas=3, now=1.0, **kw) is None


# --- signal aggregation ----------------------------------------------------
def test_aggregate_signals_sum_queue_max_burn():
    obs = aggregate_signals("app", "LLM", [
        {"role": "engine", "queued": 3, "running": 1, "burn_rate": 0.5,
         "tenant_burn": {"a": 0.5, "b": 2.0}},
        {"role": "engine", "queued": 5, "running": 2, "burn_rate": 1.5,
         "tenant_burn": {"a": 1.0}},
        "not-a-dict",  # a failed probe must not poison the fold
    ])
    assert obs.replicas == 3  # len(signals); controller overrides with live count
    assert obs.queued == 8 and obs.ongoing == 3
    assert obs.burn == 1.5
    assert obs.tenant_burn == {"a": 1.0, "b": 2.0}


# --- decision log ----------------------------------------------------------
def test_decision_log_bounded_and_round_trips():
    log = DecisionLog(cap=4)
    for i in range(10):
        log.append(rule="replica_up", app="a", deployment="d",
                   action=f"target={i}", t=float(i))
    assert len(log) == 4
    assert log.counts == {"replica_up": 10}
    assert [e["seq"] for e in log.entries()] == [7, 8, 9, 10]
    loaded = DecisionLog.load(log.dump(), cap=4)
    assert loaded.counts == {"replica_up": 10}
    assert [e["seq"] for e in loaded.entries()] == [7, 8, 9, 10]
    loaded.append(rule="replica_down", app="a")
    assert loaded.entries()[-1]["seq"] == 11  # seq survives the round trip


# --- Autopilot tick --------------------------------------------------------
def _obs(app="app", dep="LLM", **kw):
    kw.setdefault("bounds", B)
    kw.setdefault("replicas", 1)
    return DeploymentObservation(app=app, deployment=dep, **kw)


def test_tick_scale_up_then_down_full_cycle():
    ap = Autopilot()
    actions = ap.tick([_obs(queued=20.0, ongoing=2.0)], WB, now=100.0)
    assert actions == []
    actions = ap.tick([_obs(queued=20.0, ongoing=2.0)], WB, now=101.0)
    assert len(actions) == 1 and isinstance(actions[0], ScaleAction)
    assert actions[0].rule == "replica_up" and actions[0].target == 3
    assert ap.manages("app", "LLM") and ap.target_for("app", "LLM") == 3
    # Commit, then drain: sustained idle + downscale cooldown -> step down.
    ap.begin_scale_op(actions[0]).commit()
    assert actions[0].decision["outcome"] == "applied"
    down = []
    for i in range(8):
        down += ap.tick([_obs(replicas=3)], WB, now=140.0 + i)
    assert [a.rule for a in down] == ["replica_down"]
    assert ap.target_for("app", "LLM") == 2


def test_tick_ignores_router_roles():
    ap = Autopilot()
    for now in (0.0, 1.0, 2.0):
        actions = ap.tick(
            [_obs(dep="Router", role="pd_router", queued=99.0)], WB, now=now)
        assert actions == []
    assert ap.target_for("app", "Router") is None
    assert ap.manages("app", "Router")  # managed (probe answered), not scaled


def test_managed_set_is_sticky_across_empty_ticks():
    ap = Autopilot()
    ap.tick([_obs()], WB, now=0.0)
    assert ap.manages("app", "LLM")
    ap.tick([], WB, now=1.0)  # scale-to-zero: no replicas answer probes
    assert ap.manages("app", "LLM")
    ap2 = Autopilot.load(ap.dump())
    assert ap2.manages("app", "LLM")


def test_scale_op_abort_restores_target():
    ap = Autopilot()
    ap.tick([_obs(queued=20.0)], WB, now=100.0)
    action = ap.tick([_obs(queued=20.0)], WB, now=101.0)[0]
    assert ap.target_for("app", "LLM") == 3
    op = ap.begin_scale_op(action)
    op.abort()
    assert ap.target_for("app", "LLM") == 1
    assert action.decision["outcome"] == "aborted"
    op.abort()  # idempotent: double-resolve is a no-op
    op.commit()
    assert action.decision["outcome"] == "aborted"


def test_dump_load_no_flap():
    """Restart mid-loop must RESUME, not re-fire: the persisted cooldown
    clock blocks an immediate duplicate scale-up (ISSUE: 'resumes mid-loop
    without flapping')."""
    ap = Autopilot()
    ap.tick([_obs(queued=20.0)], WB, now=100.0)
    actions = ap.tick([_obs(queued=20.0)], WB, now=101.0)
    ap.begin_scale_op(actions[0]).commit()
    ap2 = Autopilot.load(ap.dump())
    assert ap2.target_for("app", "LLM") == 3
    for i in range(3):  # same pressure, inside the persisted cooldown
        assert ap2.tick([_obs(replicas=3, queued=20.0)], WB,
                        now=102.0 + i) == []


def test_tick_weight_actions_and_stats_surface():
    ap = Autopilot()
    burn = {"noisy": 3.0, "quiet": 0.1}
    actions = []
    for now in (10.0, 11.0, 12.0):
        actions += ap.tick([_obs(tenant_burn=burn)], WB, now=now)
    ups = [a for a in actions if isinstance(a, WeightAction)]
    assert [a.tenant for a in ups] == ["noisy"]
    assert ups[0].weight == pytest.approx(1.25)
    assert ap.tenant_weight("app", "noisy") == pytest.approx(1.25)
    assert ap.tenant_weight("app", "quiet") == pytest.approx(1.0)
    st = ap.stats()
    assert st["weights"]["app"]["noisy"] == pytest.approx(1.25)
    assert st["counts"].get("weight_up") == 1
    assert st["decisions"][-1]["rule"] == "weight_up"
    ap.stats()  # second flush: watermark makes the counter delta zero


def test_tick_pd_rebalance_emits_paired_actions():
    ap = Autopilot()
    obs = [
        _obs(dep="Prefill-m", role="prefill", replicas=1),
        _obs(dep="Decode-m", role="decode", replicas=3),
        _obs(dep="PDRouter-m", role="pd_router", ttft_pressure=3.0,
             tpot_pressure=0.5),
    ]
    wb = WeightBounds(sustain_ticks=2, cooldown_s=0.0)
    assert ap.tick(obs, wb, now=0.0) == []
    actions = ap.tick(obs, wb, now=1.0)
    assert {(a.deployment, a.target) for a in actions} == {
        ("Prefill-m", 2), ("Decode-m", 2)}
    assert ap.target_for("app", "Prefill-m") == 2
    assert ap.target_for("app", "Decode-m") == 2


def test_wake_arms_cold_start_guard():
    ap = Autopilot()
    b0 = ReplicaBounds(min_replicas=0, max_replicas=4, sustain_ticks=1,
                       downscale_cooldown_s=0.0, cold_start_guard_s=60.0)
    action = ap.wake("app", "LLM", b0)
    assert action is not None and action.rule == "cold_start_wake"
    assert ap.target_for("app", "LLM") == 1 and ap.manages("app", "LLM")
    ap.begin_scale_op(action).commit()
    assert ap.wake("app", "LLM", b0) is None  # already >= 1
    # The fresh replica is idle but inside the guard: no re-zero.
    t0 = action.decision["t"]
    for i in range(6):
        assert ap.tick([_obs(replicas=1, bounds=b0)], WB, now=t0 + 1 + i) == []
    assert ap.target_for("app", "LLM") == 1


# --- DPRouter autopilot hooks ---------------------------------------------
class _FakeId:
    def __init__(self, h):
        self._h = h

    def hex(self):
        return self._h


class _FakeReplica:
    def __init__(self, h):
        self._actor_id = _FakeId(h)


def _make_dp_router(replicas):
    """A DPRouter over stub handles — no cluster, no tokenizer."""
    from ray_tpu.llm.dp_serve import DPRouter

    class _FakeRouter:
        def replicas(self_inner):
            return replicas

    class _FakeMethod:
        def _get_router(self_inner):
            return _FakeRouter()

    class _FakeServer:
        generate = _FakeMethod()

    return DPRouter(_FakeServer(), assigner=None)


def test_dp_router_retire_replica_prunes_tables():
    r1, r2 = _FakeReplica("aa"), _FakeReplica("bb")
    dpr = _make_dp_router([r1, r2])
    dpr._fingerprints[r1._actor_id] = OrderedDict([(1, None), (2, None)])
    dpr._fingerprints[r2._actor_id] = OrderedDict([(3, None)])
    dpr._adapter_res[r1._actor_id] = OrderedDict([("lora-a", None)])
    dpr._bootstrapped = {r1._actor_id, r2._actor_id}
    pruned = asyncio.run(dpr.retire_replica(r1._actor_id))
    assert pruned == {"fingerprints": 2, "adapters": 1}
    assert r1._actor_id not in dpr._fingerprints
    assert r1._actor_id not in dpr._adapter_res
    assert dpr._bootstrapped == {r2._actor_id}
    assert r2._actor_id in dpr._fingerprints  # survivor untouched
    assert dpr._routing["retired_pruned"] == 1
    # The controller ships the id through pickling — hex-string ids work too.
    dpr._fingerprints[r2._actor_id] = OrderedDict([(3, None)])
    asyncio.run(dpr.retire_replica("bb"))
    assert r2._actor_id not in dpr._fingerprints


def test_dp_router_hot_prefix_lru_and_bootstrap():
    holder, fresh = _FakeReplica("aa"), _FakeReplica("bb")
    dpr = _make_dp_router([holder, fresh])
    block = dpr._block
    toks = list(range(block * 2))
    chain = dpr._chain(toks)
    assert chain
    for _ in range(3):
        dpr._note_hot_prefix(chain, toks, "lora-a")
    assert dpr._hot_prefixes[tuple(chain)]["hits"] == 3
    # LRU bound holds.
    for i in range(dpr.HOT_PREFIX_CAP + 5):
        dpr._note_hot_prefix([10_000 + i], [i] * block, "")
    assert len(dpr._hot_prefixes) == dpr.HOT_PREFIX_CAP

    dpr = _make_dp_router([holder, fresh])
    dpr._note_hot_prefix(chain, toks, "lora-a")
    dpr._record(holder._actor_id, chain, "lora-a")
    fetches = []

    async def fake_fetch(src, dst, token_ids, adapter):
        fetches.append((src._actor_id.hex(), dst._actor_id.hex(), adapter))
        return True

    dpr._remote_fetch = fake_fetch
    dpr._remote_fetch_enabled = lambda: True
    fetched = asyncio.run(dpr.bootstrap_replica(fresh))
    assert fetched == 1
    assert fetches == [("aa", "bb", "lora-a")]
    # The fresh replica's fingerprints now claim the prefix: cache-affine
    # routing can target it immediately.
    assert dpr._match_len(fresh._actor_id, chain) == len(chain)
    assert dpr._routing["bootstrap_fetched"] == 1


def test_dp_router_bootstrap_disabled_without_remote_fetch():
    holder, fresh = _FakeReplica("aa"), _FakeReplica("bb")
    dpr = _make_dp_router([holder, fresh])
    dpr._note_hot_prefix([1], [0] * dpr._block, "")
    dpr._remote_fetch_enabled = lambda: False
    assert asyncio.run(dpr.bootstrap_replica(fresh)) == 0


# --- PDRouter pressure samples ---------------------------------------------
def test_pd_router_pressure_samples():
    from ray_tpu.llm.pd_disagg import PDRouter

    pdr = PDRouter.__new__(PDRouter)
    pdr._slo_ttft_s = 0.5
    pdr._slo_tpot_s = 0.1
    pdr._ttft_samples = deque(maxlen=128)
    pdr._tpot_samples = deque(maxlen=128)
    sig = asyncio.run(pdr.autopilot_signals())
    assert sig["role"] == "pd_router" and sig["samples"] == 0
    assert sig["ttft_pressure"] == 0.0
    # prefill 1.0s against a 0.5s TTFT SLO -> pressure 2.0;
    # (1.5 - 1.0)s over 10 tokens against a 0.1s TPOT SLO -> pressure 0.5.
    pdr._note_pd_sample(1.0, 1.5, 10)
    sig = asyncio.run(pdr.autopilot_signals())
    assert sig["ttft_pressure"] == pytest.approx(2.0)
    assert sig["tpot_pressure"] == pytest.approx(0.5)
    assert sig["samples"] == 1
