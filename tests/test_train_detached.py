"""Detached train controller: the run survives driver death.

Reference: v2 TrainController spawned as a detached actor
(data_parallel_trainer.py:268); a new driver re-attaches by run name.
"""
def test_detached_controller_survives_driver_death():
    """The train controller runs as a detached actor: a driver that dies mid-run
    does not kill the run, and a new driver re-attaches by run name (reference:
    v2 TrainController as detached actor)."""
    import subprocess
    import sys
    import textwrap
    import time

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 4, "env_vars": _WORKER_ENV}
    )
    try:
        cluster.connect()
        script = textwrap.dedent(f"""
            import ray_tpu
            from ray_tpu import train
            from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

            ray_tpu.init(address="{cluster.address}", _raylet_port={cluster.head.raylet_port})

            def loop(cfg):
                import time
                from ray_tpu import train
                for i in range(12):
                    time.sleep(0.5)
                    train.report({{"step": i}})

            DataParallelTrainer(
                loop,
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(name="survivor", storage_path="/tmp/rtpu_detach_test"),
            ).fit()
        """)
        import os

        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        env.update(_WORKER_ENV)
        proc = subprocess.Popen([sys.executable, "-c", script], env=env)

        # Wait for the detached controller to come up, then kill the driver.
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                ray_tpu.get_actor("TRAIN_CONTROLLER:survivor", namespace="_train")
                break
            except Exception:
                time.sleep(0.3)
        else:
            raise AssertionError("controller actor never appeared")
        time.sleep(1.0)  # let a couple of reports land
        proc.kill()
        proc.wait(timeout=10)

        # Re-attach from this (new) driver: same run name resumes polling the
        # LIVE run and returns its final result.
        def loop(cfg):  # ignored: the existing controller keeps its own fn
            from ray_tpu import train

            train.report({"step": -1})

        result = DataParallelTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="survivor", storage_path="/tmp/rtpu_detach_test"),
        ).fit()
        assert result.metrics["step"] == 11  # the original 12-step loop finished
    finally:
        cluster.shutdown()

