"""Node-label scheduling + composite strategies (VERDICT r2 #10).

Reference: `src/ray/raylet/scheduling/policy/node_label_scheduling_policy.cc`
(hard/soft selectors) + `python/ray/util/scheduling_strategies.py:123-148`
(NodeLabelSchedulingStrategy with In/NotIn/Exists/DoesNotExist operators).
"""

import pytest

import ray_tpu
from ray_tpu.util.scheduling_strategies import (
    CompositeSchedulingStrategy,
    DoesNotExist,
    Exists,
    In,
    NodeLabelSchedulingStrategy,
    NotIn,
    match_labels,
)


def test_match_labels_operators():
    labels = {"zone": "us-east", "tier": "gpu"}

    def sel(**kw):
        from ray_tpu.util.scheduling_strategies import _selector_spec

        return _selector_spec(kw)

    assert match_labels(labels, sel(zone="us-east"))
    assert not match_labels(labels, sel(zone="eu"))
    assert match_labels(labels, sel(zone=In("us-east", "us-west")))
    assert not match_labels(labels, sel(zone=NotIn("us-east")))
    assert match_labels(labels, sel(tier=Exists()))
    assert not match_labels(labels, sel(missing=Exists()))
    assert match_labels(labels, sel(missing=DoesNotExist()))
    assert not match_labels(labels, sel(tier=DoesNotExist()))


def test_actor_and_task_schedule_by_label(ray_start_cluster):
    cluster = ray_start_cluster
    labeled = cluster.add_node(num_cpus=2, labels={"zone": "east", "disk": "ssd"})
    cluster.connect()
    assert cluster.wait_for_nodes()

    strategy = NodeLabelSchedulingStrategy(hard={"zone": "east"})

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=strategy)
    class Pinned:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id().hex()

    a = Pinned.remote()
    assert ray_tpu.get(a.where.remote(), timeout=240) == labeled.node_id_hex

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=NodeLabelSchedulingStrategy(
        hard={"disk": In("ssd", "nvme")}
    ))
    def where_task():
        return ray_tpu.get_runtime_context().get_node_id().hex()

    assert ray_tpu.get(where_task.remote(), timeout=240) == labeled.node_id_hex


def test_composite_label_or_resource_fallback(ray_start_cluster):
    """Label-OR-resource composite: with no node carrying the label, the
    second sub-strategy (plain resource scheduling) places the work."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"fallback": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    composite = CompositeSchedulingStrategy(any_of=[
        NodeLabelSchedulingStrategy(hard={"accelerator": "tpu-v9"}),  # nobody
        None,  # plain resource scheduling
    ])

    @ray_tpu.remote(num_cpus=0, resources={"fallback": 1},
                    scheduling_strategy=composite)
    def run():
        return "placed"

    assert ray_tpu.get(run.remote(), timeout=240) == "placed"

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=composite)
    class Svc:
        def ping(self):
            return "ok"

    assert ray_tpu.get(Svc.remote().ping.remote(), timeout=240) == "ok"


def test_composite_prefers_matching_label(ray_start_cluster):
    """When the labeled node EXISTS, the first sub-strategy wins."""
    cluster = ray_start_cluster
    labeled = cluster.add_node(num_cpus=1, labels={"accelerator": "tpu-v9"})
    cluster.connect()
    assert cluster.wait_for_nodes()

    composite = CompositeSchedulingStrategy(any_of=[
        NodeLabelSchedulingStrategy(hard={"accelerator": "tpu-v9"}),
        None,
    ])

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=composite)
    def where():
        return ray_tpu.get_runtime_context().get_node_id().hex()

    assert ray_tpu.get(where.remote(), timeout=240) == labeled.node_id_hex
