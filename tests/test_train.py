"""Ray Train parity tests: controller/worker-group/report/checkpoint/failure-restart.

Modeled on reference python/ray/train/v2/tests/ (controller + trainer tests) and the
fake-TPU-resources-on-CPU-nodes pattern of test_jax_trainer.py:16-55.
"""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


def test_basic_fit_reports_metrics(ray_start_regular, storage):
    def loop(config):
        ctx = train.get_context()
        assert ctx.get_world_size() == 2
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(), "loss": 1.0 / (step + 1)})

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="basic", storage_path=storage),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["rank"] == 0  # rank-0 metrics win
    # round 18: every report carried a per-step flight record; the controller
    # aggregates the four phases per rank into the final Result
    stats = result.train_stats
    assert stats["reports"] == 6  # 3 reports x 2 ranks
    assert set(stats["phases"]) == {0, 1}
    for rank_totals in stats["phases"].values():
        assert set(rank_totals) == {"data_wait_s", "step_compute_s",
                                    "report_blocked_s", "checkpoint_blocked_s"}
        assert rank_totals["step_compute_s"] >= 0.0


def test_train_stats_report_path_exposes_recorder(ray_start_regular, storage):
    """`ray_tpu.train.train_stats()` inside a worker (and the WorkerGroup
    fan-out) is the report path: per-step flight records ride the PR 13
    FlightRecorder ring, the phase totals accumulate, and the program/memory
    reports come along — none of which touches the step loop itself."""
    def loop(config):
        for step in range(2):
            train.report({"step": step})
        stats = train.train_stats()
        assert stats is not None and stats["reports"] == 2
        rec = stats["recorder"]
        assert rec["started"] == 2 and rec["finished"] == 2
        (last,) = [r for r in stats["records"] if r["rid"] == "step-1"]
        assert set(last["phases"]) == {"data-wait", "step-compute",
                                       "report-blocked", "checkpoint-blocked"}
        assert "programs" in stats and "memory" in stats
        train.report({"ok": True})

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="flight", storage_path=storage),
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["ok"] is True


def test_ranks_unique_and_broadcast(ray_start_regular, storage, tmp_path):
    rank_dir = tmp_path / "ranks"
    rank_dir.mkdir()

    def loop(config):
        import json

        ctx = train.get_context()
        from ray_tpu.train.collective import broadcast_from_rank_zero

        value = broadcast_from_rank_zero(
            {"from_rank0": ctx.get_world_rank()} if ctx.get_world_rank() == 0 else None
        )
        info = {
            "world_rank": ctx.get_world_rank(),
            "local_rank": ctx.get_local_rank(),
            "node_rank": ctx.get_node_rank(),
            "world_size": ctx.get_world_size(),
        }
        with open(config["rank_dir"] + f"/r{ctx.get_world_rank()}.json", "w") as f:
            json.dump(info, f)
        train.report({"got": value["from_rank0"], "rank": ctx.get_world_rank()})

    result = DataParallelTrainer(
        loop,
        train_loop_config={"rank_dir": str(rank_dir)},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="bcast", storage_path=storage),
    ).fit()
    assert result.metrics["got"] == 0
    import json

    infos = [json.load(open(rank_dir / f)) for f in sorted(os.listdir(rank_dir))]
    assert sorted(i["world_rank"] for i in infos) == [0, 1]
    assert all(i["world_size"] == 2 for i in infos)
    # Single node: local ranks mirror world ranks and are unique.
    assert sorted(i["local_rank"] for i in infos) == [0, 1]
    assert all(i["node_rank"] == 0 for i in infos)


def test_checkpoint_roundtrip_and_retention(ray_start_regular, storage, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(4):
            local = tmp_path / f"w{ctx.get_world_rank()}_s{step}"
            local.mkdir(exist_ok=True)
            (local / f"model_rank{ctx.get_world_rank()}.txt").write_text(f"step={step}")
            train.report({"step": step, "score": float(step)},
                         checkpoint=Checkpoint.from_directory(str(local)))

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="ckpt",
            storage_path=storage,
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"
            ),
        ),
    ).fit()
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        # Both ranks' files merged into the shared checkpoint dir.
        assert open(os.path.join(d, "model_rank0.txt")).read() == "step=3"
        assert open(os.path.join(d, "model_rank1.txt")).read() == "step=3"
    exp_dir = os.path.join(storage, "ckpt")
    kept = [d for d in os.listdir(exp_dir) if d.startswith("checkpoint_")]
    assert len(kept) == 2  # num_to_keep enforced


def test_failure_restart_resumes_from_checkpoint(ray_start_regular, storage, tmp_path):
    marker = tmp_path / "fail_once"

    def loop(config):
        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                start = int(open(os.path.join(d, "progress.txt")).read()) + 1
        for step in range(start, 4):
            local = tmp_path / f"r{ctx.get_world_rank()}_{step}"
            local.mkdir(exist_ok=True)
            (local / "progress.txt").write_text(str(step))
            train.report({"step": step, "resumed_from": start},
                         checkpoint=Checkpoint.from_directory(str(local)))
            if step == 1 and ctx.get_world_rank() == 0 and not marker.exists():
                marker.write_text("x")
                raise RuntimeError("injected failure")

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="restart",
            storage_path=storage,
            failure_config=FailureConfig(max_failures=1),
        ),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    assert result.metrics["resumed_from"] == 2  # restarted from the step-1 checkpoint


def test_failure_exhausts_budget_raises(ray_start_regular, storage):
    def loop(config):
        raise ValueError("always fails")

    with pytest.raises(train.TrainingFailedError):
        DataParallelTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="fail", storage_path=storage,
                                 failure_config=FailureConfig(max_failures=1)),
        ).fit()


def test_jax_trainer_single_worker_grad(ray_start_regular, storage):
    def loop(config):
        import jax
        import jax.numpy as jnp

        def f(w):
            return jnp.sum(w**2)

        g = jax.grad(f)(jnp.array([1.0, 2.0]))
        train.report({"g0": float(g[0]), "n_dev": len(jax.devices())})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1, use_tpu=False,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="jax1", storage_path=storage),
    ).fit()
    assert result.metrics["g0"] == 2.0
    assert result.metrics["n_dev"] >= 1


def test_scaling_config_tpu_topology_bundles():
    sc = ScalingConfig(topology="v4-16")  # 16 cores = 8 chips = 2 hosts
    assert sc.num_workers == 2
    assert sc.use_tpu
    bundles = sc.bundles()
    assert len(bundles) == 2
    assert bundles[0]["TPU-v4-16-head"] == 1.0
    assert bundles[0]["TPU"] == 4.0
    assert "TPU-v4-16-head" not in bundles[1]
    assert sc.pg_strategy == "SPREAD"

