"""Device-resident objects (the RDT / tensor_transport analog).

Reference shapes: python/ray/experimental/gpu_object_manager tests — payloads stay
on the producing actor; same-actor reuse is zero-transfer; remote fetch works.
"""

import numpy as np

import ray_tpu
from ray_tpu.experimental import device_objects as dev


def test_device_object_roundtrip(ray_start_regular):
    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            self_ref = dev.put(jnp.arange(n, dtype=jnp.float32))
            return self_ref  # tiny descriptor through the object plane

        def consume_local(self, ref):
            # Same actor: dict lookup, no transfer; mutate-free compute on device.
            arr = dev.get(ref)
            return float(arr.sum())

        def pinned(self):
            return len(dev.stored_keys())

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(1000), timeout=120)
    assert ref.shape == (1000,) and "float32" in ref.dtype

    # Zero-transfer reuse on the owner.
    assert ray_tpu.get(h.consume_local.remote(ref), timeout=120) == 999 * 1000 / 2

    # Cross-process fetch: the driver pulls through the owning actor.
    arr = dev.get(ref)
    np.testing.assert_allclose(np.asarray(arr), np.arange(1000, dtype=np.float32))

    # Another actor can fetch it too.
    @ray_tpu.remote
    class Other:
        def total(self, r):
            return float(np.asarray(dev.get(r)).sum())

    o = Other.remote()
    assert ray_tpu.get(o.total.remote(ref), timeout=120) == 999 * 1000 / 2

    # Free releases the pin on the owner.
    assert dev.free(ref) is True
    assert ray_tpu.get(h.pinned.remote(), timeout=120) == 0


def test_device_put_requires_actor(ray_start_regular):
    import pytest

    with pytest.raises(Exception, match="actor"):
        dev.put(np.ones(4))


def test_out_of_scope_frees_hbm(ray_start_regular):
    """The last descriptor dying ANYWHERE releases the owner's HBM pin — no
    explicit free (VERDICT r2 #3: fold descriptors into the ReferenceCounter;
    reference gpu_object_manager frees via the ref counter, not actor death)."""
    import time

    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.ones(n))

        def pinned(self):
            return len(dev.stored_keys())

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(4096), timeout=120)
    assert ray_tpu.get(h.pinned.remote(), timeout=120) == 1
    del ref  # the only descriptor anywhere
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        ray_tpu.put(b"drain")  # drives deferred releases on the driver
        if ray_tpu.get(h.pinned.remote(), timeout=60) == 0:
            break
        time.sleep(0.2)
    assert ray_tpu.get(h.pinned.remote(), timeout=60) == 0, (
        "HBM pin survived the last descriptor going out of scope"
    )


def test_streamed_fetch_bitwise_and_counted(ray_start_regular):
    """Cross-process get() of a payload past the devobj_stream_min_bytes
    gate rides the chunked DeviceChannel stream (round 11): payload
    bitwise-equal to the legacy object-plane blob, several chunks deep."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.experimental import tensor_transport as tt

    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.arange(n, dtype=jnp.float32))

    h = Holder.remote()
    n = max(CONFIG.devobj_stream_min_bytes,
            2 * CONFIG.llm_channel_chunk_bytes) // 4 + 1234
    ref = ray_tpu.get(h.make.remote(n), timeout=120)

    tt.reset_transport_stats()
    streamed = dev.get(ref)
    s = tt.transport_stats()
    assert s["tensor_frames_written"] == 0  # pump ran in the OWNER process
    legacy = dev.get(ref, _legacy=True)
    np.testing.assert_array_equal(np.asarray(streamed), np.asarray(legacy))
    np.testing.assert_array_equal(
        np.asarray(streamed), np.arange(n, dtype=np.float32)
    )


def test_concurrent_fetches_share_one_host_snapshot(ray_start_regular):
    """Round-11 satellite: concurrent legacy fetches of one key materialize
    the host snapshot ONCE on the owner, not once per consumer."""
    import threading

    @ray_tpu.remote
    class Holder:
        async def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.arange(n, dtype=jnp.float32))

        async def set_delay(self, s):
            dev._TEST_SNAPSHOT_DELAY_S = s
            return True

        async def materializations(self):
            return dev._snapshot_materializations

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(100_000), timeout=120)
    assert ray_tpu.get(h.set_delay.remote(0.5), timeout=60)
    base = ray_tpu.get(h.materializations.remote(), timeout=60)

    results, errors = [], []

    def fetch():
        try:
            results.append(np.asarray(dev.get(ref, _legacy=True)))
        except Exception as e:  # noqa: BLE001 - surfaced via the assert below
            errors.append(e)

    threads = [threading.Thread(target=fetch) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    assert len(results) == 3
    for arr in results:
        np.testing.assert_array_equal(
            arr, np.arange(100_000, dtype=np.float32)
        )
    made = ray_tpu.get(h.materializations.remote(), timeout=60) - base
    assert made == 1, f"expected one shared snapshot, got {made}"
    ray_tpu.get(h.set_delay.remote(0.0), timeout=60)


def test_cross_actor_transfer_p2p(ray_start_regular):
    """transfer() moves the tensor actor-to-actor: the destination pulls from
    the owner directly and pins its own refcounted copy."""
    import numpy as np

    @ray_tpu.remote
    class Node:
        def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.arange(n, dtype=jnp.float32))

        def pinned(self):
            return len(dev.stored_keys())

        def local_sum(self, r):
            return float(np.asarray(dev.get(r)).sum())

    a, b = Node.remote(), Node.remote()
    src = ray_tpu.get(a.make.remote(512), timeout=120)
    dst = dev.transfer(src, b)
    assert dst.actor_id == b._actor_id and dst.shape == (512,)
    assert ray_tpu.get(b.pinned.remote(), timeout=120) == 1
    # b's copy is local to b: zero-transfer use there.
    assert ray_tpu.get(b.local_sum.remote(dst), timeout=120) == 511 * 512 / 2
    # independent lifetimes: freeing the source leaves the copy intact
    assert dev.free(src)
    assert ray_tpu.get(b.local_sum.remote(dst), timeout=120) == 511 * 512 / 2
