"""Device-resident objects (the RDT / tensor_transport analog).

Reference shapes: python/ray/experimental/gpu_object_manager tests — payloads stay
on the producing actor; same-actor reuse is zero-transfer; remote fetch works.
"""

import numpy as np

import ray_tpu
from ray_tpu.experimental import device_objects as dev


def test_device_object_roundtrip(ray_start_regular):
    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            self_ref = dev.put(jnp.arange(n, dtype=jnp.float32))
            return self_ref  # tiny descriptor through the object plane

        def consume_local(self, ref):
            # Same actor: dict lookup, no transfer; mutate-free compute on device.
            arr = dev.get(ref)
            return float(arr.sum())

        def pinned(self):
            return len(dev.stored_keys())

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(1000), timeout=120)
    assert ref.shape == (1000,) and "float32" in ref.dtype

    # Zero-transfer reuse on the owner.
    assert ray_tpu.get(h.consume_local.remote(ref), timeout=120) == 999 * 1000 / 2

    # Cross-process fetch: the driver pulls through the owning actor.
    arr = dev.get(ref)
    np.testing.assert_allclose(np.asarray(arr), np.arange(1000, dtype=np.float32))

    # Another actor can fetch it too.
    @ray_tpu.remote
    class Other:
        def total(self, r):
            return float(np.asarray(dev.get(r)).sum())

    o = Other.remote()
    assert ray_tpu.get(o.total.remote(ref), timeout=120) == 999 * 1000 / 2

    # Free releases the pin on the owner.
    assert dev.free(ref) is True
    assert ray_tpu.get(h.pinned.remote(), timeout=120) == 0


def test_device_put_requires_actor(ray_start_regular):
    import pytest

    with pytest.raises(Exception, match="actor"):
        dev.put(np.ones(4))


def test_out_of_scope_frees_hbm(ray_start_regular):
    """The last descriptor dying ANYWHERE releases the owner's HBM pin — no
    explicit free (VERDICT r2 #3: fold descriptors into the ReferenceCounter;
    reference gpu_object_manager frees via the ref counter, not actor death)."""
    import time

    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.ones(n))

        def pinned(self):
            return len(dev.stored_keys())

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(4096), timeout=120)
    assert ray_tpu.get(h.pinned.remote(), timeout=120) == 1
    del ref  # the only descriptor anywhere
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        ray_tpu.put(b"drain")  # drives deferred releases on the driver
        if ray_tpu.get(h.pinned.remote(), timeout=60) == 0:
            break
        time.sleep(0.2)
    assert ray_tpu.get(h.pinned.remote(), timeout=60) == 0, (
        "HBM pin survived the last descriptor going out of scope"
    )


def test_cross_actor_transfer_p2p(ray_start_regular):
    """transfer() moves the tensor actor-to-actor: the destination pulls from
    the owner directly and pins its own refcounted copy."""
    import numpy as np

    @ray_tpu.remote
    class Node:
        def make(self, n):
            import jax.numpy as jnp

            return dev.put(jnp.arange(n, dtype=jnp.float32))

        def pinned(self):
            return len(dev.stored_keys())

        def local_sum(self, r):
            return float(np.asarray(dev.get(r)).sum())

    a, b = Node.remote(), Node.remote()
    src = ray_tpu.get(a.make.remote(512), timeout=120)
    dst = dev.transfer(src, b)
    assert dst.actor_id == b._actor_id and dst.shape == (512,)
    assert ray_tpu.get(b.pinned.remote(), timeout=120) == 1
    # b's copy is local to b: zero-transfer use there.
    assert ray_tpu.get(b.local_sum.remote(dst), timeout=120) == 511 * 512 / 2
    # independent lifetimes: freeing the source leaves the copy intact
    assert dev.free(src)
    assert ray_tpu.get(b.local_sum.remote(dst), timeout=120) == 511 * 512 / 2
