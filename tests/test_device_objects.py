"""Device-resident objects (the RDT / tensor_transport analog).

Reference shapes: python/ray/experimental/gpu_object_manager tests — payloads stay
on the producing actor; same-actor reuse is zero-transfer; remote fetch works.
"""

import numpy as np

import ray_tpu
from ray_tpu.experimental import device_objects as dev


def test_device_object_roundtrip(ray_start_regular):
    @ray_tpu.remote
    class Holder:
        def make(self, n):
            import jax.numpy as jnp

            self_ref = dev.put(jnp.arange(n, dtype=jnp.float32))
            return self_ref  # tiny descriptor through the object plane

        def consume_local(self, ref):
            # Same actor: dict lookup, no transfer; mutate-free compute on device.
            arr = dev.get(ref)
            return float(arr.sum())

        def pinned(self):
            return len(dev.stored_keys())

    h = Holder.remote()
    ref = ray_tpu.get(h.make.remote(1000), timeout=120)
    assert ref.shape == (1000,) and "float32" in ref.dtype

    # Zero-transfer reuse on the owner.
    assert ray_tpu.get(h.consume_local.remote(ref), timeout=120) == 999 * 1000 / 2

    # Cross-process fetch: the driver pulls through the owning actor.
    arr = dev.get(ref)
    np.testing.assert_allclose(np.asarray(arr), np.arange(1000, dtype=np.float32))

    # Another actor can fetch it too.
    @ray_tpu.remote
    class Other:
        def total(self, r):
            return float(np.asarray(dev.get(r)).sum())

    o = Other.remote()
    assert ray_tpu.get(o.total.remote(ref), timeout=120) == 999 * 1000 / 2

    # Free releases the pin on the owner.
    assert dev.free(ref) is True
    assert ray_tpu.get(h.pinned.remote(), timeout=120) == 0


def test_device_put_requires_actor(ray_start_regular):
    import pytest

    with pytest.raises(Exception, match="actor"):
        dev.put(np.ones(4))
