"""Multi-tenant serving plane (docs/multitenancy.md): HBM-budgeted LoRA
adapter paging + weighted-fair admission.

The load-bearing invariants:
- adapter churn beyond device capacity is CORRECT: greedy output is
  token-identical to an unbounded-table reference engine, and paging adds
  zero compiled programs (one install program, traced slot index);
- a pinned adapter is never evicted (in-flight requests keep their device
  slot valid); a fully-pinned cache back-pressures instead of crashing;
- under saturation, WFQ holds per-tenant decode-token share within 10% of
  the configured weights, while the FIFO control starves the light tenant;
- one tenant's overflow raises EngineOverloadedError for THAT tenant only;
- unknown adapters surface as the typed, client-visible UnknownAdapterError
  and register-time validation rejects mismatched shapes before jit.
"""

import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


def _generate(engine, prompt, n, lora="", tenant=None, **sp):
    from ray_tpu.llm import SamplingParams

    out, done = [], threading.Event()

    def cb(tok, fin):
        out.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(max_tokens=n, **sp), cb, lora=lora,
                  tenant=tenant)
    assert done.wait(180), engine.error
    return out


def _adapter_weights(cfg, seed, rank=4):
    """A strong random q/v adapter on layer 0 (definitely changes argmax)."""
    r = np.random.default_rng(seed)
    return {0: {
        "q_A": r.normal(size=(cfg.hidden, rank)).astype(np.float32),
        "q_B": r.normal(size=(rank, cfg.n_heads * cfg.head_dim)).astype(np.float32),
        "v_A": r.normal(size=(cfg.hidden, rank)).astype(np.float32),
        "v_B": r.normal(size=(rank, cfg.n_kv_heads * cfg.head_dim)).astype(np.float32),
    }}


# -- typed errors + register-time validation --------------------------------


def test_unknown_adapter_is_typed_and_client_visible(tiny_model):
    from ray_tpu.llm import DecodeEngine, SamplingParams, UnknownAdapterError

    cfg, model, params = tiny_model
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                          prefix_cache=False, decode_loop=False,
                          lora_config={"max_loras": 2, "rank": 2})
    try:
        with pytest.raises(UnknownAdapterError, match="not registered"):
            engine.submit([1, 2], SamplingParams(), lambda *a: None,
                          lora="ghost")
        with pytest.raises(UnknownAdapterError, match="not registered"):
            engine.prefill_detached([1, 2, 3], lora="ghost")
        # back-compat: pre-existing `except KeyError` handlers still catch it
        assert issubclass(UnknownAdapterError, KeyError)
    finally:
        engine.shutdown()

    # An engine with NO lora_config rejects any adapter with the same type.
    bare = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                        prefix_cache=False, decode_loop=False)
    try:
        with pytest.raises(UnknownAdapterError, match="without"):
            bare.submit([1], SamplingParams(), lambda *a: None, lora="x")
    finally:
        bare.shutdown()


def test_register_validates_shapes_before_jit(tiny_model):
    from ray_tpu.llm import DecodeEngine

    cfg, model, params = tiny_model
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                          prefix_cache=False, decode_loop=False,
                          lora_config={"max_loras": 4, "rank": 4})
    try:
        with pytest.raises(ValueError, match="exceeds this engine's rank"):
            engine.add_lora("too-wide", _adapter_weights(cfg, 0, rank=16))
        with pytest.raises(ValueError, match="does not match the model"):
            engine.add_lora("bad-hidden", {0: {
                "q_A": np.zeros((cfg.hidden + 1, 4), np.float32)}})
        with pytest.raises(ValueError, match="inconsistent LoRA rank"):
            engine.add_lora("mixed-rank", {0: {
                "q_A": np.zeros((cfg.hidden, 4), np.float32),
                "q_B": np.zeros((2, cfg.n_heads * cfg.head_dim), np.float32)}})
        with pytest.raises(ValueError, match="layer index"):
            engine.add_lora("bad-layer", {99: {
                "q_A": np.zeros((cfg.hidden, 4), np.float32)}})
        with pytest.raises(ValueError, match="2-D"):
            engine.add_lora("bad-ndim", {0: {
                "q_A": np.zeros((cfg.hidden,), np.float32)}})
        # a rank below the bucket zero-pads in (validated, accepted)
        assert engine.add_lora("narrow", _adapter_weights(cfg, 1, rank=2)) == 1
        with pytest.raises(ValueError, match="capacity"):
            for i in range(9):
                engine.add_lora(f"over-{i}", _adapter_weights(cfg, 2 + i))
    finally:
        engine.shutdown()


# -- adapter paging: correctness under churn --------------------------------


def test_adapter_churn_token_identical_to_unbounded_table(tiny_model):
    """32 registered adapters through an 8-slot device table emit greedy
    output token-identical to an engine whose table holds all 32 — paging
    (evictions + page-ins) is invisible to results, costs ZERO new compiled
    programs (ONE install trace), and the base model rides along
    unaffected."""
    from ray_tpu.llm import DecodeEngine

    cfg, model, params = tiny_model
    n_adapters, n_slots = 32, 8
    common = dict(num_slots=2, max_seq=64, prefix_cache=False)
    ref = DecodeEngine(cfg, params, lora_config={
        "max_loras": n_adapters, "rank": 4}, **common)
    paged = DecodeEngine(cfg, params, lora_config={
        "max_loras": n_adapters, "rank": 4, "cache_slots": n_slots}, **common)
    try:
        assert paged._adapters.num_slots == n_slots
        for i in range(n_adapters):
            w = _adapter_weights(cfg, 100 + i)
            ref.add_lora(f"a{i}", w, alpha=8.0)
            paged.add_lora(f"a{i}", w, alpha=8.0)
        prompt = [5, 9, 17, 3, 42, 8]
        base_expect = _generate(ref, prompt, 4)
        assert _generate(paged, prompt, 4) == base_expect
        programs_before = len(paged._jit_prefill)
        # Churn: every adapter once (4x the device capacity), then a hot
        # subset that fits the cache (the second pass must HIT, not page).
        for i in range(n_adapters):
            expect = _generate(ref, prompt, 3, lora=f"a{i}")
            got = _generate(paged, prompt, 3, lora=f"a{i}")
            assert got == expect, f"adapter a{i} diverged under paging"
        hot = [f"a{i}" for i in range(n_adapters - n_slots // 2, n_adapters)]
        for name in hot * 2:
            assert (_generate(paged, prompt, 3, lora=name)
                    == _generate(ref, prompt, 3, lora=name))
        stats = paged.adapter_stats()
        assert stats["evictions"] >= n_adapters - n_slots, stats
        assert stats["hits"] > 0, stats          # the hot subset stayed warm
        assert stats["resident"] == n_slots
        # zero new compiled programs from paging: the prefill/decode caches
        # did not grow and the install program traced exactly once
        assert len(paged._jit_prefill) == programs_before
        assert stats["install_programs"] in (1, None)
        # base model still exact after all the churn
        assert _generate(paged, prompt, 4) == base_expect
        ref_stats = ref.adapter_stats()
        assert ref_stats["evictions"] == 0       # unbounded table: no paging
    finally:
        ref.shutdown()
        paged.shutdown()


def test_eviction_refuses_pinned_adapters(tiny_model):
    """A pinned adapter is never evicted: with every slot pinned, acquire
    raises (and try_acquire returns None, the admission back-pressure path);
    releasing one pin makes the next acquire evict exactly that victim."""
    import jax.numpy as jnp

    from ray_tpu.llm.adapters import AdapterCache, AdapterCacheFullError

    cache = AdapterCache(n_layers=2, hidden=8, q_out=8, v_out=8, rank=2,
                         dtype=jnp.float32, max_adapters=4, cache_slots=2,
                         name="pin-test")
    for name in ("a", "b", "c"):
        cache.register(name, {0: {"q_A": np.ones((8, 2), np.float32)}})
    ha = cache.acquire("a")
    hb = cache.acquire("b")
    assert {ha.slot, hb.slot} == {1, 2}
    with pytest.raises(AdapterCacheFullError, match="pinned"):
        cache.acquire("c")
    assert cache.try_acquire("c") is None
    assert cache.stats()["evictions"] == 0
    assert sorted(cache.resident_adapters()) == ["a", "b"]
    ha.release()
    hc = cache.acquire("c")                     # evicts the unpinned "a"
    assert hc.slot == ha.slot
    stats = cache.stats()
    assert stats["evictions"] == 1
    assert sorted(cache.resident_adapters()) == ["b", "c"]
    assert not cache.is_resident(cache.uid_of("a"))
    # double release is a no-op, not a double unpin
    hb.release()
    hb.release()
    assert cache.stats()["pinned"] == 1
    hc.release()


def test_engine_backpressures_when_all_slots_pinned(tiny_model):
    """ONE device slot, two tenants' adapters in flight: the second request
    waits (queued, uncharged) until the first finishes and unpins — both
    complete, token-identical to a resident-table engine, and the stepper
    never dies."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    ref = DecodeEngine(cfg, params, num_slots=2, max_seq=64,
                       prefix_cache=False, lora_config={"max_loras": 2, "rank": 4})
    engine = DecodeEngine(cfg, params, num_slots=2, max_seq=64,
                          prefix_cache=False,
                          lora_config={"max_loras": 2, "rank": 4,
                                       "cache_slots": 1})
    try:
        for e in (ref, engine):
            e.add_lora("t1", _adapter_weights(cfg, 7), alpha=8.0)
            e.add_lora("t2", _adapter_weights(cfg, 8), alpha=8.0)
        prompt = [5, 9, 17, 3]
        expect = {n: _generate(ref, prompt, 6, lora=n) for n in ("t1", "t2")}

        results, done = {}, {}
        for name in ("t1", "t2"):
            done[name] = threading.Event()
            results[name] = []

            def cb(tok, fin, _n=name):
                results[_n].append(tok)
                if fin:
                    done[_n].set()

            engine.submit(prompt, SamplingParams(max_tokens=6), cb, lora=name)
        for name in ("t1", "t2"):
            assert done[name].wait(180), engine.error
            assert results[name] == expect[name], name
        assert engine.error is None
        assert engine.adapter_stats()["pinned"] == 0  # all pins released
    finally:
        ref.shutdown()
        engine.shutdown()


# -- weighted-fair admission ------------------------------------------------


def _drain_simulated(sched, waves, tokens_per_req=8):
    """Drive the scheduler host-side: each wave admits into free slots,
    'decodes' every active slot to completion, and meters the tokens —
    saturation without device work."""
    for _ in range(waves):
        plan = sched.next_plan()
        if plan.idle:
            break
        for ch in plan.chunks:
            sched.chunk_done(ch)
            sched.start_decode(ch.request, 7)
        for i, s in enumerate(sched.slots):
            if s.active:
                for _ in range(tokens_per_req):
                    sched.note_emitted(i)
                s.active = False


def _mk_request(tenant, prompt_len=8, max_tokens=8):
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request

    return Request("prompt", prompt=[1] * prompt_len,
                   sampling=SamplingParams(max_tokens=max_tokens),
                   callback=lambda *a: None, tenant=tenant)


def test_wfq_share_tracks_weights_and_fifo_starves(tiny_model):
    """Saturated 3-tenant run: WFQ decode-token share matches the 2:1:1
    weights within 10%; the FIFO control serves arrival order, so the light
    tenant (arriving behind two floods) is starved to ~zero share over the
    same service window."""
    from ray_tpu.llm.scheduler import Scheduler

    def run(wfq, weights):
        sched = Scheduler(num_slots=4, buckets=(16, 32, 64), max_seq=64,
                          token_budget=0, max_queue_depth=0, multi_step=1,
                          wfq=wfq, tenant_weights=weights, tenant_quota=0)
        for _ in range(200):
            sched.submit(_mk_request("heavy-a"))
        for _ in range(200):
            sched.submit(_mk_request("heavy-b"))
        for _ in range(200):
            sched.submit(_mk_request("light"))
        _drain_simulated(sched, waves=40)
        st = sched.stats()["tenants"]
        total = sum(v["decode_tokens"] for v in st.values())
        assert total > 0
        return {k: v["decode_tokens"] / total for k, v in st.items()}, st

    shares, st = run(True, {"heavy-a": 2.0, "heavy-b": 1.0, "light": 1.0})
    assert abs(shares["heavy-a"] - 0.5) <= 0.05, shares
    assert abs(shares["heavy-b"] - 0.25) <= 0.025, shares
    assert abs(shares["light"] - 0.25) <= 0.025, shares

    fifo_shares, _ = run(False, None)
    # 160 admissions of 600 queued: arrival order never reaches the light
    # tenant's flood, let alone fairly.
    assert fifo_shares["light"] == 0.0, fifo_shares
    assert fifo_shares["heavy-a"] > 0.9, fifo_shares


def test_wfq_integration_share_on_live_engine(tiny_model):
    """The same 2:1:1 contract through a REAL engine: three tenants keep the
    queue saturated while the stepper drains it; emitted-token share tracks
    weights within 10% of each tenant's target."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    engine = DecodeEngine(
        cfg, params, num_slots=2, max_seq=64, prefix_cache=False,
        tenant_weights={"gold": 2.0, "silver": 1.0, "bronze": 1.0},
        tenant_quota=0,
    )
    weights = {"gold": 0.5, "silver": 0.25, "bronze": 0.25}
    counts = {t: 0 for t in weights}
    done = []
    lock = threading.Lock()
    try:
        def submit_one(tenant):
            def cb(tok, fin):
                with lock:
                    counts[tenant] += 1
                if fin:
                    done.append(tenant)

            engine.submit([3, 1, 4, 1, 5], SamplingParams(max_tokens=4), cb,
                          tenant=tenant)

        # Saturate: 30 requests per tenant queued up front, 2 slots.
        for _ in range(30):
            for tenant in weights:
                submit_one(tenant)
        deadline = threading.Event()
        for _ in range(600):          # wait for ~45 completions
            if len(done) >= 45:
                break
            deadline.wait(0.05)
        # Judge the share over the SATURATED window (all queues nonempty).
        with lock:
            total = sum(counts.values())
            shares = {t: c / total for t, c in counts.items()}
        for tenant, want in weights.items():
            assert abs(shares[tenant] - want) <= 0.1, (shares, counts)
        st = engine.scheduler_stats()["tenants"]
        assert st["gold"]["weight"] == 2.0
    finally:
        engine.shutdown()


def test_tenant_quota_isolates_overflow(tiny_model):
    """Tenant A blowing its per-tenant quota gets EngineOverloadedError
    naming the tenant; tenant B keeps submitting AND completing through the
    very same engine (and the global cap still backstops everyone)."""
    from ray_tpu.llm import DecodeEngine, EngineOverloadedError, SamplingParams
    from ray_tpu.llm.scheduler import Scheduler

    # Unit-level: quota accounting precise to the request.
    sched = Scheduler(num_slots=1, buckets=(16,), max_seq=64, token_budget=0,
                      max_queue_depth=6, multi_step=1, tenant_quota=2)
    sched.submit(_mk_request("a"))
    sched.submit(_mk_request("a"))
    with pytest.raises(EngineOverloadedError, match="tenant 'a'"):
        sched.submit(_mk_request("a"))
    sched.submit(_mk_request("b"))        # other tenants unaffected
    st = sched.stats()["tenants"]
    assert st["a"]["rejected"] == 1 and st["b"]["rejected"] == 0
    assert sched.queue_depth() == 3
    drained = sched.drain()
    assert len(drained) == 3

    # Integration: the flooding tenant's rejects never touch tenant B.
    cfg, model, params = tiny_model
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                          prefix_cache=False, tenant_quota=3)
    try:
        overflow = 0
        for _ in range(12):
            try:
                engine.submit([1, 2, 3], SamplingParams(max_tokens=16),
                              lambda *a: None, tenant="flood")
            except EngineOverloadedError:
                overflow += 1
        assert overflow > 0
        # B's request flows through the saturated engine untouched.
        out = _generate(engine, [5, 9, 17], 4, tenant="b")
        assert len(out) == 4
    finally:
        engine.shutdown()


def test_admission_prefers_resident_adapters_boundedly():
    """Adapter-aware admission: the min-pass tenant with a COLD adapter is
    skipped for a resident one at most RESIDENT_SKIP_MAX times (uncharged),
    then force-picked — preference without starvation."""
    from ray_tpu.llm.scheduler import Scheduler

    resident = {2}          # adapter uid 2 is paged in; uid 1 is cold
    acquired = []

    class _H:
        slot = 1

        def release(self):
            pass

    sched = Scheduler(
        num_slots=1, buckets=(16,), max_seq=64, token_budget=0,
        max_queue_depth=0, multi_step=1, tenant_quota=0,
        adapter_acquire=lambda uid: acquired.append(uid) or _H(),
        adapter_resident=lambda uid: uid in resident,
    )
    cold, warm = _mk_request("cold"), _mk_request("warm")
    cold.adapter, warm.adapter = 1, 2
    sched.submit(cold)      # min-pass by arrival
    sched.submit(warm)
    plan = sched.next_plan()
    # the resident tenant jumped the cold head-of-line (bounded skip)
    assert plan.chunks[0].request is warm
    assert acquired == [2]
    sched.chunk_done(plan.chunks[0])
    sched.start_decode(warm, 7)
    sched.slots[0].active = False
    plan = sched.next_plan()
    # next iteration the cold tenant pages in (no one left to prefer)
    assert plan.chunks[0].request is cold
    assert acquired == [2, 1]
    stats = sched.stats()
    assert stats["resident_preferred"] == 1


# -- adapter-aware DP routing (unit) ----------------------------------------


def test_dp_router_records_and_reports_adapter_residency():
    """The router's optimistic residency map: routed adapters are remembered
    per replica (LRU-capped), surfaced via routing_stats, and dead replicas
    prune."""
    import asyncio

    from ray_tpu.llm.dp_serve import DPRouter

    class _FakeMethod:
        def __init__(self):
            self.calls = []

    router = DPRouter.__new__(DPRouter)
    router._fingerprints = {}
    router._adapter_res = {}
    router._routing = {"cache_routed": 0, "balanced": 0, "untracked": 0,
                       "adapter_routed": 0}
    router._record("r1", [11, 22], adapter="tuned")
    router._record("r2", [11, 33], adapter="other")
    router._record("r1", [], adapter="second")
    assert list(router._adapter_res["r1"]) == ["tuned", "second"]
    assert list(router._adapter_res["r2"]) == ["other"]
    # LRU cap holds
    for i in range(DPRouter.ADAPTER_CAP + 5):
        router._record("r1", [], adapter=f"x{i}")
    assert len(router._adapter_res["r1"]) == DPRouter.ADAPTER_CAP
    stats = asyncio.run(router.routing_stats())
    assert "adapter_residency" in stats and "adapter_routed" in stats


def test_dp_adapter_affinity_routing_end_to_end(ray_start_regular):
    """Two DP replicas, one registered adapter fleet-wide: repeated traffic
    for a tenant lands on the SAME replica (adapter_routed) so its paged
    adapter and its adapter-namespaced prefix cache stay hot."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2,
                  lora_config={"max_loras": 4, "rank": 2, "cache_slots": 2}),
        dp_size=2,
    )
    handle = serve.run(app, name="dp-mt", route_prefix=None, _timeout_s=300)
    try:
        from ray_tpu.models.transformer import get_config

        hidden = get_config("test-tiny").hidden
        w = {0: {"q_A": np.random.default_rng(5).normal(
            size=(hidden, 2)).astype(np.float32)}}
        # fleet-wide host-side registration through the router broadcast
        uids = handle.load_lora.remote("tuned", w, 8.0).result(timeout_s=120)
        assert len(uids) == 2
        outs = [
            handle.generate.remote("multi tenant hello", max_tokens=3,
                                   lora="tuned").result(timeout_s=300)
            for _ in range(3)
        ]
        assert len({tuple(o["token_ids"]) for o in outs}) == 1
        ranks = {o["dp_rank"] for o in outs}
        assert len(ranks) == 1, f"tenant bounced across replicas: {ranks}"
        stats = handle.routing_stats.remote().result(timeout_s=120)
        assert stats["adapter_routed"] >= 2, stats
        # the ground-truth broadcast agrees: exactly one replica paged it in
        astats = handle.adapter_stats.remote().result(timeout_s=120)
        resident = [s for s in astats if "tuned" in s.get(
            "resident_adapters", [])]
        assert len(resident) == 1, astats
        # The typed error stays catchable BY TYPE across the TWO actor hops
        # (engine -> DP replica -> router -> driver): as_instanceof_cause
        # walks nested task errors to the innermost cause.
        from ray_tpu.llm import UnknownAdapterError

        with pytest.raises(UnknownAdapterError):
            handle.generate.remote("x", max_tokens=2,
                                   lora="ghost").result(timeout_s=120)
    finally:
        serve.delete("dp-mt")
