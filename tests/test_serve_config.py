"""Declarative serve config + CLI surface.

Shape parity: reference python/ray/serve/tests/test_cli.py +
test_schema.py — config validation, YAML deploy of a 2-deployment app,
idempotent re-apply that only edits replica counts (scales in place, no
replica churn), PUT semantics (apps absent from the config are deleted),
status transitions, and `serve build` scaffolding.
"""

import time

import pytest
import yaml

import ray_tpu  # noqa: F401 - cluster fixture
from ray_tpu import serve
from ray_tpu.serve import schema as serve_schema


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield
    serve.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps():
    yield
    for app in list(serve.status()):
        serve.delete(app)


BASE_CONFIG = """
applications:
- name: main
  route_prefix: /
  import_path: tests.serve_config_apps:app
  deployments:
  - name: Doubler
    num_replicas: 1
  - name: Gateway
    num_replicas: 1
"""


def test_schema_validation():
    with pytest.raises(serve_schema.ServeConfigError, match="applications"):
        serve_schema.ServeDeploySchema.from_dict({})
    with pytest.raises(serve_schema.ServeConfigError, match="import_path"):
        serve_schema.ServeDeploySchema.from_dict(
            {"applications": [{"name": "x"}]}
        )
    with pytest.raises(serve_schema.ServeConfigError, match="module:attribute"):
        serve_schema.ServeDeploySchema.from_dict(
            {"applications": [{"import_path": "nomodsep"}]}
        )
    with pytest.raises(serve_schema.ServeConfigError, match="duplicate applica"):
        serve_schema.ServeDeploySchema.from_dict(
            {"applications": [
                {"import_path": "a:b", "name": "x"},
                {"import_path": "c:d", "name": "x", "route_prefix": "/y"},
            ]}
        )
    with pytest.raises(serve_schema.ServeConfigError, match="unknown deployment"):
        serve_schema.ServeDeploySchema.from_dict(
            {"applications": [{
                "import_path": "a:b",
                "deployments": [{"name": "d", "replicas": 2}],
            }]}
        )


def test_deploy_from_yaml_and_scale_reapply():
    """The round-5 contract: deploy a 2-deployment app from YAML, edit a
    replica count, re-apply, and watch status transition — with the original
    replicas surviving a scale-only change."""
    config = yaml.safe_load(BASE_CONFIG)
    outcomes = serve_schema.apply_config(config, wait_ready=True)
    assert outcomes == {"main": "deployed"}

    handle = serve.get_app_handle("main")
    assert handle.remote(21).result() == 43  # 21*2 + 1

    report = serve_schema.status_report()
    assert report["applications"]["main"]["status"] == "RUNNING"
    deps = report["applications"]["main"]["deployments"]
    assert deps["Doubler"]["replica_states"]["RUNNING"] == 1
    assert deps["Gateway"]["replica_states"]["RUNNING"] == 1

    pid_before = serve.get_deployment_handle("Doubler", "main").pid.remote().result()

    # Edit ONLY the replica count and re-apply (declarative scale-up).
    config["applications"][0]["deployments"][0]["num_replicas"] = 3
    outcomes = serve_schema.apply_config(config)
    assert outcomes == {"main": "deployed"}

    # status shows the transition: target moved to 3, replicas catch up.
    report = serve_schema.status_report()
    assert (report["applications"]["main"]["deployments"]["Doubler"]
            ["target_num_replicas"] == 3)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        d = serve_schema.status_report()["applications"]["main"]["deployments"]
        if (d["Doubler"]["replica_states"]["RUNNING"] == 3
                and d["Doubler"]["status"] == "HEALTHY"):
            break
        time.sleep(0.2)
    else:
        pytest.fail(f"scale-up never completed: {serve_schema.status_report()}")

    # Scale-only change keeps the original replica alive (no churn): the old
    # pid still serves. Routers refresh their replica table on a 2s TTL, so
    # sample past one refresh window before concluding about spread.
    h = serve.get_deployment_handle("Doubler", "main")
    pids = set()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and len(pids) < 2:
        pids.add(h.pid.remote().result())
        time.sleep(0.15)
    assert pid_before in pids, (pid_before, pids)
    assert len(pids) >= 2  # new replicas actually share load

    # Unchanged re-apply is a no-op reconcile.
    outcomes = serve_schema.apply_config(config)
    assert outcomes == {"main": "deployed"}
    assert handle.remote(5).result() == 11


def test_put_semantics_and_builder_args():
    config = {
        "applications": [
            {"name": "main", "route_prefix": "/",
             "import_path": "tests.serve_config_apps:app"},
            {"name": "aux", "route_prefix": "/aux",
             "import_path": "tests.serve_config_apps:build_app",
             "args": {"prefix": "hi"}},
        ]
    }
    serve_schema.apply_config(config, wait_ready=True)
    assert serve.get_app_handle("aux").remote("x").result() == "hi:x"

    # Re-apply WITHOUT aux: PUT semantics delete it.
    outcomes = serve_schema.apply_config(
        {"applications": [config["applications"][0]]}
    )
    assert outcomes == {"aux": "deleted", "main": "deployed"}
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if "aux" not in serve.status():
            break
        time.sleep(0.2)
    assert "aux" not in serve.status()


def test_override_unknown_deployment_rejected():
    config = yaml.safe_load(BASE_CONFIG)
    config["applications"][0]["deployments"].append(
        {"name": "Nonexistent", "num_replicas": 2}
    )
    with pytest.raises(serve_schema.ServeConfigError, match="Nonexistent"):
        serve_schema.apply_config(config)


def test_build_config_scaffold_roundtrip(tmp_path):
    config = serve_schema.build_config(["tests.serve_config_apps:app"])
    apps = config["applications"]
    assert len(apps) == 1 and apps[0]["import_path"] == "tests.serve_config_apps:app"
    names = {d["name"] for d in apps[0]["deployments"]}
    assert names == {"Doubler", "Gateway"}
    # The scaffold must be directly deployable.
    out = tmp_path / "built.yaml"
    out.write_text(yaml.safe_dump(config, sort_keys=False))
    serve_schema.apply_config(yaml.safe_load(out.read_text()), wait_ready=True)
    assert serve.get_app_handle("default").remote(2).result() == 5


def test_double_apply_does_not_leak_overrides_into_module():
    """Regression (raylint RL301 / ADVICE round 5): _apply_overrides used to
    mutate the imported module's Deployment.config in place, so a second
    apply_config() (or a later plain serve.run) inherited the first apply's
    overrides. Configs are now copied per apply."""
    import tests.serve_config_apps as apps_mod

    before_replicas = apps_mod.Doubler.config.num_replicas
    before_moq = apps_mod.Doubler.config.max_ongoing_requests
    config = {
        "applications": [{
            "name": "main",
            "route_prefix": "/",
            "import_path": "tests.serve_config_apps:app",
            "deployments": [
                {"name": "Doubler", "num_replicas": 2,
                 "max_ongoing_requests": 7},
            ],
        }]
    }
    serve_schema.apply_config(config, wait_ready=True)
    # The module's Deployment object is untouched by the apply...
    assert apps_mod.Doubler.config.num_replicas == before_replicas
    assert apps_mod.Doubler.config.max_ongoing_requests == before_moq
    # ...and a re-apply starts from the pristine config, not the overridden
    # one (same outcome, no accumulated state).
    serve_schema.apply_config(config, wait_ready=True)
    assert apps_mod.Doubler.config.num_replicas == before_replicas
    status = serve_schema.status_report()["applications"]["main"]
    assert status["deployments"]["Doubler"]["target_num_replicas"] == 2


def test_apply_overrides_returns_copies():
    """_apply_overrides is pure: the input spec dict and its config objects
    are never mutated."""
    import dataclasses

    import tests.serve_config_apps as apps_mod

    cfg = apps_mod.Doubler.config
    acc = {"Doubler": {"config": cfg, "name": "Doubler"}}
    out = serve_schema._apply_overrides(
        acc,
        [serve_schema.DeploymentSchema(name="Doubler", num_replicas=5)],
        "main",
    )
    assert acc["Doubler"]["config"] is cfg
    assert cfg.num_replicas != 5
    assert out["Doubler"]["config"] is not cfg
    assert out["Doubler"]["config"].num_replicas == 5
    assert dataclasses.replace(cfg)  # still a plain dataclass
