"""OOM defense: memory monitor + group-by-owner worker killing.

Shape parity: reference python/ray/tests/test_memory_pressure.py — a node under
memory pressure kills workers (retriable-first, newest-owner-first) and
survives; killed retriable tasks rerun once pressure drops.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private.memory_monitor import (
    MemoryMonitor,
    _read_meminfo,
    pick_worker_to_kill,
)


class _FakeHandle:
    def __init__(self, kind="worker", busy_task=None, actor_id=None,
                 task_started_at=0.0, started_at=0.0):
        self.kind = kind
        self.busy_task = busy_task
        self.actor_id = actor_id
        self.task_started_at = task_started_at
        self.started_at = started_at


def _task(owner: str, retries: int):
    return {"owner": {"worker_id": owner}, "retries_left": retries}


def test_policy_prefers_retriable_then_newest_owner():
    old_nonretriable = _FakeHandle(busy_task=_task("A", 0), task_started_at=1.0)
    retriable_old = _FakeHandle(busy_task=_task("B", 2), task_started_at=2.0)
    retriable_new = _FakeHandle(busy_task=_task("C", 2), task_started_at=9.0)
    victim = pick_worker_to_kill([old_nonretriable, retriable_old, retriable_new])
    # Retriable groups are preferred, and among them the newest task dies first.
    assert victim is retriable_new

    # Within one owner's group the newest worker dies first.
    a1 = _FakeHandle(busy_task=_task("A", 1), task_started_at=1.0)
    a2 = _FakeHandle(busy_task=_task("A", 1), task_started_at=5.0)
    assert pick_worker_to_kill([a1, a2]) is a2

    # Only non-retriable work left: still kills (the node must survive).
    assert pick_worker_to_kill([old_nonretriable]) is old_nonretriable

    # Drivers are never victims; actors are last resort (newest first).
    driver = _FakeHandle(kind="driver")
    actor_old = _FakeHandle(kind="actor", actor_id="x", started_at=1.0)
    actor_new = _FakeHandle(kind="actor", actor_id="y", started_at=2.0)
    assert pick_worker_to_kill([driver, actor_old, actor_new]) is actor_new
    assert pick_worker_to_kill([driver]) is None


def test_meminfo_parsing(tmp_path):
    p = tmp_path / "meminfo"
    p.write_text("MemTotal:       100 kB\nMemFree:         5 kB\nMemAvailable:   20 kB\n")
    total, avail = _read_meminfo(str(p))
    assert total == 100 * 1024 and avail == 20 * 1024
    assert abs(MemoryMonitor(str(p)).usage_fraction() - 0.8) < 1e-9
    assert MemoryMonitor(str(tmp_path / "missing")).usage_fraction() is None


def _write_usage(path, frac):
    total = 1000000
    path.write_text(
        f"MemTotal:       {total} kB\nMemAvailable:   {int(total * (1 - frac))} kB\n"
    )


def test_node_survives_memory_pressure(tmp_path, monkeypatch):
    """Retriable tasks under pressure: workers are killed, the node survives,
    and the task reruns to completion once pressure drops."""
    meminfo = tmp_path / "meminfo"
    _write_usage(meminfo, 0.10)
    monkeypatch.setenv("RAY_TPU_MEMINFO_PATH", str(meminfo))
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_REFRESH_MS", "50")
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.90")
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_MIN_WAIT_S", "0.1")
    ray_tpu.init(
        num_cpus=2, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    try:
        marker = tmp_path / "attempts"

        @ray_tpu.remote(max_retries=5)
        def slow(marker_path):
            with open(marker_path, "a") as f:
                f.write("x")
            time.sleep(3.0)
            return "done"

        ref = slow.remote(str(marker))
        # Wait for the first attempt to actually start, then apply pressure.
        deadline = time.monotonic() + 60
        while not marker.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert marker.exists(), "task never started"
        _write_usage(meminfo, 0.97)
        # Pressure stays on until the worker has been killed (a new attempt
        # will re-append to the marker file after requeue).
        first_attempts = len(marker.read_text())
        deadline = time.monotonic() + 60
        while len(marker.read_text()) <= first_attempts and time.monotonic() < deadline:
            time.sleep(0.1)
        assert len(marker.read_text()) > first_attempts, "no OOM kill + retry happened"
        _write_usage(meminfo, 0.10)  # pressure gone: the retry completes
        assert ray_tpu.get(ref, timeout=120) == "done"
    finally:
        ray_tpu.shutdown()


def test_oom_error_when_retries_exhausted(tmp_path, monkeypatch):
    """A non-retriable task killed by the memory monitor surfaces
    OutOfMemoryError with the monitor's cause attached."""
    meminfo = tmp_path / "meminfo"
    _write_usage(meminfo, 0.10)
    monkeypatch.setenv("RAY_TPU_MEMINFO_PATH", str(meminfo))
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_REFRESH_MS", "50")
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.90")
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_MIN_WAIT_S", "0.1")
    ray_tpu.init(
        num_cpus=2, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    try:
        started = tmp_path / "started"

        @ray_tpu.remote(max_retries=0)
        def hog(marker_path):
            with open(marker_path, "w") as f:
                f.write("x")
            time.sleep(30.0)

        ref = hog.remote(str(started))
        deadline = time.monotonic() + 60
        while not started.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert started.exists()
        _write_usage(meminfo, 0.97)
        with pytest.raises(ray_tpu.exceptions.OutOfMemoryError, match="memory monitor"):
            ray_tpu.get(ref, timeout=120)
    finally:
        ray_tpu.shutdown()
