"""Collective API tests (reference: python/ray/util/collective/tests/ — gloo-backend
suite run on CPU; here the HOST backend plays that role, and the XLA tier runs on the
virtual 8-device CPU mesh)."""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote
class Member:
    def __init__(self, world_size, rank, group_name):
        from ray_tpu.util import collective as col

        self.rank = rank
        col.init_collective_group(world_size, rank, backend="host", group_name=group_name)

    def do_allreduce(self, value):
        from ray_tpu.util import collective as col

        return col.allreduce(np.array(value, dtype=np.float32), group_name=self.group())

    def group(self):
        return "g-allreduce"

    def do_barrier(self):
        from ray_tpu.util import collective as col

        col.barrier(group_name=self.group())
        return self.rank

    def do_verbs(self):
        """One member runs the whole verb sequence; all members must call in lockstep."""
        from ray_tpu.util import collective as col

        g = self.group()
        out = {}
        out["allgather"] = col.allgather(np.array([self.rank]), group_name=g)
        out["bcast"] = col.broadcast(
            np.array([42.0]) if self.rank == 0 else np.array([0.0]), src_rank=0, group_name=g
        )
        out["reduce"] = col.reduce(np.array([1.0]), dst_rank=1, group_name=g)
        chunks = [np.array([float(self.rank * 10 + i)]) for i in range(col.get_collective_group_size(g))]
        out["rs"] = col.reducescatter(chunks, group_name=g)
        return out


@pytest.fixture(scope="module")
def members(ray_start_regular):
    ws = 3
    actors = [Member.remote(ws, r, "g-allreduce") for r in range(ws)]
    ray_tpu.get([a.do_barrier.remote() for a in actors])  # ensure init done
    return actors


def test_allreduce(members):
    outs = ray_tpu.get([a.do_allreduce.remote([1.0, float(i)]) for i, a in enumerate(members)])
    for out in outs:
        np.testing.assert_allclose(out, [3.0, 0.0 + 1.0 + 2.0])


def test_verbs(members):
    outs = ray_tpu.get([a.do_verbs.remote() for a in members])
    for rank, out in enumerate(outs):
        gathered = out["allgather"]
        assert [int(x[0]) for x in gathered] == [0, 1, 2]
        np.testing.assert_allclose(out["bcast"], [42.0])
        if rank == 1:
            np.testing.assert_allclose(out["reduce"], [3.0])
        else:
            assert out["reduce"] is None
        # reducescatter: rank r gets sum over src of chunk r = sum_src(src*10 + r)
        np.testing.assert_allclose(out["rs"], [0 + 10 + 20 + 3 * rank])


@ray_tpu.remote
class P2P:
    def __init__(self, world_size, rank):
        from ray_tpu.util import collective as col

        self.rank = rank
        col.init_collective_group(world_size, rank, backend="host", group_name="p2p")

    def ping(self):
        from ray_tpu.util import collective as col

        col.send(np.array([7.0]), dst_rank=1, group_name="p2p")
        return True

    def pong(self):
        from ray_tpu.util import collective as col

        return col.recv(src_rank=0, group_name="p2p")


def test_send_recv(ray_start_regular):
    a = P2P.remote(2, 0)
    b = P2P.remote(2, 1)
    r_pong = b.pong.remote()
    assert ray_tpu.get(a.ping.remote())
    np.testing.assert_allclose(ray_tpu.get(r_pong), [7.0])


def test_declarative_group(ray_start_regular):
    from ray_tpu.util import collective as col

    @ray_tpu.remote
    class Worker:
        def reduce_it(self, v):
            from ray_tpu.util import collective as col

            return col.allreduce(np.array([v], np.float32), group_name="decl")

    actors = [Worker.remote() for _ in range(2)]
    col.create_collective_group(actors, 2, [0, 1], backend="host", group_name="decl")
    outs = ray_tpu.get([a.reduce_it.remote(float(i + 1)) for i, a in enumerate(actors)])
    for out in outs:
        np.testing.assert_allclose(out, [3.0])


def test_destroy_and_recreate(ray_start_regular):
    @ray_tpu.remote
    class W:
        def join(self, ws, rank):
            from ray_tpu.util import collective as col

            col.init_collective_group(ws, rank, backend="host", group_name="dg")
            return True

        def reduce_it(self, v, ws):
            from ray_tpu.util import collective as col

            out = col.allreduce(np.array([v], np.float32), group_name="dg")
            assert col.get_collective_group_size("dg") == ws
            return out

        def leave(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group("dg")
            return True

    actors = [W.remote() for _ in range(2)]
    ray_tpu.get([a.join.remote(2, i) for i, a in enumerate(actors)])
    ray_tpu.get([a.reduce_it.remote(1.0, 2) for a in actors])
    ray_tpu.get([a.leave.remote() for a in actors])
    # Re-create under the same name with a different world size.
    actors3 = [W.remote() for _ in range(3)]
    ray_tpu.get([a.join.remote(3, i) for i, a in enumerate(actors3)])
    outs = ray_tpu.get([a.reduce_it.remote(1.0, 3) for a in actors3])
    for out in outs:
        np.testing.assert_allclose(out, [3.0])


def test_xla_tier():
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.util.collective import ReduceOp, xla
    from ray_tpu.util.jax_compat import shard_map

    mesh = create_mesh({"dp": 4})
    group = xla.MeshGroup(mesh, "dp")
    stacked = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    np.testing.assert_allclose(group.allreduce(stacked), stacked.sum(0))
    np.testing.assert_allclose(group.allreduce(stacked, ReduceOp.MAX), stacked.max(0))
    np.testing.assert_allclose(group.allreduce(stacked, ReduceOp.MEAN), stacked.mean(0))

    # In-graph verbs under shard_map.
    def step(x):
        y = xla.allreduce(x, "dp")
        z = xla.send_next(x, "dp")
        return y, z

    f = jax.jit(
        shard_map(
            step, mesh=mesh, in_specs=P("dp"), out_specs=(P(None), P("dp"))
        )
    )
    x = np.arange(4, dtype=np.float32).reshape(4, 1)
    y, z = f(x)
    np.testing.assert_allclose(np.asarray(y), [[6.0]])
    np.testing.assert_allclose(np.asarray(z).ravel(), [3.0, 0.0, 1.0, 2.0])
