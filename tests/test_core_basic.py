"""Core task/object API tests (reference: python/ray/tests/test_basic.py shapes)."""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote
def echo(x):
    return x


@ray_tpu.remote
def add(a, b):
    return a + b


def test_simple_task(ray_start_regular):
    assert ray_tpu.get(echo.remote(41), timeout=60) == 41


def test_task_chaining(ray_start_regular):
    r1 = add.remote(1, 2)
    r2 = add.remote(r1, 10)
    r3 = add.remote(r2, r1)
    assert ray_tpu.get(r3, timeout=60) == 16


def test_many_tasks(ray_start_regular):
    refs = [add.remote(i, i) for i in range(50)]
    assert ray_tpu.get(refs, timeout=120) == [2 * i for i in range(50)]


def test_put_get_roundtrip(ray_start_regular):
    for value in [1, "s", {"a": [1, 2]}, (None, True)]:
        assert ray_tpu.get(ray_tpu.put(value), timeout=60) == value


def test_put_get_large_numpy(ray_start_regular):
    arr = np.random.rand(500, 500)
    out = ray_tpu.get(ray_tpu.put(arr), timeout=60)
    np.testing.assert_array_equal(arr, out)


def test_large_arg_promotion(ray_start_regular):
    arr = np.arange(1_000_000, dtype=np.int64)

    @ray_tpu.remote
    def total(a):
        return int(a.sum())

    assert ray_tpu.get(total.remote(arr), timeout=60) == int(arr.sum())


def test_large_return(ray_start_regular):
    @ray_tpu.remote
    def big():
        return np.ones((1000, 1000))

    out = ray_tpu.get(big.remote(), timeout=60)
    assert out.shape == (1000, 1000)


def test_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom-message")

    with pytest.raises(ValueError, match="boom-message"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_error_propagation_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise KeyError("dead")

    r = add.remote(boom.remote(), 1)
    with pytest.raises(Exception):
        ray_tpu.get(r, timeout=60)


def test_num_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c], timeout=60) == [1, 2, 3]


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def slow(t):
        import time

        time.sleep(t)
        return t

    refs = [slow.remote(0.05), slow.remote(10)]
    ready, pending = ray_tpu.wait(refs, num_returns=1, timeout=8)
    assert len(ready) == 1 and len(pending) == 1
    assert ray_tpu.get(ready[0], timeout=60) == 0.05


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def forever():
        import time

        time.sleep(60)

    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(forever.remote(), timeout=0.5)


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def outer():
        inner_refs = [echo.remote(i) for i in range(3)]
        return sum(ray_tpu.get(inner_refs, timeout=60))

    assert ray_tpu.get(outer.remote(), timeout=120) == 3


def test_options_override(ray_start_regular):
    assert ray_tpu.get(echo.options(num_cpus=2).remote("hi"), timeout=60) == "hi"


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 4


def test_ref_in_collection_stays_ref(ray_start_regular):
    inner = ray_tpu.put(7)

    @ray_tpu.remote
    def unwrap(d):
        (ref,) = d["refs"]
        return ray_tpu.get(ref, timeout=60) + 1

    assert ray_tpu.get(unwrap.remote({"refs": [inner]}), timeout=60) == 8


def test_no_head_of_line_starvation(ray_start_regular):
    """Unplaceable tasks at the queue head must not block later feasible ones."""
    import ray_tpu

    @ray_tpu.remote(resources={"NONEXISTENT": 1}, max_retries=0)
    def impossible(i):
        return i

    @ray_tpu.remote(num_cpus=1)
    def possible(i):
        return i * 10

    blocked = [impossible.remote(i) for i in range(20)]  # head of the queue
    feasible = [possible.remote(i) for i in range(20)]
    assert ray_tpu.get(feasible, timeout=60) == [i * 10 for i in range(20)]
    del blocked


def test_nested_zero_cpu_tasks_progress(ray_start_regular):
    """Parents blocked in get() must not deadlock children out of worker slots."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def child(x):
        return x + 1

    @ray_tpu.remote(num_cpus=0)
    def parent(x):
        return ray_tpu.get(child.remote(x))

    out = ray_tpu.get([parent.remote(i) for i in range(8)], timeout=120)
    assert out == [i + 1 for i in range(8)]
