"""ray_tpu.serve tests.

Shape parity with the reference suite (python/ray/serve/tests/): deployment +
handle calls, multi-replica load spreading, composition via nested binds, batching,
user_config reconfigure, HTTP ingress, autoscaling target math, replica recovery.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield
    serve.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps():
    yield
    for app in list(serve.status()):
        serve.delete(app)


def test_basic_deployment_and_handle():
    @serve.deployment
    class Greeter:
        def __call__(self, name: str) -> str:
            return f"hello {name}"

        def shout(self, name: str) -> str:
            return f"HELLO {name.upper()}"

    handle = serve.run(Greeter.bind(), name="greet")
    assert handle.remote("tpu").result() == "hello tpu"
    assert handle.shout.remote("tpu").result() == "HELLO TPU"


def test_function_deployment():
    @serve.deployment
    def doubler(x: int) -> int:
        return x * 2

    handle = serve.run(doubler.bind(), name="double")
    assert handle.remote(21).result() == 42


def test_multi_replica_spreads_load():
    import os

    @serve.deployment(num_replicas=2)
    class Who:
        def __call__(self, _x) -> int:
            return os.getpid()

    handle = serve.run(Who.bind(), name="who")
    pids = {handle.remote(i).result() for i in range(20)}
    assert len(pids) == 2


def test_composition():
    @serve.deployment
    class Adder:
        def __init__(self, increment: int):
            self._inc = increment

        def __call__(self, x: int) -> int:
            return x + self._inc

    @serve.deployment
    class Combiner:
        def __init__(self, a, b):
            self._a = a
            self._b = b

        def __call__(self, x: int) -> int:
            ra = self._a.remote(x)
            rb = self._b.remote(x)
            return ra.result() + rb.result()

    app = Combiner.bind(Adder.options(name="A1").bind(1), Adder.options(name="A2").bind(10))
    handle = serve.run(app, name="compose")
    assert handle.remote(100).result() == 211


def test_init_args_and_user_config():
    @serve.deployment(user_config={"threshold": 5})
    class Thresholder:
        def __init__(self, base: int):
            self._base = base
            self._threshold = 0

        def reconfigure(self, config):
            self._threshold = config["threshold"]

        def __call__(self, x: int) -> bool:
            return x + self._base > self._threshold

    handle = serve.run(Thresholder.bind(2), name="thresh")
    assert handle.remote(4).result() is True  # 6 > 5
    assert handle.remote(2).result() is False  # 4 < 5


def test_batching():
    @serve.deployment
    class BatchModel:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_timeout_s=0.1)
        async def __call__(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def seen(self):
            return self.batch_sizes

    handle = serve.run(BatchModel.bind(), name="batching")
    responses = [handle.remote(i) for i in range(16)]
    assert sorted(r.result() for r in responses) == [i * 10 for i in range(16)]
    sizes = handle.seen.remote().result()
    assert max(sizes) > 1  # some requests actually batched together


def test_http_ingress():
    @serve.deployment
    class Echo:
        def __call__(self, request: serve.Request) -> dict:
            payload = request.json() if request.body else None
            return {"path": request.path, "q": request.query_params, "body": payload}

    serve.run(Echo.bind(), name="http-echo", route_prefix="/")
    port = serve.get_proxy_port()
    assert port is not None
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/abc?x=1", timeout=30) as resp:
        out = json.loads(resp.read())
    assert out["path"] == "/abc"
    assert out["q"] == {"x": "1"}
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=json.dumps({"k": 3}).encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.loads(resp.read())
    assert out["body"] == {"k": 3}


def test_status_and_delete():
    @serve.deployment
    def f(_x):
        return 1

    serve.run(f.bind(), name="temp")
    st = serve.status()
    assert "temp" in st
    assert st["temp"]["deployments"]["f"]["num_replicas"] == 1
    serve.delete("temp")
    assert "temp" not in serve.status()


def test_replica_recovery_after_kill():
    @serve.deployment(num_replicas=1)
    class Sturdy:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Sturdy.bind(), name="sturdy")
    assert handle.remote(1).result() == 2
    # Kill the replica; the controller must replace it.
    controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
    info = ray_tpu.get(controller.get_replicas.remote("sturdy", "Sturdy"))
    ray_tpu.kill(info["replicas"][0])
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            new_info = ray_tpu.get(controller.get_replicas.remote("sturdy", "Sturdy"))
            if (
                new_info["version"] != info["version"]
                and new_info["replicas"]
                and ray_tpu.get(new_info["replicas"][0].ready.remote(), timeout=10)
            ):
                break
        except Exception:
            pass
        time.sleep(0.2)
    handle._router = None  # drop the cached routing table (fresh handle semantics)
    assert handle.remote(5).result(timeout_s=30) == 6


def test_async_deployment_methods():
    @serve.deployment
    class AsyncD:
        async def __call__(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 3

    handle = serve.run(AsyncD.bind(), name="async")
    assert handle.remote(4).result() == 12


def test_deployment_response_chaining():
    @serve.deployment
    class Stage1:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Stage2:
        def __call__(self, x):
            return x * 2

    h1 = serve.run(Stage1.bind(), name="s1", route_prefix="/s1")
    h2 = serve.run(Stage2.bind(), name="s2", route_prefix=None)
    r1 = h1.remote(10)
    r2 = h2.remote(r1)  # response passed directly: resolved as a dependency
    assert r2.result() == 22


def test_autoscaling_scales_up():
    @serve.deployment(autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                                          "target_ongoing_requests": 1.0,
                                          "upscale_delay_s": 0.2})
    class Slow:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    handle = serve.run(Slow.bind(), name="auto")
    responses = [handle.remote(i) for i in range(12)]
    saw_scale_up = False
    deadline = time.time() + 20
    while time.time() < deadline:
        st = serve.status()
        n = st.get("auto", {}).get("deployments", {}).get("Slow", {}).get("num_replicas", 1)
        if n > 1:
            saw_scale_up = True
            break
        time.sleep(0.2)
    assert sorted(r.result(timeout_s=60) for r in responses) == list(range(12))
    assert saw_scale_up


def test_redeploy_updates_code():
    @serve.deployment
    class V:
        def __init__(self, version):
            self._v = version

        def __call__(self, _x):
            return self._v

    h = serve.run(V.bind("v1"), name="redeploy")
    assert h.remote(0).result() == "v1"
    h2 = serve.run(V.bind("v2"), name="redeploy")
    deadline = time.time() + 15
    while time.time() < deadline:
        h2._router = None
        if h2.remote(0).result(timeout_s=30) == "v2":
            break
        time.sleep(0.2)
    assert h2.remote(0).result(timeout_s=30) == "v2"


def test_duplicate_name_different_args_rejected():
    @serve.deployment
    class D:
        def __init__(self, k):
            self._k = k

        def __call__(self, x):
            return x + self._k

    @serve.deployment
    class Top:
        def __init__(self, a, b):
            pass

        def __call__(self, x):
            return x

    with pytest.raises(ValueError, match="bound twice"):
        serve.run(Top.bind(D.bind(1), D.bind(2)), name="dup")


def test_route_prefix_collision_rejected():
    @serve.deployment
    def a(_x):
        return 1

    @serve.deployment
    def b(_x):
        return 2

    serve.run(a.bind(), name="appa", route_prefix="/same")
    with pytest.raises(Exception, match="route_prefix"):
        serve.run(b.bind(), name="appb", route_prefix="/same")


# ---------------------------------------------------------------- streaming

def test_streaming_response_over_handle():
    import time as _time

    from ray_tpu import serve

    @serve.deployment
    def token_stream(request):
        for i in range(4):
            _time.sleep(0.2)
            yield f"tok{i}"

    handle = serve.run(token_stream.bind(), name="stream_app", route_prefix=None)
    gen = handle.options(stream=True).remote(None)
    t0 = _time.monotonic()
    first = next(gen)
    first_latency = _time.monotonic() - t0
    assert first == "tok0"
    rest = list(gen)
    assert rest == ["tok1", "tok2", "tok3"]
    assert first_latency < 10.0  # arrives before the ~0.8s full stream only on a warm node
    serve.delete("stream_app")


def test_streaming_http_chunked():
    import json as _json
    import socket

    from ray_tpu import serve

    @serve.deployment
    def sse(request):
        for i in range(3):
            yield {"n": i}

    serve.run(sse.bind(), name="sse_app", route_prefix="/sse")
    port = serve.get_proxy_port()
    with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
        s.sendall(b"GET /sse HTTP/1.1\r\nHost: x\r\n\r\n")
        s.settimeout(30)
        data = b""
        while True:
            try:
                chunk = s.recv(65536)
            except TimeoutError:
                break
            if not chunk:
                break
            data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    assert b"Transfer-Encoding: chunked" in head
    # Parse chunked body.
    items = []
    while body:
        size_line, _, rest = body.partition(b"\r\n")
        size = int(size_line, 16)
        if size == 0:
            break
        items.append(rest[:size])
        body = rest[size + 2:]
    parsed = [_json.loads(x) for x in items]
    assert parsed == [{"n": 0}, {"n": 1}, {"n": 2}]
    serve.delete("sse_app")


# ---------------------------------------------------------------- multiplexing

def test_model_multiplexing():
    from ray_tpu import serve

    @serve.deployment
    class MuxModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def load_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": len(model_id)}

        async def __call__(self, request):
            model_id = serve.get_multiplexed_model_id()
            model = await self.load_model(model_id)
            return {"served_by": model["id"], "loads": list(self.loads)}

    handle = serve.run(MuxModel.bind(), name="mux_app", route_prefix=None)

    r1 = handle.options(multiplexed_model_id="alpha").remote(None).result(timeout_s=60)
    assert r1["served_by"] == "alpha"
    r2 = handle.options(multiplexed_model_id="alpha").remote(None).result(timeout_s=60)
    assert r2["served_by"] == "alpha"
    assert r2["loads"].count("alpha") == 1  # cached, loaded once

    # LRU eviction at max 2: loading beta+gamma evicts alpha; re-request reloads.
    handle.options(multiplexed_model_id="beta").remote(None).result(timeout_s=60)
    handle.options(multiplexed_model_id="gamma").remote(None).result(timeout_s=60)
    r5 = handle.options(multiplexed_model_id="alpha").remote(None).result(timeout_s=60)
    assert r5["loads"].count("alpha") == 2
    serve.delete("mux_app")


def test_multiplex_evict_runs_model_unload_hook():
    """Evicted models free their device memory through __model_unload__
    (preferred over the generic teardown verbs), exactly once — never via a
    direct __del__ call (GC would double-release) — and the decorator's
    on_evict callback observes every eviction. Unit-level: _ModelCache is
    pure asyncio, no cluster needed."""
    import asyncio

    from ray_tpu.serve.multiplex import _ModelCache

    unloads, closes, evict_cb = [], [], []

    class _DeviceModel:
        def __init__(self, mid):
            self.mid = mid

        def __model_unload__(self):
            unloads.append(self.mid)

        def close(self):  # must NOT be reached: __model_unload__ wins
            closes.append(self.mid)

    async def scenario():
        cache = _ModelCache(
            lambda mid: _DeviceModel(mid), None, max_models=2,
            on_evict=lambda mid, model: evict_cb.append((mid, model.mid)),
        )
        await cache.get("a")
        await cache.get("b")
        await cache.get("c")       # evicts "a" (LRU)
        assert unloads == ["a"] and closes == []
        assert evict_cb == [("a", "a")]
        assert cache.model_ids == ["b", "c"]
        # an async unload hook (awaitable) works too
        class _AsyncModel:
            def __init__(self, mid):
                self.mid = mid

            async def __model_unload__(self):
                unloads.append("async-" + self.mid)

        cache2 = _ModelCache(lambda mid: _AsyncModel(mid), None, max_models=1)
        await cache2.get("x")
        await cache2.get("y")
        assert unloads[-1] == "async-x"
        # a RAISING unload hook must not wedge eviction
        class _BadModel:
            def __model_unload__(self):
                raise RuntimeError("boom")

        cache3 = _ModelCache(lambda mid: _BadModel(), None, max_models=1)
        await cache3.get("p")
        await cache3.get("q")      # evicts p; hook raises, eviction proceeds
        assert cache3.model_ids == ["q"]

    asyncio.run(scenario())


# ---------------------------------------------------------------- per-node proxies

def test_proxy_port_and_table():
    from ray_tpu import serve

    @serve.deployment
    def hello(request):
        return "hi"

    serve.run(hello.bind(), name="hello_app", route_prefix="/hello")
    ports = serve.proxy_ports()
    assert len(ports) >= 1  # one proxy per alive node
    assert serve.get_proxy_port() in ports.values()
    serve.delete("hello_app")


def test_proxy_port_reports_bound_port_on_conflict():
    """Contract (reference proxy.py: one fixed port per node): when the
    configured port is taken, the proxy falls back to an ephemeral port and
    get_proxy_port()/proxy_ports() must report the port ACTUALLY BOUND —
    never the configured number — and HTTP must answer on it."""
    import socket

    from ray_tpu import serve

    squat = socket.socket()
    squat.bind(("127.0.0.1", 0))
    squat.listen(1)
    taken = squat.getsockname()[1]
    try:
        serve.start(http_options={"port": taken})

        @serve.deployment
        def pong(request):
            return "pong"

        serve.run(pong.bind(), name="pong_app", route_prefix="/pong")
        port = serve.get_proxy_port()
        assert port and port != taken, (
            f"get_proxy_port() returned the configured (unbindable) port {taken}"
        )
        assert port in serve.proxy_ports().values()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/pong", timeout=60
        ).read()
        assert body == b"pong"
        serve.delete("pong_app")
    finally:
        squat.close()
        # Restore default options so later tests aren't pinned to `taken`.
        serve.shutdown()


def test_grpc_ingress(_cluster):
    """gRPC ingress beside HTTP (reference: the serve gRPC proxy): any
    /<app>/<method> unary call routes to the app's ingress with raw bytes."""
    grpc = pytest.importorskip("grpc")

    @serve.deployment
    class Echo:
        def __call__(self, request):
            assert request.method == "GRPC"
            body = request.body.decode()
            return {"echo": body, "path": request.path}

    serve.start(http_options={"grpc_port": 0})
    serve.run(Echo.bind(), name="grpcapp", route_prefix="/grpcapp", _timeout_s=120)
    port = serve.get_grpc_port()
    assert port, "grpc ingress did not start"
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    rpc = channel.unary_unary(
        "/grpcapp/Predict",
        request_serializer=None,
        response_deserializer=None,
    )
    out = rpc(b"hello-grpc", timeout=120)
    payload = json.loads(out)
    assert payload["echo"] == "hello-grpc"
    assert payload["path"] == "/grpcapp/Predict"
    channel.close()


def test_multiplex_cluster_wide_routing(_cluster):
    """A FRESH router (no per-caller state) routes a multiplexed model to a
    replica that reported it loaded — cluster-wide replica-reported ids, not
    per-caller learning (VERDICT weak #11)."""
    import time as _time

    from ray_tpu.serve.handle import _Router

    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Mux:
        @serve.multiplexed(max_num_models_per_replica=4)
        async def load(self, model_id: str):
            return {"id": model_id}

        async def __call__(self, request):
            model = await self.load(serve.get_multiplexed_model_id())
            return {"model": model["id"]}

    handle = serve.run(Mux.bind(), name="muxapp", route_prefix="/mux",
                       _timeout_s=120)
    out = handle.options(multiplexed_model_id="m1").remote(None).result(timeout_s=60)
    assert out["model"] == "m1"
    # Wait for the controller's stats poll to pick up the replica's model list,
    # observed through a BRAND-NEW router with no local affinity.
    deadline = _time.monotonic() + 60
    router = None
    while _time.monotonic() < deadline:
        router = _Router("muxapp", "Mux")
        router._refresh(force=True)
        if any("m1" in ids for ids in router._mux.values()):
            break
        _time.sleep(0.5)
    assert router is not None and any(
        "m1" in ids for ids in router._mux.values()
    ), "controller never reported multiplexed ids"
    # The fresh router picks a replica that actually holds m1.
    for _ in range(3):
        pick = router.pick("m1")
        assert "m1" in router._mux.get(pick._actor_id, ()), "routed off-holder"
        router.done(pick)
