"""Autoscaler tests: demand-driven upscale, idle downscale, request_resources.

Shape parity: reference python/ray/tests/test_autoscaler_e2e.py +
autoscaler/v2/tests (reconciler logic against a local provider).
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    LocalNodeProvider,
    request_resources,
)
from ray_tpu.cluster_utils import Cluster

_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1, "env_vars": _WORKER_ENV})
    c.connect()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_upscale_on_pending_tasks(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=2, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )

    @ray_tpu.remote(num_cpus=2)  # can never fit on the 1-CPU head
    def big(x):
        return x * 2

    refs = [big.remote(i) for i in range(4)]
    # demand reaches the GCS via heartbeats; reconcile until nodes appear
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_ups >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_ups >= 1
    assert ray_tpu.get(refs, timeout=120) == [0, 2, 4, 6]


def test_downscale_idle_nodes(cluster):
    provider = LocalNodeProvider(cluster)
    autoscaler = Autoscaler(
        provider,
        AutoscalingConfig(min_workers=0, max_workers=2,
                          worker_resources={"CPU": 1}, idle_timeout_s=1.0),
    )
    provider.create_node({"CPU": 1})
    deadline = time.time() + 20
    while time.time() < deadline and len(ray_tpu.nodes()) < 2:
        time.sleep(0.2)
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_downs >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_downs >= 1
    assert provider.non_terminated_nodes() == []


def test_request_resources_floor(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=3, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )
    request_resources(num_cpus=4)  # head has 1; needs 2 worker nodes of 2
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        total = ray_tpu.cluster_resources().get("CPU", 0)
        if total >= 4:
            break
        time.sleep(0.5)
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 4


def test_upscale_on_pending_actor(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=1, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )

    @ray_tpu.remote(num_cpus=2)
    class Heavy:
        def ping(self):
            return "up"

    a = Heavy.remote()  # unplaceable on the 1-CPU head
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_ups >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_ups >= 1
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "up"


def test_gce_tpu_provider_dryrun():
    """GCETPUNodeProvider against recorded GCE responses (VERDICT #9;
    reference: python/ray/autoscaler/_private/gcp/node_provider.py)."""
    from ray_tpu.autoscaler.gcp import GCETPUNodeProvider, RecordedTransport

    transport = RecordedTransport()
    provider = GCETPUNodeProvider(
        project="proj", zone="us-central2-b", accelerator_type="v5litepod-16",
        head_address="10.0.0.1:6379", cluster_name="testcl",
        transport=transport,
    )
    nid = provider.create_node({
        "CPU": 1, "TPU": 4, "TPU-v5litepod-16": 1, "TPU-v5litepod-16-head": 1,
        "TPU-testslice": 1, "my_custom": 2, "very_custom": 1,
    })
    method, url, body = transport.requests[-1]
    assert method == "POST" and f"nodeId={nid}" in url
    assert body["acceleratorType"] == "v5litepod-16"
    script = body["metadata"]["startup-script"]
    assert "ray_tpu start --address=10.0.0.1:6379" in script
    # TPU/pod/head resources must NOT be baked into the startup script: it runs
    # on every host of the slice, and only TPU_WORKER_ID==0 may advertise the
    # gang-scheduling head resource (per-host discovery derives all of these).
    assert "head" not in script and "TPU" not in script and "v5litepod" not in script
    assert "my_custom" in script and "very_custom" in script
    assert body["labels"]["ray-tpu-cluster"] == "testcl"

    assert provider.non_terminated_nodes() == [nid]
    addr = provider.cluster_address(nid)
    assert addr is not None and addr[0].startswith("10.0.0.")
    provider.terminate_node(nid)
    assert provider.non_terminated_nodes() == []
    # Foreign/deleting slices are excluded from the cluster's node view.
    transport._nodes["other"] = {"name": "nodes/other", "state": "READY",
                                "labels": {"ray-tpu-cluster": "another"}}
    transport._nodes["dying"] = {"name": "nodes/dying", "state": "DELETING",
                                 "labels": {"ray-tpu-cluster": "testcl"}}
    assert provider.non_terminated_nodes() == []


def test_upscale_on_slice_head_gated_demand(cluster):
    """An actor gang-gated on a TPU slice-head resource drives the autoscaler
    to provision a slice-shaped node, and the gang then schedules (the
    FakeMultiNode-style e2e of VERDICT #9)."""
    slice_resources = {"CPU": 1, "TPU": 4.0, "TPU-v5e-16": 1.0,
                      "TPU-v5e-16-head": 1.0}
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=2, worker_resources=slice_resources,
                          idle_timeout_s=300),
    )

    @ray_tpu.remote(resources={"TPU-v5e-16-head": 1.0}, num_cpus=0)
    class SliceHead:
        def where(self):
            return "on-slice"

    a = SliceHead.remote()
    ref = a.where.remote()
    deadline = time.time() + 90
    added = 0
    while time.time() < deadline:
        added += autoscaler.reconcile_once()["added"]
        if added:
            break
        time.sleep(1.0)
    assert added >= 1, "autoscaler never provisioned a slice for the gated actor"
    assert ray_tpu.get(ref, timeout=120) == "on-slice"


def test_yaml_cluster_config_roundtrip(tmp_path):
    """`ray_tpu up/down` config parsing + provider construction."""
    from ray_tpu.scripts.scripts import _build_provider, _load_cluster_yaml

    cfg_file = tmp_path / "cluster.yaml"
    cfg_file.write_text("""
cluster_name: mypod
provider:
  type: gcp_tpu
  project: proj
  zone: us-central2-b
  accelerator_type: v5litepod-16
head:
  num_cpus: 4
workers:
  min_workers: 0
  max_workers: 8
  resources: {TPU: 4, TPU-v5litepod-16: 1}
""")
    cfg = _load_cluster_yaml(str(cfg_file))
    assert cfg["cluster_name"] == "mypod"
    assert cfg["workers"]["max_workers"] == 8
    from ray_tpu.autoscaler.gcp import GCETPUNodeProvider, RecordedTransport

    provider = _build_provider(cfg, head_address="10.0.0.1:6379")
    assert isinstance(provider, GCETPUNodeProvider)
    provider._transport = RecordedTransport()
    nid = provider.create_node(dict(cfg["workers"]["resources"]))
    assert provider.non_terminated_nodes() == [nid]
