"""Autoscaler tests: demand-driven upscale, idle downscale, request_resources.

Shape parity: reference python/ray/tests/test_autoscaler_e2e.py +
autoscaler/v2/tests (reconciler logic against a local provider).
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    LocalNodeProvider,
    request_resources,
)
from ray_tpu.cluster_utils import Cluster

_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1, "env_vars": _WORKER_ENV})
    c.connect()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_upscale_on_pending_tasks(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=2, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )

    @ray_tpu.remote(num_cpus=2)  # can never fit on the 1-CPU head
    def big(x):
        return x * 2

    refs = [big.remote(i) for i in range(4)]
    # demand reaches the GCS via heartbeats; reconcile until nodes appear
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_ups >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_ups >= 1
    assert ray_tpu.get(refs, timeout=120) == [0, 2, 4, 6]


def test_downscale_idle_nodes(cluster):
    provider = LocalNodeProvider(cluster)
    autoscaler = Autoscaler(
        provider,
        AutoscalingConfig(min_workers=0, max_workers=2,
                          worker_resources={"CPU": 1}, idle_timeout_s=1.0),
    )
    provider.create_node({"CPU": 1})
    deadline = time.time() + 20
    while time.time() < deadline and len(ray_tpu.nodes()) < 2:
        time.sleep(0.2)
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_downs >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_downs >= 1
    assert provider.non_terminated_nodes() == []


def test_request_resources_floor(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=3, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )
    request_resources(num_cpus=4)  # head has 1; needs 2 worker nodes of 2
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        total = ray_tpu.cluster_resources().get("CPU", 0)
        if total >= 4:
            break
        time.sleep(0.5)
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 4


def test_upscale_on_pending_actor(cluster):
    autoscaler = Autoscaler(
        LocalNodeProvider(cluster),
        AutoscalingConfig(max_workers=1, worker_resources={"CPU": 2},
                          idle_timeout_s=300),
    )

    @ray_tpu.remote(num_cpus=2)
    class Heavy:
        def ping(self):
            return "up"

    a = Heavy.remote()  # unplaceable on the 1-CPU head
    deadline = time.time() + 60
    while time.time() < deadline:
        autoscaler.reconcile_once()
        if autoscaler.num_scale_ups >= 1:
            break
        time.sleep(0.5)
    assert autoscaler.num_scale_ups >= 1
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "up"
