"""Test fixtures.

Parity with the reference test strategy (SURVEY.md §4): ray_start_regular boots a real
single-node cluster; ray_start_cluster yields a Cluster for multi-node tests with real
raylet processes. JAX tests run on a virtual 8-device CPU mesh (the reference pattern of
faking TPU resources on CPU nodes, python/ray/train/v2/tests/test_jax_trainer.py:16-55).
"""

import os

# Tests run on a virtual 8-device CPU mesh, even when a real TPU plugin (axon) was
# registered by sitecustomize at interpreter start: jax backends initialize lazily, so
# overriding the platform in-process before first use wins.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running chaos/stress variants excluded from the tier-1 "
        "wall-clock budget (tier-1 runs -m 'not slow')",
    )


# -- leaksan guard (docs/raylint.md §leaksan) ---------------------------------
# The suites whose tests exercise the acquire/release-paired resource planes
# (slot-view leases, KV prefix leases, arena pins, device-object stream
# pumps): each test in them runs under the runtime leak sanitizer and FAILS
# if it grows the live-handle registry.
LEAKSAN_SUITES = {
    "test_tensor_channel.py",
    "test_llm_kvcache.py",
    "test_llm_kvtier.py",
    "test_llm_multitenant.py",
    "test_device_objects.py",
    "test_llm_tp.py",
    "test_flight_recorder.py",
    "test_xprof.py",
    "test_autopilot.py",
    "test_llm_generate.py",
    "test_llm_stream.py",
    "test_llm_batch.py",
}


@pytest.fixture(autouse=True)
def leaksan_guard(request):
    fspath = getattr(request.node, "fspath", None)
    name = os.path.basename(str(fspath)) if fspath is not None else ""
    if name not in LEAKSAN_SUITES:
        yield
        return
    from ray_tpu.devtools import leaksan

    leaksan.enable()
    before = leaksan.snapshot()
    yield
    # rpc conns are cached per (process, peer) for the process lifetime by
    # design, so they are reported but not failed on; pump threads and every
    # lease/pin/view/stream kind must return to the baseline (gc-collected-
    # without-release counts as a leak too — see leaksan.check_growth).
    growth = leaksan.check_growth(before, settle_s=5.0)
    if growth:
        report = growth.pop("report", {})
        pytest.fail(
            f"leaksan: resource handles leaked by this test: {growth}\n"
            f"live handles: {report}", pytrace=False,
        )

# -- distsan guard (docs/raylint.md §distsan) ---------------------------------
# The suites that drive the tagged hot-path/report-path/finalizer contexts
# (the llm decode loop, scheduler stats export, stream finalizers): each test
# runs under the runtime distributed-contract sanitizer and FAILS if a metric
# mutation or GCS call landed inside a hot/finalizer context.
DISTSAN_SUITES = {
    "test_llm_engine_hotpath.py",
    "test_llm_scheduler.py",
    "test_llm_multitenant.py",
    "test_serve_observability.py",
    "test_autopilot.py",
    "test_llm_generate.py",
    "test_llm_stream.py",
    "test_llm_batch.py",
}


@pytest.fixture(autouse=True)
def distsan_guard(request):
    fspath = getattr(request.node, "fspath", None)
    name = os.path.basename(str(fspath)) if fspath is not None else ""
    if name not in DISTSAN_SUITES:
        yield
        return
    from ray_tpu.devtools import distsan

    distsan.enable()
    distsan.reset()
    yield
    found = distsan.violations()
    distsan.disable()
    distsan.reset()
    if found:
        pytest.fail(
            "distsan: control-plane traffic recorded inside a hot/finalizer "
            f"context during this test: {found}", pytrace=False,
        )


_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


@pytest.fixture(scope="module")
def ray_start_regular():
    """A single-node cluster shared by the tests in one module (fast on 1-core CI)."""
    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=_WORKER_ENV)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_isolated():
    """A fresh single-node cluster per test (for tests that mutate cluster state)."""
    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=_WORKER_ENV)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1, "env_vars": _WORKER_ENV})
    yield cluster
    cluster.shutdown()


# -- multi-device-on-CPU harness (docs/serving_tp.md) -------------------------
# Mesh/TP tests need several XLA devices, which only exist if XLA_FLAGS was
# set BEFORE jax initialized. This conftest forces it for in-process tests;
# the subprocess harness below makes mesh tests robust even when the parent
# interpreter's jax initialized under different flags (plugin sitecustomize,
# a bare `pytest tests/test_llm_tp.py -p no:conftest`, an embedding harness),
# so the tier-1 command exercises real meshes on any CPU-only CI box.

def run_multi_device_subprocess(code: str, *, timeout: float = 600,
                                env_extra: dict | None = None) -> dict:
    """Run `code` in a fresh interpreter with the 8-virtual-device CPU env
    forced. The snippet reports by printing one line `RESULT <json>`;
    the parsed object is returned. Failure surfaces stdout+stderr."""
    import json
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(_WORKER_ENV)
    if env_extra:
        env.update(env_extra)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=repo_root,
    )
    assert proc.returncode == 0, (
        f"multi-device subprocess failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}"
    )
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(
        f"multi-device subprocess printed no RESULT line:\n{proc.stdout[-2000:]}"
    )


@pytest.fixture(scope="session")
def multi_device_run():
    """The subprocess-spawned multi-device test group runner (TP mesh tests
    ride it so CI without TPUs — or with a parent jax initialized under
    different XLA flags — still runs them against a real 8-device mesh)."""
    return run_multi_device_subprocess
