"""Guided decoding (docs/generation.md): constraint-masked generation on the
one decode scheduler.

The contract under test: every guided output is 100% valid under its spec
(regex / JSON schema / grammar); guidance is token-identical to unconstrained
greedy whenever the unconstrained argmax is already legal; the masked
spec-verify gate is token-identical to masked plain decode; and the
per-request constraint state balances its leaksan books on every end-of-life
path (this suite runs under the leaksan + distsan autouse guards).
"""

import json
import re
import threading

import pytest


@pytest.fixture(scope="module")
def tiny():
    """Shared test-tiny config + params (engines are cheap, init is not)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def _engine(tiny, **kw):
    from ray_tpu.llm import DecodeEngine

    cfg, params = tiny
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 128)
    return DecodeEngine(cfg, params, **kw)


def _run(engine, token_ids, sampling, constraint=None):
    """Blocking generate via the raw callback surface; returns token list."""
    acc = []
    done = threading.Event()

    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()

    engine.submit(list(token_ids), sampling, cb, constraint=constraint)
    assert done.wait(300), "generation did not finish"
    return [t for t in acc if t >= 0]


def _compile(spec, vocab):
    from ray_tpu.llm import ByteTokenizer
    from ray_tpu.llm.generate import compile_constraint

    return compile_constraint(spec, ByteTokenizer(), vocab)


def test_guided_regex_output_fullmatches(tiny):
    from ray_tpu.llm import ByteTokenizer, SamplingParams

    cfg, _ = tiny
    engine = _engine(tiny)
    try:
        constraint = _compile("[0-9]{4}", cfg.vocab_size)
        toks = _run(engine, b"ab", SamplingParams(max_tokens=16),
                    constraint=constraint)
        text = ByteTokenizer().decode(toks)
        # The accepting dead-end finishes the slot at exactly 4 digits —
        # no stop token, no burned max_tokens budget.
        assert re.fullmatch(r"[0-9]{4}", text), (toks, text)
    finally:
        engine.shutdown()


def test_guided_json_schema_output_parses_valid(tiny):
    from ray_tpu.llm import ByteTokenizer, SamplingParams

    cfg, _ = tiny
    schema = {
        "type": "object",
        "properties": {"ok": {"type": "boolean"}, "n": {"type": "integer"}},
        "required": ["ok", "n"],
    }
    engine = _engine(tiny)
    try:
        constraint = _compile({"json_schema": schema}, cfg.vocab_size)
        toks = _run(engine, b"x", SamplingParams(max_tokens=48),
                    constraint=constraint)
        obj = json.loads(ByteTokenizer().decode(toks))
        assert isinstance(obj["ok"], bool)
        assert isinstance(obj["n"], int)
        assert set(obj) == {"ok", "n"}
    finally:
        engine.shutdown()


def test_guided_grammar_output_matches_lowered_regex(tiny):
    from ray_tpu.llm import ByteTokenizer, SamplingParams
    from ray_tpu.llm.generate import grammar_to_regex

    cfg, _ = tiny
    rules = {"root": "<word>(,<word>){0,2}", "word": "[a-z]{2,4}"}
    engine = _engine(tiny)
    try:
        constraint = _compile({"grammar": rules}, cfg.vocab_size)
        toks = _run(engine, b"q", SamplingParams(max_tokens=24),
                    constraint=constraint)
        text = ByteTokenizer().decode(toks)
        # The lowered grammar is a plain regex in both the engine's subset
        # and Python's re — validate against the exact same pattern.
        assert re.fullmatch(grammar_to_regex(rules), text), text
    finally:
        engine.shutdown()


def test_guided_identity_when_argmax_always_legal(tiny):
    """A constraint that allows every byte adds 0 to every legal logit, so
    guided greedy must be TOKEN-IDENTICAL to unconstrained greedy — and
    guidance must compile ZERO new device programs (the masks are host-side
    numpy on the already-pulled logits row)."""
    from ray_tpu.llm import SamplingParams

    cfg, _ = tiny
    engine = _engine(tiny)
    try:
        prompt = b"hello"
        base = _run(engine, prompt, SamplingParams(max_tokens=8))
        compiles = engine.scheduler_stats()["programs"]["totals"]["compiles_total"]
        constraint = _compile("(.|\n)*", cfg.vocab_size)
        guided = _run(engine, prompt, SamplingParams(max_tokens=8),
                      constraint=constraint)
        assert guided == base
        after = engine.scheduler_stats()["programs"]["totals"]["compiles_total"]
        assert after == compiles, "guided decoding compiled a new program"
    finally:
        engine.shutdown()


def test_guided_budget_steering_completes_within_max_tokens(tiny):
    """An unbounded quantifier (JSON integers, a{1,50}) must not eat the
    whole max_tokens budget and truncate mid-pattern: as the remaining
    budget tightens, the mask steers onto a completable path, so the output
    is ALWAYS a full match — for any model, any sampling."""
    from ray_tpu.llm import ByteTokenizer, SamplingParams

    cfg, _ = tiny
    engine = _engine(tiny)
    try:
        constraint = _compile("a{1,50}b", cfg.vocab_size)
        toks = _run(engine, b"go", SamplingParams(max_tokens=3),
                    constraint=constraint)
        text = ByteTokenizer().decode(toks)
        assert re.fullmatch(r"a{1,50}b", text), text

        schema = {"type": "object",
                  "properties": {"ok": {"type": "boolean"},
                                 "n": {"type": "integer"}},
                  "required": ["ok", "n"]}
        constraint = _compile({"json_schema": schema}, cfg.vocab_size)
        toks = _run(engine, b"x", SamplingParams(max_tokens=20),
                    constraint=constraint)
        obj = json.loads(ByteTokenizer().decode(toks))
        assert isinstance(obj["ok"], bool) and isinstance(obj["n"], int)
    finally:
        engine.shutdown()


def test_guided_spec_verify_matches_plain_decode(tiny):
    """The batched spec-verify gate composes the same per-position masks as
    the host sampling row: masked spec decode ≡ masked plain decode."""
    from ray_tpu.llm import SamplingParams

    cfg, _ = tiny
    constraint = _compile("[0-9]{6}", cfg.vocab_size)
    plain = _engine(tiny, multi_step=1)
    try:
        want = _run(plain, b"n=", SamplingParams(max_tokens=12),
                    constraint=constraint)
    finally:
        plain.shutdown()
    spec = _engine(tiny, spec_config={"num_spec_tokens": 6})
    try:
        got = _run(spec, b"n=", SamplingParams(max_tokens=12),
                   constraint=constraint)
        st = spec.scheduler_stats()
        assert st["spec"]["proposed_tokens"] > 0  # the gate actually ran
    finally:
        spec.shutdown()
    assert got == want


def test_constraint_vocab_mismatch_rejected_loudly(tiny):
    """A constraint compiled against the wrong logits width must raise at
    submit, never silently mask garbage — and must not leak state."""
    from ray_tpu.llm import SamplingParams

    cfg, _ = tiny
    engine = _engine(tiny)
    try:
        bad = _compile("[0-9]+", cfg.vocab_size + 64)
        with pytest.raises(ValueError, match="vocab"):
            engine.submit([1, 2], SamplingParams(max_tokens=4),
                          lambda t, f: None, constraint=bad)
    finally:
        engine.shutdown()


def test_constraint_compiler_caches_by_spec(tiny):
    from ray_tpu.llm import ByteTokenizer
    from ray_tpu.llm.generate import ConstraintCompiler

    cfg, _ = tiny
    comp = ConstraintCompiler(ByteTokenizer(), cfg.vocab_size, capacity=2)
    a1 = comp.get({"regex": "[0-9]+"})
    a2 = comp.get({"regex": "[0-9]+"})
    assert a1 is a2  # LRU hit skips DFA construction
    comp.get({"regex": "[a-z]+"})
    comp.get({"regex": "[A-Z]+"})  # evicts the oldest entry
    assert comp.get({"regex": "[0-9]+"}) is not a1


def test_fixture_catches_planted_constraint_state_leak(tiny):
    """The leaksan contract for the guided plane: a ConstraintState begun
    and never released grows the `constraint_state` kind; releasing clears
    it (this is what fails any engine path that strands one)."""
    from ray_tpu.devtools import leaksan

    cfg, _ = tiny
    constraint = _compile("[0-9]{2}", cfg.vocab_size)
    before = leaksan.snapshot()
    state = constraint.begin("planted-leak")
    growth = leaksan.check_growth(before, settle_s=0.2)
    assert "constraint_state" in growth, growth
    state.release()
    assert leaksan.check_growth(before, settle_s=0.2) == {}


def test_guided_json_schema_through_http(ray_start_regular):
    """End-to-end acceptance: an OpenAI `response_format` json_schema
    request through the HTTP proxy returns parseable, schema-valid output;
    an unsupported guided spec fails as a 4xx-shaped error, not a hang."""
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, build_openai_app

    app = build_openai_app([LLMConfig(model_id="test-tiny", num_slots=2)])
    serve.run(app, name="openai-guided", route_prefix="/", _timeout_s=240)
    try:
        port = serve.get_proxy_port()

        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                data=json.dumps(payload).encode(), method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                return json.loads(resp.read())

        schema = {
            "type": "object",
            "properties": {"ok": {"type": "boolean"}},
            "required": ["ok"],
        }
        out = post({
            "model": "test-tiny",
            "messages": [{"role": "user", "content": "give me json"}],
            "max_tokens": 32,
            "response_format": {"type": "json_schema",
                                "json_schema": {"schema": schema}},
        })
        content = out["choices"][0]["message"]["content"]
        obj = json.loads(content)
        assert isinstance(obj["ok"], bool) and set(obj) == {"ok"}

        bad = post({
            "model": "test-tiny",
            "messages": [{"role": "user", "content": "x"}],
            "max_tokens": 8,
            "guided_json": {"type": "tuple"},  # outside the supported subset
        })
        assert bad["error"]["code"] == "guided_decoding"
    finally:
        serve.delete("openai-guided")
        serve.shutdown()


def test_guided_state_released_on_cancel(tiny):
    """cancel() of a guided request frees the constraint state within one
    scheduler iteration (the leaksan guard on this suite enforces the
    balance; this asserts the cancelled flight record too)."""
    from ray_tpu.llm import SamplingParams

    cfg, _ = tiny
    engine = _engine(tiny)
    try:
        constraint = _compile("[0-9]{64}", cfg.vocab_size)
        done = threading.Event()
        engine.submit([1], SamplingParams(max_tokens=120),
                      lambda t, f: done.set() if f else None,
                      request_id="guided-cancel", constraint=constraint)
        engine.cancel("guided-cancel")
        assert done.wait(60)
        stats = engine.recorder_stats()
        assert stats["cancelled"] >= 1
    finally:
        engine.shutdown()
