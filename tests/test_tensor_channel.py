"""Tensor-native channel plane (round 11, docs/device_channels.md).

Covers the ISSUE-8 acceptance surface: array payloads ride raw-buffer frames
(no cloudpickle of tensor bytes), chunked DeviceChannel streams are bitwise
across chunk-size sweeps (incl. non-divisible sizes), rings stay coherent
after tensor writes, ChannelClosed mid-stream unwinds without leaking pins,
RpcChannel readers ride transient failures, and PD KV handoff over the new
transport is token-identical to the pre-change host path.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental import tensor_transport as tt
from ray_tpu.experimental.channel import Channel, ChannelClosed, RpcChannel
from ray_tpu.experimental.device_channel import DeviceChannel


def test_channel_tensor_fastpath_roundtrip():
    """Array-bearing values cross a shm Channel as tensor frames (small
    pickled skeleton + raw leaf bytes); scalars still pickle."""
    tt.reset_transport_stats()
    ch = Channel(capacity=1 << 20, num_readers=1, num_slots=2)
    try:
        r = ch.reader(0)
        value = {
            "a": np.arange(5000, dtype=np.float32),
            "nested": [np.ones((16, 16), np.int16), "tag"],
            "n": 7,
        }
        ch.write(value)
        out = r.read()
        np.testing.assert_array_equal(out["a"], value["a"])
        np.testing.assert_array_equal(out["nested"][0], value["nested"][0])
        assert out["nested"][1] == "tag" and out["n"] == 7
        # Decoded arrays OWN their bytes: the ring slot may recycle.
        assert out["a"].flags.owndata and out["a"].flags.writeable

        ch.write({"just": "pickle"})
        assert r.read() == {"just": "pickle"}

        s = tt.transport_stats()
        assert s["tensor_frames_written"] == 1, s
        assert s["tensor_frames_read"] == 1, s
        assert s["pickle_frames_written"] == 1, s
        assert s["tensor_bytes_written"] >= value["a"].nbytes
    finally:
        ch.destroy()


def test_ring_reuse_after_tensor_writes():
    """Ring slots cycle through tensor and pickle frames interleaved, well
    past the slot count, with every payload intact bitwise."""
    ch = Channel(capacity=256 << 10, num_readers=1, num_slots=3)
    try:
        r = ch.reader(0)
        rng = np.random.default_rng(0)
        for i in range(20):
            arr = rng.standard_normal(1 + 997 * i % 4096).astype(np.float32)
            ch.write({"i": i, "arr": arr})
            out = r.read()
            assert out["i"] == i
            np.testing.assert_array_equal(out["arr"], arr)
            ch.write(("plain", i))
            assert r.read() == ("plain", i)
    finally:
        ch.destroy()


def test_read_view_lease_blocks_writer_not_corrupts():
    """A zero-copy SlotView defers the ack: the writer back-pressures on the
    leased slot instead of overwriting the bytes under the alias."""
    ch = Channel(capacity=64 << 10, num_readers=1, num_slots=2)
    try:
        r = ch.reader(0)
        payload = np.arange(4096, dtype=np.int32)
        ch.write(payload)
        view = r.read_view()
        alias = tt.decode(view.mv, copy=False)
        assert not alias.flags.owndata  # genuinely aliases the slot
        np.testing.assert_array_equal(alias, payload)

        ch.write({"fill": 1})  # second slot
        blocked = threading.Thread(
            target=lambda: ch.write({"third": 2}, timeout=10)
        )
        blocked.start()
        time.sleep(0.2)
        assert blocked.is_alive(), "writer must wait for the leased slot"
        snapshot = alias.copy()
        del alias
        view.release()
        blocked.join(5)
        assert not blocked.is_alive()
        np.testing.assert_array_equal(snapshot, payload)
        assert r.read() == {"fill": 1} and r.read() == {"third": 2}
    finally:
        ch.destroy()


@pytest.mark.parametrize("chunk_bytes", [1000, 4096, 12345, 1 << 16])
def test_chunked_stream_numerics_sweep(chunk_bytes):
    """DeviceChannel streams are bitwise across chunk sizes, including sizes
    that do not divide the payload and mixed/extension dtypes."""
    import jax.numpy as jnp

    ch = DeviceChannel.create(same_node=True, chunk_bytes=chunk_bytes)
    try:
        rng = np.random.default_rng(1)
        tree = {
            "kv": rng.standard_normal((4, 2, 33, 2, 8)).astype(np.float32),
            "bf16": jnp.arange(777, dtype=jnp.bfloat16),
            "i8": rng.integers(-100, 100, 100003).astype(np.int8),
            "empty": np.zeros((0, 3), np.float32),
            "meta": {"prompt_len": 33},
        }
        writer = threading.Thread(target=lambda: ch.send(tree))
        writer.start()
        out = ch.recv(timeout=60)
        writer.join(30)
        np.testing.assert_array_equal(out["kv"], tree["kv"])
        np.testing.assert_array_equal(out["bf16"], np.asarray(tree["bf16"]))
        np.testing.assert_array_equal(out["i8"], tree["i8"])
        assert out["empty"].shape == (0, 3)
        assert out["meta"] == {"prompt_len": 33}

        # Device-staged assembly (per-chunk device_put + one concat).
        writer = threading.Thread(target=lambda: ch.send(tree))
        writer.start()
        dev = ch.recv_device(timeout=60)
        writer.join(30)
        np.testing.assert_array_equal(np.asarray(dev["kv"]), tree["kv"])
        np.testing.assert_array_equal(
            np.asarray(dev["bf16"]), np.asarray(tree["bf16"])
        )
        assert ch.drain(10)
    finally:
        ch.destroy()


def test_device_channel_local_handoff():
    """Same-process handoff moves device arrays by reference; a target
    sharding rides jax.device_put (the ICI path on real meshes)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    ch = DeviceChannel.create(local=True)
    try:
        x = jnp.arange(1024.0)
        ch.send(x)
        assert ch.recv(timeout=10) is x  # zero transfer, zero staging

        mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
        sharding = NamedSharding(mesh, PartitionSpec("x"))
        ch.send(x, sharding=sharding)
        out = ch.recv(timeout=10)
        assert out.sharding == sharding
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    finally:
        ch.destroy()


def test_channel_closed_mid_stream_unwinds_writer():
    """Reader closing (or dying) mid-stream wakes the blocked writer with
    ChannelClosed instead of wedging it on a full ring."""
    ch = DeviceChannel.create(same_node=True, chunk_bytes=4096, num_slots=2)
    outcome = []

    def writer():
        try:
            ch.send({"big": np.arange(1_000_000, dtype=np.float32)},
                    timeout=30)
            outcome.append("sent")
        except ChannelClosed:
            outcome.append("closed")

    t = threading.Thread(target=writer)
    t.start()
    time.sleep(0.15)  # writer is deep in the chunk loop on a full ring
    ch.close()
    t.join(10)
    assert outcome == ["closed"], outcome
    ch.destroy()


def test_stream_fetch_closed_reader_releases_owner_pins(ray_start_regular):
    """A consumer that aborts a device-object stream mid-pull must not leak
    the owner's pump (snapshot reference + shm segment): active_streams()
    returns to zero and the pinned object survives for later readers."""
    import jax.numpy as jnp

    from ray_tpu.experimental import device_objects as dev

    @ray_tpu.remote
    class Owner:
        def make(self, n):
            return dev.put(jnp.arange(n, dtype=jnp.float32))

        def open_stream(self, key, node):
            return dev._open_stream(None, key, node, 4096)

        def streams(self):
            return dev.active_streams()

        def pinned(self):
            return len(dev.stored_keys())

    owner = Owner.remote()
    ref = ray_tpu.get(owner.make.remote(500_000), timeout=120)

    w = ray_tpu.global_worker()
    ch = ray_tpu.get(
        owner.open_stream.remote(ref.key, w.node_id), timeout=120
    )
    # Read ONLY the header + one chunk, then abandon the stream.
    header = ch._transport.read_bytes(timeout=30)
    assert bytes(header[:4]) == b"RTS1"
    ch._transport.read_bytes(timeout=30)
    ch.close()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ray_tpu.get(owner.streams.remote(), timeout=60) == 0:
            break
        time.sleep(0.1)
    assert ray_tpu.get(owner.streams.remote(), timeout=60) == 0, (
        "aborted stream leaked its owner-side pump"
    )
    # The pin itself is untouched: a fresh full fetch still works.
    assert ray_tpu.get(owner.pinned.remote(), timeout=60) == 1
    out = dev.get(ref)
    np.testing.assert_array_equal(
        out, np.arange(500_000, dtype=np.float32)
    )


def test_rpc_channel_transient_failures_retry_then_recover(ray_start_regular):
    """Transient RpcError/OSError during a pull retries with backoff inside
    the reconnect window (evicting dead conns from the cache) instead of
    instantly declaring ChannelClosed; a persistent outage still closes."""
    from ray_tpu._private import rpc
    from ray_tpu.experimental import channel as chan_mod

    ch = RpcChannel(capacity=1 << 16, num_readers=1, num_slots=2,
                    owner=("addr", ("127.0.0.1", 1)))
    ch.write({"v": 41})
    ch.write({"v": 42})

    fails = {"n": 2}

    class FlakyConn:
        closed = False

        async def call(self, method, name, reader, index, poll):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise rpc.RpcError("transient blip")
            return chan_mod._ring_pull(name, reader, index)

    class DeadConn:
        closed = True

    reader = ch.reader(0)
    flaky = FlakyConn()
    reader._writer_conn = lambda: flaky
    # A dead cached conn for the same writer must be evicted on failure.
    with chan_mod._registry_lock:
        chan_mod._conn_cache[("127.0.0.1", 1)] = DeadConn()
    t0 = time.monotonic()
    assert reader.read(timeout=30) == {"v": 41}
    assert fails["n"] == 0
    assert time.monotonic() - t0 < 10
    with chan_mod._registry_lock:
        assert ("127.0.0.1", 1) not in chan_mod._conn_cache
    # Healthy again: the retry window re-arms, next reads are clean.
    assert reader.read(timeout=30) == {"v": 42}

    # Persistent failure: ChannelClosed after the reconnect window.
    class AlwaysDown:
        closed = False

        async def call(self, *a, **k):
            raise OSError("writer gone")

    down = AlwaysDown()
    reader2 = ch.reader(0)
    reader2._next = 2
    reader2._writer_conn = lambda: down
    ch.write({"v": 43})
    with pytest.raises(ChannelClosed):
        reader2.read(timeout=30)
    ch.destroy()


def _run_engine(engine, submit):
    out = []
    done = threading.Event()

    def cb(tok, fin):
        out.append(tok)
        if fin:
            done.set()

    submit(cb)
    assert done.wait(300)
    return out


def test_engine_attaches_device_resident_kv():
    """submit_prefilled accepts a jax-Array KV prefix (the streamed
    recv_device path) and emits exactly the host-path greedy tokens."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompt = [5, 9, 17, 3, 42, 8]
    n = 6

    prefiller = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                             decode_loop=False)
    host_dec = DecodeEngine(cfg, params, num_slots=1, max_seq=128)
    dev_dec = DecodeEngine(cfg, params, num_slots=1, max_seq=128)
    try:
        first_logits, kv, plen = prefiller.prefill_detached(prompt)
        expect = _run_engine(host_dec, lambda cb: host_dec.submit_prefilled(
            kv, plen, first_logits, SamplingParams(max_tokens=n), cb,
            token_ids=prompt))
        got = _run_engine(dev_dec, lambda cb: dev_dec.submit_prefilled(
            jnp.asarray(kv), plen, first_logits,
            SamplingParams(max_tokens=n), cb, token_ids=prompt))
        assert got == expect
    finally:
        prefiller.shutdown()
        host_dec.shutdown()
        dev_dec.shutdown()


def test_pd_token_identity_stream_vs_host_path(ray_start_regular):
    """End-to-end PD across real actor processes: the chunked tensor stream
    must produce byte-equal greedy output to the legacy host-blob path AND to
    a monolithic single-engine reference."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    prompt = [7, 21, 3, 9, 54, 11, 2, 30]
    n = 8

    @ray_tpu.remote
    class Prefill:
        def __init__(self):
            from ray_tpu.experimental import device_objects as dev_mod

            cfg = get_config("test-tiny", scan_layers=False, remat=False)
            params = Transformer(cfg).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )["params"]
            self._dev = dev_mod
            self._engine = DecodeEngine(cfg, params, num_slots=1,
                                        max_seq=128, decode_loop=False)

        def prefill(self, token_ids):
            first_logits, kv, plen = self._engine.prefill_detached(token_ids)
            return {"logits": first_logits, "kv": self._dev.put(kv),
                    "plen": plen}

    @ray_tpu.remote
    class Decode:
        def __init__(self):
            cfg = get_config("test-tiny", scan_layers=False, remat=False)
            params = Transformer(cfg).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )["params"]
            self._engine = DecodeEngine(cfg, params, num_slots=2, max_seq=128)

        def generate(self, pre, token_ids, max_tokens, legacy):
            from ray_tpu.experimental import device_objects as dev_mod

            if legacy:
                kv = dev_mod.get(pre["kv"], _legacy=True)
            else:
                # Force the chunked stream (tiny test prefixes sit below the
                # devobj_stream_min_bytes production gate).
                kv = dev_mod._stream_fetch(pre["kv"], to_device=False)
            out, done = [], threading.Event()

            def cb(tok, fin):
                out.append(tok)
                if fin:
                    done.set()

            self._engine.submit_prefilled(
                kv, pre["plen"], pre["logits"],
                SamplingParams(max_tokens=max_tokens), cb,
                token_ids=token_ids,
            )
            assert done.wait(300)
            return out

    # Monolithic reference in the driver.
    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mono = DecodeEngine(cfg, params, num_slots=1, max_seq=128)
    try:
        expect = _run_engine(mono, lambda cb: mono.submit(
            prompt, SamplingParams(max_tokens=n), cb))
    finally:
        mono.shutdown()

    prefill, decode = Prefill.remote(), Decode.remote()
    pre = ray_tpu.get(prefill.prefill.remote(prompt), timeout=300)
    streamed = ray_tpu.get(
        decode.generate.remote(pre, prompt, n, False), timeout=300
    )
    host_blob = ray_tpu.get(
        decode.generate.remote(pre, prompt, n, True), timeout=300
    )
    assert streamed == host_blob == expect, (streamed, host_blob, expect)
