"""Metrics-plane regressions (docs/observability.md): latency-scale default
Histogram buckets and dead-worker series pruning in collect_all()."""

import time

import pytest

import ray_tpu
from ray_tpu.util.metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    collect_all,
    prometheus_text,
)


def test_histogram_default_is_latency_scale():
    """The old default ([0.1, 1, 10, 100, 1000]) put every sub-second
    serving latency in one bucket. The default is now the log-spaced
    ms-to-minutes scale; explicit boundaries= still wins."""
    h = Histogram("t_hist_default", "d")
    assert h._boundaries == sorted(LATENCY_BUCKETS_S)
    assert h._boundaries[0] == 0.001 and h._boundaries[-1] == 600.0
    # log-spaced: each boundary grows by a bounded multiplicative step
    ratios = [b / a for a, b in zip(h._boundaries, h._boundaries[1:])]
    assert all(1.5 <= r <= 3.5 for r in ratios), ratios
    explicit = Histogram("t_hist_explicit", "d", boundaries=[1, 10])
    assert explicit._boundaries == [1, 10]


def test_latency_histogram_exposition(ray_start_isolated):
    """A sub-second observation lands in discriminating buckets and renders
    proper exposition output (name_bucket{le=...}/_sum/_count)."""
    h = Histogram("t_ttft_seconds", "ttft")
    h.observe(0.003)
    h.observe(0.04)
    h.observe(2.0)
    h.flush()
    text = prometheus_text()
    # 0.003 is counted from the 0.005 bucket on; 0.04 from 0.05; 2.0 from 2.5
    assert 't_ttft_seconds_bucket{le="0.005"} 1.0' in text
    assert 't_ttft_seconds_bucket{le="0.05"} 2.0' in text
    assert 't_ttft_seconds_bucket{le="2.5"} 3.0' in text
    assert 't_ttft_seconds_bucket{le="+Inf"} 3.0' in text
    assert "t_ttft_seconds_count 3.0" in text
    assert "t_ttft_seconds_sum" in text


@ray_tpu.remote
class _MetricActor:
    def emit(self):
        g = Gauge("t_replica_gauge", "per-replica gauge")
        g.set(42.0)
        g.flush()
        return True

    def pid(self):
        import os

        return os.getpid()


def test_collect_all_prunes_dead_worker_series(ray_start_isolated):
    """A killed worker's gauge disappears at collect time (and its KV entry
    is reaped) while a live worker's counter survives even when stale —
    without pruning, every dead replica's series lives in GCS KV forever."""
    c = Counter("t_driver_counter", "driver-side counter")
    c.inc(3.0)
    c.flush()

    actor = _MetricActor.remote()
    assert ray_tpu.get(actor.emit.remote(), timeout=120)

    names = {m["name"] for m in collect_all()}
    assert {"t_driver_counter", "t_replica_gauge"} <= names

    ray_tpu.kill(actor)
    from ray_tpu.util.state import list_actors

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        actors = list_actors()
        if all(a.get("state") == "DEAD" for a in actors):
            break
        time.sleep(0.2)
    time.sleep(1.2)  # let the gauge's last flush age past the test TTL

    pruned = collect_all(ttl_s=1.0)
    names = {m["name"] for m in pruned}
    assert "t_replica_gauge" not in names, names
    # the driver's counter is just as stale, but its worker is alive
    assert "t_driver_counter" in names
    # the prune deleted the KV entry, not just filtered the listing
    again = {m["name"] for m in collect_all(prune=False)}
    assert "t_replica_gauge" not in again


def test_collect_all_prune_keeps_live_actor_series(ray_start_isolated):
    """Liveness beats staleness: a LIVE actor's stale series survives any
    TTL (a quiet gauge is not a dead one)."""
    actor = _MetricActor.remote()
    assert ray_tpu.get(actor.emit.remote(), timeout=120)
    time.sleep(1.2)
    names = {m["name"] for m in collect_all(ttl_s=0.5)}
    assert "t_replica_gauge" in names
    ray_tpu.kill(actor)
