"""Structured export events.

Shape parity: reference src/ray/protobuf/export_*.proto +
observability/ray_event_recorder.cc + dashboard/modules/aggregator — cluster
state transitions (nodes, actors, tasks) land as durable JSONL records an
external aggregator can consume without touching the GCS tables.
"""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture
def export_cluster(tmp_path, monkeypatch):
    exp = tmp_path / "exports"
    monkeypatch.setenv("RAY_TPU_EXPORT_EVENTS_DIR", str(exp))
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=2, num_tpus=0,
        worker_env={
            "RAY_TPU_EXPORT_EVENTS_DIR": str(exp),
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    yield str(exp)
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_EXPORT_EVENTS_DIR")
    CONFIG._reset()


def test_export_events_recorded_and_aggregatable(export_cluster):
    exp = export_cluster

    @ray_tpu.remote
    class Recorder:
        def mark(self):
            return "done"

    @ray_tpu.remote
    def traced_task():
        return 1

    a = Recorder.remote()
    assert ray_tpu.get(a.mark.remote(), timeout=120) == "done"
    ray_tpu.kill(a)
    # Task events flush from live workers on a 5s cadence (a killed actor's
    # buffer dies with it): a plain task's worker stays alive to flush.
    assert ray_tpu.get(traced_task.remote(), timeout=120) == 1

    # Node + actor transitions and task events flush on their own timers.
    deadline = time.time() + 30
    while time.time() < deadline:
        nodes = state.list_export_events(exp, source_type="node")
        actors = state.list_export_events(exp, source_type="actor")
        tasks = state.list_export_events(exp, source_type="task")
        if nodes and actors and any(
            e["event_data"].get("name") == "traced_task" for e in tasks
        ):
            break
        time.sleep(0.5)
    assert nodes, "no node export events"
    assert any(e["event_data"].get("node", {}).get("is_head") for e in nodes)
    states = {e["event_data"].get("actor", {}).get("state") for e in actors}
    assert "ALIVE" in states and "DEAD" in states, states
    # Records carry the export schema and survive raw JSONL parsing.
    for rec in (nodes + actors)[:5]:
        assert rec["source_type"] in ("node", "actor")
        assert rec["event_id"] and rec["timestamp"] > 0
    raw = open(os.path.join(exp, "export_actor.jsonl")).read().splitlines()
    assert all(json.loads(line) for line in raw)
    # The combined aggregator view is time-ordered across source types.
    combined = state.list_export_events(exp)
    times = [r["timestamp"] for r in combined]
    assert times == sorted(times)
    assert {r["source_type"] for r in combined} >= {"node", "actor", "task"}
