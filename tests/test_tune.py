"""ray_tpu.tune tests.

Shape parity with the reference suite (python/ray/tune/tests/): variant generation,
Tuner.fit over function trainables, schedulers (ASHA early stopping, PBT
exploit/explore), checkpointing, stop conditions, and Tuner(trainer) integration.
"""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import RunConfig
from ray_tpu.tune.search import BasicVariantGenerator


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_variant_generation_grid_and_samples():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0, 1),
        "n": tune.choice([1, 2, 3]),
        "nested": {"depth": tune.grid_search([2, 4])},
    }
    gen = BasicVariantGenerator(space, num_samples=3, seed=0)
    assert gen.total_variants == 2 * 2 * 3
    cfgs = [gen.suggest(f"t{i}") for i in range(gen.total_variants)]
    assert all(c["lr"] in (0.1, 0.01) for c in cfgs)
    assert all(c["nested"]["depth"] in (2, 4) for c in cfgs)
    assert all(0 <= c["wd"] <= 1 for c in cfgs)
    assert gen.suggest("extra") is None


def test_tuner_basic(tmp_path):
    def trainable(config):
        tune.report({"score": config["x"] * 2})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 5, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 3
    best = results.get_best_result()
    assert best.metrics["score"] == 10
    assert best.config["x"] == 5


def test_tuner_multi_iteration_and_stop_dict(tmp_path):
    def trainable(config):
        for i in range(100):
            tune.report({"loss": 1.0 / (i + 1)})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(storage_path=str(tmp_path), stop={"training_iteration": 7}),
    )
    results = tuner.fit()
    assert results[0].metrics["training_iteration"] >= 7
    assert results[0].metrics["training_iteration"] < 100


def test_tuner_errors_surface(tmp_path):
    def bad(config):
        if config["x"] == 1:
            raise ValueError("sad trial")
        tune.report({"score": 1})

    results = tune.Tuner(
        bad,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert results.num_errors == 1
    assert "sad trial" in str(results.errors[0])


def test_checkpoint_roundtrip(tmp_path):
    from ray_tpu.train import Checkpoint

    def trainable(config):
        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "it.txt")) as f:
                start = int(f.read())
        for i in range(start, 3):
            d = os.path.join(tune.get_trial_dir(), f"tmp_{i}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "it.txt"), "w") as f:
                f.write(str(i + 1))
            tune.report({"it": i + 1}, checkpoint=Checkpoint.from_directory(d))

    results = tune.Tuner(
        trainable,
        param_space={},
        tune_config=tune.TuneConfig(metric="it", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    best = results.get_best_result()
    assert best.checkpoint is not None
    with open(os.path.join(best.checkpoint.path, "it.txt")) as f:
        assert f.read() == "3"


def test_asha_stops_bad_trials(tmp_path):
    def trainable(config):
        for i in range(20):
            tune.report({"acc": config["q"] + i * 0.001})

    results = tune.Tuner(
        trainable,
        param_space={"q": tune.grid_search([0.0, 0.1, 0.2, 0.9])},
        tune_config=tune.TuneConfig(
            metric="acc",
            mode="max",
            scheduler=tune.ASHAScheduler(grace_period=2, reduction_factor=2, max_t=20),
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    iters = {r.config["q"]: r.metrics.get("training_iteration", 0) for r in results}
    assert iters[0.9] >= max(iters.values()) - 1  # best trial ran longest (or tied)
    assert results.get_best_result().config["q"] == 0.9


def test_pbt_exploits_and_perturbs(tmp_path):
    from ray_tpu.train import Checkpoint

    def trainable(config):
        import time

        # score grows at rate lr; checkpoint carries accumulated score. The sleep
        # paces the trial so controller polls interleave with results (PBT acts on
        # a live population, not on an already-finished one).
        score = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.txt")) as f:
                score = float(f.read())
        for i in range(30):
            time.sleep(0.05)
            score += config["lr"]
            d = os.path.join(tune.get_trial_dir(), f"c{i}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "s.txt"), "w") as f:
                f.write(str(score))
            tune.report({"score": score}, checkpoint=Checkpoint.from_directory(d))

    results = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.001, 1.0])},
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            scheduler=tune.PopulationBasedTraining(
                perturbation_interval=5,
                hyperparam_mutations={"lr": tune.uniform(0.5, 2.0)},
                quantile_fraction=0.5,
                seed=0,
            ),
        ),
        run_config=RunConfig(storage_path=str(tmp_path), stop={"training_iteration": 25}),
    ).fit()
    # The weak trial must have been exploited: its final score reflects the strong
    # trial's checkpoint (score >> 30 * 0.001).
    scores = sorted(r.metrics["score"] for r in results)
    assert scores[0] > 1.0


def test_tuner_over_trainer(tmp_path):
    import ray_tpu.train as train

    def loop(config):
        train.report({"final": config["k"] * 10})

    trainer = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="inner"),
    )
    results = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {"k": tune.grid_search([1, 4])}},
        tune_config=tune.TuneConfig(metric="final", mode="max", max_concurrent_trials=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert results.get_best_result().metrics["final"] == 40


def test_median_stopping(tmp_path):
    def trainable(config):
        for i in range(15):
            tune.report({"m": config["v"]})

    results = tune.Tuner(
        trainable,
        param_space={"v": tune.grid_search([1.0, 1.0, 0.0])},
        tune_config=tune.TuneConfig(
            metric="m",
            mode="max",
            scheduler=tune.MedianStoppingRule(grace_period=3, min_samples_required=2),
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert len(results) == 3


def test_custom_searcher_is_used(tmp_path):
    class FixedSearcher(tune.Searcher):
        def __init__(self):
            self.completed = []

        def suggest(self, trial_id):
            return {"x": 7}

        def on_trial_complete(self, trial_id, result, error=False):
            self.completed.append(trial_id)

    searcher = FixedSearcher()

    def trainable(config):
        tune.report({"score": config["x"]})

    results = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1)},  # must be ignored: searcher wins
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=3, search_alg=searcher
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert len(results) == 3
    assert all(r.metrics["score"] == 7 for r in results)
    assert len(searcher.completed) == 3


def test_tuner_over_trainer_flat_param_space(tmp_path):
    import ray_tpu.train as train

    def loop(config):
        train.report({"final": config["k"] * 10 + config.get("base", 0)})

    trainer = train.DataParallelTrainer(
        loop,
        train_loop_config={"base": 1},
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="inner2"),
    )
    results = tune.Tuner(
        trainer,
        param_space={"k": tune.grid_search([2, 5])},  # flat: merged over base config
        tune_config=tune.TuneConfig(metric="final", mode="max", max_concurrent_trials=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert results.get_best_result().metrics["final"] == 51


def test_tpe_search_converges_better_than_random(ray_start_regular, tmp_path):
    """TPE concentrates samples near the optimum once results feed back
    (reference parity for the search-algorithm integrations; TPESearch is the
    dependency-free native equivalent of hyperopt/optuna TPE)."""
    from ray_tpu.tune.search import TPESearch

    def objective(config):
        x = config["x"]
        tune.report({"score": -(x - 3.0) ** 2})

    space = {"x": tune.uniform(-10.0, 10.0)}
    searcher = TPESearch(space, metric="score", mode="max", n_initial=4, seed=0)
    tuner = tune.Tuner(
        objective,
        param_space=space,
        tune_config=tune.TuneConfig(
            num_samples=16, metric="score", mode="max", search_alg=searcher,
            max_concurrent_trials=2,
        ),
        run_config=tune.RunConfig(name="tpe", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    xs = [r.config["x"] for r in grid]
    assert len(xs) == 16
    # Later (adaptive) suggestions cluster near x=3 much tighter than the
    # initial random phase.
    late = xs[8:]
    assert sum(1 for x in late if abs(x - 3.0) < 2.5) >= len(late) // 2, xs
    best = grid.get_best_result(metric="score", mode="max")
    assert abs(best.config["x"] - 3.0) < 2.0


def test_tpe_handles_choice_and_randint(ray_start_regular, tmp_path):
    from ray_tpu.tune.search import TPESearch

    def objective(config):
        score = (config["opt"] == "good") * 10 + (5 - abs(config["k"] - 5))
        tune.report({"score": float(score)})

    space = {"opt": tune.choice(["good", "bad", "ugly"]),
             "k": tune.randint(0, 10)}
    searcher = TPESearch(space, metric="score", mode="max", n_initial=3, seed=1)
    grid = tune.Tuner(
        objective,
        param_space=space,
        tune_config=tune.TuneConfig(num_samples=10, metric="score", mode="max",
                                    search_alg=searcher,
                                    max_concurrent_trials=2),
        run_config=tune.RunConfig(name="tpe2", storage_path=str(tmp_path)),
    ).fit()
    best = grid.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] >= 10.0
