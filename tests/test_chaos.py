"""Chaos harness: random worker kills against long-running workloads.

Shape parity: reference `python/ray/tests/chaos/` — a resource killer runs
beside a real workload, SIGKILLing worker processes on a cadence, and the
workload must still complete CORRECTLY (retries + lineage reconstruction +
actor restarts absorbing the failures). This is the systematic concurrency/
failure stressor beyond targeted fault-injection tests.
"""

import os
import random
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def chaos_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S", "2")
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "RAY_TPU_BORROW_AUDIT_INTERVAL_S": "2",
        },
    )
    yield
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S")
    CONFIG._reset()


class _WorkerKiller(threading.Thread):
    """SIGKILL a random live task-worker pid every `period_s` (reference:
    chaos killer actors). Runs in the driver for determinism of teardown."""

    def __init__(self, get_pids, period_s: float = 1.5, seed: int = 0):
        super().__init__(daemon=True)
        self._get_pids = get_pids
        self._period = period_s
        self._rng = random.Random(seed)
        self._halt = threading.Event()
        self.kills = 0

    def run(self):
        while not self._halt.wait(self._period):
            pids = [p for p in self._get_pids() if p and p != os.getpid()]
            if not pids:
                continue
            victim = self._rng.choice(pids)
            try:
                os.kill(victim, signal.SIGKILL)
                self.kills += 1
            except ProcessLookupError:
                pass

    def stop(self):
        self._halt.set()


def test_tasks_survive_random_worker_kills(chaos_cluster):
    """200 retriable tasks complete with correct results while a killer
    SIGKILLs a random worker every 1.5s."""
    seen_pids = set()
    pid_lock = threading.Lock()

    @ray_tpu.remote(max_retries=10)
    def work(i):
        time.sleep(0.1)
        return i * i, os.getpid()

    def snapshot_pids():
        # The killer thread must read under the lock: an unlocked set copy
        # racing update() raises mid-iteration and silently kills the killer.
        with pid_lock:
            return list(seen_pids)

    killer = _WorkerKiller(snapshot_pids, period_s=1.5)
    killer.start()
    try:
        results = []
        for wave in range(10):
            refs = [work.remote(wave * 20 + i) for i in range(20)]
            out = ray_tpu.get(refs, timeout=300)
            with pid_lock:
                seen_pids.update(p for _v, p in out)
            results.extend(v for v, _p in out)
        expected = [i * i for i in range(200)]
        assert sorted(results) == sorted(expected)
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2, "chaos never actually killed anyone"


def test_restartable_actor_pipeline_survives_kills(chaos_cluster):
    """A restartable stateful actor keeps serving (reconstructing its state
    from constructor args) while being SIGKILLed mid-stream; owned objects
    referenced across the kills stay readable via lineage/borrow machinery."""

    @ray_tpu.remote(max_restarts=20, max_retries=10)
    class Accumulator:
        def __init__(self):
            self.pid = os.getpid()

        def process(self, arr):
            time.sleep(0.15)  # long enough that kills land mid-workload
            return float(np.asarray(arr).sum()), os.getpid()

    acc = Accumulator.remote()
    data_refs = [ray_tpu.put(np.full(50_000, i, np.float64)) for i in range(8)]
    first_sum, first_pid = ray_tpu.get(
        acc.process.remote(data_refs[0]), timeout=120
    )
    assert first_sum == 0.0
    pids = {first_pid}
    latest = [first_pid]  # killer targets the LIVE incarnation, not ghosts
    killer = _WorkerKiller(lambda: [latest[0]], period_s=2.0, seed=7)
    killer.start()
    def call_with_retry(make_ref, attempts=10):
        # Chaos-workload idiom: a kill can land mid-call; the caller resubmits
        # against the restarted actor (reference chaos tests do the same).
        last = None
        for _ in range(attempts):
            try:
                return ray_tpu.get(make_ref(), timeout=120)
            except Exception as e:  # noqa: BLE001 - actor died mid-call
                last = e
                time.sleep(1.0)
        raise AssertionError(f"call never succeeded through chaos: {last}")

    try:
        totals = []
        for round_i in range(6):
            for ref in data_refs:
                s, pid = call_with_retry(lambda r=ref: acc.process.remote(r))
                totals.append(s)
                pids.add(pid)
                latest[0] = pid
        expected = [i * 50_000.0 for i in range(8)] * 6
        assert totals == expected
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2
    assert len(pids) >= 2, "actor was never actually restarted"
