"""Chaos harness: random worker kills against long-running workloads.

Shape parity: reference `python/ray/tests/chaos/` — a resource killer runs
beside a real workload, SIGKILLing worker processes on a cadence, and the
workload must still complete CORRECTLY (retries + lineage reconstruction +
actor restarts absorbing the failures). This is the systematic concurrency/
failure stressor beyond targeted fault-injection tests.
"""

import os
import random
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def chaos_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S", "2")
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "RAY_TPU_BORROW_AUDIT_INTERVAL_S": "2",
        },
    )
    yield
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S")
    CONFIG._reset()


class _WorkerKiller(threading.Thread):
    """SIGKILL a random live task-worker pid every `period_s` (reference:
    chaos killer actors). Runs in the driver for determinism of teardown."""

    def __init__(self, get_pids, period_s: float = 1.5, seed: int = 0):
        super().__init__(daemon=True)
        self._get_pids = get_pids
        self._period = period_s
        self._rng = random.Random(seed)
        self._halt = threading.Event()
        self.kills = 0

    def run(self):
        while not self._halt.wait(self._period):
            pids = [p for p in self._get_pids() if p and p != os.getpid()]
            if not pids:
                continue
            victim = self._rng.choice(pids)
            try:
                os.kill(victim, signal.SIGKILL)
                self.kills += 1
            except ProcessLookupError:
                pass

    def stop(self):
        self._halt.set()


def test_tasks_survive_random_worker_kills(chaos_cluster):
    """200 retriable tasks complete with correct results while a killer
    SIGKILLs a random worker every 1.5s."""
    seen_pids = set()
    pid_lock = threading.Lock()

    @ray_tpu.remote(max_retries=10)
    def work(i):
        time.sleep(0.1)
        return i * i, os.getpid()

    def snapshot_pids():
        # The killer thread must read under the lock: an unlocked set copy
        # racing update() raises mid-iteration and silently kills the killer.
        with pid_lock:
            return list(seen_pids)

    killer = _WorkerKiller(snapshot_pids, period_s=1.5)
    killer.start()
    try:
        results = []
        for wave in range(10):
            refs = [work.remote(wave * 20 + i) for i in range(20)]
            out = ray_tpu.get(refs, timeout=300)
            with pid_lock:
                seen_pids.update(p for _v, p in out)
            results.extend(v for v, _p in out)
        expected = [i * i for i in range(200)]
        assert sorted(results) == sorted(expected)
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2, "chaos never actually killed anyone"


def test_restartable_actor_pipeline_survives_kills(chaos_cluster):
    """A restartable stateful actor keeps serving (reconstructing its state
    from constructor args) while being SIGKILLed mid-stream; owned objects
    referenced across the kills stay readable via lineage/borrow machinery."""

    @ray_tpu.remote(max_restarts=20, max_retries=10)
    class Accumulator:
        def __init__(self):
            self.pid = os.getpid()

        def process(self, arr):
            time.sleep(0.15)  # long enough that kills land mid-workload
            return float(np.asarray(arr).sum()), os.getpid()

    acc = Accumulator.remote()
    data_refs = [ray_tpu.put(np.full(50_000, i, np.float64)) for i in range(8)]
    first_sum, first_pid = ray_tpu.get(
        acc.process.remote(data_refs[0]), timeout=120
    )
    assert first_sum == 0.0
    pids = {first_pid}
    latest = [first_pid]  # killer targets the LIVE incarnation, not ghosts
    killer = _WorkerKiller(lambda: [latest[0]], period_s=2.0, seed=7)
    killer.start()
    def call_with_retry(make_ref, attempts=10):
        # Chaos-workload idiom: a kill can land mid-call; the caller resubmits
        # against the restarted actor (reference chaos tests do the same).
        last = None
        for _ in range(attempts):
            try:
                return ray_tpu.get(make_ref(), timeout=120)
            except Exception as e:  # noqa: BLE001 - actor died mid-call
                last = e
                time.sleep(1.0)
        raise AssertionError(f"call never succeeded through chaos: {last}")

    try:
        totals = []
        for round_i in range(6):
            for ref in data_refs:
                s, pid = call_with_retry(lambda r=ref: acc.process.remote(r))
                totals.append(s)
                pids.add(pid)
                latest[0] = pid
        expected = [i * 50_000.0 for i in range(8)] * 6
        assert totals == expected
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2
    assert len(pids) >= 2, "actor was never actually restarted"


# ---------------------------------------------------------------- node chaos

_NODE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


def test_tasks_survive_node_kill():
    """SIGKILL a whole worker NODE (raylet + its workers) mid-wave: retriable
    tasks that were running there re-execute elsewhere and every result is
    still correct (reference: RayletKiller chaos,
    python/ray/_private/test_utils.py:1479)."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    n2 = cluster.add_node(num_cpus=2, env_vars=_NODE_ENV)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote(max_retries=10)
        def work(i):
            time.sleep(0.3)
            return i * i, ray_tpu.get_runtime_context().get_node_id()

        n2_id = next(n["node_id"] for n in ray_tpu.nodes()
                     if n["node_id"].hex() == n2.node_id_hex)
        # SOFT affinity to node 2: tasks start there, and their retries may
        # reschedule anywhere once the node is gone.
        on_n2 = work.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(n2_id, soft=True)
        )

        # Warm a first wave and require node 2 to actually execute work —
        # otherwise killing it proves nothing.
        first = ray_tpu.get([on_n2.remote(i) for i in range(4)], timeout=300)
        nodes_seen = {n.hex() for _v, n in first}
        assert n2.node_id_hex in nodes_seen, f"work never ran on node 2: {nodes_seen}"

        # Launch a big wave biased onto node 2, then kill the node while much
        # of it is in flight.
        refs = [(on_n2 if i % 2 else work).remote(i) for i in range(40)]
        time.sleep(0.8)  # several tasks are mid-sleep on n2 right now
        cluster.kill_node(n2)
        out = ray_tpu.get(refs, timeout=300)
        assert sorted(v for v, _n in out) == sorted(i * i for i in range(40))
        # Everything after the kill ran on the surviving node(s).
        alive = {n["node_id"].hex() for n in ray_tpu.nodes() if n["alive"]}
        assert n2.node_id_hex not in alive
    finally:
        cluster.shutdown()


def test_elastic_trainer_survives_node_kill_and_reexpands(tmp_path):
    """An elastic JaxTrainer run loses a NODE to SIGKILL mid-attempt, resumes
    at N-1, then re-expands to full size IN THE SAME RUN once capacity
    returns (reference: chaos suite + elastic scaling policy)."""
    import os
    import threading

    from ray_tpu import train
    from ray_tpu.train import (
        FailureConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                     env_vars=_NODE_ENV)
    n2 = cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                          env_vars=_NODE_ENV)
    cluster.connect()
    cluster.wait_for_nodes()
    marker_dir = str(tmp_path)
    try:
        def loop(config):
            import os as _os

            ctx = train.get_context()
            world = ctx.get_world_size()
            rank = ctx.get_world_rank()
            mk = config["markers"]
            open(_os.path.join(mk, f"started_{world}_{rank}"), "w").write("x")
            if world == 2 and not _os.path.exists(
                _os.path.join(mk, "expanded")
            ):
                # First full-size attempt: park until the driver SIGKILLs a
                # node out from under one of us.
                time.sleep(600)
            if world == 1:
                # Shrunk attempt: wait for the driver to restore capacity,
                # then fail ONCE so the elastic policy re-evaluates and
                # re-expands the SAME run.
                deadline = time.monotonic() + 240
                while not _os.path.exists(_os.path.join(mk, "capacity_back")):
                    if time.monotonic() > deadline:
                        break
                    time.sleep(0.5)
                open(_os.path.join(mk, "expanded"), "w").write("x")
                raise RuntimeError("chaos: trigger elastic re-expansion")
            train.report({"world": world, "rank": rank})

        trainer = JaxTrainer(
            loop,
            train_loop_config={"markers": marker_dir},
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, use_tpu=False,
                resources_per_worker={"trainslot": 1.0},
            ),
            run_config=RunConfig(
                name="node-chaos", storage_path=str(tmp_path / "storage"),
                failure_config=FailureConfig(max_failures=4),
            ),
        )

        result_box = {}

        def fit():
            result_box["result"] = trainer.fit()

        t = threading.Thread(target=fit)
        t.start()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if len([f for f in os.listdir(marker_dir)
                    if f.startswith("started_2_")]) >= 2:
                break
            time.sleep(0.2)
        assert len([f for f in os.listdir(marker_dir)
                    if f.startswith("started_2_")]) >= 2

        cluster.kill_node(n2)  # SIGKILL raylet + its workers, mid-attempt

        # The run shrinks to world 1; then we restore capacity.
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if any(f.startswith("started_1_") for f in os.listdir(marker_dir)):
                break
            time.sleep(0.5)
        assert any(f.startswith("started_1_") for f in os.listdir(marker_dir)), (
            "run never resumed at N-1 after the node kill"
        )
        cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                         env_vars=_NODE_ENV)
        cluster.wait_for_nodes()
        open(os.path.join(marker_dir, "capacity_back"), "w").write("x")

        t.join(timeout=420)
        assert not t.is_alive(), "trainer did not finish after node chaos"
        result = result_box["result"]
        assert result.error is None, result.error
        # The final attempt re-expanded to the full world size.
        assert result.metrics["world"] == 2
    finally:
        cluster.shutdown()


# ------------------------------------------------------- control-plane chaos
#
# Reference shape: python/ray/tests/chaos/ also kills the HEAD services under
# live workloads. The contract here (docs/fault_tolerance.md): the GCS and the
# serve/train controllers are restartable without dropping live work — data
# plane traffic rides cached handles and direct connections, control state
# recovers from the persistent store / GCS KV.


def test_serve_traffic_rides_through_gcs_kill():
    """SIGKILL the GCS under a deployed serve app with live HTTP traffic:
    zero replica processes die, traffic keeps flowing during the outage
    (routers and proxies ride cached handles + direct connections), and after
    the GCS restarts responses are identical to pre-kill responses for the
    same prompts."""
    import json
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    try:
        cluster.connect()

        @serve.deployment(num_replicas=2)
        class Echo:
            def pid(self):
                return os.getpid()

            def __call__(self, request):
                p = request.query_params.get("p", "")
                return {"out": f"{p}::{len(p)}"}

        serve.run(Echo.bind(), name="gcs-chaos", route_prefix="/")
        port = serve.get_proxy_port()

        def ask(p, timeout=10):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/?p={p}", timeout=timeout
            ) as r:
                return json.loads(r.read())["out"]

        prompts = [f"prompt-{i}" for i in range(4)]
        baseline = {p: ask(p) for p in prompts}
        pid_handle = serve.DeploymentHandle("gcs-chaos", "Echo", "pid")
        pids_before = sorted(pid_handle.broadcast())
        assert len(pids_before) == 2

        ok_during: list = []
        errors: list = []
        halt = threading.Event()

        def traffic():
            i = 0
            while not halt.is_set():
                p = prompts[i % len(prompts)]
                i += 1
                try:
                    ok_during.append((p, ask(p, timeout=5)))
                except Exception as e:  # noqa: BLE001 - tallied, asserted below
                    errors.append(repr(e))
                time.sleep(0.05)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(1.0)  # warm: routes cached, direct connections established
        n_before_kill = len(ok_during)
        cluster.head.kill_gcs()
        time.sleep(3.0)  # the GCS is DOWN for this whole window
        n_during_kill = len(ok_during)
        cluster.head.restart_gcs()
        # Raylets re-register; the driver's gcs_call reconnects with backoff.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if [n for n in ray_tpu.nodes() if n["alive"]]:
                    break
            except Exception:
                time.sleep(0.5)
        time.sleep(1.0)
        halt.set()
        t.join(timeout=30)

        # Traffic flowed WHILE the GCS was down, not just after recovery.
        assert n_during_kill - n_before_kill >= 10, (
            f"only {n_during_kill - n_before_kill} requests succeeded during "
            f"the outage ({len(errors)} errors: {errors[:3]})"
        )
        # Every response that succeeded — before, during, after — is correct.
        for p, out in ok_during:
            assert out == baseline[p], f"divergent response for {p!r}"
        # Post-recovery responses are token-identical to pre-kill responses.
        post = {p: ask(p, timeout=30) for p in prompts}
        assert post == baseline
        # Zero replica processes died across the GCS restart.
        pids_after = sorted(pid_handle.broadcast())
        assert pids_after == pids_before
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_serve_controller_sigkill_recovers_and_adopts(chaos_cluster):
    """SIGKILL the serve controller under a deployed app: calls keep serving
    off cached routing tables, a new incarnation recovers the app table from
    GCS KV, RE-ADOPTS the live replicas (same pids, same count — no
    double-create), and a replayed deploy of the same app is a no-op."""
    from ray_tpu import serve
    from ray_tpu.serve._common import CONTROLLER_NAME, SERVE_NAMESPACE

    @serve.deployment(num_replicas=2)
    class Stable:
        def pid(self):
            return os.getpid()

        def __call__(self, x):
            return x * 3

    handle = serve.run(Stable.bind(), name="ctrl-chaos", route_prefix=None)
    assert handle.remote(7).result(timeout_s=60) == 21
    pid_handle = serve.DeploymentHandle("ctrl-chaos", "Stable", "pid")
    pids_before = sorted(pid_handle.broadcast())
    assert len(pids_before) == 2

    controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    ctrl_pid = ray_tpu.get(controller.health.remote(), timeout=30)["pid"]
    os.kill(ctrl_pid, signal.SIGKILL)

    # Live replicas keep serving through the controller outage: the router's
    # cached table needs no controller round-trip.
    assert handle.remote(9).result(timeout_s=60) == 27

    # A new incarnation restarts (max_restarts=-1) and answers from a new pid.
    deadline = time.monotonic() + 90
    new_pid = None
    while time.monotonic() < deadline:
        try:
            h = ray_tpu.get(controller.health.remote(), timeout=10)
            if h["pid"] != ctrl_pid:
                new_pid = h["pid"]
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert new_pid is not None, "controller never restarted"

    # The app table recovered from GCS KV...
    status = serve.status()
    assert "ctrl-chaos" in status
    # ...and the live replicas were ADOPTED, not restarted (same pids) and not
    # double-created (same count).
    info = ray_tpu.get(
        controller.get_replicas.remote("ctrl-chaos", "Stable"), timeout=60
    )
    assert len(info["replicas"]) == 2
    assert info["exists"]
    pids_after = sorted(pid_handle.broadcast())
    assert pids_after == pids_before, "recovery restarted live replicas"

    # Replayed deploy_app of the identical app (the checkpoint-idempotency
    # contract, mirroring the GCS bundle-reservation replay guard): replicas
    # stay in place.
    serve.run(Stable.bind(), name="ctrl-chaos", route_prefix=None)
    assert sorted(pid_handle.broadcast()) == pids_before
    assert handle.remote(5).result(timeout_s=60) == 15
    serve.shutdown()


def test_train_run_rides_through_gcs_kill(tmp_path):
    """SIGKILL the GCS mid-train: workers keep stepping on their raylets, the
    (detached) controller's monitor loop tolerates the control-plane outage
    instead of declaring workers dead, and the run completes with a result
    bitwise-equal to an undisturbed run."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    marker = str(tmp_path / "mid_run")
    try:
        cluster.connect()

        def loop(config):
            import os as _os

            from ray_tpu import train as _train

            total = 0.0
            for step in range(30):
                total += float((step * 7 + 3) % 11) * 0.5
                if step == 3:
                    open(config["marker"], "w").write("x")
                time.sleep(0.25)
                _train.report({"step": step, "total": total})

        result_box = {}

        def fit():
            result_box["result"] = DataParallelTrainer(
                loop,
                train_loop_config={"marker": marker},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(
                    name="gcs-chaos-train", storage_path=str(tmp_path / "storage")
                ),
            ).fit()

        t = threading.Thread(target=fit, daemon=True)
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not os.path.exists(marker):
            time.sleep(0.1)
        assert os.path.exists(marker), "run never reached mid-flight"

        cluster.head.kill_gcs()
        time.sleep(2.0)  # several training steps happen with the GCS DOWN
        cluster.head.restart_gcs()

        t.join(timeout=240)
        assert not t.is_alive(), "trainer did not finish after GCS chaos"
        result = result_box["result"]
        assert result.error is None, result.error
        expected = 0.0
        for step in range(30):
            expected += float((step * 7 + 3) % 11) * 0.5
        # Bitwise-equal to an undisturbed run: same float accumulation order.
        assert result.metrics["total"] == expected
        assert result.metrics["step"] == 29
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_detached_train_controller_sigkill_resumes_from_checkpoint(
    chaos_cluster, tmp_path
):
    """SIGKILL the detached train controller mid-run: a new incarnation
    detects its run-in-progress marker, recovers COMMITTED sharded
    checkpoints from storage, and resumes the run from the newest one instead
    of restarting from scratch."""
    import numpy as np

    import ray_tpu.checkpoint as ckpt
    from ray_tpu.train import (
        DataParallelTrainer,
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )

    storage = str(tmp_path / "storage")
    attempts = str(tmp_path / "attempts")
    os.makedirs(attempts, exist_ok=True)

    def loop(config):
        import os as _os

        import numpy as _np

        from ray_tpu import train as _train

        start = 0
        prev = _train.get_checkpoint()
        if prev is not None:
            start = int(prev.to_pytree()["step"]) + 1
        open(_os.path.join(config["attempts"], f"start_{start}"), "w").write("x")
        import jax.numpy as _jnp

        for step in range(start, 6):
            _train.report(
                {"step": step, "resumed_from": start},
                checkpoint=ckpt.ShardedState(
                    {"step": _np.int64(step), "w": _jnp.full((4,), float(step))}
                ),
            )
            if step == 3 and start == 0:
                # First attempt parks here until the controller is killed.
                time.sleep(600)

    result_box = {}

    def fit():
        result_box["result"] = DataParallelTrainer(
            loop,
            train_loop_config={"attempts": attempts},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="ctrl-kill-train", storage_path=storage,
                failure_config=FailureConfig(max_failures=2),
            ),
        ).fit()

    t = threading.Thread(target=fit, daemon=True)
    t.start()

    # Wait for the first attempt to reach step 3 with checkpoint_3 COMMITTED.
    manifest = os.path.join(storage, "ctrl-kill-train", "checkpoint_000003",
                            "MANIFEST.json")
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline and not os.path.exists(manifest):
        time.sleep(0.2)
    assert os.path.exists(manifest), "checkpoint_3 never committed"

    runner = ray_tpu.get_actor("TRAIN_CONTROLLER:ctrl-kill-train",
                               namespace="_train")
    ctrl_pid = ray_tpu.get(runner.status.remote(), timeout=30)["pid"]
    os.kill(ctrl_pid, signal.SIGKILL)

    t.join(timeout=300)
    assert not t.is_alive(), "driver never got a result after controller kill"
    result = result_box["result"]
    assert result.error is None, result.error
    # The resumed attempt started from the latest committed checkpoint, not 0.
    assert result.metrics["resumed_from"] >= 1
    assert result.metrics["step"] == 5
    starts = sorted(os.listdir(attempts))
    assert "start_0" in starts
    assert any(s != "start_0" for s in starts), "run never resumed"
    tree = result.checkpoint.to_pytree()
    np.testing.assert_array_equal(np.asarray(tree["w"]), np.full((4,), 5.0))


# ------------------------------------------------- replicated-GCS chaos
#
# Quorum-HA contract (docs/fault_tolerance.md): with gcs_replicas=3 the GCS
# primary majority-acks every durable mutation to follower candidates and
# holds a time-bounded lease; SIGKILLing the PRIMARY promotes the most
# caught-up follower within ~2x the lease window, every majority-acked
# record survives, clients fail over transparently inside gcs_call's
# backoff/deadline machinery, and a deposed primary's stragglers are
# epoch-fenced. gcs_replicas=1 (the default) is byte-for-byte the classic
# single-process GCS.


def _wait_new_gcs_primary(head, old_primary_idx, old_epoch, timeout=25.0):
    """(index, status, seconds-to-promotion) of the follower that took over."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        for i in range(len(head.gcs_procs)):
            if i == old_primary_idx:
                continue
            st = head.gcs_candidate_status(i)
            if st and st.get("role") == "primary" and st["epoch"] > old_epoch:
                return i, st, time.monotonic() - t0
        time.sleep(0.1)
    raise AssertionError("no follower promoted itself in time")


def test_serve_traffic_rides_through_gcs_primary_kill(monkeypatch):
    """SIGKILL the GCS *primary* (of 3 candidates) under a deployed serve app
    with live HTTP traffic: a follower promotes within ~2x the lease window,
    every majority-acked KV/actor/serve-target record survives (verified by a
    known key set written immediately before the kill), HTTP responses stay
    token-identical, a fenced old-epoch write is provably rejected, and the
    failover is observable through the control-plane stats report path."""
    import asyncio
    import json
    import urllib.request

    from ray_tpu import serve
    from ray_tpu._private import rpc as rpclib
    from ray_tpu._private.config import CONFIG
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_GCS_REPLICAS", "3")
    monkeypatch.setenv("RAY_TPU_GCS_LEASE_S", "1.5")
    CONFIG._reset()
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    try:
        cluster.connect()
        w = ray_tpu.global_worker()
        assert len(cluster.head.gcs_procs) == 3

        @serve.deployment(num_replicas=2)
        class Echo:
            def pid(self):
                return os.getpid()

            def __call__(self, request):
                p = request.query_params.get("p", "")
                return {"out": f"{p}::{len(p)}"}

        serve.run(Echo.bind(), name="gcs-ha-chaos", route_prefix="/")
        port = serve.get_proxy_port()

        def ask(p, timeout=10):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/?p={p}", timeout=timeout
            ) as r:
                return json.loads(r.read())["out"]

        prompts = [f"prompt-{i}" for i in range(4)]
        baseline = {p: ask(p) for p in prompts}
        pid_handle = serve.DeploymentHandle("gcs-ha-chaos", "Echo", "pid")
        pids_before = sorted(pid_handle.broadcast())
        assert len(pids_before) == 2

        @ray_tpu.remote(name="ha-counter")
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        counter = Counter.remote()
        assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1

        ok_during: list = []
        errors: list = []
        halt = threading.Event()

        def traffic():
            i = 0
            while not halt.is_set():
                p = prompts[i % len(prompts)]
                i += 1
                try:
                    ok_during.append((p, ask(p, timeout=5)))
                except Exception as e:  # noqa: BLE001 - tallied below
                    errors.append(repr(e))
                time.sleep(0.05)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(1.0)  # warm: routes cached, direct connections live

        # The known key set, written (and majority-acked) RIGHT before the
        # kill: every one of these must survive the primary's death.
        for i in range(30):
            w.gcs_kv_put("ha", f"k{i}".encode(), str(i).encode())

        primary_idx = cluster.head.gcs_primary_index()
        old_st = cluster.head.gcs_candidate_status(primary_idx)
        n_before_kill = len(ok_during)
        cluster.head.kill_gcs_candidate(primary_idx)  # SIGKILL the primary

        new_idx, new_st, promote_s = _wait_new_gcs_primary(
            cluster.head, primary_idx, old_st["epoch"])
        lease_s = CONFIG.gcs_lease_s
        # ~2x the lease window: one window of silence detection + the
        # election round (+ scheduler slack for the subprocess probes).
        assert promote_s <= 2.0 * lease_s + 2.0, (
            f"promotion took {promote_s:.2f}s with lease {lease_s}s")

        # Every majority-acked record survives, read through the client's
        # transparent failover path.
        for i in range(30):
            assert w.gcs_kv_get("ha", f"k{i}".encode()) == str(i).encode(), (
                f"majority-acked key k{i} lost in failover")
        # Actor table survived (replicated spec + raylet re-report)...
        h = ray_tpu.get_actor("ha-counter")
        assert ray_tpu.get(h.incr.remote(), timeout=120) == 2
        # ...and so did the serve controller's target state.
        assert "gcs-ha-chaos" in serve.status()

        # A fenced old-primary straggler is provably rejected: an append
        # stamped with the dead primary's epoch bounces off the quorum.
        async def fenced_write():
            conn = await rpclib.connect(
                *cluster.head.gcs_addrs[new_idx], name="fence-probe")
            try:
                return await conn.call(
                    "repl_append", old_st["epoch"],
                    [(new_st["seq"] + 1,
                      ("put", "kv", ("ha", b"fenced"), b"x"))],
                    primary_idx,
                )
            finally:
                await conn.close()

        reply = asyncio.run(fenced_write())
        assert reply["ok"] is False and reply["promised"] > old_st["epoch"]
        assert w.gcs_kv_get("ha", b"fenced") is None

        time.sleep(1.0)
        halt.set()
        t.join(timeout=30)

        # Traffic kept flowing across the failover window, token-identical.
        assert len(ok_during) - n_before_kill >= 5, (
            f"only {len(ok_during) - n_before_kill} requests succeeded "
            f"through the failover ({len(errors)} errors: {errors[:3]})"
        )
        for p, out in ok_during:
            assert out == baseline[p], f"divergent response for {p!r}"
        post = {p: ask(p, timeout=30) for p in prompts}
        assert post == baseline
        assert sorted(pid_handle.broadcast()) == pids_before, (
            "failover restarted live serve replicas")

        # Observability rides the report path ONLY: calling it surfaces the
        # store/replication series (PR 9 leaksan deadlock lesson).
        from ray_tpu.util import metrics as util_metrics
        from ray_tpu.util.state import control_plane_stats

        stats = control_plane_stats()
        assert stats["repl"]["role"] == "primary"
        assert stats["repl"]["failovers"] >= 1
        assert stats["store"]["appends"] > 0
        names = {m["name"] for m in util_metrics.collect_all()}
        for name in ("gcs_store_append_seconds", "gcs_store_log_bytes",
                     "gcs_store_compactions_total", "gcs_repl_lag_records",
                     "gcs_failovers_total"):
            assert name in names, name
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()
        CONFIG._reset()


def test_train_run_rides_through_gcs_primary_kill(tmp_path, monkeypatch):
    """SIGKILL the GCS *primary* mid-train (3 candidates, NO restart): the
    promoted follower takes over the control plane, workers keep stepping,
    and the run completes with a result bitwise-equal to an undisturbed
    run."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    monkeypatch.setenv("RAY_TPU_GCS_REPLICAS", "3")
    monkeypatch.setenv("RAY_TPU_GCS_LEASE_S", "1.5")
    CONFIG._reset()
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    marker = str(tmp_path / "mid_run")
    try:
        cluster.connect()

        def loop(config):
            from ray_tpu import train as _train

            total = 0.0
            for step in range(24):
                total += float((step * 7 + 3) % 11) * 0.5
                if step == 3:
                    open(config["marker"], "w").write("x")
                time.sleep(0.25)
                _train.report({"step": step, "total": total})

        result_box = {}

        def fit():
            result_box["result"] = DataParallelTrainer(
                loop,
                train_loop_config={"marker": marker},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(
                    name="gcs-ha-train", storage_path=str(tmp_path / "storage")
                ),
            ).fit()

        t = threading.Thread(target=fit, daemon=True)
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not os.path.exists(marker):
            time.sleep(0.1)
        assert os.path.exists(marker), "run never reached mid-flight"

        primary_idx = cluster.head.gcs_primary_index()
        old_st = cluster.head.gcs_candidate_status(primary_idx)
        cluster.head.kill_gcs_candidate(primary_idx)
        # The dead candidate is NOT restarted: the promoted follower owns the
        # control plane for the rest of the run.
        _wait_new_gcs_primary(cluster.head, primary_idx, old_st["epoch"])

        t.join(timeout=240)
        assert not t.is_alive(), "trainer did not finish after primary kill"
        result = result_box["result"]
        assert result.error is None, result.error
        expected = 0.0
        for step in range(24):
            expected += float((step * 7 + 3) % 11) * 0.5
        # Bitwise-equal to an undisturbed run: same float accumulation order.
        assert result.metrics["total"] == expected
        assert result.metrics["step"] == 23
    finally:
        cluster.shutdown()
        CONFIG._reset()


def test_single_candidate_gcs_mode_unchanged(monkeypatch):
    """gcs_replicas=1 (set explicitly) is today's behavior: ONE GCS process
    over the classic store dir, reporting itself primary with no quorum
    machinery, and the restart-recovery path works exactly as before."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_GCS_REPLICAS", "1")
    CONFIG._reset()
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "env_vars": _NODE_ENV})
    try:
        cluster.connect()
        w = ray_tpu.global_worker()
        assert len(cluster.head.gcs_procs) == 1
        assert os.path.basename(cluster.head.gcs_store_dir) == "gcs_store"
        st = cluster.head.gcs_candidate_status(0)
        assert st["role"] == "primary" and st["replicas"] == 1
        assert st["epoch"] == 0, "single mode must not run the lease protocol"

        w.gcs_kv_put("solo", b"k", b"v1")
        cluster.head.kill_gcs()
        time.sleep(0.5)
        cluster.head.restart_gcs()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if [n for n in ray_tpu.nodes() if n["alive"]]:
                    break
            except Exception:
                time.sleep(0.5)
        assert w.gcs_kv_get("solo", b"k") == b"v1"

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get(f.remote(41), timeout=120) == 42
    finally:
        cluster.shutdown()
        CONFIG._reset()


# ---------------------------------------------------------- autopilot chaos

# The autopilot flag + timing knobs must reach the controller process (CONFIG
# reads env per process): tiny hysteresis so pressure resolves in test time.
_AUTOPILOT_ENV = {
    **_NODE_ENV,
    "RAY_TPU_SERVE_AUTOPILOT": "1",
    "RAY_TPU_SERVE_AUTOPILOT_INTERVAL_S": "0.1",
    "RAY_TPU_SERVE_AUTOPILOT_SUSTAIN_TICKS": "2",
    "RAY_TPU_SERVE_AUTOPILOT_UPSCALE_COOLDOWN_S": "0.2",
    "RAY_TPU_SERVE_AUTOPILOT_DOWNSCALE_COOLDOWN_S": "0.5",
    "RAY_TPU_SERVE_AUTOPILOT_COLD_START_GUARD_S": "1.0",
    "RAY_TPU_SERVE_AUTOPILOT_QUEUE_HIGH": "8",
}


def _wait_until(pred, timeout_s=60.0, interval_s=0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval_s)
    return None


def _serve_replica_count(app, deployment):
    from ray_tpu import serve

    try:
        st = serve.status()
    except Exception:
        return -1
    return (st.get(app, {}).get("deployments", {})
            .get(deployment, {}).get("num_replicas", 0))


def test_autopilot_scaleup_rides_through_gcs_kill():
    """SIGKILL the GCS in the middle of an autopilot scale-up: the scale-op
    either completes once the GCS returns or rolls back cleanly — and no
    replica PROCESS is orphaned (every pid the deployment ever started is
    either in the final registered replica set or dead)."""
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 4,
                                      "env_vars": _AUTOPILOT_ENV})
    try:
        cluster.connect()

        @ray_tpu.remote
        class Box:
            def __init__(self):
                self._sig = {"queued": 0, "running": 1, "burn_rate": 0.0}
                self._pids = []

            def set_pressure(self, **kw):
                self._sig.update(kw)

            def signals(self):
                return dict(self._sig)

            def note_pid(self, pid):
                self._pids.append(pid)

            def pids(self):
                return list(self._pids)

        box = Box.remote()

        @serve.deployment(autoscaling_config={
            "min_replicas": 1, "max_replicas": 3,
            "target_ongoing_requests": 1e9,
        })
        class Engine:
            def __init__(self, b):
                self._box = b
                ray_tpu.get(b.note_pid.remote(os.getpid()))

            def pid(self):
                return os.getpid()

            def autopilot_signals(self):
                sig = ray_tpu.get(self._box.signals.remote())
                sig["role"] = "engine"
                return sig

            def __call__(self, x):
                return x

        handle = serve.run(Engine.bind(box), name="ap-gcs", route_prefix=None)
        assert handle.remote(1).result(timeout_s=60) == 1

        # Hot pressure, then kill the GCS right as the sustain window (2
        # ticks at 0.25s loop interval) is about to fire the scale-up.
        ray_tpu.get(box.set_pressure.remote(queued=30, burn_rate=3.0))
        time.sleep(0.4)
        cluster.head.kill_gcs()
        time.sleep(3.0)
        cluster.head.restart_gcs()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if [n for n in ray_tpu.nodes() if n["alive"]]:
                    break
            except Exception:
                time.sleep(0.5)

        # Pressure is still hot: the scale-up must COMPLETE once the control
        # plane is back (a rolled-back op re-fires on a later tick).
        assert _wait_until(
            lambda: _serve_replica_count("ap-gcs", "Engine") >= 2,
            timeout_s=90), "scale-up never completed after GCS recovery"
        ray_tpu.get(box.set_pressure.remote(queued=0, running=1,
                                            burn_rate=0.0))
        time.sleep(1.0)

        # No orphans: every pid this deployment ever started is either a
        # currently-registered replica or a dead process.
        pid_handle = serve.DeploymentHandle("ap-gcs", "Engine", "pid")
        registered = set(pid_handle.broadcast())
        started = set(ray_tpu.get(box.pids.remote()))
        orphans = []
        for pid in started - registered:
            try:
                os.kill(pid, 0)
                orphans.append(pid)
            except (ProcessLookupError, PermissionError):
                pass
        assert not orphans, f"orphan replica processes: {orphans}"
        # Registered count agrees with the serve status view (consistency:
        # the op committed; no half-applied target left behind).
        assert len(registered) == _serve_replica_count("ap-gcs", "Engine")
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def test_autopilot_absorbs_poisson_rate_step_surge():
    """3x Poisson rate step against a single-slot engine: the SLO burn rate
    (measured by the replicas themselves) must trigger an autopilot
    scale-up, and goodput (fraction of requests under the 0.5s SLO) must
    recover within the deadline after the fleet widens."""
    import asyncio
    from collections import deque as _deque

    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6, num_tpus=0, worker_env=_AUTOPILOT_ENV)
    try:

        @serve.deployment(autoscaling_config={
            "min_replicas": 1, "max_replicas": 3,
            "target_ongoing_requests": 1e9,
        })
        class SurgeEngine:
            """One request slot per replica (0.04s service time); queue wait
            shows up as latency, latency breaches show up as burn."""

            def __init__(self):
                self._sem = asyncio.Semaphore(1)
                self._waiting = 0
                self._lat = _deque(maxlen=64)

            def autopilot_signals(self):
                lat = list(self._lat)
                # Burn = breach fraction / error budget (SLO 0.2s in-replica,
                # 1% budget): one sustained breach saturates the signal.
                breaches = sum(1 for x in lat if x > 0.2)
                burn = (breaches / len(lat)) / 0.01 if lat else 0.0
                return {"role": "engine", "queued": self._waiting,
                        "running": 1, "burn_rate": burn}

            async def __call__(self, _x):
                t0 = time.monotonic()
                self._waiting += 1
                async with self._sem:
                    self._waiting -= 1
                    await asyncio.sleep(0.04)
                self._lat.append(time.monotonic() - t0)
                return 0

        handle = serve.run(SurgeEngine.bind(), name="ap-surge",
                           route_prefix=None)
        rng = random.Random(7)
        lock = threading.Lock()
        done = []  # (t_completed, latency_s)
        halt = threading.Event()

        def fire():
            t0 = time.monotonic()
            try:
                handle.remote(0).result(timeout_s=60)
                with lock:
                    done.append((time.monotonic(), time.monotonic() - t0))
            except Exception:
                with lock:
                    done.append((time.monotonic(), float("inf")))

        def traffic(rate_fn):
            while not halt.is_set():
                threading.Thread(target=fire, daemon=True).start()
                time.sleep(rng.expovariate(rate_fn()))

        # Warm phase at 10 rps (utilization 0.4 on one slot), step to 30 rps.
        t_start = time.monotonic()
        step_at = t_start + 2.0

        def rate():
            return 10.0 if time.monotonic() < step_at else 30.0

        t = threading.Thread(target=traffic, args=(rate,), daemon=True)
        t.start()
        try:
            assert _wait_until(
                lambda: _serve_replica_count("ap-surge", "SurgeEngine") >= 2,
                timeout_s=45), "burn rate never triggered a scale-up"
            t_scaled = time.monotonic()

            def goodput_recovered():
                with lock:
                    recent = [lat for (ts, lat) in done
                              if ts > time.monotonic() - 2.0]
                return (len(recent) >= 20
                        and sum(1 for x in recent if x < 0.5) / len(recent)
                        >= 0.7)

            assert _wait_until(goodput_recovered, timeout_s=45), \
                "goodput did not recover after the scale-up"
            assert t_scaled - step_at < 45.0
        finally:
            halt.set()
            t.join(timeout=10)
        time.sleep(0.5)  # let in-flight fire() threads drain
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_autopilot_scale_to_zero_and_first_request_cold_start():
    """min_replicas=0 round trip: the deployment drains to ZERO replicas
    when idle, the first request wakes it (handle -> controller wake path),
    completes, and the cold-start guard keeps the fresh replica alive long
    enough to serve before the idle law may retire it again."""
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=_AUTOPILOT_ENV)
    try:

        @serve.deployment(autoscaling_config={
            "min_replicas": 0, "max_replicas": 2,
            "target_ongoing_requests": 1e9,
        })
        class ColdEngine:
            def autopilot_signals(self):
                return {"role": "engine", "queued": 0, "running": 0,
                        "burn_rate": 0.0}

            def __call__(self, x):
                return x * 2

        handle = serve.run(ColdEngine.bind(), name="ap-cold",
                           route_prefix=None)
        assert _serve_replica_count("ap-cold", "ColdEngine") == 0

        # First request: wake -> spawn -> serve, inside the routing deadline.
        assert handle.remote(21).result(timeout_s=90) == 42
        assert _serve_replica_count("ap-cold", "ColdEngine") == 1

        # Idle past the cold-start guard (1s) + sustain + cooldown: back to 0.
        assert _wait_until(
            lambda: _serve_replica_count("ap-cold", "ColdEngine") == 0,
            timeout_s=90) is not None, "idle deployment never drained to zero"

        # And it wakes AGAIN: scale-to-zero is a cycle, not a one-way door.
        assert handle.remote(4).result(timeout_s=90) == 8
        assert _serve_replica_count("ap-cold", "ColdEngine") >= 1
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
