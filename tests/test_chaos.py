"""Chaos harness: random worker kills against long-running workloads.

Shape parity: reference `python/ray/tests/chaos/` — a resource killer runs
beside a real workload, SIGKILLing worker processes on a cadence, and the
workload must still complete CORRECTLY (retries + lineage reconstruction +
actor restarts absorbing the failures). This is the systematic concurrency/
failure stressor beyond targeted fault-injection tests.
"""

import os
import random
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def chaos_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S", "2")
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "RAY_TPU_BORROW_AUDIT_INTERVAL_S": "2",
        },
    )
    yield
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S")
    CONFIG._reset()


class _WorkerKiller(threading.Thread):
    """SIGKILL a random live task-worker pid every `period_s` (reference:
    chaos killer actors). Runs in the driver for determinism of teardown."""

    def __init__(self, get_pids, period_s: float = 1.5, seed: int = 0):
        super().__init__(daemon=True)
        self._get_pids = get_pids
        self._period = period_s
        self._rng = random.Random(seed)
        self._halt = threading.Event()
        self.kills = 0

    def run(self):
        while not self._halt.wait(self._period):
            pids = [p for p in self._get_pids() if p and p != os.getpid()]
            if not pids:
                continue
            victim = self._rng.choice(pids)
            try:
                os.kill(victim, signal.SIGKILL)
                self.kills += 1
            except ProcessLookupError:
                pass

    def stop(self):
        self._halt.set()


def test_tasks_survive_random_worker_kills(chaos_cluster):
    """200 retriable tasks complete with correct results while a killer
    SIGKILLs a random worker every 1.5s."""
    seen_pids = set()
    pid_lock = threading.Lock()

    @ray_tpu.remote(max_retries=10)
    def work(i):
        time.sleep(0.1)
        return i * i, os.getpid()

    def snapshot_pids():
        # The killer thread must read under the lock: an unlocked set copy
        # racing update() raises mid-iteration and silently kills the killer.
        with pid_lock:
            return list(seen_pids)

    killer = _WorkerKiller(snapshot_pids, period_s=1.5)
    killer.start()
    try:
        results = []
        for wave in range(10):
            refs = [work.remote(wave * 20 + i) for i in range(20)]
            out = ray_tpu.get(refs, timeout=300)
            with pid_lock:
                seen_pids.update(p for _v, p in out)
            results.extend(v for v, _p in out)
        expected = [i * i for i in range(200)]
        assert sorted(results) == sorted(expected)
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2, "chaos never actually killed anyone"


def test_restartable_actor_pipeline_survives_kills(chaos_cluster):
    """A restartable stateful actor keeps serving (reconstructing its state
    from constructor args) while being SIGKILLed mid-stream; owned objects
    referenced across the kills stay readable via lineage/borrow machinery."""

    @ray_tpu.remote(max_restarts=20, max_retries=10)
    class Accumulator:
        def __init__(self):
            self.pid = os.getpid()

        def process(self, arr):
            time.sleep(0.15)  # long enough that kills land mid-workload
            return float(np.asarray(arr).sum()), os.getpid()

    acc = Accumulator.remote()
    data_refs = [ray_tpu.put(np.full(50_000, i, np.float64)) for i in range(8)]
    first_sum, first_pid = ray_tpu.get(
        acc.process.remote(data_refs[0]), timeout=120
    )
    assert first_sum == 0.0
    pids = {first_pid}
    latest = [first_pid]  # killer targets the LIVE incarnation, not ghosts
    killer = _WorkerKiller(lambda: [latest[0]], period_s=2.0, seed=7)
    killer.start()
    def call_with_retry(make_ref, attempts=10):
        # Chaos-workload idiom: a kill can land mid-call; the caller resubmits
        # against the restarted actor (reference chaos tests do the same).
        last = None
        for _ in range(attempts):
            try:
                return ray_tpu.get(make_ref(), timeout=120)
            except Exception as e:  # noqa: BLE001 - actor died mid-call
                last = e
                time.sleep(1.0)
        raise AssertionError(f"call never succeeded through chaos: {last}")

    try:
        totals = []
        for round_i in range(6):
            for ref in data_refs:
                s, pid = call_with_retry(lambda r=ref: acc.process.remote(r))
                totals.append(s)
                pids.add(pid)
                latest[0] = pid
        expected = [i * 50_000.0 for i in range(8)] * 6
        assert totals == expected
    finally:
        killer.stop()
        killer.join(timeout=5)
    assert killer.kills >= 2
    assert len(pids) >= 2, "actor was never actually restarted"


# ---------------------------------------------------------------- node chaos

_NODE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
}


def test_tasks_survive_node_kill():
    """SIGKILL a whole worker NODE (raylet + its workers) mid-wave: retriable
    tasks that were running there re-execute elsewhere and every result is
    still correct (reference: RayletKiller chaos,
    python/ray/_private/test_utils.py:1479)."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    n2 = cluster.add_node(num_cpus=2, env_vars=_NODE_ENV)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote(max_retries=10)
        def work(i):
            time.sleep(0.3)
            return i * i, ray_tpu.get_runtime_context().get_node_id()

        n2_id = next(n["node_id"] for n in ray_tpu.nodes()
                     if n["node_id"].hex() == n2.node_id_hex)
        # SOFT affinity to node 2: tasks start there, and their retries may
        # reschedule anywhere once the node is gone.
        on_n2 = work.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(n2_id, soft=True)
        )

        # Warm a first wave and require node 2 to actually execute work —
        # otherwise killing it proves nothing.
        first = ray_tpu.get([on_n2.remote(i) for i in range(4)], timeout=300)
        nodes_seen = {n.hex() for _v, n in first}
        assert n2.node_id_hex in nodes_seen, f"work never ran on node 2: {nodes_seen}"

        # Launch a big wave biased onto node 2, then kill the node while much
        # of it is in flight.
        refs = [(on_n2 if i % 2 else work).remote(i) for i in range(40)]
        time.sleep(0.8)  # several tasks are mid-sleep on n2 right now
        cluster.kill_node(n2)
        out = ray_tpu.get(refs, timeout=300)
        assert sorted(v for v, _n in out) == sorted(i * i for i in range(40))
        # Everything after the kill ran on the surviving node(s).
        alive = {n["node_id"].hex() for n in ray_tpu.nodes() if n["alive"]}
        assert n2.node_id_hex not in alive
    finally:
        cluster.shutdown()


def test_elastic_trainer_survives_node_kill_and_reexpands(tmp_path):
    """An elastic JaxTrainer run loses a NODE to SIGKILL mid-attempt, resumes
    at N-1, then re-expands to full size IN THE SAME RUN once capacity
    returns (reference: chaos suite + elastic scaling policy)."""
    import os
    import threading

    from ray_tpu import train
    from ray_tpu.train import (
        FailureConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "env_vars": _NODE_ENV})
    cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                     env_vars=_NODE_ENV)
    n2 = cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                          env_vars=_NODE_ENV)
    cluster.connect()
    cluster.wait_for_nodes()
    marker_dir = str(tmp_path)
    try:
        def loop(config):
            import os as _os

            ctx = train.get_context()
            world = ctx.get_world_size()
            rank = ctx.get_world_rank()
            mk = config["markers"]
            open(_os.path.join(mk, f"started_{world}_{rank}"), "w").write("x")
            if world == 2 and not _os.path.exists(
                _os.path.join(mk, "expanded")
            ):
                # First full-size attempt: park until the driver SIGKILLs a
                # node out from under one of us.
                time.sleep(600)
            if world == 1:
                # Shrunk attempt: wait for the driver to restore capacity,
                # then fail ONCE so the elastic policy re-evaluates and
                # re-expands the SAME run.
                deadline = time.monotonic() + 240
                while not _os.path.exists(_os.path.join(mk, "capacity_back")):
                    if time.monotonic() > deadline:
                        break
                    time.sleep(0.5)
                open(_os.path.join(mk, "expanded"), "w").write("x")
                raise RuntimeError("chaos: trigger elastic re-expansion")
            train.report({"world": world, "rank": rank})

        trainer = JaxTrainer(
            loop,
            train_loop_config={"markers": marker_dir},
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, use_tpu=False,
                resources_per_worker={"trainslot": 1.0},
            ),
            run_config=RunConfig(
                name="node-chaos", storage_path=str(tmp_path / "storage"),
                failure_config=FailureConfig(max_failures=4),
            ),
        )

        result_box = {}

        def fit():
            result_box["result"] = trainer.fit()

        t = threading.Thread(target=fit)
        t.start()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if len([f for f in os.listdir(marker_dir)
                    if f.startswith("started_2_")]) >= 2:
                break
            time.sleep(0.2)
        assert len([f for f in os.listdir(marker_dir)
                    if f.startswith("started_2_")]) >= 2

        cluster.kill_node(n2)  # SIGKILL raylet + its workers, mid-attempt

        # The run shrinks to world 1; then we restore capacity.
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if any(f.startswith("started_1_") for f in os.listdir(marker_dir)):
                break
            time.sleep(0.5)
        assert any(f.startswith("started_1_") for f in os.listdir(marker_dir)), (
            "run never resumed at N-1 after the node kill"
        )
        cluster.add_node(num_cpus=1, resources={"trainslot": 1.0},
                         env_vars=_NODE_ENV)
        cluster.wait_for_nodes()
        open(os.path.join(marker_dir, "capacity_back"), "w").write("x")

        t.join(timeout=420)
        assert not t.is_alive(), "trainer did not finish after node chaos"
        result = result_box["result"]
        assert result.error is None, result.error
        # The final attempt re-expanded to the full world size.
        assert result.metrics["world"] == 2
    finally:
        cluster.shutdown()
