"""Native C++ object store tests: direct API plus end-to-end through the runtime.

Parity role: the reference plasma store's C++ unit tests
(src/ray/object_manager/plasma/ + store tests) — create/seal/get lifecycle, LRU
eviction of freed objects, allocator coalescing under churn, and cross-process reads.
"""

import os
import sys

import numpy as np
import pytest

import ray_tpu
from ray_tpu._native.shmstore import NativeStoreClient, NativeStoreServer, load
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import (
    LocalObjectReader,
    NativeSharedObjectStore,
    SharedObjectStore,
)

pytestmark = pytest.mark.skipif(load() is None, reason="native toolchain unavailable")


def test_native_lifecycle_and_eviction():
    srv = NativeStoreServer(f"rtpu_t1_{os.getpid()}", 4 << 20)
    try:
        a = bytes([7] * 16)
        off = srv.alloc(a, 2 << 20)
        srv.write(off, b"a" * (2 << 20))
        assert srv.lookup(a) is None  # unsealed: invisible
        srv.seal(a)
        assert srv.lookup(a) == (off, 2 << 20)
        # second big object requires evicting the freed first
        assert srv.alloc(bytes([8] * 16), 3 << 20) is None
        srv.free(a)
        off2 = srv.alloc(bytes([8] * 16), 3 << 20)
        assert off2 is not None and srv.num_evictions == 1
    finally:
        srv.destroy()


def test_native_allocator_churn_preserves_data():
    srv = NativeStoreServer(f"rtpu_t2_{os.getpid()}", 8 << 20)
    try:
        rng = np.random.default_rng(0)
        live = {}
        for round_ in range(300):
            oid = int(round_).to_bytes(16, "big")
            size = int(rng.integers(100, 50_000))
            off = srv.alloc(oid, size)
            if off is None:
                break
            payload = bytes([round_ % 256]) * size
            srv.write(off, payload)
            srv.seal(oid)
            live[oid] = (off, size, round_ % 256)
            if rng.random() < 0.4 and live:
                victim = list(live)[int(rng.integers(len(live)))]
                srv.free(victim, eager=True)
                del live[victim]
        # all remaining objects intact
        for oid, (off, size, byte) in live.items():
            got = srv.lookup(oid)
            assert got == (off, size)
            view = srv.read(off, size)
            assert view[0] == byte and view[size - 1] == byte
    finally:
        srv.destroy()


def test_store_api_native_backend():
    store = SharedObjectStore(4 << 20)
    assert isinstance(store, NativeSharedObjectStore), "native backend expected"
    try:
        oid = ObjectID.rand() if hasattr(ObjectID, "rand") else ObjectID(os.urandom(ObjectID.SIZE))
        name = store.put_bytes(oid, b"hello world")
        assert name.startswith("@")
        assert store.contains(oid)
        got_name, size = store.info(oid)
        assert size == 11
        assert store.read_bytes(oid) == b"hello world"
        assert store.read_bytes(oid, offset=6, length=5) == b"world"
        reader = LocalObjectReader()
        assert bytes(reader.read(got_name, size)) == b"hello world"
        store.free(oid, eager=True)
        assert not store.contains(oid)
        st = store.stats()
        assert st["backend"] == "native"
    finally:
        store.destroy()


def test_runtime_end_to_end_on_native_store(ray_start_isolated):
    # the module fixture cluster in other files may predate this test; isolated
    # cluster guarantees the native store is what backs put/get here.
    arr = np.arange(200_000, dtype=np.float64)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)

    @ray_tpu.remote
    def double(x):
        return x * 2

    np.testing.assert_array_equal(ray_tpu.get(double.remote(ref)), arr * 2)


@pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="zero-copy pinned views need PEP 688 __buffer__ (3.12+); older "
    "Pythons use the pin->copy->release fallback, so there is no alias "
    "holding the pin to test",
)
def test_pinned_read_survives_eviction():
    srv = NativeStoreServer(f"rtpu_t3_{os.getpid()}", 4 << 20)
    try:
        a = bytes([9] * 16)
        off = srv.alloc(a, 1 << 20)
        srv.write(off, b"\xab" * (1 << 20))
        srv.seal(a)
        cli = NativeStoreClient(srv.name)
        view = cli.read_pinned(a, off, 1 << 20)
        arr = np.frombuffer(view, dtype=np.uint8)
        # free + pressure: allocator must NOT recycle the pinned block
        srv.free(a)
        filler = bytes([10] * 16)
        got = srv.alloc(filler, 2500 << 10)  # fits without touching pinned block
        assert got is not None
        srv.write(got, b"\x00" * (2500 << 10))
        # a second alloc that WOULD need the pinned block must fail
        assert srv.alloc(bytes([11] * 16), 1 << 20) is None
        assert arr[0] == 0xAB and arr[-1] == 0xAB  # data intact under pressure
        # drop the alias: pin releases, eviction proceeds
        del arr, view
        import gc

        gc.collect()
        assert srv.alloc(bytes([11] * 16), 1 << 20) is not None
    finally:
        srv.destroy()


def test_write_view_writable_on_all_pythons():
    """The put/pull WRITE path must get a raw writable view (write_view), never
    read()'s pinned view: on Python < 3.12 read_pinned degrades to a read-only
    copy (no PEP 688 __buffer__), which would TypeError on chunk writes — the
    bug that silently broke every cross-node pull on 3.10."""
    store = SharedObjectStore(1 << 20)
    try:
        oid = ObjectID(os.urandom(ObjectID.SIZE))
        name = store.create(oid, 16)
        reader = LocalObjectReader()
        view = reader.write_view(name, 16)
        view[:16] = b"0123456789abcdef"  # must not raise on any Python
        store.seal(oid)
        assert bytes(reader.read(name, 16)) == b"0123456789abcdef"
    finally:
        store.destroy()


def test_reader_write_bounds_checked():
    store = SharedObjectStore(1 << 20)
    try:
        oid = ObjectID(os.urandom(ObjectID.SIZE))
        name = store.create(oid, 100)
        reader = LocalObjectReader()
        with pytest.raises(ValueError, match="exceeds"):
            reader.write(name, b"z" * 4096)
        reader.write(name, b"ok")
        store.seal(oid)
        assert store.read_bytes(oid, length=2) == b"ok"
    finally:
        store.destroy()


def test_dag_oversized_output_surfaces_error(ray_start_isolated):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Big:
        def make(self, n):
            return np.zeros(n, np.uint8)

    b = Big.remote()
    with InputNode() as inp:
        dag = b.make.bind(inp)
    compiled = dag.experimental_compile(buffer_size_bytes=1 << 16)
    try:
        with pytest.raises(Exception, match="exceeds"):
            compiled.execute(1 << 20).get()
        # loop survives: a small value goes through fine afterwards
        out = compiled.execute(100).get()
        assert len(out) == 100
    finally:
        compiled.teardown()


def test_spilling_over_capacity():
    """Objects beyond arena capacity spill to disk and stay readable."""
    store = SharedObjectStore(8 << 20)
    try:
        payloads = {}
        for i in range(8):  # 8 x 2MB = 16MB through an 8MB arena
            oid = ObjectID(os.urandom(ObjectID.SIZE))
            data = bytes([i]) * (2 << 20)
            store.put_bytes(oid, data)
            payloads[oid] = data
        st = store.stats()
        assert st["num_spilled"] >= 2, st
        reader = LocalObjectReader()
        for oid, data in payloads.items():
            assert store.contains(oid)
            name, size = store.info(oid)
            got = bytes(reader.read(name, size))
            assert got == data  # both in-arena and spilled objects read back
        # free removes spilled files too
        for oid in payloads:
            store.free(oid, eager=True)
        assert store.stats()["spilled_bytes"] == 0
    finally:
        store.destroy()


def test_runtime_survives_store_pressure(ray_start_isolated):
    """End-to-end: puts well beyond object_store_memory keep working via spill."""
    import ray_tpu as rt

    rt.shutdown()
    rt.init(num_cpus=2, object_store_memory=16 << 20)
    try:
        arrs = [np.full(1 << 20, i, np.uint8) for i in range(40)]  # 40MB total
        refs = [rt.put(a) for a in arrs]
        for i, r in enumerate(refs):
            got = rt.get(r)
            assert got[0] == i and got.nbytes == 1 << 20
    finally:
        rt.shutdown()
