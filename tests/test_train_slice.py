"""JaxTrainer on a fake multi-host TPU slice (CPU nodes with TPU resources).

Reference pattern: python/ray/train/v2/tests/test_jax_trainer.py:16-55 — simulate a TPU
slice by granting CPU nodes TPU/TPU-<pod>-head resources.
"""

import ray_tpu
from ray_tpu import train
from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig


def test_jax_trainer_on_fake_tpu_slice(ray_start_cluster):
    """Reference pattern (test_jax_trainer.py): fake TPU resources on CPU nodes."""
    cluster = ray_start_cluster
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PALLAS_AXON_POOL_IPS": "",
    }
    cluster.add_node(num_cpus=2, resources={"TPU": 4.0, "TPU-v4-16": 1.0,
                                            "TPU-v4-16-head": 1.0}, env_vars=env)
    cluster.add_node(num_cpus=2, resources={"TPU": 4.0, "TPU-v4-16": 1.0}, env_vars=env)
    cluster.connect()
    cluster.wait_for_nodes()

    def loop(config):
        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "world": ctx.get_world_size()})

    result = JaxTrainer(
        loop,
        jax_config=train.JaxConfig(distributed=False),
        scaling_config=ScalingConfig(topology="v4-16"),
        # max_failures: a worker lost to spawn-storm load on the shared CI host
        # restarts the group from checkpoint — the recovery path under test.
        run_config=RunConfig(name="slice", storage_path="/tmp/rtpu_slice_test",
                             failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert result.metrics["world"] == 2
