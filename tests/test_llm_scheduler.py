"""Iteration-level scheduler (ray_tpu/llm/scheduler, docs/scheduler.md):
chunked prefill interleaved with decode under a token budget, and
speculative decoding as a scheduler-scheduled phase with batched verify.

The load-bearing invariants:
- greedy output is TOKEN-IDENTICAL across every scheduling shape (whole
  prompt vs chunked, cached prefix vs cold, spec vs plain decode);
- a long prefill cannot stall in-flight decodes beyond the token budget;
- prefix-cache hits stay spec-eligible (the PR-3 behavior of silently
  downgrading to plain decode is gone);
- every chunk shape comes from the static bucket table (no new programs).
"""

import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


def _generate(engine, prompt, n, **sp):
    from ray_tpu.llm import SamplingParams

    out, done = [], threading.Event()

    def cb(tok, fin):
        out.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(max_tokens=n, **sp), cb)
    assert done.wait(180), engine.error
    return out


# -- scheduler unit tests (no device work) ---------------------------------


def _unit_sched(**kw):
    from ray_tpu.llm.scheduler import Scheduler

    args = dict(num_slots=2, buckets=(16, 32, 64, 128), max_seq=128,
                token_budget=64, max_queue_depth=0, multi_step=1)
    args.update(kw)
    return Scheduler(**args)


def _fake_running(sched, slot, max_tokens=1000):
    """Put a fabricated request into the decode phase on `slot`."""
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request

    req = Request("prompt", prompt=[1, 2, 3],
                  sampling=SamplingParams(max_tokens=max_tokens),
                  callback=lambda *a: None)
    req.slot = slot
    sched.start_decode(req, 7)
    return req


def test_scheduler_chunks_long_prefill_and_never_stalls_decode():
    """Unit-level starvation bound: with a decode in flight, a long prompt
    is split into bucketed chunks and EVERY iteration still schedules the
    decode slot — prefill can never exclude decode from an iteration."""
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request

    sched = _unit_sched(token_budget=64)
    _fake_running(sched, 0)
    long_req = Request("prompt", prompt=list(range(1, 121)),
                       sampling=SamplingParams(max_tokens=4),
                       callback=lambda *a: None)
    sched.submit(long_req)

    chunks_seen, iters = [], 0
    while long_req.prefilled < long_req.prompt_len:
        iters += 1
        assert iters < 20, "prefill failed to make progress"
        plan = sched.next_plan()
        assert plan.decode_slots == [0], "decode stalled by prefill"
        # budget respected: decode reserved first, chunks fill the rest
        assert plan.decode_tokens + plan.prefill_tokens <= 64
        assert plan.chunks, "no prefill progress scheduled"
        for chunk in plan.chunks:
            assert chunk.bucket in (16, 32, 64, 128)
            chunks_seen.append(len(chunk.tokens))
            sched.chunk_done(chunk)
        sched.slots[0].generated += 1  # simulate the decode phase
    assert len(chunks_seen) >= 3, chunks_seen   # 120 tokens / <=63-token grants
    assert sum(chunks_seen) == 120
    stats = sched.stats()
    assert stats["interleaved_iterations"] == iters
    assert stats["prefill_chunks"] == len(chunks_seen)


def test_scheduler_head_of_line_prefill_progress_under_full_decode_load():
    """Even when decode reservations consume the whole budget, the
    head-of-line prefill still gets one minimum bucket per iteration."""
    sched = _unit_sched(num_slots=8, token_budget=8)  # 8 decode slots > budget
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request

    for i in range(7):
        _fake_running(sched, i)
    req = Request("prompt", prompt=list(range(1, 40)),
                  sampling=SamplingParams(max_tokens=2),
                  callback=lambda *a: None)
    sched.submit(req)
    plan = sched.next_plan()
    assert len(plan.decode_slots) == 7
    assert len(plan.chunks) == 1 and plan.chunks[0].bucket == 16


def test_scheduler_unbudgeted_mode_is_whole_prompt():
    """token_budget=0 reproduces the legacy shape: one whole-prompt chunk."""
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request

    sched = _unit_sched(token_budget=0)
    req = Request("prompt", prompt=list(range(1, 121)),
                  sampling=SamplingParams(max_tokens=4),
                  callback=lambda *a: None)
    sched.submit(req)
    plan = sched.next_plan()
    assert len(plan.chunks) == 1
    assert len(plan.chunks[0].tokens) == 120
    assert plan.chunks[0].is_first and plan.chunks[0].is_last


def test_scheduler_queue_cap_and_drain():
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.scheduler import Request
    from ray_tpu.llm.scheduler.scheduler import EngineOverloadedError

    sched = _unit_sched(max_queue_depth=2)
    mk = lambda: Request("prompt", prompt=[1, 2],
                         sampling=SamplingParams(), callback=lambda *a: None)
    sched.submit(mk())
    sched.submit(mk())
    with pytest.raises(EngineOverloadedError, match="admission queue"):
        sched.submit(mk())
    assert len(sched.drain()) == 2
    assert sched.queue_depth() == 0


# -- token-identity across scheduling shapes -------------------------------


def test_chunked_prefill_token_identical(tiny_model):
    """Multi-chunk prefill (budget forces >= 3 chunks) emits exactly the
    same greedy tokens as whole-prompt prefill."""
    from ray_tpu.llm import DecodeEngine

    cfg, model, params = tiny_model
    rng = np.random.default_rng(3)
    prompt = list(map(int, rng.integers(0, cfg.vocab_size, 70)))

    whole = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False, token_budget=0)
    chunked = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                           prefix_cache=False, token_budget=32)
    try:
        expect = _generate(whole, prompt, 8)
        got = _generate(chunked, prompt, 8)
        assert got == expect
        lp = chunked.last_prefill
        assert lp["chunks"] >= 3, lp        # 70 tokens through a 32 budget
        assert lp["offset"] == 0 and lp["prompt_len"] == 70
        stats = chunked.scheduler_stats()
        assert stats["prefill_chunks"] >= 3
    finally:
        whole.shutdown()
        chunked.shutdown()


def test_chunked_prefill_with_cached_prefix_token_identical(tiny_model):
    """Chunked prefill composes with prefix-cache leases: a warm hit
    attaches cached blocks, the SUFFIX prefills in chunks, and greedy
    output still matches the cache-disabled whole-prompt engine."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.kvcache import PrefixCacheManager

    cfg, model, params = tiny_model
    rng = np.random.default_rng(7)
    prefix = list(map(int, rng.integers(0, cfg.vocab_size, 48)))
    p1 = prefix + list(map(int, rng.integers(0, cfg.vocab_size, 40)))
    p2 = prefix + list(map(int, rng.integers(0, cfg.vocab_size, 37)))

    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False, token_budget=0)
    cached = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128, token_budget=32,
        prefix_cache=PrefixCacheManager(16, 8 << 20, name="sched-equiv"),
    )
    try:
        expect = [_generate(plain, p, 6) for p in (p1, p2)]
        got1 = _generate(cached, p1, 6)
        assert cached.last_prefill["offset"] == 0
        assert cached.last_prefill["chunks"] >= 2
        got2 = _generate(cached, p2, 6)
        lp = cached.last_prefill
        assert lp["offset"] == 48, lp       # 3 whole blocks attached
        assert lp["chunks"] >= 2, lp        # 37-token suffix through budget 32
        assert [got1, got2] == expect
        stats = cached.prefix_cache_stats()
        assert stats["hits"] == 1 and stats["leases_active"] == 0
    finally:
        plain.shutdown()
        cached.shutdown()


def _prompt_slot_kv(engine, prompt):
    """Host copy of the prompt's KV rows [0, len(prompt)) on whichever slot
    served it: [L, 2, len(prompt), Hkv, D]. Call only on an idle engine."""
    slot = next(i for i, s in enumerate(engine._slots)
                if s.history[: len(prompt)] == prompt)
    n = len(prompt)
    return np.stack([
        np.stack([np.asarray(ck[slot, :n]), np.asarray(cv[slot, :n])])
        for ck, cv in engine._caches
    ])


def test_long_prefill_does_not_stall_decode_integration(tiny_model):
    """Integration starvation bound AND interleaving correctness: tokens
    keep flowing on a running decode while a long prompt prefills in chunks,
    and BOTH streams emit exactly the tokens a whole-prompt (unchunked)
    reference engine emits. A decode dispatch that writes an ungated KV row
    into the mid-prefill slot (stale lens) corrupts the long prompt's cache
    permanently — sequential token-identity tests can never catch that. One
    corrupted row of ~110 may not flip a tiny model's argmax, so the
    prompt's KV rows themselves are ALSO compared against the reference
    (the decisive detector)."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    stream_prompt = [5, 9, 17]
    long_prompt = list(map(
        int, np.random.default_rng(0).integers(0, cfg.vocab_size, 110)))

    ref = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                       prefix_cache=False, token_budget=0)
    engine = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                          prefix_cache=False, token_budget=16, multi_step=1)
    try:
        # Sequential, whole-prompt prefill: no interleaving anywhere.
        expect_stream = _generate(ref, stream_prompt, 60)
        expect_long = _generate(ref, long_prompt, 4)

        stream_done = threading.Event()
        stream_out = []

        def stream_cb(tok, fin):
            stream_out.append(tok)
            if fin:
                stream_done.set()

        engine.submit(stream_prompt, SamplingParams(max_tokens=60), stream_cb)
        while len(stream_out) < 5:          # the stream is decoding
            assert engine.error is None
            threading.Event().wait(0.01)
        got = _generate(engine, long_prompt, 4)   # ~7 chunks at budget 16
        assert got == expect_long, (
            "interleaved decode corrupted the chunk-prefilling slot's KV"
        )
        assert stream_done.wait(180)
        assert stream_out == expect_stream
        stats = engine.scheduler_stats()
        # the long prefill's chunks shared iterations with the live decode
        assert stats["interleaved_iterations"] >= 3, stats
        assert stats["prefill_chunks"] >= 7, stats
        # Row-level corruption check: the interleaved engine's prompt KV
        # must match the whole-prompt reference row for row (tolerance for
        # the different prefill program shapes, decisive against a stray
        # decode write replacing a row outright).
        np.testing.assert_allclose(
            _prompt_slot_kv(engine, long_prompt),
            _prompt_slot_kv(ref, long_prompt),
            atol=5e-2, rtol=0,
            err_msg="interleaved decode dispatch wrote into prompt KV rows",
        )
    finally:
        ref.shutdown()
        engine.shutdown()


# -- speculative decoding as a scheduler phase -----------------------------


def test_spec_ngram_repeat_traffic_token_identical_and_accepts(tiny_model):
    """Retrieval (ngram) speculation: the first request builds the
    continuation store, a repeat re-proposes its completion and the batched
    verify accepts — output stays token-identical to a plain engine, at a
    measured (non-all-accept) acceptance rate."""
    from ray_tpu.llm import DecodeEngine

    cfg, model, params = tiny_model
    prompt = [5, 9, 17, 3, 42, 8, 7, 21]
    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False)
    spec = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128, prefix_cache=False,
        spec_config={"method": "ngram", "num_spec_tokens": 8},
    )
    try:
        expect = _generate(plain, prompt, 24)
        first = _generate(spec, prompt, 24)     # builds the store on finish
        repeat = _generate(spec, prompt, 24)
        assert first == expect and repeat == expect
        stats = spec.scheduler_stats()["spec"]
        assert stats["rounds"] > 0
        assert stats["accepted_tokens"] > 0
        assert 0 < stats["accept_rate"] <= 1.0
        assert stats["draft"]["kind"] == "ngram"
    finally:
        plain.shutdown()
        spec.shutdown()


def test_spec_stays_eligible_on_prefix_cache_hit(tiny_model):
    """A slot admitted via a prefix-cache hit must STILL run speculative
    rounds (draft cache catch-up on the attached prefix) instead of
    silently downgrading to plain decode — and emit identical tokens."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.kvcache import PrefixCacheManager

    cfg, model, params = tiny_model
    rng = np.random.default_rng(13)
    prompt = list(map(int, rng.integers(0, cfg.vocab_size, 40)))

    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False)
    spec = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128,
        prefix_cache=PrefixCacheManager(16, 8 << 20, name="spec-hit"),
        spec_config={"num_spec_tokens": 4},   # self-draft: all-accept rig
    )
    try:
        expect = _generate(plain, prompt, 10)
        got_cold = _generate(spec, prompt, 10)
        rounds_cold = spec.scheduler_stats()["spec"]["rounds"]
        assert rounds_cold > 0
        got_warm = _generate(spec, prompt, 10)
        lp = spec.last_prefill
        assert lp["offset"] == 32, lp           # the cache hit really happened
        stats = spec.scheduler_stats()["spec"]
        assert stats["rounds"] > rounds_cold, (
            "cache-hit admission downgraded to plain decode"
        )
        assert got_cold == expect and got_warm == expect
    finally:
        plain.shutdown()
        spec.shutdown()


def test_spec_multi_slot_batched_verify_token_identical(tiny_model):
    """Several slots speculate CONCURRENTLY through one batched gated
    verify dispatch; every stream stays token-identical to the plain
    engine."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    prompts = [[5, 9, 17, 3], [8, 2, 44, 7, 19, 21, 6], [33, 11, 90]]
    plain = DecodeEngine(cfg, params, num_slots=4, max_seq=128,
                         prefix_cache=False)
    spec = DecodeEngine(
        cfg, params, num_slots=4, max_seq=128, prefix_cache=False,
        spec_config={"num_spec_tokens": 4},   # self-draft: deterministic
    )
    try:
        expect = [_generate(plain, p, 12) for p in prompts]
        results = {}
        done = threading.Event()

        def cb_for(idx):
            acc = []

            def cb(tok, fin):
                acc.append(tok)
                if fin:
                    results[idx] = acc
                    if len(results) == len(prompts):
                        done.set()

            return cb

        for idx, p in enumerate(prompts):
            spec.submit(p, SamplingParams(max_tokens=12), cb_for(idx))
        assert done.wait(180), spec.error
        assert [results[i] for i in range(len(prompts))] == expect
        stats = spec.scheduler_stats()["spec"]
        assert stats["rounds"] > 0
        # self-draft accepts everything it proposes
        assert stats["accepted_tokens"] == stats["proposed_tokens"] > 0
    finally:
        plain.shutdown()
        spec.shutdown()


def test_spec_eligible_after_pd_transfer_with_token_ids(tiny_model):
    """A PD-disagg transferred prefix that carries its token ids feeds the
    scheduler's running queue AND stays spec-eligible (the draft catches up
    on the token history)."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    prompt = [5, 9, 17, 3, 42, 8]
    plain = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                         prefix_cache=False)
    prefiller = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                             decode_loop=False, prefix_cache=False)
    decoder = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128, prefix_cache=False,
        spec_config={"num_spec_tokens": 4},
    )
    try:
        expect = _generate(plain, prompt, 10)
        first_logits, kv, plen = prefiller.prefill_detached(prompt)
        out, done = [], threading.Event()

        def cb(tok, fin):
            out.append(tok)
            if fin:
                done.set()

        decoder.submit_prefilled(kv, plen, first_logits,
                                 SamplingParams(max_tokens=10), cb,
                                 token_ids=prompt)
        assert done.wait(180), decoder.error
        assert out == expect
        stats = decoder.scheduler_stats()["spec"]
        assert stats["rounds"] > 0, "transferred prefix downgraded to plain"
    finally:
        plain.shutdown()
        prefiller.shutdown()
        decoder.shutdown()


def test_early_exit_draft_shares_target_params(tiny_model):
    """EAGLE-style early-exit draft: first j layers + embeddings shared with
    the target (no copies), and generation stays token-identical (the
    verify phase corrects every wrong proposal)."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.scheduler import early_exit_draft

    cfg, model, params = tiny_model
    d_cfg, d_params = early_exit_draft(cfg, params, 1)
    assert d_cfg.n_layers == 1
    assert d_params["embedding"] is params["embedding"]  # shared, not copied
    with pytest.raises(ValueError, match="draft_layers"):
        early_exit_draft(cfg, params, cfg.n_layers)

    prompt = [5, 9, 17, 3]
    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False)
    spec = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128, prefix_cache=False,
        spec_config={"draft_layers": 1, "num_spec_tokens": 4},
    )
    try:
        expect = _generate(plain, prompt, 16)
        got = _generate(spec, prompt, 16)
        assert got == expect
        stats = spec.scheduler_stats()["spec"]
        assert stats["rounds"] > 0
        assert stats["draft"]["draft_layers"] == 1
    finally:
        plain.shutdown()
        spec.shutdown()
