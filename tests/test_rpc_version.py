"""Wire-protocol version handshake.

Parity role: the reference's protobuf schemas gate cross-version clusters at
the schema layer; here every peer announces PROTOCOL_VERSION in its first
frame and a mismatch fails calls with a crisp error instead of a pickle
decode crash deep inside a handler.
"""

import asyncio

import pytest

from ray_tpu._private import rpc


class _Handler:
    def rpc_echo(self, conn, x):
        return x


def test_same_version_handshake_and_calls():
    async def main():
        server = rpc.RpcServer(lambda conn: _Handler())
        await server.start()
        conn = await rpc.connect("127.0.0.1", server.port, handler=_Handler())
        assert await conn.call("echo", 7, timeout=10) == 7
        # Both sides learned each other's version.
        deadline = asyncio.get_running_loop().time() + 5
        while conn.peer_protocol is None:
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError("no HELLO received")
            await asyncio.sleep(0.01)
        assert conn.peer_protocol == rpc.PROTOCOL_VERSION
        await conn.close()
        await server.close()

    asyncio.run(main())


def test_version_mismatch_fails_calls_crisply():
    async def main():
        server = rpc.RpcServer(lambda conn: _Handler())
        await server.start()
        # A client from a hypothetical future release.
        conn = await rpc.connect("127.0.0.1", server.port, handler=_Handler(),
                                 _protocol_version=99)
        # The server's v1 HELLO trips the client's check (and vice versa on
        # the server); every call on the connection fails with the crisp
        # message, whether issued before or after the handshake lands.
        with pytest.raises(rpc.RpcError) as ei:
            for _ in range(50):
                await conn.call("echo", 1, timeout=10)
                await asyncio.sleep(0.05)
            raise AssertionError("mismatched peers kept talking")
        assert "wire-protocol mismatch" in str(ei.value) or isinstance(
            ei.value, rpc.ConnectionLost
        )
        # Once the connection is torn down the error is always the crisp one.
        with pytest.raises(rpc.RpcError, match="wire-protocol mismatch"):
            deadline = asyncio.get_running_loop().time() + 5
            while True:
                try:
                    await conn.call("echo", 1, timeout=10)
                except rpc.RpcError as e:
                    if "wire-protocol mismatch" in str(e):
                        raise
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("never settled on the crisp error")
                await asyncio.sleep(0.05)
        await server.close()

    asyncio.run(main())


def test_legacy_peer_without_hello_warns(caplog):
    """A pre-handshake peer never sends HELLO — its first _REQUEST must
    surface a 'legacy peer' warning (detection starts at v1; older builds
    can't be failed crisply, only diagnosed)."""
    import logging
    import pickle
    import struct

    async def main():
        server = rpc.RpcServer(lambda conn: _Handler())
        await server.start()
        # Hand-rolled pre-v1 client: speaks frames but no HELLO.
        reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
        payload = pickle.dumps((0, 1, "echo", (42,), {}))  # _REQUEST frame
        writer.write(struct.pack("<Q", len(payload)) + payload)
        await writer.drain()
        # The v1 server still answers (payloads happen to be compatible)...
        header = await asyncio.wait_for(reader.readexactly(8), 10)
        (length,) = struct.unpack("<Q", header)
        frames = pickle.loads(await reader.readexactly(length))
        if frames[0] == 3:  # the server's own HELLO arrives first
            header = await asyncio.wait_for(reader.readexactly(8), 10)
            (length,) = struct.unpack("<Q", header)
            frames = pickle.loads(await reader.readexactly(length))
        assert frames[:3] == (1, 1, True) and frames[3] == 42
        writer.close()
        await server.close()

    with caplog.at_level(logging.WARNING, logger="ray_tpu._private.rpc"):
        asyncio.run(main())
    assert any("before any HELLO" in r.message for r in caplog.records)
