"""Node-IP advertisement: the direct-call data plane must publish routable addresses.

Round-2 advisor (high): worker direct servers bound 127.0.0.1 and the raylet
published direct_addr=("127.0.0.1", port) into GCS records, so on multi-host
clusters remote peers would dial themselves. Reference pattern:
`python/ray/_private/services.py` get_node_ip_address (UDP-connect trick,
env-overridable) + NodeManager registering its routable node_manager_address.
"""

import socket

import pytest

import ray_tpu


def _host_ip():
    """A non-loopback IP of this host, or None (UDP connect sends no packets)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.254.254.254", 80))
            ip = s.getsockname()[0]
        finally:
            s.close()
        return None if ip.startswith("127.") else ip
    except OSError:
        return None


def test_get_node_ip_resolution(monkeypatch):
    from ray_tpu._private.config import get_node_ip

    monkeypatch.setenv("RAY_TPU_NODE_IP", "10.1.2.3")
    assert get_node_ip() == "10.1.2.3"
    assert get_node_ip("192.168.0.1") == "10.1.2.3"  # env wins over probing
    monkeypatch.delenv("RAY_TPU_NODE_IP")
    # loopback probe host (single-host cluster) never yields a routable IP
    assert get_node_ip("127.0.0.1") == "127.0.0.1"
    assert get_node_ip(None) == "127.0.0.1"


def test_gcs_vets_loopback_direct_addr():
    from ray_tpu._private.gcs import GcsService
    from ray_tpu._private.ids import NodeID

    g = GcsService()

    class _Node:
        def __init__(self, host):
            self.address = (host, 4321)

    routable = NodeID.from_random()
    g.nodes[routable] = _Node("10.0.0.5")
    # loopback direct addr on a routable node is undialable remotely: dropped
    assert g._vet_direct_addr(routable, ("127.0.0.1", 9)) is None
    assert g._vet_direct_addr(routable, ("10.0.0.5", 9)) == ("10.0.0.5", 9)

    local = NodeID.from_random()
    g.nodes[local] = _Node("127.0.0.1")
    # single-host clusters legitimately ride loopback
    assert g._vet_direct_addr(local, ("127.0.0.1", 9)) == ("127.0.0.1", 9)
    assert g._vet_direct_addr(local, None) is None


@pytest.mark.skipif(_host_ip() is None, reason="host has no non-loopback interface")
def test_cluster_advertises_routable_direct_addrs(monkeypatch):
    """End to end: with RAY_TPU_NODE_IP set, GCS actor records carry the routable
    IP (not loopback) in direct_addr and direct actor calls still work."""
    ip = _host_ip()
    monkeypatch.setenv("RAY_TPU_NODE_IP", ip)
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.connect()

        @ray_tpu.remote
        class Echo:
            def ping(self):
                return "pong"

        a = Echo.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"

        from ray_tpu.util.state import list_actors

        [rec] = [r for r in list_actors() if r["state"] == "ALIVE"]
        daddr = (rec["address"] or {}).get("direct_addr")
        assert daddr is not None, "actor should expose a direct addr"
        assert daddr[0] == ip, f"direct_addr advertises {daddr[0]}, want {ip}"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
