"""apisurface: the committed API_SURFACE.json / docs/flags.md drift gate.

The contract surface (actor classes + methods, remote functions, protocol
rosters, GCS verbs, flags) is snapshotted into committed artifacts; this is
the tier-1 test that fails when the surface drifts without regenerating
them. Regeneration is one command: `python -m ray_tpu.devtools.apisurface
--write`.
"""

import json
import os
import shutil
import subprocess
import sys

from ray_tpu.devtools import apisurface


def test_committed_surface_in_sync():
    """THE drift gate: the shipped tree matches the committed snapshot.

    If this fails, either the drift is intentional (regenerate with
    `python -m ray_tpu.devtools.apisurface --write` and commit the result)
    or a change leaked onto the cross-process surface by accident — the
    printed diff names exactly what moved.
    """
    problems = apisurface.check()
    assert problems == [], "\n".join(problems)


def test_surface_build_is_deterministic():
    a = apisurface.render_surface(apisurface.build_surface())
    b = apisurface.render_surface(apisurface.build_surface())
    assert a == b
    doc = json.loads(a)
    # stable top-level shape, sorted keys, trailing newline
    assert list(doc) == sorted(doc)
    assert set(doc) == {"actor_classes", "remote_functions", "protocols",
                        "gcs_verbs", "flags"}
    assert a.endswith("\n")


def test_surface_carries_the_contract_sections():
    doc = json.loads(apisurface.render_surface(apisurface.build_surface()))
    # spot-check each section against known shipped surface members
    assert "RayTrainWorker" in doc["actor_classes"]
    assert "kv_put" in doc["gcs_verbs"]
    assert "llm-stats-surface" in doc["protocols"]
    assert "data_block_target_bytes" in doc["flags"]
    for name, flag in doc["flags"].items():
        assert set(flag) == {"type", "default", "doc", "section"}, name


def test_drift_produces_readable_diff(tmp_path):
    """Mutating a copy of the committed snapshot yields +/-/~ lines that
    name the drifted path, not a bare 'files differ'."""
    root = apisurface.repo_root()
    shutil.copy(os.path.join(root, apisurface.FLAGS_MD),
                tmp_path / "flags.md")
    committed = json.load(open(os.path.join(root, apisurface.SURFACE_FILE)))
    committed["flags"].pop("data_block_target_bytes")
    committed["flags"]["phantom_flag"] = {
        "type": "int", "default": "0", "doc": "never existed", "section": "x",
    }
    os.makedirs(tmp_path / "docs")
    shutil.move(str(tmp_path / "flags.md"), tmp_path / "docs" / "flags.md")
    (tmp_path / apisurface.SURFACE_FILE).write_text(
        json.dumps(committed, indent=2, sort_keys=True) + "\n")
    problems = apisurface.check(root=str(tmp_path))
    text = "\n".join(problems)
    assert "flags.data_block_target_bytes" in text
    assert "flags.phantom_flag" in text
    assert any(p.startswith("+") for p in problems)
    assert any(p.startswith("-") for p in problems)


def test_missing_snapshot_is_drift(tmp_path):
    problems = apisurface.check(root=str(tmp_path))
    assert any(apisurface.SURFACE_FILE in p for p in problems)


def test_flags_md_staleness_gate(tmp_path):
    """docs/flags.md is generated, committed, and part of the same gate:
    a stale copy fails check() with the regeneration command in the
    message."""
    root = apisurface.repo_root()
    shutil.copy(os.path.join(root, apisurface.SURFACE_FILE),
                tmp_path / apisurface.SURFACE_FILE)
    os.makedirs(tmp_path / "docs")
    (tmp_path / "docs" / "flags.md").write_text("# stale by hand\n")
    problems = apisurface.check(root=str(tmp_path))
    stale = [p for p in problems if "flags.md" in p]
    assert stale and "--flags-md" in stale[0]


def test_flags_md_matches_generator():
    root = apisurface.repo_root()
    want = apisurface.render_flags_md(apisurface.build_surface())
    have = open(os.path.join(root, apisurface.FLAGS_MD),
                encoding="utf-8").read()
    assert have == want
    assert "GENERATED" in want  # the do-not-edit banner survives


def test_cli_check_and_usage_exit_codes():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.apisurface", "--check"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "in sync" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.apisurface", "--bogus"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 2
    assert "usage" in proc.stderr


def test_write_roundtrips_to_in_sync(tmp_path):
    os.makedirs(tmp_path / "docs")
    assert apisurface.check(root=str(tmp_path)) != []
    written = apisurface.write(root=str(tmp_path))
    assert len(written) == 2
    assert apisurface.check(root=str(tmp_path)) == []
