"""ray_tpu.llm tests: decode engine correctness + OpenAI-compatible serving.

Shape parity: reference python/ray/llm tests — engine generation, server
deployment, router request shapes, multi-request batching.
"""

import json
import threading
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield
    serve.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps():
    yield
    for app in list(serve.status()):
        serve.delete(app)


def test_engine_matches_full_forward():
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def greedy_full(prompt, n):
        toks = list(prompt)
        for _ in range(n):
            logits = model.apply({"params": params}, jnp.asarray([toks]))
            toks.append(int(jnp.argmax(logits[0, -1])))
        return toks[len(prompt):]

    engine = DecodeEngine(cfg, params, num_slots=2, max_seq=128)
    try:
        results = {}
        done = threading.Event()

        def cb_for(key):
            acc = []

            def cb(tok, fin):
                acc.append(tok)
                if fin:
                    results[key] = acc
                    if len(results) == 2:
                        done.set()

            return cb

        p1, p2 = [5, 9, 17, 3], [8, 2, 44, 7, 19, 21, 6]
        engine.submit(p1, SamplingParams(max_tokens=6), cb_for("a"))
        engine.submit(p2, SamplingParams(max_tokens=6), cb_for("b"))
        assert done.wait(180), results
        assert results["a"] == greedy_full(p1, 6)
        assert results["b"] == greedy_full(p2, 6)
    finally:
        engine.shutdown()


def test_multi_step_decode_stop_rollback_and_slot_reuse():
    """Multi-step decode (N tokens per dispatch, on-device argmax): a
    stop_token firing mid-chunk must roll the slot's device state back to the
    consumed prefix, and the slot's next occupant must decode correctly from
    the rolled-back cache rows."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    # PRNGKey(1), not 0: seed 0's greedy output from this prompt is the
    # constant 121 121 121..., which makes stop == the FIRST token and the
    # engine (correctly) halts at one token while the rollback assertion
    # expects three — the test then "fails" without testing anything. Seed 1
    # gives a non-degenerate reference (asserted below), so the stop really
    # fires mid-chunk and the rollback is exercised for real.
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def greedy_full(prompt, n):
        toks = list(prompt)
        for _ in range(n):
            logits = model.apply({"params": params}, jnp.asarray([toks]))
            toks.append(int(jnp.argmax(logits[0, -1])))
        return toks[len(prompt):]

    def generate(engine, prompt, **sp):
        acc, done = [], threading.Event()

        def cb(tok, fin):
            acc.append(tok)
            if fin:
                done.set()

        engine.submit(prompt, SamplingParams(**sp), cb)
        assert done.wait(180)
        return acc

    prompt = [5, 9, 17, 3]
    ref = greedy_full(prompt, 12)
    stop = ref[2]  # fires mid-chunk for multi_step=8
    assert stop not in ref[:2], (
        "degenerate reference: the stop token must not appear before the "
        "position the rollback assertion depends on"
    )
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=128, multi_step=8)
    try:
        out = generate(engine, prompt, max_tokens=12, stop_token_id=stop)
        assert out == ref[:3], (out, ref)  # stop token emitted, then halt
        # Slot reuse after the rollback: fresh request, full budget.
        prompt2 = [8, 2, 44, 7]
        assert generate(engine, prompt2, max_tokens=10) == greedy_full(prompt2, 10)
    finally:
        engine.shutdown()


def test_llm_server_deployment_generate():
    from ray_tpu.llm import LLMConfig, build_llm_deployment

    app = build_llm_deployment(LLMConfig(model_id="test-tiny", num_slots=2))
    handle = serve.run(app, name="llm", route_prefix=None, _timeout_s=240)
    out = handle.generate.remote("hi", max_tokens=8).result(timeout_s=240)
    assert len(out["token_ids"]) == 8
    assert out["usage"]["prompt_tokens"] == 2
    assert isinstance(out["text"], str)
    # deterministic: same prompt, greedy -> same tokens
    out2 = handle.generate.remote("hi", max_tokens=8).result(timeout_s=120)
    assert out2["token_ids"] == out["token_ids"]
    # concurrent requests share the batch
    rs = [handle.generate.remote(f"p{i}", max_tokens=4) for i in range(6)]
    outs = [r.result(timeout_s=240) for r in rs]
    assert all(len(o["token_ids"]) == 4 for o in outs)


def test_openai_app_http():
    from ray_tpu.llm import LLMConfig, build_openai_app

    app = build_openai_app([LLMConfig(model_id="test-tiny", num_slots=2)])
    serve.run(app, name="openai", route_prefix="/", _timeout_s=240)
    port = serve.get_proxy_port()

    def post(path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=240) as resp:
            return json.loads(resp.read())

    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/models", timeout=120) as r:
        models = json.loads(r.read())
    assert models["data"][0]["id"] == "test-tiny"

    out = post("/v1/completions",
               {"model": "test-tiny", "prompt": "ab", "max_tokens": 5})
    assert out["object"] == "text_completion"
    assert out["usage"]["completion_tokens"] == 5

    chat = post("/v1/chat/completions",
                {"model": "test-tiny",
                 "messages": [{"role": "user", "content": "hello"}],
                 "max_tokens": 5})
    assert chat["object"] == "chat.completion"
    assert chat["choices"][0]["message"]["role"] == "assistant"

    # SSE streaming: "stream": true yields text/event-stream data: events
    # terminated by [DONE] (reference: router.py StreamingResponse path).
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        data=json.dumps({"model": "test-tiny",
                         "messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 5, "stream": True}).encode(),
        method="POST", headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=240) as resp:
        assert resp.headers.get("Content-Type", "").startswith("text/event-stream")
        raw = resp.read().decode()
    events = [ln[len("data: "):] for ln in raw.splitlines() if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    streamed = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert streamed  # tokens actually arrived incrementally


def test_pd_disagg_matches_monolithic():
    """Prefill-elsewhere + decode must produce the same greedy tokens as the
    monolithic engine (KV prefix transfer is lossless)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    prompt = [5, 9, 17, 3, 42, 8]
    n = 6

    mono = DecodeEngine(cfg, params, num_slots=1, max_seq=128)
    prefiller = DecodeEngine(cfg, params, num_slots=1, max_seq=128, decode_loop=False)
    decoder = DecodeEngine(cfg, params, num_slots=2, max_seq=128)
    try:
        def run(engine, submit):
            out = []
            done = threading.Event()

            def cb(tok, fin):
                out.append(tok)
                if fin:
                    done.set()

            submit(cb)
            assert done.wait(180)
            return out

        expect = run(mono, lambda cb: mono.submit(
            prompt, SamplingParams(max_tokens=n), cb))

        first_logits, kv, plen = prefiller.prefill_detached(prompt)
        assert plen == len(prompt)
        got = run(decoder, lambda cb: decoder.submit_prefilled(
            kv, plen, first_logits, SamplingParams(max_tokens=n), cb))
        assert got == expect
    finally:
        mono.shutdown()
        prefiller.shutdown()
        decoder.shutdown()


def test_lora_adapters_batch_independently():
    """Index-0 (base) requests are unchanged by loaded adapters; a nonzero
    adapter alters generation; both kinds batch together in one engine."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = [7, 21, 3, 9]
    n = 5

    def run(engine, lora=""):
        out = []
        done = threading.Event()

        def cb(tok, fin):
            out.append(tok)
            if fin:
                done.set()

        engine.submit(prompt, SamplingParams(max_tokens=n), cb, lora=lora)
        assert done.wait(180)
        return out

    base_engine = DecodeEngine(cfg, params, num_slots=2, max_seq=128)
    lora_engine = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128,
        lora_config={"max_loras": 2, "rank": 4},
    )
    try:
        base_out = run(base_engine)
        assert run(lora_engine) == base_out  # engine with lora enabled, base request

        # A strong random adapter on q/v of layer 0 must change the output.
        rng = np.random.default_rng(0)
        r = 4
        w = {0: {
            "q_A": rng.normal(size=(cfg.hidden, r)).astype(np.float32) * 2.0,
            "q_B": rng.normal(size=(r, cfg.n_heads * cfg.head_dim)).astype(np.float32) * 2.0,
            "v_A": rng.normal(size=(cfg.hidden, r)).astype(np.float32) * 2.0,
            "v_B": rng.normal(size=(r, cfg.n_kv_heads * cfg.head_dim)).astype(np.float32) * 2.0,
        }}
        lora_engine.add_lora("tuned", w, alpha=8.0)
        tuned_out = run(lora_engine, lora="tuned")
        assert tuned_out != base_out
        # Base requests remain unaffected after the adapter loaded.
        assert run(lora_engine) == base_out
    finally:
        base_engine.shutdown()
        lora_engine.shutdown()


def test_pd_disagg_app_end_to_end():
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.pd_disagg import build_pd_openai_app

    app = build_pd_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128),
        num_prefill=1, num_decode=1,
    )
    handle = serve.run(app, name="pd_app", route_prefix=None)
    resp = handle.generate.remote("hello world", max_tokens=8).result(timeout_s=300)
    assert len(resp["token_ids"]) == 8
    assert resp["usage"]["completion_tokens"] == 8
    assert resp["prefill_s"] > 0
    serve.delete("pd_app")


def test_speculative_decode_correct_and_faster():
    """Spec decode (draft-k scan + single verify) emits exactly the greedy
    sequence and beats plain decode tokens/s at batch 1 (VERDICT r2 #9;
    reference: vLLM speculative decoding). A self-draft makes every proposal
    accepted, so the speedup bound is deterministic: k+1 tokens for ~2-3
    dispatches vs one per token."""
    import time

    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # The plain engine is the greedy reference: test_engine_matches_full_forward
    # already proves it bit-exact against the unjitted full forward.
    prompt, N = [5, 9, 17, 3], 96

    def run(engine):
        out, done, marks = [], threading.Event(), []

        def cb(tok, fin):
            if not out:
                marks.append(time.monotonic())  # first token: decode begins
            out.append(tok)
            if fin:
                marks.append(time.monotonic())
                done.set()

        # warm the programs with one full generation, then take best-of-3
        # timings (this 1-core CI host runs cluster daemons concurrently;
        # min-time is the standard noise-robust estimator)
        engine.submit(prompt, SamplingParams(max_tokens=N), cb)
        assert done.wait(300)
        first = list(out)
        times, last = [], None
        for _ in range(3):
            out.clear(); done.clear(); marks.clear()
            engine.submit(prompt, SamplingParams(max_tokens=N), cb)
            assert done.wait(300)
            # decode tokens/s: first-token -> done (prefill/admit excluded)
            times.append(marks[-1] - marks[0])
            last = list(out)
        return first, last, min(times)

    # multi_step=1: the spec-decode claim is against per-token dispatch (its
    # design point). Multi-step greedy decode is a separate optimization that
    # reaches similar dispatch savings without a draft model.
    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128, multi_step=1)
    try:
        _, plain_toks, plain_t = run(plain)
    finally:
        plain.shutdown()
    spec = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128,
        spec_config={"num_spec_tokens": 6},  # self-draft: all accepted
    )
    try:
        spec_first, spec_toks, spec_t = run(spec)
    finally:
        spec.shutdown()

    expected = plain_toks
    assert len(expected) == N
    assert spec_first == expected and spec_toks == expected
    speedup = plain_t / spec_t
    assert speedup >= 1.5, f"spec decode {speedup:.2f}x (plain {plain_t:.2f}s, spec {spec_t:.2f}s)"


def test_dp_serving_routes_across_replicas():
    """Data-parallel serving: dp_size=2 engine replicas claim distinct ranks
    and concurrent requests reach BOTH (VERDICT r2 #9; reference:
    deployments/data_parallel/dp_server.py + dp_rank_assigner.py)."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=2
    )
    handle = serve.run(app, name="dp-llm", route_prefix=None, _timeout_s=300)

    ranks = handle.ranks.remote().result(timeout_s=120)
    assert sorted(ranks.values()) == [0, 1], ranks

    rs = [handle.generate.remote(f"req {i}", max_tokens=4) for i in range(12)]
    outs = [r.result(timeout_s=300) for r in rs]
    assert all(len(o["token_ids"]) == 4 for o in outs)
    seen = {o["dp_rank"] for o in outs}
    assert seen == {0, 1}, f"requests reached only ranks {seen}"
    # determinism across ranks: same prompt, greedy -> same tokens everywhere
    a = handle.generate.remote("same", max_tokens=6).result(timeout_s=120)
    b = handle.generate.remote("same", max_tokens=6).result(timeout_s=120)
    assert a["token_ids"] == b["token_ids"]
