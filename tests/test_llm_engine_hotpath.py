"""Decode-engine hot-path regressions: bounded jit-program caches and a
host-native decode loop (the two compute-plane fixes jaxlint RL602/RL603
gate — see docs/raylint.md "writing jit-safe hot paths")."""

import threading

import numpy as np
import pytest


def _tiny_engine(**kwargs):
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import DecodeEngine
    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return DecodeEngine(cfg, params, **kwargs)


def _generate(engine, prompt, lora="", **sp):
    from ray_tpu.llm import SamplingParams

    acc, done = [], threading.Event()

    def cb(tok, fin):
        acc.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(**sp), cb, lora=lora)
    assert done.wait(180), engine.error
    return acc


def test_jit_program_cache_bounded_under_adversarial_length_mix(monkeypatch):
    """An adversarial prompt-length mix (every bucket distinct) must not grow
    the compiled-program caches past llm_max_jit_programs — and an evicted
    program must rebuild with identical numerics when its bucket returns."""
    from ray_tpu._private.config import CONFIG

    monkeypatch.setitem(CONFIG._cache, "llm_prefill_bucket_min", 2)
    monkeypatch.setitem(CONFIG._cache, "llm_max_jit_programs", 3)
    monkeypatch.setitem(CONFIG._cache, "llm_prefix_cache_bytes", 0)
    engine = _tiny_engine(num_slots=1, max_seq=64, decode_loop=False)
    try:
        assert engine._prefill_buckets == (2, 4, 8, 16, 32, 64)
        first_ref, _, _ = engine.prefill_detached([5, 9])
        lengths = (3, 5, 9, 17, 33)  # buckets 4, 8, 16, 32, 64
        for n in lengths:
            engine.prefill_detached(list(range(1, n + 1)))
            assert len(engine._jit_prefill) <= 3, engine._jit_prefill.keys()
        # bucket-2 program was evicted along the way; re-running the same
        # prompt re-jits and must reproduce the original logits exactly
        assert ("detached", 2) not in engine._jit_prefill
        first_again, _, _ = engine.prefill_detached([5, 9])
        np.testing.assert_allclose(first_ref, first_again, rtol=1e-5)
        assert len(engine._jit_prefill) <= 3
        # The eviction rebuild IS the planted retrace the program registry
        # exists to catch: the bucket-2 key compiled twice, and exactly the
        # rebuild shows up as a recompile (xla_recompiles_total's source).
        rows = {r["key"]: r
                for r in engine._xprof.report(owner=engine._xprof_owner)["programs"]}
        bucket2 = rows[("detached", 2)]
        assert bucket2["compiles"] == 2 and bucket2["recompiles"] == 1, bucket2
    finally:
        engine.shutdown()


def test_jit_program_cache_bounded_under_adversarial_chunk_mix(monkeypatch):
    """Chunked prefill must add ZERO program-cache growth: an adversarial
    prompt-length mix driven through the scheduler with a tiny token budget
    (so every prompt splits into chunks) draws every chunk shape from the
    bucket table, and the spec plane's verify program is keyed only by k —
    all through the capped `_program` helper."""
    from ray_tpu._private.config import CONFIG

    monkeypatch.setitem(CONFIG._cache, "llm_prefill_bucket_min", 4)
    monkeypatch.setitem(CONFIG._cache, "llm_max_jit_programs", 3)
    monkeypatch.setitem(CONFIG._cache, "llm_prefix_cache_bytes", 0)
    engine = _tiny_engine(num_slots=2, max_seq=64, token_budget=4,
                          prefix_cache=False,
                          spec_config={"method": "ngram", "num_spec_tokens": 3})
    try:
        assert engine._prefill_buckets == (4, 8, 16, 32, 64)
        for n in (3, 5, 9, 17, 33, 21, 13):   # every bucket, revisited
            out = _generate(engine, list(range(1, n + 1)), max_tokens=2)
            assert len(out) == 2
            assert len(engine._jit_prefill) <= 3, engine._jit_prefill.keys()
            assert len(engine._jit_spec_verify) <= 1
        stats = engine.scheduler_stats()
        assert stats["prefill_chunks"] > 7  # the mix really was chunked
    finally:
        engine.shutdown()


def test_jit_program_cap_zero_is_unbounded(monkeypatch):
    from ray_tpu._private.config import CONFIG

    monkeypatch.setitem(CONFIG._cache, "llm_prefill_bucket_min", 2)
    monkeypatch.setitem(CONFIG._cache, "llm_max_jit_programs", 0)
    monkeypatch.setitem(CONFIG._cache, "llm_prefix_cache_bytes", 0)
    engine = _tiny_engine(num_slots=1, max_seq=64, decode_loop=False)
    try:
        for n in (2, 3, 5, 9, 17):
            engine.prefill_detached(list(range(1, n + 1)))
        assert len(engine._jit_prefill) == 5
    finally:
        engine.shutdown()


def test_adapter_paging_adds_zero_programs_under_churn(monkeypatch):
    """Paging adapters through a smaller-than-registry device table must not
    grow ANY program cache: churn across 6 adapters on 2 slots re-uses the
    same prefill/decode programs and exactly ONE adapter-install trace (the
    RL602/RL604 contract: slot index is a traced scalar, blob shapes are
    fixed at construction). See docs/multitenancy.md."""
    import numpy as np

    from ray_tpu._private.config import CONFIG

    monkeypatch.setitem(CONFIG._cache, "llm_prefix_cache_bytes", 0)
    engine = _tiny_engine(
        num_slots=2, max_seq=64, decode_loop=True, prefix_cache=False,
        lora_config={"max_loras": 6, "rank": 2, "cache_slots": 2},
    )
    try:
        hidden = engine.cfg.hidden
        for i in range(6):
            engine.add_lora(f"a{i}", {0: {"q_A": np.random.default_rng(i).normal(
                size=(hidden, 2)).astype(np.float32)}}, alpha=4.0)
        _generate(engine, [5, 9, 17], max_tokens=2)   # warm base programs
        programs = len(engine._jit_prefill)
        # churn: every adapter twice through the 2-slot budget
        for _ in range(2):
            for i in range(6):
                _generate(engine, [5, 9, 17], max_tokens=2, lora=f"a{i}")
        stats = engine.adapter_stats()
        assert stats["evictions"] > 0, stats       # churn really paged
        assert stats["install_programs"] in (1, None), stats
        assert len(engine._jit_prefill) == programs, (
            "adapter paging grew the prefill program cache"
        )
    finally:
        engine.shutdown()


class _NpSpy:
    """Stand-in for the engine module's `np` that counts device->host pulls
    (np.asarray/np.array on jax Arrays) and delegates everything else."""

    def __init__(self):
        import jax

        self._jax = jax
        self.device_pulls = 0

    def __getattr__(self, name):
        return getattr(np, name)

    def asarray(self, x, *args, **kwargs):
        if isinstance(x, self._jax.Array):
            self.device_pulls += 1
        return np.asarray(x, *args, **kwargs)

    def array(self, x, *args, **kwargs):
        if isinstance(x, self._jax.Array):
            self.device_pulls += 1
        return np.array(x, *args, **kwargs)


def test_decode_loop_is_host_native_one_pull_per_dispatch(monkeypatch):
    """The micro-assert for the decode loop: slot bookkeeping (lens,
    last_token, adapter ids) lives host-side, decode never calls
    jax.device_get, and the ONLY device->host transfer per decode dispatch
    is the batched logits readback — so max_tokens tokens cost exactly
    1 admission pull + (max_tokens - 1) decode pulls."""
    import jax

    from ray_tpu.llm import _engine as engine_mod

    spy = _NpSpy()
    monkeypatch.setattr(engine_mod, "np", spy)

    def _no_device_get(*a, **k):  # decode path must never block through this
        raise AssertionError("jax.device_get called in the decode path")

    monkeypatch.setattr(jax, "device_get", _no_device_get)

    # multi_step=1 pins one dispatch per token (the tightest accounting)
    engine = _tiny_engine(num_slots=2, max_seq=64, multi_step=1,
                          prefix_cache=False)
    try:
        assert isinstance(engine._lens, np.ndarray)
        assert isinstance(engine._last_token, np.ndarray)
        assert isinstance(engine._adapter_ids, np.ndarray)
        # The flight recorder must be LIVE for this accounting: the bound
        # being asserted is that per-request observability adds zero
        # device syncs to the decode loop (docs/observability.md).
        assert engine._recorder.capacity > 0
        max_tokens = 8
        out = _generate(engine, [5, 9, 17, 3], max_tokens=max_tokens)
        assert len(out) == max_tokens
        assert spy.device_pulls == max_tokens  # 1 admission + 7 decode steps
        # host mirrors advanced without ever pulling device state
        assert int(engine._lens[0]) == 4 + max_tokens - 1
        assert int(engine._last_token[0]) == out[-1]
        # ...and the recorder really observed the request (phases + every
        # token timestamped) without a single extra pull showing up above.
        rec = engine._recorder.records()[-1]
        assert rec["tokens"] == max_tokens
        assert "prefill-chunk" in rec["phases"] and "decode" in rec["phases"]
    finally:
        engine.shutdown()


def test_observability_reports_add_zero_pulls_and_zero_programs(monkeypatch):
    """The round-18 micro-assert: the program registry and device-memory
    ledger ride the existing report paths — exercising scheduler_stats()
    (which now carries both reports) against a WARM engine adds zero
    device->host pulls, zero compiled programs, and zero recompiles, and a
    warm generate after the reports costs exactly its token accounting."""
    from ray_tpu.llm import _engine as engine_mod

    spy = _NpSpy()
    monkeypatch.setattr(engine_mod, "np", spy)
    engine = _tiny_engine(num_slots=2, max_seq=64, multi_step=1,
                          prefix_cache=False)
    try:
        _generate(engine, [5, 9, 17, 3], max_tokens=4)  # warm every program
        programs = len(engine._jit_prefill)
        pulls = spy.device_pulls
        recompiles_before = engine._xprof.recompiles_total
        for _ in range(2):
            stats = engine.scheduler_stats()
        assert spy.device_pulls == pulls, "stats reports pulled device state"
        assert len(engine._jit_prefill) == programs
        assert engine._xprof.recompiles_total == recompiles_before
        # the reports really flowed: registry rows for this engine's owner
        # and a ledger row attributing its KV bytes
        prog_report = stats["programs"]
        assert prog_report["totals"]["programs"] > 0
        assert all(r["owner"] == engine._xprof_owner
                   for r in prog_report["programs"])
        mem = stats["memory"]
        owner_row = mem["owners"][engine._xprof_owner]
        assert owner_row["components"]["kv_slots"] > 0
        assert mem["tracked_bytes_total"] >= owner_row["bytes"]
        # a warm generate after the reports stays at the exact pull bound
        out = _generate(engine, [5, 9, 17, 3], max_tokens=4)
        assert len(out) == 4
        assert spy.device_pulls == pulls + 4  # 1 admission + 3 decode steps
    finally:
        engine.shutdown()


def test_multi_step_decode_single_pull_per_chunk(monkeypatch):
    """Multi-step chunks amortize further: n tokens per dispatch -> one
    batched token readback per CHUNK, never a lens/last_token pull."""
    import jax

    from ray_tpu.llm import _engine as engine_mod

    spy = _NpSpy()
    monkeypatch.setattr(engine_mod, "np", spy)
    engine = _tiny_engine(num_slots=1, max_seq=64, multi_step=4,
                          prefix_cache=False)
    try:
        out = _generate(engine, [5, 9, 17, 3], max_tokens=9)
        assert len(out) == 9
        # 1 admission pull + ceil(8 / 4) = 2 chunk pulls
        assert spy.device_pulls == 3
    finally:
        engine.shutdown()
