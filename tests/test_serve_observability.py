"""Serve-plane observability (docs/observability.md): cross-process trace
propagation through the real serve path, response timing metadata with the
DP routing reason, and the one-call `serve_stats()` operator snapshot."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from tests.conftest import _WORKER_ENV

# Tracing must be on in EVERY serve process (proxy/router/replica), not just
# the driver: enabled() reads this env in each worker.
_TRACED_ENV = {**_WORKER_ENV, "RAY_TPU_TRACING": "1"}


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    from ray_tpu.util import tracing

    ray_tpu.init(num_cpus=4, num_tpus=0, worker_env=_TRACED_ENV)
    tracing.enable()
    yield
    tracing.disable()
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _fresh_apps():
    yield
    for app in list(serve.status()):
        serve.delete(app)


def _events_for_trace(trace_id, expect=(), deadline_s=60):
    """Poll the GCS task-event pipeline until the trace carries every
    expected span name (worker event buffers flush on independent 5s
    timers, so different processes' spans land in different batches)."""
    w = ray_tpu.global_worker()

    def have(events, name):
        return any(
            (e.get("name") or "").startswith(name[:-1]) if name.endswith("*")
            else e.get("name") == name
            for e in events
        )

    deadline = time.monotonic() + deadline_s
    events = []
    while time.monotonic() < deadline:
        events = [e for e in w.gcs_call("list_task_events", 100000)
                  if e.get("trace_id") == trace_id]
        if events and all(have(events, n) for n in expect):
            return events
        time.sleep(1.0)
    return events


def test_http_request_yields_one_cross_process_span_tree():
    """One traced HTTP request -> ONE trace_id whose span tree covers
    proxy (http span) -> router -> replica task spans -> the engine's named
    phases (queue/admit/prefill-chunk/decode), spanning >= 2 processes."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app
    from ray_tpu.util.tracing_export import spans_from_task_events

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=1
    )
    handle = serve.run(app, name="obs-dp", route_prefix="/", _timeout_s=300)
    port = serve.get_proxy_port()

    body = json.dumps({
        "prompt": "a traced request with enough bytes to fingerprint blocks",
        "max_tokens": 4,
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        out = json.loads(resp.read())
    assert len(out["token_ids"]) == 4
    # per-request timing breakdown rides the response metadata
    assert out["timing"]["tokens"] == 4
    assert out["timing"]["trace_id"], out["timing"]
    trace_id = out["timing"]["trace_id"]

    # The report path is what flushes recorder spans to the event pipeline.
    handle.recorder_stats.remote().result(timeout_s=120)

    events = _events_for_trace(
        trace_id, expect=("http:*", "llm:request", "llm:decode"))
    names = {e.get("name") for e in events}
    assert any(n and n.startswith("http:") for n in names), names  # proxy
    assert "llm:request" in names, names
    assert {"llm:queued", "llm:admitted", "llm:decode"} <= names, names
    assert "llm:prefill-chunk" in names, names
    workers = {e.get("worker_id") for e in events if e.get("worker_id")}
    assert len(workers) >= 2, f"trace stayed in one process: {workers}"

    # And the tree is connected: pair events into spans, walk parent links.
    spans = spans_from_task_events(events)
    by_id = {s["span_id"]: s for s in spans}
    req_span = next(s for s in spans if s["name"] == "llm:request")
    # llm:request hangs off the replica's generate/handle_request task span
    assert req_span["parent_span_id"] in by_id, "request root is an orphan"
    for s in spans:
        if s["name"].startswith("llm:") and s["name"] != "llm:request":
            assert s["parent_span_id"] == req_span["span_id"]
    assert len({s["trace_id"] for s in spans}) == 1


def test_dp_routing_reason_in_timing_metadata():
    """The DP router's pick reason (balanced/cache_routed/...) rides into
    the replica's flight record and back out in response metadata."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=1
    )
    handle = serve.run(app, name="obs-route", route_prefix=None,
                       _timeout_s=300)
    prompt = "a shared system prompt long enough to cover whole kv blocks"
    first = handle.generate.remote(prompt, max_tokens=2).result(timeout_s=300)
    again = handle.generate.remote(prompt, max_tokens=2).result(timeout_s=300)
    assert first["timing"]["route"] in ("balanced", "cache_routed",
                                        "adapter_routed")
    assert again["timing"]["route"] == "cache_routed", again["timing"]
    assert "prefill-chunk" in again["timing"]["phases"]


def test_pd_prefill_and_decode_spans_share_trace():
    """A PD-disaggregated request's prefill-side and decode-side flight
    records share ONE trace: the span set covers both replica processes."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.pd_disagg import build_pd_openai_app
    from ray_tpu.util import tracing

    app = build_pd_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128),
        num_prefill=1, num_decode=1,
    )
    handle = serve.run(app, name="obs-pd", route_prefix=None, _timeout_s=300)
    with tracing.trace("pd-request") as root:
        out = handle.generate.remote(
            "disaggregated traced request", max_tokens=3
        ).result(timeout_s=300)
    assert len(out["token_ids"]) == 3
    assert out["timing"] is not None and "pd-attach" in out["timing"]["phases"]
    handle.recorder_stats.remote().result(timeout_s=120)

    events = _events_for_trace(
        root["trace_id"],
        expect=("llm:prefill-detached", "llm:pd-attach", "llm:decode"))
    names = {e.get("name") for e in events}
    assert "llm:prefill-detached" in names, names   # prefill-side engine
    assert "llm:pd-attach" in names, names          # decode-side engine
    assert "llm:decode" in names, names
    # two llm:request roots (one per phase engine), one shared trace
    roots = [e for e in events if e.get("name") == "llm:request"]
    assert len({e["trace_id"] for e in roots}) == 1
    workers = {e.get("worker_id") for e in events if e.get("worker_id")}
    assert len(workers) >= 2, workers


def test_serve_stats_one_call_snapshot():
    """ray_tpu.util.state.serve_stats() aggregates the scattered surfaces
    (scheduler/adapter/routing/cache/recorder + transport + control plane)
    into one operator snapshot."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app
    from ray_tpu.util.state import serve_stats

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=1
    )
    handle = serve.run(app, name="obs-stats", route_prefix=None,
                       _timeout_s=300)
    handle.generate.remote("warm request", max_tokens=2).result(timeout_s=300)

    snap = serve_stats(timeout_s=120)
    assert "obs-stats" in snap["apps"], snap["apps"].keys()
    app_stats = snap["apps"]["obs-stats"]
    assert "scheduler_stats" in app_stats     # replica scheduler occupancy
    assert "routing_stats" in app_stats       # DP router pick counters
    assert "recorder_stats" in app_stats      # flight recorder counters
    rec = app_stats["recorder_stats"][0]
    assert rec["started"] >= 1
    sched = app_stats["scheduler_stats"][0]
    assert sched["iterations"] >= 1 and "recorder" in sched
    assert isinstance(snap["transport"], dict)
    assert isinstance(snap["control_plane"], dict)


def test_capture_profile_round_trip_on_live_replicas(tmp_path):
    """`ray_tpu.util.state.capture_profile` starts a trace capture on every
    replica of a DP=2 app simultaneously (two live worker processes) and
    gathers non-empty trace artifacts back to the driver, writing them under
    out_dir/<app>/rank<k>/."""
    import os

    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app
    from ray_tpu.util.state import capture_profile

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=2
    )
    handle = serve.run(app, name="obs-prof", route_prefix=None,
                       _timeout_s=300)
    handle.generate.remote("warm request", max_tokens=2).result(timeout_s=300)

    rows = capture_profile(["obs-prof"], duration_s=0.3,
                           out_dir=str(tmp_path))
    (row,) = rows
    assert row["target"] == "obs-prof"
    assert "error" not in row, row
    caps = row["capture"]
    assert isinstance(caps, list) and len(caps) == 2, caps  # DP fan-out
    ranks = {c["dp_rank"] for c in caps}
    assert ranks == {0, 1}, ranks
    for c in caps:
        assert c["files"], c                 # non-empty trace artifacts
        assert "capture_manifest.json" in c["files"]
        assert c["manifest"]["duration_s"] >= 0.3
    assert row["gathered"], row
    for path in row["gathered"]:
        assert os.path.isfile(path) and os.path.getsize(path) > 0
    # both ranks' artifacts landed in distinct per-rank dirs
    rank_dirs = {os.path.relpath(p, tmp_path).split(os.sep)[1]
                 for p in row["gathered"]}
    assert rank_dirs == {"rank0", "rank1"}, rank_dirs
    # a bogus target reports its error without failing the sweep
    bad = capture_profile(["no-such-app"], duration_s=0.1)
    assert "error" in bad[0]


def test_status_cli_smoke_on_live_cluster(capsys):
    """`ray_tpu status` against the running mini-cluster: exits cleanly and
    renders the node/actor/serve/program/memory sections from one
    cluster_status() snapshot (the acceptance smoke for the operator CLI).
    Reuses the test's driver connection — cmd_status skips the address file
    when ray_tpu is already initialized."""
    import argparse

    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app
    from ray_tpu.scripts.scripts import cmd_status, main

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=1
    )
    handle = serve.run(app, name="obs-cli", route_prefix=None,
                       _timeout_s=300)
    handle.generate.remote("warm request", max_tokens=2).result(timeout_s=300)

    main(["status"])  # raises on nonzero exit; smoke = it renders
    text = capsys.readouterr().out
    for section in ("== nodes ==", "== actors ==", "== serve ==",
                    "== programs (driver) ==", "== memory (driver) =="):
        assert section in text, text[:2000]
    assert "obs-cli" in text                  # the live app shows up
    assert "ALIVE" in text                    # node listing rendered
    assert ray_tpu.is_initialized()           # borrowed connection kept open

    cmd_status(argparse.Namespace(json=True))
    snapshot = json.loads(capsys.readouterr().out)
    assert snapshot["summary"]["alive_nodes"] >= 1
    assert "obs-cli" in snapshot["serve"]["apps"]
    assert "programs" in snapshot and "memory" in snapshot
