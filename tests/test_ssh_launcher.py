"""SSH cluster launcher: `ray_tpu up` provisions worker hosts over SSH.

Shape parity: reference python/ray/tests/test_cli.py + the NodeUpdater
provisioning path of autoscaler/_private/commands.py — here driven end to end
with a fake ssh/rsync that executes locally, so the FULL phase sequence
(rsync file mounts -> setup commands -> remote start joined to the head) runs
against real node processes.
"""

import json
import os
import signal
import stat
import subprocess
import sys
import time

import pytest


FAKE_SSH = """#!/bin/sh
# fake ssh: drop the host argument, run the command locally.
echo "$1" >> {log}
shift
exec sh -c "$1"
"""

FAKE_RSYNC = """#!/bin/sh
# fake rsync: -az local host:remote -> cp
shift
src="$1"
dst="${2#*:}"
mkdir -p "$dst"
cp -r "$src" "$dst"
"""


@pytest.fixture
def fake_remote(tmp_path):
    ssh_log = tmp_path / "ssh_hosts.log"
    ssh = tmp_path / "fake_ssh"
    ssh.write_text(FAKE_SSH.format(log=ssh_log))
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    rsync = tmp_path / "fake_rsync"
    rsync.write_text(FAKE_RSYNC)
    rsync.chmod(rsync.stat().st_mode | stat.S_IEXEC)
    return {"ssh": str(ssh), "rsync": str(rsync), "log": str(ssh_log)}


def test_ssh_provider_provision_phases(fake_remote, tmp_path):
    """Unit: rsync mounts land in target_dir, setup commands run, the start
    command receives the substituted head address, terminate stops the node."""
    from ray_tpu.autoscaler.ssh import SSHNodeProvider

    target = tmp_path / "remote"
    payload = tmp_path / "payload"
    payload.mkdir()
    (payload / "data.txt").write_text("shipped")
    provider = SSHNodeProvider(
        {
            "hosts": ["hostA", "hostB"],
            "target_dir": str(target),
            "file_mounts": {"files": str(payload)},
            "setup_commands": ["echo setup-ran > setup.marker"],
            "worker_start_command": "echo started-{address} > start.marker",
        },
        head_address="10.0.0.1:6379",
        ssh_cmd=[fake_remote["ssh"]],
        rsync_cmd=[fake_remote["rsync"]],
    )
    nid = provider.create_node({"CPU": 1})
    assert provider.non_terminated_nodes() == [nid]
    assert (target / "files" / "payload" / "data.txt").read_text() == "shipped"
    assert (target / "setup.marker").read_text().strip() == "setup-ran"
    deadline = time.time() + 10
    while time.time() < deadline and not (target / "start.marker").exists():
        time.sleep(0.1)
    assert (target / "start.marker").read_text().strip() == "started-10.0.0.1:6379"
    # both hosts provisioned distinctly
    nid2 = provider.create_node({"CPU": 1})
    assert provider.cluster_address(nid) == ("hostA", 0)
    assert provider.cluster_address(nid2) == ("hostB", 0)
    with pytest.raises(RuntimeError, match="exhausted"):
        provider.create_node({"CPU": 1})
    provider.terminate_node(nid)
    assert provider.non_terminated_nodes() == [nid2]
    hosts_seen = open(fake_remote["log"]).read()
    assert "hostA" in hosts_seen and "hostB" in hosts_seen


def test_ray_tpu_up_ssh_two_host_cluster(fake_remote, tmp_path):
    """E2E: `ray_tpu up` with an ssh provider brings a head + 2 fake-SSH
    "hosts" online from YAML; every provisioned node registers with the GCS."""
    import yaml

    target_a = tmp_path / "host_a"
    target_b = tmp_path / "host_b"
    # One target dir per "host": the fake ssh runs locally, so distinct dirs
    # stand in for distinct machines. worker_start uses this module's python.
    config = {
        "cluster_name": "ssh-e2e",
        # head.host pinned to loopback: the fake-ssh "hosts" run locally, and
        # this sandbox's egress-interface probe returns an unreachable IP.
        "head": {"num_cpus": 1, "host": "127.0.0.1"},
        "provider": {
            "type": "ssh",
            "hosts": ["127.0.0.1"],
            "ssh_cmd": [fake_remote["ssh"]],
            "rsync_cmd": [fake_remote["rsync"]],
            "target_dir": str(target_a),
            "setup_commands": ["echo setup-ran > setup.marker"],
            "worker_start_command": (
                f"{sys.executable} -m ray_tpu.scripts.scripts start "
                "--address={address} --num-cpus=1"
            ),
        },
        "workers": {"min_workers": 1, "max_workers": 1, "resources": {"CPU": 1}},
    }
    del target_b  # single remote host keeps the 1-core CI load sane
    cfg_path = tmp_path / "cluster.yaml"
    cfg_path.write_text(yaml.safe_dump(config))
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["TMPDIR"] = str(tmp_path)  # isolate the head address file
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PALLAS_AXON_POOL_IPS"] = ""
    up = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.scripts", "up", str(cfg_path)],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        start_new_session=True,
    )
    addr_file = tmp_path / "ray_tpu" / "head_address.json"
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not addr_file.exists():
            if up.poll() is not None:
                pytest.fail(f"up exited early:\n{up.stdout.read()}")
            time.sleep(0.5)
        assert addr_file.exists(), "head never wrote its address file"
        addr = json.loads(addr_file.read_text())
        gcs_port = addr["gcs_port"]

        import ray_tpu

        os.environ["RAY_TPU_RAYLET_PORT"] = str(addr["raylet_port"])
        ray_tpu.init(address=f"127.0.0.1:{gcs_port}")
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                nodes = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(nodes) >= 2:  # head + the SSH-provisioned worker
                    break
                time.sleep(1.0)
            assert len(nodes) >= 2, f"worker never joined: {nodes}"
            # the provisioning phases really ran on the "remote" host
            assert (target_a / "setup.marker").read_text().strip() == "setup-ran"
            # and the joined node is schedulable
            @ray_tpu.remote(num_cpus=1)
            def where():
                return "ok"

            assert ray_tpu.get(where.remote(), timeout=120) == "ok"
        finally:
            ray_tpu.shutdown()
            os.environ.pop("RAY_TPU_RAYLET_PORT", None)
    finally:
        try:
            os.killpg(up.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            up.wait(timeout=30)
        except subprocess.TimeoutExpired:
            os.killpg(up.pid, signal.SIGKILL)
