"""Actor concurrency groups + out-of-order execution.

Shape parity with the reference suite (python/ray/tests/test_concurrency_group.py):
group isolation (a blocked group cannot starve another), per-group limits,
in-group ordering, method->group binding via @ray_tpu.method, per-call
.options(concurrency_group=...), async-actor compatibility, and the explicit
out-of-order mode (reference: out_of_order_actor_submit_queue.cc).
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_group_isolation_blocked_compute_does_not_starve_io():
    """compute (limit 1) blocks until an io call lands: only possible if io
    runs on its own pool while compute holds its thread."""

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class A:
        def __init__(self):
            self.flag = False

        @ray_tpu.method(concurrency_group="compute")
        def wait_for_flag(self):
            deadline = time.monotonic() + 60
            while not self.flag:
                if time.monotonic() > deadline:
                    return "timeout"
                time.sleep(0.01)
            return "released"

        @ray_tpu.method(concurrency_group="io")
        def set_flag(self):
            self.flag = True
            return "set"

    a = A.remote()
    blocked = a.wait_for_flag.remote()
    time.sleep(0.5)  # compute is now parked in its group's only thread
    assert ray_tpu.get(a.set_flag.remote(), timeout=60) == "set"
    assert ray_tpu.get(blocked, timeout=60) == "released"
    ray_tpu.kill(a)


def test_group_limit_and_in_group_ordering():
    """A limit-1 group executes its calls strictly in submission order; a
    limit-2 group overlaps its calls."""

    @ray_tpu.remote(concurrency_groups={"solo": 1, "pair": 2})
    class B:
        def __init__(self):
            self.order = []

        @ray_tpu.method(concurrency_group="solo")
        def seq(self, i):
            self.order.append(i)
            time.sleep(0.05)
            return i

        @ray_tpu.method(concurrency_group="pair")
        def overlap(self):
            t0 = time.monotonic()
            time.sleep(0.5)
            return (t0, time.monotonic())

        def get_order(self):
            return self.order

    b = B.remote()
    refs = [b.seq.remote(i) for i in range(8)]
    ray_tpu.get(refs, timeout=60)
    assert ray_tpu.get(b.get_order.remote(), timeout=60) == list(range(8))
    spans = ray_tpu.get([b.overlap.remote(), b.overlap.remote()], timeout=60)
    (s0, e0), (s1, e1) = spans
    assert max(s0, s1) < min(e0, e1), "limit-2 group calls did not overlap"
    ray_tpu.kill(b)


def test_per_call_options_concurrency_group():
    """.options(concurrency_group=...) routes an unbound method into a group
    (reference: actor_method.options in python/ray/actor.py)."""

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class C:
        def __init__(self):
            self.flag = False

        def block(self):  # default group (max_concurrency=1)
            deadline = time.monotonic() + 60
            while not self.flag:
                if time.monotonic() > deadline:
                    return "timeout"
                time.sleep(0.01)
            return "released"

        def poke(self):
            self.flag = True
            return "ok"

    c = C.remote()
    blocked = c.block.remote()
    time.sleep(0.3)
    # Default pool is busy; routing poke through "io" unblocks it.
    assert ray_tpu.get(
        c.poke.options(concurrency_group="io").remote(), timeout=60
    ) == "ok"
    assert ray_tpu.get(blocked, timeout=60) == "released"
    ray_tpu.kill(c)


def test_async_actor_group_limits():
    """Async actors honor per-group semaphores: a limit-1 group serializes
    coroutines while the default group stays wide."""

    @ray_tpu.remote(concurrency_groups={"solo": 1})
    class D:
        @ray_tpu.method(concurrency_group="solo")
        async def solo(self):
            import asyncio

            t0 = time.monotonic()
            await asyncio.sleep(0.4)
            return (t0, time.monotonic())

        async def wide(self):
            import asyncio

            t0 = time.monotonic()
            await asyncio.sleep(0.4)
            return (t0, time.monotonic())

    d = D.remote()
    solos = ray_tpu.get([d.solo.remote(), d.solo.remote()], timeout=60)
    (s0, e0), (s1, e1) = solos
    assert min(e0, e1) <= max(s0, s1) + 0.05, "limit-1 async group overlapped"
    wides = ray_tpu.get([d.wide.remote(), d.wide.remote()], timeout=60)
    (s0, e0), (s1, e1) = wides
    assert max(s0, s1) < min(e0, e1), "default async group serialized"
    ray_tpu.kill(d)


def test_unknown_group_fails_cleanly():
    """Undeclared group at declaration time raises immediately; per-call
    unknown group fails that call with ValueError without wedging the queue."""

    with pytest.raises(ValueError, match="concurrency group"):

        @ray_tpu.remote(concurrency_groups={"io": 1})
        class Bad:
            @ray_tpu.method(concurrency_group="nope")
            def f(self):
                pass

        Bad.remote()

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class E:
        def f(self):
            return "ok"

    e = E.remote()
    with pytest.raises(ValueError, match="no concurrency group"):
        ray_tpu.get(e.f.options(concurrency_group="ghost").remote(), timeout=60)
    # queue not wedged: later calls still work
    assert ray_tpu.get(e.f.remote(), timeout=60) == "ok"
    ray_tpu.kill(e)


def test_get_actor_handle_preserves_method_metadata():
    """Named-actor handles must behave like the creator's: @ray_tpu.method
    num_returns arity and group bindings survive the GCS round trip."""

    @ray_tpu.remote(name="cg_named", concurrency_groups={"io": 1})
    class G:
        @ray_tpu.method(num_returns=2, concurrency_group="io")
        def pair(self):
            return 1, 2

    g = G.remote()
    assert ray_tpu.get(g.pair.remote(), timeout=60) == [1, 2]
    h = ray_tpu.get_actor("cg_named")
    r1, r2 = h.pair.remote()  # arity preserved -> two refs
    assert ray_tpu.get([r1, r2], timeout=60) == [1, 2]
    ray_tpu.kill(g)


def test_out_of_order_execution_skips_seq_gating():
    """With allow_out_of_order_execution a seq hole does NOT wedge the actor:
    dispatch happens on arrival (reference: out_of_order_actor_submit_queue).
    The ordered mode would buffer forever waiting for the missing seq."""
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote(allow_out_of_order_execution=True, max_concurrency=2)
    class F:
        def ping(self, i):
            return i

    f = F.remote()
    assert ray_tpu.get(f.ping.remote(0), timeout=60) == 0
    # Punch a hole in this caller's seq stream for the actor.
    worker = global_worker()
    counter = worker._actor_seq[f._actor_id]
    with counter._lock:
        counter._value += 3
    assert ray_tpu.get(f.ping.remote(1), timeout=30) == 1
    ray_tpu.kill(f)
