"""Env↔module connector pipelines (reference: rllib/connectors/env_to_module/
+ module_to_env/).

Unit math for every piece (running-stat merge, frame stacking, prev-action
append, action clip/unsquash), then the round-5 contract end to end: PPO on an
ill-scaled continuous-control env LEARNS with a MeanStdFilter pipeline where
raw observations fail (the test asserts the gap), with filter stats merged
across two env runners and checkpoint/restored with the algorithm.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPO, PPOConfig
from ray_tpu.rllib.env_connectors import (
    ClipActions,
    EnvToModulePipeline,
    FlattenObservations,
    FrameStacking,
    MeanStdFilter,
    PrevActionsPrevRewards,
    RunningStat,
    UnsquashActions,
)


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_running_stat_merge_matches_numpy():
    rng = np.random.default_rng(0)
    a = rng.normal(3.0, 2.0, (100, 4))
    b = rng.normal(-1.0, 0.5, (57, 4))
    s1, s2 = RunningStat((4,)), RunningStat((4,))
    s1.push_batch(a)
    s2.push_batch(b)
    s1.merge(s2)
    both = np.concatenate([a, b])
    np.testing.assert_allclose(s1.mean, both.mean(axis=0), rtol=1e-10)
    np.testing.assert_allclose(s1.std, both.std(axis=0, ddof=1), rtol=1e-8)
    # State round-trip.
    s3 = RunningStat.from_state(s1.to_state())
    np.testing.assert_allclose(s3.mean, s1.mean)


def test_mean_std_filter_normalizes_and_merges():
    import gymnasium as gym

    space = gym.spaces.Box(-np.inf, np.inf, (3,), np.float32)
    f = MeanStdFilter()
    f.setup(space, None, 2)
    rng = np.random.default_rng(1)
    data = rng.normal(50.0, 5.0, (200, 3)).astype(np.float32)
    for i in range(0, 200, 2):
        out = f(data[i:i + 2])
    assert np.abs(out).max() < 5.0  # normalized scale
    # no_update peeks must not advance the stats.
    before = f.get_delta()["local"]["count"]
    f(data[:2], {"no_update": True})
    assert f.get_delta()["local"]["count"] == before
    # Cross-runner merge: two filters' deltas combine into near-global stats.
    g = MeanStdFilter()
    g.setup(space, None, 2)
    g(data[:100])
    merged = MeanStdFilter.merge(None, [f.get_delta(), g.get_delta()])
    stat = RunningStat.from_state(merged["base"])
    assert stat.count == 300
    np.testing.assert_allclose(stat.mean, 50.0, atol=2.0)


def test_frame_stacking_stacks_and_resets():
    import gymnasium as gym

    space = gym.spaces.Box(-1, 1, (2,), np.float32)
    fs = FrameStacking(num_frames=3)
    fs.setup(space, None, 1)
    o1 = fs(np.array([[1.0, 1.0]], np.float32))
    o2 = fs(np.array([[2.0, 2.0]], np.float32))
    assert o2.shape == (1, 6)
    np.testing.assert_allclose(o2[0], [0, 0, 1, 1, 2, 2])
    # Peek stacks without advancing.
    peek = fs(np.array([[9.0, 9.0]], np.float32), {"no_update": True})
    np.testing.assert_allclose(peek[0], [1, 1, 2, 2, 9, 9])
    o3 = fs(np.array([[3.0, 3.0]], np.float32))
    np.testing.assert_allclose(o3[0], [1, 1, 2, 2, 3, 3])
    fs.reset(0)
    o4 = fs(np.array([[5.0, 5.0]], np.float32))
    np.testing.assert_allclose(o4[0], [0, 0, 0, 0, 5, 5])
    assert o1.shape == (1, 6)


def test_prev_actions_prev_rewards_appends():
    import gymnasium as gym

    obs_space = gym.spaces.Box(-1, 1, (2,), np.float32)
    act_space = gym.spaces.Discrete(3)
    pc = PrevActionsPrevRewards()
    pc.setup(obs_space, act_space, 2)
    out = pc(np.zeros((2, 2), np.float32))
    assert out.shape == (2, 2 + 3 + 1)
    np.testing.assert_allclose(out[:, 2:], 0.0)  # episode start: zeros
    pc.observe(np.array([2, 0]), np.array([1.5, -0.5]))
    out = pc(np.ones((2, 2), np.float32))
    np.testing.assert_allclose(out[0, 2:], [0, 0, 1, 1.5])
    np.testing.assert_allclose(out[1, 2:], [1, 0, 0, -0.5])
    pc.reset(0)
    out = pc(np.ones((2, 2), np.float32))
    np.testing.assert_allclose(out[0, 2:], [0, 0, 0, 0])
    np.testing.assert_allclose(out[1, 2:], [1, 0, 0, -0.5])


def test_module_to_env_action_transforms():
    import gymnasium as gym

    box = gym.spaces.Box(np.array([0.0, -2.0]), np.array([1.0, 2.0]))
    clip = ClipActions()
    clip.setup(None, box, 1)
    out = clip(np.array([[5.0, -5.0]], np.float32))
    np.testing.assert_allclose(out[0], [1.0, -2.0])
    unsq = UnsquashActions()
    unsq.setup(None, box, 1)
    out = unsq(np.array([[0.0, 0.0]], np.float32))  # tanh(0)=0 -> mid-range
    np.testing.assert_allclose(out[0], [0.5, 0.0])
    big = unsq(np.array([[50.0, 50.0]], np.float32))  # saturates to high
    np.testing.assert_allclose(big[0], [1.0, 2.0], atol=1e-3)
    # Discrete: both are no-ops.
    clip_d = ClipActions()
    clip_d.setup(None, gym.spaces.Discrete(4), 1)
    np.testing.assert_array_equal(clip_d(np.array([3, 1])), [3, 1])


class _IllScaledTargetEnv:
    """Continuous control with pathologically scaled observations: the signal
    feature arrives at 1e-3 scale, a distractor at 1e+3. A tanh MLP on raw
    observations saturates on the distractor and never sees the signal; with
    mean-std normalization both features are O(1) and the task is trivial.
    One step per episode; reward = 1 - |action - 0.7*sign|."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-np.inf, np.inf, (2,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.default_rng(0)
        self._sign = 1.0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._sign = float(self._rng.choice([-1.0, 1.0]))
        obs = np.array(
            [self._sign * 1e-3, self._rng.uniform(-1, 1) * 1e3], np.float32
        )
        return obs, {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        reward = 1.0 - abs(a - 0.7 * self._sign)
        obs, _ = self.reset()
        return obs, reward, True, False, {}


def _run_ppo(with_filter: bool, iters: int = 25) -> float:
    config = (
        PPOConfig()
        .environment(_IllScaledTargetEnv)
        .env_runners(
            num_env_runners=2,
            env_to_module_connector=(
                (lambda obs, act: [MeanStdFilter()]) if with_filter else None
            ),
        )
        .training(train_batch_size=256, minibatch_size=128, num_epochs=4,
                  lr=5e-3)
        .debugging(seed=7)
    )
    algo = PPO(config)
    try:
        last = None
        for _ in range(iters):
            last = algo.train()
        return float(last["episode_return_mean"])
    finally:
        algo.stop()


def test_ppo_mean_std_filter_learns_where_raw_fails():
    filtered = _run_ppo(with_filter=True)
    raw = _run_ppo(with_filter=False)
    # The filtered run must actually solve the task AND beat raw by a clear
    # margin (raw tops out near reward-for-ignoring-the-signal).
    assert filtered > 0.62, f"filtered PPO did not learn: {filtered:.3f}"
    assert filtered > raw + 0.15, (
        f"no normalization gap: filtered {filtered:.3f} vs raw {raw:.3f}"
    )


def test_connector_state_checkpoints_with_algorithm(tmp_path):
    config = (
        PPOConfig()
        .environment(_IllScaledTargetEnv)
        .env_runners(
            num_env_runners=2,
            env_to_module_connector=lambda obs, act: [MeanStdFilter()],
        )
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .debugging(seed=3)
    )
    algo = PPO(config)
    try:
        for _ in range(3):
            algo.train()
        state = algo.env_runner_group.get_connector_state()
        assert state and 0 in state, state
        count = state[0]["base"]["count"]
        assert count > 0
        path = algo.save_to_path(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    algo2 = PPO(config)
    try:
        algo2.restore_from_path(path)
        restored = algo2.env_runner_group.get_connector_state()
        assert restored[0]["base"]["count"] == count
        np.testing.assert_allclose(
            restored[0]["base"]["mean"], state[0]["base"]["mean"]
        )
        # Restored stats actually reach the runners and training continues.
        algo2.train()
    finally:
        algo2.stop()
