"""Compiled graph (aDAG) tests.

Shape parity with the reference suite (python/ray/dag/tests/): interpreted
execution, single-actor compiled chains, multi-actor pipelines, MultiOutputNode
fan-out, error propagation through pinned loops, repeated executes (channel reuse),
teardown, and a throughput sanity check vs regular actor calls.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


@ray_tpu.remote
class Worker:
    def __init__(self, bias: int = 0):
        self._bias = bias
        self._calls = 0

    def inc(self, x):
        self._calls += 1
        return x + 1 + self._bias

    def double(self, x):
        return x * 2

    def add(self, a, b):
        return a + b

    def boom(self, x):
        raise ValueError("dag boom")

    def calls(self):
        return self._calls


def test_interpreted_execute():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.double.bind(w.inc.bind(inp))
    assert dag.execute(5) == 12  # (5+1)*2


def test_compiled_single_actor_chain():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.double.bind(w.inc.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i).get() == (i + 1) * 2
    finally:
        compiled.teardown()


def test_compiled_multi_actor_pipeline():
    a = Worker.remote(bias=0)
    b = Worker.remote(bias=0)
    with InputNode() as inp:
        dag = b.double.bind(a.inc.bind(inp))
    compiled = dag.experimental_compile()
    try:
        results = [compiled.execute(i) for i in range(5)]
        assert [r.get() for r in results] == [(i + 1) * 2 for i in range(5)]
    finally:
        compiled.teardown()


def test_multi_output():
    a = Worker.remote()
    b = Worker.remote()
    with InputNode() as inp:
        dag = MultiOutputNode([a.inc.bind(inp), b.double.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        r1, r2 = compiled.execute(10)
        assert r1.get() == 11
        assert r2.get() == 20
    finally:
        compiled.teardown()


def test_fan_in():
    a = Worker.remote()
    b = Worker.remote()
    c = Worker.remote()
    with InputNode() as inp:
        dag = c.add.bind(a.inc.bind(inp), b.double.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(3).get() == (3 + 1) + (3 * 2)
    finally:
        compiled.teardown()


def test_error_propagates_and_loop_survives():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.boom.bind(inp)
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(ValueError, match="dag boom"):
            compiled.execute(1).get()
        # Loop must still be alive for the next execute.
        with pytest.raises(ValueError, match="dag boom"):
            compiled.execute(2).get()
    finally:
        compiled.teardown()


def test_numpy_payloads():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.double.bind(inp)
    compiled = dag.experimental_compile()
    try:
        x = np.arange(10000, dtype=np.float32)
        out = compiled.execute(x).get()
        np.testing.assert_allclose(out, x * 2)
    finally:
        compiled.teardown()


def test_dag_array_payloads_ride_tensor_fastpath():
    """Compiled-DAG edges carrying arrays move them as raw-buffer tensor
    frames — cloudpickle never sees the array bytes (round 11; counted via
    the per-process transport stats on the driver's input/output edges)."""
    from ray_tpu.experimental import tensor_transport as tt

    w = Worker.remote()
    with InputNode() as inp:
        dag = w.double.bind(inp)
    compiled = dag.experimental_compile()
    try:
        x = np.arange(10000, dtype=np.float32)
        compiled.execute(x).get()  # warm the loop off-stats
        tt.reset_transport_stats()
        out = compiled.execute(x).get()
        np.testing.assert_allclose(out, x * 2)
        s = tt.transport_stats()
        # Driver wrote the input edge and read the output edge as tensor
        # frames (actor-side edges run the same code path in-process).
        assert s["tensor_frames_written"] >= 1, s
        assert s["tensor_frames_read"] >= 1, s
        assert s["tensor_bytes_written"] >= x.nbytes, s

        # Scalar payloads still pickle (the fast path is size-gated).
        tt.reset_transport_stats()
        assert compiled.execute(3).get() == 6
        s = tt.transport_stats()
        assert s["tensor_frames_written"] == 0, s
        assert s["pickle_frames_written"] >= 1, s
    finally:
        compiled.teardown()


def test_input_attribute_access():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.add.bind(inp["a"], inp["b"])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute({"a": 4, "b": 7}).get() == 11
    finally:
        compiled.teardown()


def test_compiled_faster_than_actor_calls():
    w = Worker.remote()
    n = 200

    def time_actor():
        t0 = time.monotonic()
        for i in range(n):
            ray_tpu.get(w.inc.remote(i))
        return time.monotonic() - t0

    ray_tpu.get(w.inc.remote(0))  # warm up the regular path
    actor_time = min(time_actor(), time_actor())

    with InputNode() as inp:
        dag = w.inc.bind(inp)
    compiled = dag.experimental_compile()

    def time_dag():
        t0 = time.monotonic()
        for i in range(n):
            compiled.execute(i).get()
        return time.monotonic() - t0

    try:
        compiled.execute(0).get()  # warm up
        # Best-of-two on BOTH paths: a single load spike (shared CI host)
        # must not flip a 5x structural gap into a flake.
        dag_time = min(time_dag(), time_dag())
    finally:
        compiled.teardown()
    # The pinned-loop path must beat the submit-per-call path comfortably.
    assert dag_time < actor_time, (dag_time, actor_time)


def test_same_node_passed_twice():
    w = Worker.remote()
    v = Worker.remote()
    with InputNode() as inp:
        x = w.inc.bind(inp)
        dag = v.add.bind(x, x)  # one node consumed twice by one bind
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(4).get() == 10  # (4+1) + (4+1)
    finally:
        compiled.teardown()


def test_input_passed_twice():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.add.bind(inp, inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(6).get() == 12
    finally:
        compiled.teardown()


def test_teardown_with_inflight_executions():
    @ray_tpu.remote
    class Slow:
        def work(self, x):
            time.sleep(3.0)
            return x

    w = Slow.remote()
    with InputNode() as inp:
        dag = w.work.bind(inp)
    # Rings are sized to max_inflight (reference: num_shm_buffers =
    # max_inflight_executions), so a bound-respecting driver can't wedge a
    # writer; teardown safety is exercised with the loop mid-compute and
    # unconsumed results in flight.
    compiled = dag.experimental_compile(max_inflight_executions=4)
    try:
        for i in range(4):
            compiled.execute(i)
        time.sleep(0.2)  # loop is inside work() with 3 more queued
    finally:
        compiled.teardown()  # must not hang or leave the actor wedged



def test_max_inflight_capacity_raises():
    """Past max_inflight_executions, execute() raises instead of wedging
    (reference compiled_dag_node.py:2223 RayCgraphCapacityExceeded)."""
    from ray_tpu.exceptions import RayCgraphCapacityExceeded

    w = Worker.remote()
    with InputNode() as inp:
        dag = w.inc.bind(inp)
    compiled = dag.experimental_compile(max_inflight_executions=2)
    try:
        r0 = compiled.execute(0)
        compiled.execute(1)
        with pytest.raises(RayCgraphCapacityExceeded):
            compiled.execute(2)
        assert r0.get(timeout=60) == 1  # consuming a result frees a slot
        r2 = compiled.execute(2)
        assert r2.get(timeout=60) == 3
    finally:
        compiled.teardown()


def test_execute_async_overlaps_inflight():
    """execute_async pipelines: the second submission lands while the first
    result is still unread, and awaiting runs off the event loop — a
    concurrent ticker task keeps ticking while results are pending
    (reference compiled_dag_node.py execute_async :2627)."""
    import asyncio

    @ray_tpu.remote
    class Paced:
        def work(self, x):
            time.sleep(0.4)
            return x * 10

    w = Paced.remote()
    with InputNode() as inp:
        dag = w.work.bind(inp)
    compiled = dag.experimental_compile(max_inflight_executions=4)

    async def drive():
        ticks = 0
        stop = asyncio.Event()

        async def ticker():
            nonlocal ticks
            while not stop.is_set():
                ticks += 1
                await asyncio.sleep(0.02)

        t = asyncio.create_task(ticker())
        t0 = time.monotonic()
        f1 = await compiled.execute_async(1)
        f2 = await compiled.execute_async(2)  # in flight before f1 is read
        submit_time = time.monotonic() - t0
        v1 = await f1
        v2 = await f2
        stop.set()
        await t
        return submit_time, v1, v2, ticks

    try:
        submit_time, v1, v2, ticks = asyncio.run(drive())
        assert (v1, v2) == (10, 20)
        # Submissions don't wait for results (two 0.4s computes pending).
        assert submit_time < 0.3, f"submit blocked: {submit_time:.2f}s"
        # The event loop stayed live while ~0.8s of compute drained.
        assert ticks >= 10, f"event loop starved: {ticks} ticks"
    finally:
        compiled.teardown()


def test_execute_async_error_propagates():
    import asyncio

    w = Worker.remote()
    with InputNode() as inp:
        dag = w.boom.bind(inp)
    compiled = dag.experimental_compile()

    async def drive():
        fut = await compiled.execute_async(1)
        with pytest.raises(ValueError, match="dag boom"):
            await fut

    try:
        asyncio.run(drive())
    finally:
        compiled.teardown()



def test_collective_allreduce_node():
    """In-graph allreduce: each participant's loop reduces every peer's
    contribution (reference: dag/collective_node.py + allreduce.bind)."""
    import numpy as np

    from ray_tpu.dag import InputNode, MultiOutputNode, collective

    @ray_tpu.remote
    class Shard:
        def __init__(self, scale):
            self.scale = scale

        def grads(self, x):
            return np.full(4, float(x) * self.scale)

        def apply(self, reduced):
            return float(reduced.sum())

    a, b, c = Shard.remote(1.0), Shard.remote(10.0), Shard.remote(100.0)
    with InputNode() as inp:
        contribs = [a.grads.bind(inp), b.grads.bind(inp), c.grads.bind(inp)]
        reduced = collective.allreduce.bind(contribs, op="sum")
        # Each participant consumes ITS copy of the reduced tensor.
        outs = MultiOutputNode([
            a.apply.bind(reduced[0]),
            b.apply.bind(reduced[1]),
            c.apply.bind(reduced[2]),
        ])
    dag = outs.experimental_compile()
    try:
        for x in (2.0, 3.0):
            refs = dag.execute(x)
            expect = 4 * x * (1 + 10 + 100)
            vals = [r.get(timeout=120) for r in refs]
            assert vals == [expect] * 3, vals
    finally:
        dag.teardown()


def test_collective_mean_and_validation():
    import numpy as np

    from ray_tpu.dag import InputNode, MultiOutputNode, collective

    @ray_tpu.remote
    class W:
        def val(self, x):
            return np.asarray([float(x)])

    w1, w2 = W.remote(), W.remote()
    with InputNode() as inp:
        n1, n2 = w1.val.bind(inp), w2.val.bind(inp)
        r = collective.allreduce.bind([n1, n2], op="mean")
        outs = MultiOutputNode(r)
    dag = outs.experimental_compile()
    try:
        refs = dag.execute(8.0)
        assert [float(x.get(timeout=120)[0]) for x in refs] == [8.0, 8.0]
    finally:
        dag.teardown()

    with pytest.raises(ValueError, match="distinct actors"):
        with InputNode() as inp:
            n = w1.val.bind(inp)
            collective.allreduce.bind([n, n])
    with pytest.raises(ValueError, match="reduce op"):
        with InputNode() as inp:
            collective.allreduce.bind(
                [w1.val.bind(inp), w2.val.bind(inp)], op="xor"
            )


def test_dropped_refs_release_capacity():
    """Fire-and-forget execute() past max_inflight must NOT wedge the DAG:
    refs dropped unread mark their slot consumable and the next capacity-bound
    submit drains them (reference: CompiledDAGRef.__del__ consumes unread
    results)."""
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.inc.bind(inp)
    compiled = dag.experimental_compile(max_inflight_executions=3)
    try:
        # 3x the bound, every ref dropped on the floor.
        for i in range(9):
            compiled.execute(i)  # raylint: disable=RL501 (the wedge under test)
        # The graph still works and the next read sees the newest round.
        ref = compiled.execute(100)
        assert ref.get(timeout=60) == 101
    finally:
        compiled.teardown()


def test_released_ref_cannot_be_read():
    w = Worker.remote()
    with InputNode() as inp:
        dag = w.inc.bind(inp)
    compiled = dag.experimental_compile(max_inflight_executions=2)
    try:
        ref = compiled.execute(1)
        ref.release()
        with pytest.raises(ValueError):
            ref.get(timeout=5)
        # The released round's capacity comes back.
        for i in range(4):
            r = compiled.execute(i)
            r.release()
        ref2 = compiled.execute(7)
        assert ref2.get(timeout=60) == 8
    finally:
        compiled.teardown()


def test_dropped_multi_output_refs_release_capacity():
    """Abandoning only ONE of a round's outputs must also free the round once
    the other output is read (per-output consumption accounting)."""
    a, b = Worker.remote(), Worker.remote(bias=10)
    with InputNode() as inp:
        dag = MultiOutputNode([a.inc.bind(inp), b.inc.bind(inp)])
    compiled = dag.experimental_compile(max_inflight_executions=2)
    try:
        for i in range(5):
            r1, _r2 = compiled.execute(i)  # _r2 dropped every round
            assert r1.get(timeout=60) == i + 1
            del _r2
        r1, r2 = compiled.execute(50)
        assert r2.get(timeout=60) == 61
        r1.release()
    finally:
        compiled.teardown()



def test_compiled_dag_across_two_nodes():
    """A compiled DAG pins loops on actors on TWO nodes: cross-node edges ride
    RpcChannel (ring in the writer, readers pull over direct worker conns) and
    same-node edges stay on shm — selection is automatic (VERDICT #6;
    reference: cross-node mutable-object channels,
    experimental_mutable_object_provider.h:143)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode

    ray_tpu.shutdown()
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "env_vars": env})
    cluster.add_node(num_cpus=1, resources={"stage2": 1.0}, env_vars=env)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(num_cpus=0)
        class A:
            def double(self, x):
                return x * 2

        @ray_tpu.remote(num_cpus=0, resources={"stage2": 0.1})
        class B:
            def add_one(self, x):
                return x + 1

        a, b = A.remote(), B.remote()
        with InputNode() as inp:
            mid = a.double.bind(inp)      # head node
            out = b.add_one.bind(mid)     # second node: cross-node edge
        dag = out.experimental_compile()
        try:
            from ray_tpu.experimental.channel import RpcChannel

            # The a->b edge and the b->driver edge must be RPC channels; the
            # driver->a input edge stays local (driver and A share the head).
            kinds = [type(ch).__name__ for ch in dag._channels]
            assert "RpcChannel" in kinds, kinds
            for i in range(5):
                assert dag.execute(i).get(timeout=120) == i * 2 + 1
        finally:
            dag.teardown()
    finally:
        cluster.shutdown()


def test_compiled_dag_overlap_and_profiling():
    """Overlap scheduling: a two-stage cross-node DAG pipelines channel I/O
    with compute, so busy-time (read+compute) exceeds wall time on the second
    stage — measured via the new per-op profile (VERDICT r2 #8; reference:
    dag_node_operation.py READ/COMPUTE/WRITE reordering +
    compiled_dag_node.py op profiling)."""
    import time

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode

    ray_tpu.shutdown()
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "env_vars": env})
    cluster.add_node(num_cpus=1, resources={"stage2": 1.0}, env_vars=env)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(num_cpus=0)
        class Producer:
            def slow(self, x):
                time.sleep(0.05)
                return x

        @ray_tpu.remote(num_cpus=0, resources={"stage2": 0.1})
        class Consumer:
            def work(self, x):
                time.sleep(0.05)
                return x + 1

        a, b = Producer.remote(), Consumer.remote()
        with InputNode() as inp:
            out = b.work.bind(a.slow.bind(inp))
        dag = out.experimental_compile(max_inflight_executions=16)
        try:
            assert dag.execute(0).get(timeout=120) == 1  # warm both loops
            K = 12
            t0 = time.monotonic()
            refs = [dag.execute(i) for i in range(1, K + 1)]
            vals = [r.get(timeout=120) for r in refs]
            elapsed = time.monotonic() - t0
            assert vals == [i + 1 for i in range(1, K + 1)]
            # Serial (no overlap) would cost K * (producer + consumer) >= 1.2s
            # on the consumer's critical path; pipelining bounds it near
            # K * max(stage) + one pipeline fill.
            assert elapsed < K * 0.1 * 0.9, f"no pipelining: {elapsed:.2f}s"

            # Per-op profile: the consumer overlapped its reads (waiting on the
            # producer) with its own compute, so busy time exceeds wall time.
            deadline = time.monotonic() + 30
            prof = {}
            while time.monotonic() < deadline:
                prof = dag.op_profile()
                # Emission is windowed: half the iterations is enough signal.
                done = [p for p in prof.values() if p.get("iters", 0) >= K // 2]
                if len(done) >= 2:
                    break
                time.sleep(1.0)
            assert len(prof) >= 2, prof
            busy = sum(p.get("read_s", 0) + p.get("compute_s", 0)
                       for p in prof.values())
            assert busy > elapsed * 1.2, (
                f"no measured overlap: busy {busy:.2f}s vs wall {elapsed:.2f}s "
                f"({prof})"
            )
        finally:
            dag.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
