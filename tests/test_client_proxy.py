"""Client proxy (ray_tpu+proxy://): one public port fronting the cluster.

Reference shape: python/ray/util/client/server/proxier.py — external clients
terminate at a dedicated proxy process, which validates/relays their traffic
into the cluster and tracks per-client sessions.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture()
def cluster_and_proxy():
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.client.proxier import serve_proxy
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    host, port = cluster.address.split(":")
    proxy, loop = serve_proxy((host, int(port)), host="127.0.0.1")
    yield cluster, proxy
    loop.run(proxy.close(), 10)
    loop.stop()
    cluster.shutdown()


def test_proxy_thin_client_end_to_end(cluster_and_proxy):
    """A ray_tpu+proxy:// client runs tasks/actors/objects while touching ONLY
    the proxy's port — the GCS address never appears client-side (the routing
    envelope carries the symbolic 'gcs' target)."""
    _cluster, proxy = cluster_and_proxy
    ctx = ray_tpu.init(address=f"ray_tpu+proxy://127.0.0.1:{proxy.port}")
    try:
        assert ctx is not None
        w = ray_tpu.global_worker()
        assert w.remote_data_plane and w.proxy is not None
        assert w.gcs_addr[0] == "gcs"  # client never learned the real GCS addr

        @ray_tpu.remote
        def double(x):
            return x * 2

        assert ray_tpu.get(double.remote(21), timeout=120) == 42

        big = np.arange(200_000, dtype=np.float64)
        np.testing.assert_array_equal(ray_tpu.get(ray_tpu.put(big), timeout=120), big)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=120) == 1
        assert ray_tpu.get(c.incr.remote(), timeout=120) == 2
    finally:
        ray_tpu.shutdown()


def test_proxy_sessions_and_control_plane(cluster_and_proxy):
    """The proxy tracks per-client sessions while connected and drops them on
    disconnect (per-client isolation bookkeeping); the control channel serves
    ping/list_clients/stats."""
    import time

    from ray_tpu.util.client.proxier import control_call

    _cluster, proxy = cluster_and_proxy
    addr = ("127.0.0.1", proxy.port)
    assert control_call(addr, "ping")["ok"]

    ray_tpu.init(address=f"ray_tpu+proxy://127.0.0.1:{proxy.port}")
    try:
        clients = control_call(addr, "list_clients")["clients"]
        assert len(clients) == 1
        assert clients[0]["tunnels"] >= 2  # gcs + raylet at minimum
        assert clients[0]["bytes_up"] > 0
        assert control_call(addr, "stats")["num_clients"] == 1
    finally:
        ray_tpu.shutdown()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if control_call(addr, "stats")["num_clients"] == 0:
            break
        time.sleep(0.2)
    assert control_call(addr, "stats")["num_clients"] == 0


def _send_envelope(proxy_port: int, envelope: dict) -> bytes:
    """Write a JSON routing envelope; return what the proxy sends back (b'' on
    close-without-relay)."""
    import socket

    from ray_tpu.util.client.proxier import _json_frame

    with socket.create_connection(("127.0.0.1", proxy_port), timeout=10) as s:
        s.sendall(_json_frame(envelope))
        s.settimeout(10)
        return s.recv(1)


def test_proxy_rejects_bad_targets(cluster_and_proxy):
    """The proxy is not an open relay: unknown hosts AND unlisted ports on
    known hosts are refused (exact registered-endpoint policy), as are
    non-JSON envelopes (the proxy never unpickles client bytes)."""
    import socket
    import struct

    _cluster, proxy = cluster_and_proxy
    # off-cluster host
    assert _send_envelope(proxy.port, {"route": ["203.0.113.7", 4444],
                                      "client_id": "evil"}) == b""
    # known host, arbitrary port (e.g. SSH) — host-level trust is not enough
    assert _send_envelope(proxy.port, {"route": ["127.0.0.1", 22],
                                      "client_id": "evil"}) == b""
    # pickled (non-JSON) envelope: dropped at the codec, never deserialized
    import pickle

    payload = pickle.dumps({"route": ("gcs", 0)}, protocol=5)
    with socket.create_connection(("127.0.0.1", proxy.port), timeout=10) as s:
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        s.settimeout(10)
        assert s.recv(1) == b""


def test_proxy_token_auth():
    """With a shared token configured, tunnels and control calls without it
    are refused; ray_tpu+proxy://token@host:port authenticates."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.client.proxier import control_call, serve_proxy
    from tests.conftest import _WORKER_ENV

    cluster = Cluster(
        initialize_head=True, head_node_args={"num_cpus": 2, "env_vars": _WORKER_ENV}
    )
    host, port = cluster.address.split(":")
    proxy, loop = serve_proxy((host, int(port)), host="127.0.0.1", token="s3cret")
    try:
        assert _send_envelope(proxy.port, {"route": ["gcs", 0],
                                          "client_id": "nope"}) == b""
        with pytest.raises(Exception):
            control_call(("127.0.0.1", proxy.port), "ping")
        assert control_call(("127.0.0.1", proxy.port), "ping", token="s3cret")["ok"]

        ctx = ray_tpu.init(address=f"ray_tpu+proxy://s3cret@127.0.0.1:{proxy.port}")
        try:
            assert ctx is not None

            @ray_tpu.remote
            def one():
                return 1

            assert ray_tpu.get(one.remote(), timeout=120) == 1
        finally:
            ray_tpu.shutdown()
    finally:
        loop.run(proxy.close(), 10)
        loop.stop()
        cluster.shutdown()
