"""Actor semantics tests (reference: python/ray/tests/test_actor.py shapes)."""

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    def get_value(self):
        return self.value


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs, timeout=120) == list(range(1, 21))


def test_actor_init_args(ray_start_regular):
    c = Counter.remote(start=100)
    assert ray_tpu.get(c.get_value.remote(), timeout=60) == 100


def test_named_actor(ray_start_regular):
    c = Counter.options(name="counter-x").remote(7)
    ray_tpu.get(c.incr.remote(), timeout=60)
    c2 = ray_tpu.get_actor("counter-x")
    assert ray_tpu.get(c2.get_value.remote(), timeout=60) == 8
    ray_tpu.kill(c)


def test_get_if_exists(ray_start_regular):
    a = Counter.options(name="gie", get_if_exists=True).remote(1)
    b = Counter.options(name="gie", get_if_exists=True).remote(999)
    ray_tpu.get(a.incr.remote(), timeout=60)
    assert ray_tpu.get(b.get_value.remote(), timeout=60) == 2
    ray_tpu.kill(a)


def test_actor_error_propagation(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor-err")

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor-err"):
        ray_tpu.get(b.fail.remote(), timeout=60)


def test_actor_handle_passing(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.incr.remote(), timeout=60)

    assert ray_tpu.get(bump.remote(c), timeout=120) == 1
    assert ray_tpu.get(c.get_value.remote(), timeout=60) == 1


def test_async_actor_concurrency(ray_start_regular):
    @ray_tpu.remote
    class Async:
        async def sleepy(self, i):
            import asyncio

            await asyncio.sleep(0.05)
            return i

    a = Async.remote()
    import time

    ray_tpu.get(a.sleepy.remote(-1), timeout=60)  # warmup: actor worker spawn
    t0 = time.monotonic()
    out = ray_tpu.get([a.sleepy.remote(i) for i in range(20)], timeout=60)
    elapsed = time.monotonic() - t0
    assert out == list(range(20))
    assert elapsed < 0.8  # concurrent (~0.05s) rather than 20 * 0.05s serial


def test_threaded_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Threaded:
        def work(self, i):
            import time

            time.sleep(0.05)
            return i

    t = Threaded.remote()
    out = ray_tpu.get([t.work.remote(i) for i in range(8)], timeout=60)
    assert sorted(out) == list(range(8))


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.incr.remote(), timeout=60)
    ray_tpu.kill(c)
    with pytest.raises(Exception):
        ray_tpu.get(c.incr.remote(), timeout=10)


def test_kill_during_creation_releases_resources(ray_start_regular):
    """kill() while the actor's worker is still being created must not leak the
    worker or its resource hold (regression: DEAD runners pinned CPUs until the
    cluster reported 0 available and every later actor went unschedulable)."""
    import time

    @ray_tpu.remote(num_cpus=1)
    class SlowInit:
        def __init__(self):
            time.sleep(3)  # keep create_actor in flight while kill() lands

        def ping(self):
            return "pong"

    def cpu_avail():
        return ray_tpu.available_resources().get("CPU", 0)

    baseline = cpu_avail()
    actors = [SlowInit.remote() for _ in range(2)]
    time.sleep(0.3)  # creation definitely started, init still sleeping
    for a in actors:
        ray_tpu.kill(a)
    deadline = time.monotonic() + 30
    avail = -1.0
    while time.monotonic() < deadline:
        avail = cpu_avail()
        if avail >= baseline:
            break
        time.sleep(0.25)
    assert avail >= baseline, f"leaked CPUs: {avail} available, baseline {baseline}"
    # and the killed actors are reported dead, not resurrected
    for a in actors:
        with pytest.raises(Exception):
            ray_tpu.get(a.ping.remote(), timeout=10)


def test_actor_creation_error(ray_start_regular):
    @ray_tpu.remote
    class BadInit:
        def __init__(self):
            raise ValueError("init-fail")

        def ping(self):
            return "pong"

    b = BadInit.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=30)
