"""External datasources (Lance / Iceberg / BigQuery) — plumbing tests.

Shape parity with the reference suite (python/ray/data/tests/test_lance.py,
test_iceberg.py, test_bigquery.py): the client libraries are optional, so these
tests inject in-memory fakes through the datasources' factory seams and assert
the ReadTask fan-out and row round-trip; absence of the real library must
surface as a clear ImportError naming the dependency.
"""

import numpy as np
import pyarrow as pa
import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_read_lance_fragment_parallel():
    class FakeFragment:
        def __init__(self, fid, table):
            self.fragment_id = fid
            self._table = table

        def count_rows(self):
            return self._table.num_rows

        def to_table(self, columns=None, filter=None):
            t = self._table
            if columns:
                t = t.select(columns)
            return t

    class FakeLanceDataset:
        def __init__(self, frags):
            self._frags = {f.fragment_id: f for f in frags}

        def get_fragments(self):
            return list(self._frags.values())

        def get_fragment(self, fid):
            return self._frags[fid]

    class FakeLance:
        def __init__(self):
            self._ds = FakeLanceDataset([
                FakeFragment(0, pa.table({"x": [1, 2], "y": ["a", "b"]})),
                FakeFragment(1, pa.table({"x": [3], "y": ["c"]})),
            ])

        def dataset(self, uri):
            return self._ds

    ds = rd.read_lance("lance://t", lance_mod=FakeLance())
    rows = sorted(r["x"] for r in ds.take_all())
    assert rows == [1, 2, 3]
    # column projection flows through
    ds2 = rd.read_lance("lance://t", columns=["x"], lance_mod=FakeLance())
    batch = next(iter(ds2.iter_batches(batch_size=10)))
    assert set(batch.keys()) == {"x"}


def test_read_iceberg_whole_scan_fallback():
    """Without pyiceberg's arrow reader the scan degrades to one whole-scan
    task driven through the injected catalog."""

    class FakeScan:
        table_metadata = None
        io = None
        row_filter = None
        case_sensitive = True

        def plan_files(self):
            return []

        def to_arrow(self):
            return pa.table({"id": [10, 20, 30]})

    class FakeTable:
        def scan(self, **kw):
            assert kw["selected_fields"] == ("*",)
            return FakeScan()

    class FakeCatalog:
        def load_table(self, ident):
            assert ident == "db.events"
            return FakeTable()

    ds = rd.read_iceberg("db.events", catalog_factory=lambda: FakeCatalog())
    assert sorted(r["id"] for r in ds.take_all()) == [10, 20, 30]


def test_read_bigquery_stream_parallel():
    class FakePage:
        def __init__(self, table):
            self._t = table

        def to_arrow(self):
            return self._t

    class FakeRows:
        def __init__(self, pages):
            self.pages = pages

    class FakeReader:
        def __init__(self, pages):
            self._pages = pages

        def rows(self):
            return FakeRows(self._pages)

    class FakeReadClient:
        _data = {
            "s1": [FakePage(pa.table({"v": [1, 2]}))],
            "s2": [FakePage(pa.table({"v": [3]})), FakePage(pa.table({"v": [4]}))],
        }

        def create_read_session(self, parent, read_session, max_stream_count):
            assert "projects/p1/datasets/d/tables/t" == read_session["table"]

            class Stream:
                def __init__(self, name):
                    self.name = name

            class Session:
                streams = [Stream("s1"), Stream("s2")]

            return Session()

        def read_rows(self, name):
            return FakeReader(self._data[name])

    class FakeClient:
        pass

    ds = rd.read_bigquery(
        "p1", dataset="d.t",
        client_factory=lambda: (FakeClient(), FakeReadClient()),
    )
    assert sorted(r["v"] for r in ds.take_all()) == [1, 2, 3, 4]


def test_missing_optional_dependency_is_clear():
    with pytest.raises(ImportError, match="read_lance.*lance"):
        rd.read_lance("lance://t")
    with pytest.raises(ImportError, match="read_iceberg.*pyiceberg"):
        rd.read_iceberg("db.t")
    with pytest.raises(ImportError, match="read_bigquery"):
        rd.read_bigquery("p", dataset="d.t")
    with pytest.raises(ValueError, match="exactly one"):
        from ray_tpu.data.ext_datasources import BigQueryDatasource

        BigQueryDatasource("p", dataset="d.t", query="select 1",
                           client_factory=lambda: (None, None))