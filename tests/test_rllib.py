"""ray_tpu.rllib tests.

Shape parity with the reference suite (rllib/algorithms/ppo/tests/ +
rllib/core/tests/): GAE math, module distribution math, a learning smoke test on a
trivially learnable env, CartPole end-to-end sampling/training, checkpoint
save/restore, and learner-actor placement.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPO, PPOConfig, compute_gae
from ray_tpu.rllib.core.rl_module import Columns, DefaultActorCriticModule


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_gae_matches_reference_math():
    rewards = np.array([1.0, 1.0, 1.0], np.float32)
    vf = np.array([0.5, 0.4, 0.3], np.float32)
    gamma, lam = 0.9, 0.8
    adv, targets = compute_gae(rewards, vf, bootstrap=0.2, gamma=gamma, lam=lam)
    # hand-rolled backward recursion
    deltas = [1.0 + gamma * 0.4 - 0.5, 1.0 + gamma * 0.3 - 0.4, 1.0 + gamma * 0.2 - 0.3]
    a2 = deltas[2]
    a1 = deltas[1] + gamma * lam * a2
    a0 = deltas[0] + gamma * lam * a1
    np.testing.assert_allclose(adv, [a0, a1, a2], rtol=1e-5)
    np.testing.assert_allclose(targets, adv + vf, rtol=1e-5)


def test_module_distribution_math():
    import jax
    import jax.numpy as jnp

    m = DefaultActorCriticModule(obs_dim=3, action_dim=4, discrete=True)
    params = m.init_params(jax.random.PRNGKey(0))
    batch = {Columns.OBS: jnp.ones((5, 3))}
    out = m.forward_inference(params, batch)
    logits = out[Columns.ACTION_DIST_INPUTS]
    assert logits.shape == (5, 4)
    assert out[Columns.VF_PREDS].shape == (5,)
    actions = m.dist_sample(logits, jax.random.PRNGKey(1))
    logp = m.dist_logp(logits, actions)
    assert logp.shape == (5,)
    assert float(jnp.exp(logp).max()) <= 1.0 + 1e-5
    ent = m.dist_entropy(logits)
    # near-uniform init → entropy close to log(4)
    assert float(ent.mean()) == pytest.approx(np.log(4), abs=0.1)


class _BanditEnv:
    """One-step env: reward +1 iff action matches the sign feature. Learnable in a
    handful of PPO iterations — the learning-progress smoke test. Deliberately NOT a
    gym.Env subclass: exercises the runner's duck-typed env adapter."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.default_rng(0)
        self._obs = None

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        sign = self._rng.choice([-1.0, 1.0])
        self._obs = np.array([sign, 1.0], np.float32)
        return self._obs, {}

    def step(self, action):
        correct = (self._obs[0] > 0) == (int(action) == 1)
        obs, _ = self.reset()
        return obs, (1.0 if correct else 0.0), True, False, {}

    def close(self):
        pass


def test_connector_pipeline_matches_monolithic_postprocess():
    """The composable GAE->flatten->normalize pipeline produces exactly what
    the monolithic ppo_postprocess produced (reference: ConnectorV2 learner
    pipelines replacing evaluation/postprocessing.py)."""
    from ray_tpu.rllib.algorithms.ppo import ppo_postprocess
    from ray_tpu.rllib.connectors import default_ppo_learner_pipeline

    rng = np.random.default_rng(0)
    fragments = []
    for n in (5, 3):
        fragments.append({
            Columns.OBS: rng.normal(size=(n, 4)).astype(np.float32),
            Columns.ACTIONS: rng.integers(0, 2, n),
            Columns.ACTION_LOGP: rng.normal(size=n).astype(np.float32),
            Columns.REWARDS: rng.normal(size=n).astype(np.float32),
            Columns.VF_PREDS: rng.normal(size=n).astype(np.float32),
            "bootstrap_value": 0.3,
        })
    import copy

    expected = ppo_postprocess(copy.deepcopy(fragments), 0.95, 0.9)
    got = default_ppo_learner_pipeline()(
        copy.deepcopy(fragments), {"gamma": 0.95, "lambda_": 0.9}
    )
    for k in expected:
        np.testing.assert_allclose(got[k], expected[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)


def test_connector_pipeline_splicing_and_custom_hook():
    """Users splice pieces into the default pipeline via the config hook
    (reference: AlgorithmConfig.learner_connector)."""
    from ray_tpu.rllib.connectors import (
        ClipRewards,
        ConnectorPipelineV2,
        default_ppo_learner_pipeline,
    )

    pipeline = default_ppo_learner_pipeline()
    names = [c.name for c in pipeline.connectors]
    assert names == ["ComputeGAE", "FragmentsToBatch", "NormalizeAdvantages"]
    pipeline.insert_before("ComputeGAE", ClipRewards(0.5))
    pipeline.insert_after("FragmentsToBatch", lambda b, ctx: b)
    pipeline.remove("NormalizeAdvantages")
    assert [c.name for c in pipeline.connectors][:2] == [
        "ClipRewards", "ComputeGAE"
    ]
    # reward clipping actually applies before GAE
    frag = {
        Columns.OBS: np.zeros((2, 4), np.float32),
        Columns.ACTIONS: np.zeros(2, np.int64),
        Columns.ACTION_LOGP: np.zeros(2, np.float32),
        Columns.REWARDS: np.array([10.0, -7.0], np.float32),
        Columns.VF_PREDS: np.zeros(2, np.float32),
        "bootstrap_value": 0.0,
    }
    out = pipeline([frag], {"gamma": 1.0, "lambda_": 1.0})
    # clipped rewards [0.5, -0.5] with zero values/bootstrap -> returns [0, -0.5]
    np.testing.assert_allclose(out[Columns.VALUE_TARGETS], [0.0, -0.5])

    # The PPO config hook reaches the algorithm's pipeline.
    captured = {}

    def hook(p: ConnectorPipelineV2):
        captured["pipeline"] = p
        return p

    cfg = PPOConfig()
    cfg.learner_connector = hook
    algo = PPO.__new__(PPO)  # postprocess needs only the config
    algo.config = cfg
    out2 = algo.postprocess([dict(frag, bootstrap_value=0.0)])
    assert isinstance(captured.get("pipeline"), ConnectorPipelineV2)
    assert Columns.ADVANTAGES in out2


def test_ppo_learns_bandit():
    config = (
        PPOConfig()
        .environment(lambda cfg: _BanditEnv())
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=6, lr=0.02,
                  entropy_coeff=0.0)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        assert "episode_return_mean" in first
        last = first
        for _ in range(6):
            last = algo.train()
        assert last["episode_return_mean"] > max(0.75, first["episode_return_mean"])
    finally:
        algo.stop()


def test_ppo_cartpole_smoke():
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=1)
        .training(train_batch_size=400, minibatch_size=128, num_epochs=2, lr=3e-4)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        # recorded transitions: ~train_batch_size minus the per-episode autoreset
        # bookkeeping steps that are (correctly) not recorded as experience
        assert result["num_env_steps_sampled_lifetime"] >= 300
        assert result["episodes_this_iter"] >= 1
        assert np.isfinite(result["learner/total_loss"])
    finally:
        algo.stop()


def test_checkpoint_save_restore(tmp_path):
    import jax

    config = (
        PPOConfig()
        .environment(lambda cfg: _BanditEnv())
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()
        path = algo.save_to_path(str(tmp_path / "ckpt"))
        w1 = algo.get_weights()
        algo2 = config.copy().build_algo()
        try:
            algo2.restore_from_path(path)
            assert algo2.iteration == algo.iteration
            w2 = algo2.get_weights()
            for a, b in zip(jax.tree_util.tree_leaves(w1), jax.tree_util.tree_leaves(w2)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        finally:
            algo2.stop()
    finally:
        algo.stop()


def test_learner_actor_placement():
    config = (
        PPOConfig()
        .environment(lambda cfg: _BanditEnv())
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .learners(num_learners=1, learner_resources={"num_cpus": 1})
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert np.isfinite(result["learner/total_loss"])
    finally:
        algo.stop()


class _TruncOnlyEnv:
    """Ends every episode via truncation after 5 steps — exercises the stats path
    for TimeLimit-style envs and the gymnasium next-step autoreset handling."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return np.zeros(2, np.float32), {}

    def step(self, action):
        self._t += 1
        return np.zeros(2, np.float32), 1.0, False, self._t >= 5, {}

    def close(self):
        pass


def test_truncated_episodes_counted_in_stats():
    config = (
        PPOConfig()
        .environment(lambda cfg: _TruncOnlyEnv())
        .training(train_batch_size=64, minibatch_size=32, num_epochs=1)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        # every episode is exactly 5 steps of reward 1.0
        assert result["episodes_this_iter"] >= 5
        assert result["episode_return_mean"] == pytest.approx(5.0)
        assert result["episode_len_mean"] == pytest.approx(5.0)
    finally:
        algo.stop()


def test_replay_buffer_fifo_and_sampling():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10)
    batch = {
        "obs": np.arange(6, dtype=np.float32).reshape(6, 1),
        "actions": np.arange(6),
        "rewards": np.ones(6, np.float32),
        "next_obs": np.arange(1, 7, dtype=np.float32).reshape(6, 1),
        "dones": np.zeros(6, np.float32),
    }
    buf.add_batch(batch)
    assert len(buf) == 6
    buf.add_batch(batch)  # 12 > capacity: oldest overwritten
    assert len(buf) == 10
    sample = buf.sample(32, np.random.default_rng(0))
    assert sample["obs"].shape == (32, 1)
    assert set(sample["actions"].tolist()) <= set(range(6))


def test_dqn_learns_bandit():
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment(lambda cfg: _BanditEnv())
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .training(
            train_batch_size=256, minibatch_size=64, lr=5e-3,
            learning_starts=100, n_updates_per_iter=20,
            target_network_update_freq=256,
        )
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        # one-step episodes: every other env step is autoreset bookkeeping,
        # so ~train_batch_size/2 transitions land in the buffer per iteration
        assert first["replay_size"] >= 100
        last = first
        for _ in range(8):
            last = algo.train()
        assert np.isfinite(last["learner/total_loss"])
        # Boltzmann sampling over converged Q-values (1 vs 0) caps the return at
        # e/(e+1) ~= 0.73; clearly above the 0.5 chance level proves learning.
        assert last["episode_return_mean"] > max(0.65, first["episode_return_mean"])
        assert last["learner/td_error_mean"] < 0.5
    finally:
        algo.stop()


def test_dqn_checkpoint_roundtrip(tmp_path):
    import jax

    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment(lambda cfg: _BanditEnv())
        .training(train_batch_size=128, minibatch_size=32, learning_starts=64,
                  n_updates_per_iter=2)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()
        path = algo.save_to_path(str(tmp_path / "dqn"))
        algo2 = config.copy().build_algo()
        try:
            algo2.restore_from_path(path)
            for a, b in zip(
                jax.tree_util.tree_leaves(algo.get_weights()),
                jax.tree_util.tree_leaves(algo2.get_weights()),
            ):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        finally:
            algo2.stop()
    finally:
        algo.stop()


class _ContinuousBanditEnv:
    """One-step continuous env: reward = -(a - 0.5)^2. SAC should steer the
    squashed-gaussian policy mean toward 0.5."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._obs = np.array([0.3, -0.7], np.float32)

    def reset(self, *, seed=None, options=None):
        return self._obs, {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        reward = -((a - 0.5) ** 2)
        return self._obs, reward, True, False, {}

    def close(self):
        pass


def test_sac_learns_continuous_bandit():
    from ray_tpu.rllib import SACConfig

    config = (
        SACConfig()
        .environment(lambda cfg: _ContinuousBanditEnv())
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .training(
            train_batch_size=256, minibatch_size=128, lr=3e-3,
            learning_starts=200, n_updates_per_iter=40, tau=0.02, initial_alpha=0.1,
        )
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        last = first
        for _ in range(8):
            last = algo.train()
        assert np.isfinite(last["learner/critic_loss"])
        assert last["learner/alpha"] > 0.0
        # Optimal reward is 0 (action 0.5); random-ish is around -0.5.
        assert last["episode_return_mean"] > -0.15, last["episode_return_mean"]
    finally:
        algo.stop()


def test_sac_dqn_mesh_learner():
    """use_mesh data-parallel learners now work for target-network algorithms:
    targets are Learner state injected inside the jitted step (replicated),
    never sharded batch payload (round-2 divergence, deleted)."""
    from ray_tpu.rllib import DQNConfig, SACConfig

    sac_cfg = (
        SACConfig()
        .environment(lambda cfg: _ContinuousBanditEnv())
        .training(train_batch_size=64, minibatch_size=64, learning_starts=32,
                  n_updates_per_iter=2, tau=0.05)
        .learners(use_mesh=True)
        .debugging(seed=0)
    )
    algo = sac_cfg.build_algo()
    try:
        last = {}
        for _ in range(3):
            last = algo.train()
        assert np.isfinite(last["learner/critic_loss"])
        # polyak ran inside the jitted step: target != online but moved toward it
        online = algo.learner_group.get_params()
        target = algo.learner_group.get_target()
        import jax

        diffs = [
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(
                jax.tree_util.tree_leaves({"q1": online["q1"], "q2": online["q2"]}),
                jax.tree_util.tree_leaves(target),
            )
        ]
        assert any(d > 0 for d in diffs)      # target lags online
        assert max(diffs) < 1.0               # but tracks it
    finally:
        algo.stop()

    dqn_cfg = (
        DQNConfig()
        .environment(lambda cfg: _BanditEnv())
        .training(train_batch_size=64, minibatch_size=32, learning_starts=32,
                  n_updates_per_iter=2, target_network_update_freq=64)
        .learners(use_mesh=True)
        .debugging(seed=0)
    )
    algo = dqn_cfg.build_algo()
    try:
        last = {}
        for _ in range(3):
            last = algo.train()
        assert np.isfinite(last["learner/td_error_mean"])
    finally:
        algo.stop()


def test_mesh_learner_rebuilds_on_nondivisible_batch():
    """A later batch whose leading dim stops dividing over dp must trigger a
    sharding rebuild (replicated), not crash against the cached P('dp') jit —
    offline tails and async pow-2 buckets both produce this."""
    import cloudpickle

    from ray_tpu.rllib import Learner
    from ray_tpu.rllib.core.rl_module import Columns, DefaultActorCriticModule

    m = DefaultActorCriticModule(obs_dim=2, action_dim=2, discrete=True)

    def loss(module, params, batch):
        import jax.numpy as jnp

        out = module.forward_train(params, batch)
        logp = module.dist_logp(out[Columns.ACTION_DIST_INPUTS], batch[Columns.ACTIONS])
        return -jnp.mean(logp), {}

    learner = Learner(m, loss, use_mesh=True)
    big = {Columns.OBS: np.zeros((64, 2), np.float32),
           Columns.ACTIONS: np.zeros((64,), np.int64)}
    small = {Columns.OBS: np.zeros((3, 2), np.float32),
             Columns.ACTIONS: np.zeros((3,), np.int64)}
    assert np.isfinite(learner.update(big)["total_loss"])
    assert np.isfinite(learner.update(small)["total_loss"])  # rebuild, replicated
    assert np.isfinite(learner.update(big)["total_loss"])    # and back


def test_impala_vtrace_math():
    """V-trace targets with rho=c=1 and on-policy logp reduce to n-step returns."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.impala import _impala_loss_factory
    from ray_tpu.rllib.core.rl_module import DefaultActorCriticModule

    m = DefaultActorCriticModule(obs_dim=2, action_dim=2, discrete=True)
    params = m.init_params(jax.random.PRNGKey(0))
    loss = _impala_loss_factory(1.0, 1.0, 0.5, 0.0, 0.9)
    B, T = 2, 4
    obs = np.zeros((B, T, 2), np.float32)
    batch = {
        Columns.OBS: jnp.asarray(obs),
        Columns.ACTIONS: jnp.zeros((B, T), jnp.int32),
        Columns.REWARDS: jnp.ones((B, T), jnp.float32),
        "dones": jnp.zeros((B, T), jnp.float32),
        "mask": jnp.ones((B, T), jnp.float32),
        "bootstrap_value": jnp.zeros((B,), jnp.float32),
        "last_idx": jnp.full((B,), T - 1, jnp.int32),
    }
    # Behavior logp == target logp -> rho = 1 (on-policy): vs must equal the
    # discounted n-step return of the constant-reward sequence.
    out = m.forward_inference(params, {Columns.OBS: obs.reshape(B * T, 2)})
    logp = m.dist_logp(
        out[Columns.ACTION_DIST_INPUTS].reshape(B, T, -1), batch[Columns.ACTIONS]
    )
    batch[Columns.ACTION_LOGP] = logp
    total, metrics = loss(m, params, batch)
    assert np.isfinite(float(total))
    # n-step return for T=4, gamma=.9, r=1, v_T=0: 1+.9+.81+.729 at t=0
    expected_t0 = 1 + 0.9 + 0.81 + 0.729
    # vtrace_mean averages vs over all t; just sanity-bound it
    assert 0.9 < float(metrics["vtrace_mean"]) < expected_t0 + 0.1


def test_impala_learns_bandit():
    from ray_tpu.rllib import IMPALAConfig

    config = (
        IMPALAConfig()
        .environment(lambda cfg: _BanditEnv())
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .training(train_batch_size=256, lr=0.02, entropy_coeff=0.003,
                  rollout_fragment_length=8, broadcast_interval=2)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        last = first
        for _ in range(10):
            last = algo.train()
        assert np.isfinite(last["learner/policy_loss"])
        # Async sampling updates the learner within the very first train() call,
        # so `first` can already be at the 1.0 optimum — assert the level, not
        # strict improvement over iteration one.
        assert last["episode_return_mean"] > 0.75, last["episode_return_mean"]
        assert first["num_env_steps_sampled_lifetime"] > 0
    finally:
        algo.stop()


def test_bc_clones_expert():
    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.core.rl_module import Columns as C

    # Expert for _BanditEnv: action = 1 iff obs[0] > 0.
    rng = np.random.default_rng(0)
    signs = rng.choice([-1.0, 1.0], size=2000)
    obs = np.stack([signs, np.ones(2000)], axis=1).astype(np.float32)
    actions = (signs > 0).astype(np.int64)
    data = [{C.OBS: obs, C.ACTIONS: actions}]

    config = (
        BCConfig()
        .environment(lambda cfg: _BanditEnv())
        .training(train_batch_size=2000, minibatch_size=256, num_epochs=3, lr=5e-3)
        .debugging(seed=0)
    )
    config.offline(data)
    algo = config.build_algo()
    try:
        for _ in range(5):
            metrics = algo.train()
        assert metrics["learner/bc_logp_mean"] > -0.2  # near-deterministic clone
        ev = algo.evaluate(num_episodes=10)
        assert ev["evaluation/episode_return_mean"] > 0.9
    finally:
        algo.stop()


def test_impala_vtrace_truncated_tail_uses_bootstrap():
    """A sequence shorter than T must bootstrap off its LAST REAL step, with the
    pad region contributing nothing (regression: bootstrap landed on pad index)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.impala import _impala_loss_factory
    from ray_tpu.rllib.core.rl_module import DefaultActorCriticModule

    m = DefaultActorCriticModule(obs_dim=2, action_dim=2, discrete=True)
    params = m.init_params(jax.random.PRNGKey(0))
    gamma = 0.9
    loss = _impala_loss_factory(1.0, 1.0, 0.5, 0.0, gamma)
    B, T, L = 1, 6, 3  # 3 real steps, 3 pads
    obs = np.zeros((B, T, 2), np.float32)
    mask = np.zeros((B, T), np.float32); mask[:, :L] = 1.0
    dones = np.zeros((B, T), np.float32); dones[:, L:] = 1.0  # pads marked done
    bootstrap = 7.0
    base = {
        Columns.OBS: jnp.asarray(obs),
        Columns.ACTIONS: jnp.zeros((B, T), jnp.int32),
        Columns.REWARDS: jnp.ones((B, T), jnp.float32),
        "dones": jnp.asarray(dones),
        "mask": jnp.asarray(mask),
        "bootstrap_value": jnp.asarray([bootstrap], jnp.float32),
        "last_idx": jnp.asarray([L - 1], jnp.int32),
    }
    out = m.forward_inference(params, {Columns.OBS: obs.reshape(B * T, 2)})
    logp = m.dist_logp(
        out[Columns.ACTION_DIST_INPUTS].reshape(B, T, -1), base[Columns.ACTIONS]
    )
    base[Columns.ACTION_LOGP] = logp  # on-policy: rho = c = 1
    _, metrics = loss(m, params, base)
    # With rho=c=1 on-policy, vs_t for real steps is the discounted n-step return
    # ending in the bootstrap: vs_2 = 1 + g*7, vs_1 = 1 + g*vs_2, vs_0 = 1 + g*vs_1.
    v_net = float(np.asarray(out[Columns.VF_PREDS])[0])  # same value every obs
    vs2 = 1 + gamma * bootstrap
    vs1 = 1 + gamma * vs2
    vs0 = 1 + gamma * vs1
    expected_mean = (vs0 + vs1 + vs2) / 3.0
    np.testing.assert_allclose(float(metrics["vtrace_mean"]), expected_mean, rtol=1e-5)


class _TwoPolicyBandit:
    """Multi-agent bandit: two agents with OPPOSITE reward structures, so the
    test fails unless each policy actually learns its own mapping (shared
    weights would cap joint reward at one agent's optimum)."""

    possible_agents = ["good", "evil"]

    def __init__(self):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self.observation_spaces = {a: self.observation_space
                                   for a in self.possible_agents}
        self.action_spaces = {a: self.action_space for a in self.possible_agents}
        self._t = 0

    def reset(self, seed=None, options=None):
        self._t = 0
        obs = {a: np.zeros(2, np.float32) for a in self.possible_agents}
        return obs, {}

    def step(self, actions):
        self._t += 1
        rewards = {
            "good": 1.0 if actions.get("good") == 1 else 0.0,
            "evil": 1.0 if actions.get("evil") == 0 else 0.0,
        }
        done = self._t >= 8
        obs = {a: np.zeros(2, np.float32) for a in self.possible_agents}
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


def test_multi_agent_ppo_two_policies_learn():
    """Two-policy multi-agent env trains with per-policy losses (VERDICT #8;
    reference: rllib/env/multi_agent_env_runner.py + policy_mapping_fn)."""
    config = (
        PPOConfig()
        .environment(lambda cfg: _TwoPolicyBandit())
        .env_runners(num_env_runners=1)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=6, lr=0.02,
                  entropy_coeff=0.0)
        .multi_agent(policies=["good", "evil"],
                     policy_mapping_fn=lambda aid: aid)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    from ray_tpu.rllib import MultiAgentPPO

    assert isinstance(algo, MultiAgentPPO)
    try:
        last = None
        for _ in range(8):
            last = algo.train()
        # Per-policy learner metrics reported under "<policy>/<metric>".
        assert np.isfinite(last["good/total_loss"])
        assert np.isfinite(last["evil/total_loss"])
        # Joint return approaches 16 (8 steps x 2 agents x reward 1) only if
        # BOTH policies learned their (opposite) optimal actions.
        assert last["episode_return_mean"] > 12.0, last["episode_return_mean"]
    finally:
        algo.stop()


def test_multi_agent_shared_policy():
    """Many agents can share one policy via the mapping fn."""
    config = (
        PPOConfig()
        .environment(lambda cfg: _TwoPolicyBandit())
        .env_runners(num_env_runners=1)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=2, lr=0.01)
        .multi_agent(policies=["shared"], policy_mapping_fn=lambda aid: "shared")
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert result["episodes_this_iter"] >= 1
        assert np.isfinite(result["shared/total_loss"])
    finally:
        algo.stop()


class _SleepyBanditEnv:
    """_BanditEnv with simulated env latency: sampling wall-clock dominates, so
    async actor-queue sampling (learn while others act) visibly beats the
    round-based barrier loop."""

    def __init__(self, *_a, **_k):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._obs = np.zeros(2, np.float32)

    def reset(self, *, seed=None, options=None):
        rng = np.random.default_rng(seed)
        self._obs = np.array([rng.choice([-1.0, 1.0]), 1.0], np.float32)
        return self._obs, {}

    def step(self, action):
        import time

        time.sleep(0.002)
        reward = 1.0 if (action == 1) == (self._obs[0] > 0) else -1.0
        obs = self._obs
        self._obs = np.array([np.sign(np.random.randn()) or 1.0, 1.0], np.float32)
        return obs, reward, True, False, {}

    def close(self):
        pass


def test_impala_async_overlaps_sampling_with_learning():
    """VERDICT r2 #5: the async actor-queue loop must beat its round-based self
    on wall-clock. Setup makes BOTH phases substantial (2ms env steps; 10
    learner epochs on a 128-wide net): round-based pays sample + learn
    serially each iteration, async overlaps the learner with the runners'
    next in-flight chunks."""
    import time

    from ray_tpu.rllib import APPOConfig

    def build(async_mode):
        return (
            APPOConfig()
            .environment(lambda cfg: _SleepyBanditEnv())
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
            .training(train_batch_size=256, lr=0.01, rollout_fragment_length=8,
                      sample_async=async_mode, async_chunk_timesteps=128,
                      num_epochs=10, model={"hiddens": (128, 128)})
            .debugging(seed=0)
        ).build_algo()

    def timed(algo, iters=4):
        warm = algo.train()  # warm-up: jit compiles + runner startup off the clock
        start_steps = warm["num_env_steps_sampled_lifetime"]
        t0 = time.monotonic()
        last = {}
        for _ in range(iters):
            last = algo.train()
        elapsed = time.monotonic() - t0
        # Normalize per trained-on timestep: the two modes consume different
        # step counts per train() call, wall-clock alone compares nothing.
        return elapsed / max(1, last["num_env_steps_sampled_lifetime"] - start_steps)

    # The structural win is T_sample + T_learn (sync) vs max(T_sample, T_learn)
    # (async); require a strict improvement with margin. One retry absorbs a
    # scheduler-jitter outlier (this is a comparative benchmark, not logic).
    last = None
    for _attempt in range(2):
        sync_algo = build(False)
        try:
            sync_s_per_step = timed(sync_algo)
        finally:
            sync_algo.stop()
        async_algo = build(True)
        try:
            async_s_per_step = timed(async_algo)
        finally:
            async_algo.stop()
        last = (async_s_per_step, sync_s_per_step)
        if async_s_per_step < sync_s_per_step * 0.97:
            break
    else:
        raise AssertionError(f"async did not beat sync per-step: {last}")


def test_impala_async_runner_death_recovers():
    """Killing an env-runner mid-stream: the group replaces it, re-pushes
    weights, and the train loop keeps consuming."""
    from ray_tpu.rllib import IMPALAConfig

    config = (
        IMPALAConfig()
        .environment(lambda cfg: _BanditEnv())
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(train_batch_size=128, lr=0.02, rollout_fragment_length=8)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()
        ray_tpu.kill(algo.env_runner_group._runners[0])
        result = algo.train()   # absorbs the failure, replaces, keeps going
        assert result["num_env_steps_sampled_lifetime"] > 0
        result = algo.train()
        assert np.isfinite(result["learner/policy_loss"])
    finally:
        algo.stop()


def test_appo_learns_bandit_and_beats_impala_roundtrip():
    """APPO trains on the same env/machinery as IMPALA with the PPO clip
    objective (VERDICT #8; reference rllib/algorithms/appo/appo.py)."""
    from ray_tpu.rllib import APPOConfig

    config = (
        APPOConfig()
        .environment(lambda cfg: _BanditEnv())
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
        .training(train_batch_size=256, lr=0.02, entropy_coeff=0.003,
                  rollout_fragment_length=8)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        last = first
        for _ in range(10):
            last = algo.train()
        assert np.isfinite(last["learner/policy_loss"])
        assert "learner/mean_ratio" in last
        # (level, not improvement-over-first: async learns within iteration one)
        assert last["episode_return_mean"] > 0.75, last["episode_return_mean"]
        assert first["num_env_steps_sampled_lifetime"] > 0
    finally:
        algo.stop()
