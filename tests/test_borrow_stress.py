"""Borrower-tree stress: intermediate crashes must never free what a live
transitive borrower still holds.

VERDICT r4 weak #6: the mirrored-borrow protocol (worker.py ReferenceCounter,
docs/divergences.md "sequenced borrower tree") documents two narrow residual
windows; this stress test actively tries to break the load-bearing property —
an intermediate borrower dying (SIGKILL, no cleanup) between handing a ref to
a grandchild and its own release must NOT let the owner free the object while
the grandchild lives (reference: reference_counter.h:43 transitive borrower
merge-on-reply).
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def borrow_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S", "1")
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "RAY_TPU_BORROW_AUDIT_INTERVAL_S": "1",
        },
    )
    yield
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S")
    CONFIG._reset()


def test_grandchild_borrow_survives_intermediate_sigkill(borrow_cluster):
    """driver(owner) -> Middle -> Holder chains; every Middle is SIGKILLed
    after the handoff; many audit cycles later the Holders must still read
    every array correctly, then release and the driver's session stays
    healthy."""

    @ray_tpu.remote(max_restarts=0)
    class Holder:
        def __init__(self):
            self.kept = {}

        def hold(self, key, wrapped):
            self.kept[key] = wrapped[0]  # keep the BORROWED inner ref
            return os.getpid()

        def read(self, key):
            return float(ray_tpu.get(self.kept[key]).sum())

        def release(self, key):
            self.kept.pop(key, None)
            return True

    @ray_tpu.remote(max_restarts=0)
    class Middle:
        def forward(self, holder, key, wrapped):
            # Sub-borrow: this actor borrows from the owner and hands the ref
            # onward; the grandchild's registration must be MIRRORED to the
            # owner so this process's death cannot free the object.
            pid = ray_tpu.get(holder.hold.remote(key, wrapped), timeout=60)
            assert pid
            return os.getpid()

    holders = [Holder.remote() for _ in range(2)]
    n_objects = 8
    expected = {}
    middle_pids = []
    refs = {}
    for i in range(n_objects):
        arr = np.full(20_000, float(i + 1), np.float64)
        expected[i] = float(arr.sum())
        ref = ray_tpu.put(arr)
        refs[i] = ref
        middle = Middle.remote()
        pid = ray_tpu.get(
            middle.forward.remote(holders[i % 2], i, [ref]), timeout=120
        )
        middle_pids.append(pid)
        # SIGKILL the intermediate right after the handoff: no graceful
        # release, no mirror retraction — the worst-case crash point.
        os.kill(pid, signal.SIGKILL)

    # Drop the driver's own refs: the ONLY thing keeping the objects alive is
    # now the grandchild borrow that was mirrored through dead intermediates.
    del refs
    import gc

    gc.collect()

    # Let several audit cycles run: the audit must reconcile the DEAD
    # intermediates' counts without touching the live grandchildren's.
    time.sleep(5.0)

    for i in range(n_objects):
        got = ray_tpu.get(
            holders[i % 2].read.remote(i), timeout=120
        )
        assert got == expected[i], f"object {i} corrupted or freed: {got}"

    # Release everything; the cluster stays healthy for fresh work.
    for i in range(n_objects):
        assert ray_tpu.get(holders[i % 2].release.remote(i), timeout=60)

    @ray_tpu.remote
    def ping():
        return 42

    assert ray_tpu.get(ping.remote(), timeout=60) == 42


def test_repeated_handoff_churn_with_audit_pressure(borrow_cluster):
    """Rapid borrow/release churn through a relay while the audit runs on a
    1s interval: the three-strike reconcile must never fire on an entry whose
    holder is alive and actively handing off (the false-positive window the
    ledger documents)."""

    @ray_tpu.remote
    class Relay:
        def bounce(self, wrapped):
            return float(ray_tpu.get(wrapped[0]).sum())

    relay = Relay.remote()
    arr = np.full(10_000, 3.0, np.float64)
    ref = ray_tpu.put(arr)
    want = float(arr.sum())
    deadline = time.time() + 8.0  # >> several audit cycles at 1s
    rounds = 0
    while time.time() < deadline:
        assert ray_tpu.get(relay.bounce.remote([ref]), timeout=60) == want
        rounds += 1
    assert rounds >= 10
    # The owner's ref is still valid after sustained audit pressure.
    assert float(ray_tpu.get(ref).sum()) == want
