"""Offline batch on the shared fleet (docs/generation.md): EngineStage rows
ride the decode scheduler as the zero-floor-weight batch WFQ tenant.

The contract under test: online traffic always preempts queued batch rows
(online TTFT stays in tolerance of a no-batch baseline even over a deep
batch backlog); the batch tenant's floor weight is pinned (not reshareable);
the autopilot's control-law signals exclude batch pressure entirely (a deep
offline backlog must never scale the fleet); and a dying engine stepper
cancels/drains the in-flight batch instead of hanging the Data job — with
zero live slots, leases, or flight records left behind (this suite runs
under the leaksan + distsan autouse guards).
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield
    serve.shutdown()


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def _engine(tiny, **kw):
    from ray_tpu.llm import DecodeEngine

    cfg, params = tiny
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 128)
    return DecodeEngine(cfg, params, **kw)


def _submit_timed(engine, token_ids, *, max_tokens, tenant, results, idx):
    """Submit one request; records (ttft_s, finished_at) into results[idx]."""
    from ray_tpu.llm import SamplingParams

    t0 = time.monotonic()
    state = {"ttft": None, "done": threading.Event()}
    results[idx] = state

    def cb(tok, fin):
        if state["ttft"] is None and tok >= 0:
            state["ttft"] = time.monotonic() - t0
        if fin:
            state["end"] = time.monotonic()
            state["done"].set()

    engine.submit(list(token_ids), SamplingParams(max_tokens=max_tokens),
                  cb, tenant=tenant)


def test_online_ttft_survives_batch_backlog(tiny):
    """Admission-level preemption: with a deep batch-tenant backlog queued,
    online arrivals still reach a slot ahead of every queued batch row, so
    online p99 TTFT stays within tolerance of the no-batch baseline (the
    worst case is draining the one in-flight batch row per slot)."""
    from ray_tpu._private.config import CONFIG

    engine = _engine(tiny)
    try:
        # -- no-batch baseline -------------------------------------------
        base: list = [None] * 4
        for i in range(4):
            _submit_timed(engine, b"online", max_tokens=8,
                          tenant="online", results=base, idx=i)
        for s in base:
            assert s["done"].wait(300)
        base_p99 = max(s["ttft"] for s in base)

        # -- deep batch backlog + online arrivals ------------------------
        batch: list = [None] * 10
        for i in range(10):
            _submit_timed(engine, b"batchrow", max_tokens=24,
                          tenant=CONFIG.llm_batch_tenant, results=batch, idx=i)
        online: list = [None] * 4
        for i in range(4):
            _submit_timed(engine, b"online", max_tokens=8,
                          tenant="online", results=online, idx=i)
        for s in online:
            assert s["done"].wait(300)
        online_p99 = max(s["ttft"] for s in online)
        # Tolerance: one in-flight batch row per slot may drain first (24
        # tokens), plus generous CI scheduling slack. What this catches is
        # the failure mode — online queued BEHIND the 10-row backlog, whose
        # TTFT would be the whole backlog's decode time.
        assert online_p99 <= base_p99 * 10 + 3.0, (
            f"online TTFT {online_p99:.3f}s vs baseline {base_p99:.3f}s: "
            f"batch backlog starved online admission"
        )
        last_online = max(s["end"] for s in online)
        for s in batch:
            assert s["done"].wait(300)
        last_batch = max(s["end"] for s in batch)
        assert last_online < last_batch, (
            "every online request should complete before the batch backlog "
            "drains (batch is the background tenant)"
        )
    finally:
        engine.shutdown()


def test_batch_tenant_floor_weight_pinned(tiny):
    """The batch tenant's WFQ weight is a floor, not a knob: the autopilot's
    set_tenant_weight actuator must not reshare it upward."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams

    engine = _engine(tiny)
    try:
        done = threading.Event()
        engine.submit(list(b"b"), SamplingParams(max_tokens=4),
                      lambda t, f: done.set() if f else None,
                      tenant=CONFIG.llm_batch_tenant)
        assert done.wait(300)
        engine.set_tenant_weight(CONFIG.llm_batch_tenant, 100.0)
        st = engine.scheduler_stats()
        weight = st["tenants"][CONFIG.llm_batch_tenant]["weight"]
        assert weight <= max(1e-6, CONFIG.llm_batch_weight)
    finally:
        engine.shutdown()


def test_autopilot_signals_exclude_batch_pressure(tiny):
    """A deep offline backlog is NON-SLO load: the autopilot's queued depth,
    tenant weights, and burn map must not see the batch tenant at all."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.llm import SamplingParams

    engine = _engine(tiny)
    try:
        dones = []
        for _ in range(12):
            ev = threading.Event()
            dones.append(ev)
            engine.submit(
                list(b"backlog"), SamplingParams(max_tokens=16),
                lambda t, f, ev=ev: ev.set() if f else None,
                tenant=CONFIG.llm_batch_tenant)
        st = engine.scheduler_stats()
        sig = engine.autopilot_signals()
        if st["tenants"][CONFIG.llm_batch_tenant]["queued"] > 0:
            # Backlog still queued when sampled: the signal must hide it.
            assert sig["queued"] == 0, (st, sig)
        assert CONFIG.llm_batch_tenant not in sig["tenant_weights"]
        assert CONFIG.llm_batch_tenant not in sig["tenant_burn"]
        for ev in dones:
            assert ev.wait(300)
    finally:
        engine.shutdown()


def _stage_batch(n, prompt=b"row"):
    return {"tokenized_prompt": np.array([list(prompt) for _ in range(n)])}


def test_engine_stage_rides_batch_tenant(tiny):
    """The Data-plane stage tags every row as the batch tenant (that is what
    makes coexistence and non-SLO treatment structural, not opt-in)."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.data.llm import EngineProcessorConfig, EngineStage

    cfg = EngineProcessorConfig(
        model_id="test-tiny",
        engine_kwargs={"num_slots": 2, "max_seq": 128},
        sampling_params={"max_tokens": 6},
        log_stats=False,
    )
    stage = EngineStage(cfg)
    try:
        out = stage(_stage_batch(5))
        assert all(n == 6 for n in out["num_generated_tokens"])
        st = stage._engine.scheduler_stats()
        assert st["tenants"][CONFIG.llm_batch_tenant]["admitted"] == 5
    finally:
        stage._engine.shutdown()


def test_engine_stage_poisoned_stepper_cancels_and_raises(tiny):
    """Stepper-death regression: a fault in the decode loop mid-batch must
    fail the stage call loudly (RuntimeError, not a hang), cancel/drain
    every unfinished row, and leave zero live slots or flight records —
    the leaksan guard on this suite enforces the book balance."""
    from ray_tpu.data.llm import EngineProcessorConfig, EngineStage

    cfg = EngineProcessorConfig(
        model_id="test-tiny",
        engine_kwargs={"num_slots": 2, "max_seq": 128},
        sampling_params={"max_tokens": 120},
        log_stats=False,
    )
    stage = EngineStage(cfg)
    engine = stage._engine
    err: list = []

    def run():
        try:
            stage(_stage_batch(4))
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=run)
    t.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if engine._sched.stats()["running"] > 0:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("batch never reached the engine")

        def boom():
            raise RuntimeError("injected stepper fault")

        engine._process_cancels = boom  # poison: dies at the next iteration
        t.join(120)
        assert not t.is_alive(), "stage call hung on a dead stepper"
        assert err and "stepper died" in str(err[0])
        assert engine.error is not None
        st = engine._sched.stats()
        assert st["running"] == 0 and st["queue_depth"] == 0
        rec = engine._recorder.stats()
        assert rec["live"] == 0  # every flight record retired
        with pytest.raises(RuntimeError, match="stepper died"):
            from ray_tpu.llm import SamplingParams

            engine.submit([1], SamplingParams(max_tokens=2), lambda a, b: None)
    finally:
        t.join(5)
        engine.shutdown()


@pytest.fixture(scope="module")
def llm_handle(_cluster):
    from ray_tpu.llm import LLMConfig, build_llm_deployment

    app = build_llm_deployment(LLMConfig(model_id="test-tiny", num_slots=2))
    handle = serve.run(app, name="llm-batch", route_prefix=None,
                       _timeout_s=240)
    yield handle
    serve.delete("llm-batch")


def test_engine_stage_shared_fleet_via_serve_handle(tiny, llm_handle):
    """Shared-fleet mode: serve_handle routes the stage's rows into LIVE
    serve replicas as the batch tenant — no local engine, no new compiled
    programs, and the replica's scheduler sees the batch tenant."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.data.llm import EngineProcessorConfig, EngineStage

    cfg = EngineProcessorConfig(
        model_id="test-tiny",
        sampling_params={"max_tokens": 5},
        serve_handle=llm_handle,
        log_stats=False,
    )
    stage = EngineStage(cfg)
    assert stage._engine is None  # no dedicated engine in shared-fleet mode
    out = stage(_stage_batch(4))
    assert all(n == 5 for n in out["num_generated_tokens"])
    st = llm_handle.scheduler_stats.remote().result(timeout_s=120)
    assert st["tenants"][CONFIG.llm_batch_tenant]["admitted"] >= 4
