"""Replicated GCS protocol tests: lease-based quorum HA in-process.

Three real GcsCandidate instances (each with its own RpcServer + store dir)
run on one asyncio loop, which makes the protocol properties directly
assertable: majority election, majority-ack replication, NOT_PRIMARY
redirects, epoch fencing of a deposed primary, quorum-loss demotion, and the
acquire->release books of the lease token and peer links. The full-cluster
chaos coverage (SIGKILL the primary process under serve/train traffic) lives
in tests/test_chaos.py.
"""

import asyncio
import os
import socket
import time

import pytest

from ray_tpu._private import rpc
from ray_tpu._private.gcs_replication import (
    GcsCandidate,
    ReplicatedFileStore,
    parse_addrs,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _boot(n, tmp_path, lease_s=0.8, quorum_timeout_s=2.0):
    ports = [_free_port() for _ in range(n)]
    peers = [("127.0.0.1", p) for p in ports]
    cands = []
    for i in range(n):
        c = GcsCandidate(i, peers, os.path.join(str(tmp_path), f"s{i}"),
                         lease_s=lease_s, quorum_timeout_s=quorum_timeout_s)
        server = rpc.RpcServer(lambda conn, c=c: c.facade(conn))
        await server.start(host="127.0.0.1", port=ports[i])
        c.server = server
        c.start_background()
        cands.append(c)
    return cands


async def _wait_primary(cands, timeout=10.0, exclude=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        prim = [c for c in cands
                if c.role == "primary" and c not in exclude]
        if prim:
            return prim[0]
        await asyncio.sleep(0.02)
    raise AssertionError("no primary elected in time")


async def _shutdown_all(cands):
    for c in cands:
        try:
            await c.shutdown()
        except Exception:
            pass


def test_parse_addrs_shapes():
    assert parse_addrs("h:1") == [("h", 1)]
    assert parse_addrs("a:1, b:2,c:3") == [("a", 1), ("b", 2), ("c", 3)]
    assert parse_addrs(("h", 1)) == [("h", 1)]
    assert parse_addrs([("h", 1), ["g", 2]]) == [("h", 1), ("g", 2)]
    assert parse_addrs(None) == []


def test_replicated_store_stamps_position_across_compaction(tmp_path,
                                                           monkeypatch):
    """The (epoch, seq, promised) stamp rides the same log as the data:
    compaction rewrites it with the live keys, and a reload restores the
    replication coordinates exactly (epoch-stamped compaction)."""
    from ray_tpu._private.config import CONFIG

    monkeypatch.setenv("RAY_TPU_GCS_STORE_COMPACT_THRESHOLD", "100")
    CONFIG._reset()
    try:
        store = ReplicatedFileStore(str(tmp_path / "s"))
        store.load()
        store.epoch = 7
        for i in range(90):  # 2 appends per apply: crosses the threshold
            store.apply_replicated(7, i + 1, ("put", "t", f"k{i % 5}", i))
        assert store._stats["compactions"] >= 1, "compaction never ran"
        assert store.seq == 90 and store.epoch == 7
        store.grant(9)
        store.close()

        store2 = ReplicatedFileStore(str(tmp_path / "s"))
        store2.load()
        assert (store2.epoch, store2.seq, store2.promised) == (7, 90, 9)
        assert store2.get("t", "k4") == 89
        store2.close()
    finally:
        monkeypatch.delenv("RAY_TPU_GCS_STORE_COMPACT_THRESHOLD")
        CONFIG._reset()


def test_non_primary_store_drops_originated_writes(tmp_path):
    """Local fencing: without the primary's fan-out installed, GcsService-
    style put/delete calls are dropped — a zombie scheduler task on a deposed
    candidate cannot diverge the follower's replicated log."""
    store = ReplicatedFileStore(str(tmp_path / "s"))
    store.load()
    store.put("kv", ("ns", b"k"), b"zombie-write")
    assert store.get("kv", ("ns", b"k")) is None
    assert store.seq == 0
    # The replicated apply path still works.
    store.apply_replicated(1, 1, ("put", "kv", ("ns", b"k"), b"v"))
    assert store.get("kv", ("ns", b"k")) == b"v"
    store.close()


def test_election_replication_and_redirect(tmp_path):
    async def run():
        cands = await _boot(3, tmp_path)
        try:
            primary = await _wait_primary(cands)
            # A follower redirects client calls at the primary.
            follower = next(c for c in cands if c is not primary)
            conn = await rpc.connect(*follower.addr, name="cli")
            with pytest.raises(rpc.NotPrimaryError) as ei:
                await conn.call("kv_put", "ns", b"k", b"v", True)
            assert tuple(ei.value.primary) == tuple(primary.addr)
            await conn.close()

            # Mutations through the primary are majority-acked and reach
            # every live follower's warm store.
            pconn = await rpc.connect(*primary.addr, name="cli")
            for i in range(25):
                assert await pconn.call(
                    "kv_put", "ns", f"k{i}".encode(), str(i).encode(), True
                ) is True
            assert await pconn.call("kv_get", "ns", b"k3") == b"3"
            st = await pconn.call("repl_status")
            assert st["role"] == "primary" and st["replicas"] == 3
            await pconn.close()
            for c in cands:
                if c is primary:
                    continue
                deadline = time.monotonic() + 5
                while (c.store.get("kv", ("ns", b"k24")) != b"24"
                       and time.monotonic() < deadline):
                    await asyncio.sleep(0.02)
                assert c.store.get("kv", ("ns", b"k24")) == b"24"
                assert c.store.seq == primary.store.seq
        finally:
            await _shutdown_all(cands)

    asyncio.run(run())


def test_failover_promotes_caught_up_follower_and_fences_old_epoch(tmp_path):
    """Primary death: a follower promotes within ~2x the lease window at a
    higher epoch, majority-acked records survive, and a straggler append
    stamped with the dead primary's epoch is rejected by the quorum."""
    lease_s = 0.8

    async def run():
        cands = await _boot(3, tmp_path, lease_s=lease_s)
        try:
            primary = await _wait_primary(cands)
            pconn = await rpc.connect(*primary.addr, name="cli")
            for i in range(10):
                await pconn.call("kv_put", "ns", f"k{i}".encode(),
                                 str(i).encode(), True)
            await pconn.close()
            old_epoch = primary.store.epoch

            t0 = time.monotonic()
            await primary.shutdown()  # the in-process stand-in for SIGKILL
            new_primary = await _wait_primary(
                cands, timeout=10.0, exclude=(primary,))
            promote_s = time.monotonic() - t0
            assert promote_s <= 2.0 * lease_s + 1.0, (
                f"promotion took {promote_s:.2f}s (lease {lease_s}s)")
            assert new_primary.store.epoch > old_epoch

            nconn = await rpc.connect(*new_primary.addr, name="cli")
            for i in range(10):
                assert await nconn.call(
                    "kv_get", "ns", f"k{i}".encode()) == str(i).encode()
            # Epoch fencing: the deposed primary's straggler bounces off
            # both the new primary and the remaining follower.
            straggler = (new_primary.store.seq + 1,
                         ("put", "kv", ("ns", b"fenced"), b"x"))
            reply = await nconn.call("repl_append", old_epoch, [straggler],
                                     primary.candidate_id)
            assert reply["ok"] is False
            assert reply["promised"] > old_epoch
            assert await nconn.call("kv_get", "ns", b"fenced") is None
            await nconn.close()
            follower = next(c for c in cands
                            if c not in (primary, new_primary))
            fconn = await rpc.connect(*follower.addr, name="cli")
            reply = await fconn.call("repl_append", old_epoch, [straggler],
                                     primary.candidate_id)
            assert reply["ok"] is False
            await fconn.close()
            assert follower.store.get("kv", ("ns", b"fenced")) is None
        finally:
            await _shutdown_all(cands)

    asyncio.run(run())


def test_rejoined_candidate_truncates_unacked_tail(tmp_path):
    """A candidate that diverged (its log holds records the quorum never
    acked) is snapshot-resynced when the live primary reconnects to it: the
    stale tail is truncated and its tables converge to the quorum state."""

    async def run():
        cands = await _boot(3, tmp_path)
        try:
            primary = await _wait_primary(cands)
            pconn = await rpc.connect(*primary.addr, name="cli")
            await pconn.call("kv_put", "ns", b"base", b"1", True)

            follower = next(c for c in cands if c is not primary)
            # Forge a diverged tail directly into the follower's store (the
            # moral equivalent of a deposed primary's unacked appends), then
            # break the primary's replication link — a rejoining deposed
            # candidate always gets a fresh connect, and every fresh connect
            # starts with a snapshot sync that truncates whatever the quorum
            # never acked.
            follower.store.apply_replicated(
                follower.store.epoch, follower.store.seq + 5,
                ("put", "kv", ("ns", b"stale"), b"tail"))
            assert follower.store.get("kv", ("ns", b"stale")) == b"tail"
            link = primary._links.get(follower.candidate_id)
            assert link is not None
            await link.conn.close()

            await pconn.call("kv_put", "ns", b"after", b"2", True)
            deadline = time.monotonic() + 8
            while (follower.store.get("kv", ("ns", b"after")) != b"2"
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
            assert follower.store.get("kv", ("ns", b"after")) == b"2"
            assert follower.store.get("kv", ("ns", b"stale")) is None, (
                "unacked tail survived the resync")
            assert follower.store.seq == primary.store.seq
            await pconn.close()
        finally:
            await _shutdown_all(cands)

    asyncio.run(run())


def test_quorum_loss_demotes_primary_and_fails_writes(tmp_path):
    """Majority loss is unavailability, not divergence: with both followers
    gone the primary cannot ack a mutation, demotes itself, and the client
    sees a retryable NotPrimaryError (docs/fault_tolerance.md: what survives
    primary loss vs majority loss)."""

    async def run():
        cands = await _boot(3, tmp_path, lease_s=0.6, quorum_timeout_s=1.0)
        try:
            primary = await _wait_primary(cands)
            pconn = await rpc.connect(*primary.addr, name="cli")
            await pconn.call("kv_put", "ns", b"k", b"v", True)
            for c in cands:
                if c is not primary:
                    await c.shutdown()
            with pytest.raises(rpc.NotPrimaryError):
                await pconn.call("kv_put", "ns", b"k2", b"v2", True)
            assert primary.role == "follower", "primary kept its lease"
        finally:
            await _shutdown_all(cands)

    asyncio.run(run())


def test_demotion_releases_lease_and_peer_links(tmp_path):
    """leaksan books: promotion acquires the lease token and per-peer links;
    demotion releases every one of them — a deposed primary must not strand
    follower connections or keep a released lease handle alive."""
    from ray_tpu.devtools import leaksan

    leaksan.reset()
    leaksan.enable()
    try:
        async def run():
            cands = await _boot(3, tmp_path, lease_s=0.6,
                                quorum_timeout_s=1.0)
            try:
                primary = await _wait_primary(cands)
                deadline = time.monotonic() + 5
                while (len(primary._links) < 2
                       and time.monotonic() < deadline):
                    await asyncio.sleep(0.05)
                counts = leaksan.live_counts()
                assert counts.get("gcs_lease", 0) == 1
                assert counts.get("gcs_repl_peer", 0) == 2
                for c in cands:
                    if c is not primary:
                        await c.shutdown()
                pconn = await rpc.connect(*primary.addr, name="cli")
                with pytest.raises(rpc.NotPrimaryError):
                    await pconn.call("kv_put", "ns", b"k", b"v", True)
                await pconn.close()
                counts = leaksan.live_counts()
                assert counts.get("gcs_lease", 0) == 0, counts
                assert counts.get("gcs_repl_peer", 0) == 0, counts
            finally:
                await _shutdown_all(cands)

        asyncio.run(run())
    finally:
        leaksan.disable()
        leaksan.reset()


def test_single_gcs_answers_replication_surface():
    """A lone GcsService speaks the same probe surface the failover clients
    use, reporting itself primary — gcs_replicas=1 keeps one code path."""

    async def run():
        from ray_tpu._private.gcs import GcsService

        gcs = GcsService()
        server = rpc.RpcServer(lambda conn: gcs)
        await server.start(host="127.0.0.1", port=0)
        conn = await rpc.connect("127.0.0.1", server.port, name="cli")
        st = await conn.call("repl_status")
        assert st["role"] == "primary" and st["replicas"] == 1
        stats = await conn.call("store_stats")
        assert stats["repl"]["failovers"] == 0
        await conn.close()
        await server.close()

    asyncio.run(run())
