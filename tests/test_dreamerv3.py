"""DreamerV3: world model + imagination actor-critic.

Shape parity: reference rllib/algorithms/dreamerv3/tests — the world model's
losses drop on a deterministic environment (it IS learnable dynamics), the
imagination machinery produces finite lambda-return training signals, and the
policy improves on a trivially predictable chain task.
"""

import numpy as np
import pytest


class ChainEnv:
    """5-state chain: start at 0, action 1 moves right (+reward at the end),
    action 0 moves left. Deterministic — a world model can learn it exactly."""

    def __init__(self, length=5, horizon=12):
        import gymnasium as gym

        self._len = length
        self._horizon = horizon
        self.observation_space = gym.spaces.Box(0.0, 1.0, (length,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._pos = 0
        self._t = 0

    def _obs(self):
        out = np.zeros(self._len, np.float32)
        out[self._pos] = 1.0
        return out

    def reset(self, seed=None, options=None):
        self._pos, self._t = 0, 0
        return self._obs(), {}

    def step(self, action):
        self._t += 1
        self._pos = min(self._len - 1, self._pos + 1) if action == 1 else max(
            0, self._pos - 1
        )
        reward = 1.0 if self._pos == self._len - 1 else 0.0
        trunc = self._t >= self._horizon
        return self._obs(), reward, False, trunc, {}

    def close(self):
        pass


def _config(**over):
    from ray_tpu.rllib import DreamerV3Config

    cfg = DreamerV3Config().environment(lambda c: ChainEnv()).debugging(seed=0)
    cfg.deter_size = 64
    cfg.units = 64
    cfg.stoch_classes = 4
    cfg.stoch_size = 4
    cfg.sequence_length = 12
    cfg.batch_size_seqs = 8
    cfg.imagination_horizon = 6
    cfg.env_steps_per_iter = 256
    cfg.updates_per_iter = 4
    cfg.learning_starts = 128
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


def test_world_model_learns_deterministic_dynamics():
    """The RSSM world-model loss (reconstruction + reward + KL) must fall
    substantially on deterministic dynamics."""
    algo = _config().build_algo()
    try:
        first = None
        last = None
        for _ in range(10):
            m = algo.train()
            if "learner/wm_loss" in m:
                if first is None:
                    first = m["learner/wm_loss"]
                last = m["learner/wm_loss"]
        assert first is not None, "world model never trained"
        assert np.isfinite(last)
        assert last < 0.7 * first, (first, last)
        # imagination produced finite return signals
        assert np.isfinite(m["learner/imag_return_mean"])
        assert np.isfinite(m["learner/critic_loss"])
        assert np.isfinite(m["learner/actor_loss"])
    finally:
        algo.stop()


def test_policy_improves_on_chain():
    """Acting in imagination reaches the right end of the chain more often
    as training progresses (return = steps spent at the rewarding state)."""
    algo = _config(entropy_coeff=1e-3).build_algo()
    try:
        early = algo.train()["episode_return_mean"]
        for _ in range(14):
            m = algo.train()
        late = m["episode_return_mean"]
        # Random walk on the chain rarely reaches the end (return ~<2 of max
        # 8); a learned go-right policy collects most of the horizon.
        assert late > max(2.0, early + 1.0), (early, late)
    finally:
        algo.stop()


def test_checkpoint_roundtrip(tmp_path):
    algo = _config().build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save_to_path(str(tmp_path / "ck"))
        ts = algo._total_timesteps
        algo2 = _config().build_algo()
        try:
            algo2.restore_from_path(path)
            assert algo2._total_timesteps == ts
            m = algo2.train()  # restored params keep training
            assert np.isfinite(m.get("learner/wm_loss", 0.0))
        finally:
            algo2.stop()
    finally:
        algo.stop()
