"""Multi-node behavior: spillback scheduling, cross-node objects, node failure.

Reference pattern: python/ray/tests with ray_start_cluster adding real raylet processes
(conftest.py:680 + cluster_utils.py).
"""

import pytest

import ray_tpu


def test_spillback_to_resource_node(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"special": 2})
    cluster.connect()
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"special": 1}, num_cpus=0)
    def where():
        return ray_tpu.get_runtime_context().get_node_id().hex()

    node_hex = ray_tpu.get(where.remote(), timeout=120)
    assert node_hex == cluster.worker_nodes[0].node_id_hex


def test_cross_node_object_transfer(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"remote_node": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    import numpy as np

    @ray_tpu.remote(resources={"remote_node": 1}, num_cpus=0)
    def produce():
        return np.full((500, 500), 3.0)

    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    # consume runs on the head node; the array must be pulled across nodes.
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 3.0 * 500 * 500


def test_actor_on_remote_node(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"away": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"away": 1})
    class Remote:
        def pid_node(self):
            return ray_tpu.get_runtime_context().get_node_id().hex()

    a = Remote.remote()
    assert ray_tpu.get(a.pid_node.remote(), timeout=120) == cluster.worker_nodes[0].node_id_hex


def test_node_failure_kills_actor(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1})
    cluster.connect()
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 1})
    class Doomed:
        def ping(self):
            return "pong"

    a = Doomed.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"
    cluster.remove_node(node)
    with pytest.raises(Exception):
        ray_tpu.get(a.ping.remote(), timeout=20)


def test_strict_spread_placement_group(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()
    assert cluster.wait_for_nodes()

    from ray_tpu.util import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(60)
    allocations = pg.allocations()
    assert len({a.hex() for a in allocations}) == 2
