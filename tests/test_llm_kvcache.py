"""Paged KV prefix cache tests: pool/radix mechanics + engine integration.

Covers the docs/kvcache.md contracts: shared-prefix dedup, LRU eviction that
refuses ref-held blocks, concurrent insert/lookup, token-exact equivalence of
cached vs uncached greedy generation (with suffix-only prefill verified via
the prefill bucket), bounded admission, prompt-overflow errors, and the DP
router's full sampling-surface forwarding.
"""

import threading

import numpy as np
import pytest


def _manager(capacity_blocks: int, block_size: int = 4, layers: int = 2,
             heads: int = 2, dim: int = 3):
    """A manager sized in BLOCKS (capacity = exactly N blocks of this shape)."""
    from ray_tpu.llm.kvcache import PrefixCacheManager

    block_bytes = layers * 2 * block_size * heads * dim * 4  # float32
    mgr = PrefixCacheManager(block_size, capacity_blocks * block_bytes,
                             name=f"test-{capacity_blocks}")
    shape = (layers, 2, heads, dim)
    return mgr, shape


def _kv_for(tokens, shape):
    """Deterministic per-token KV rows so block content is checkable."""
    layers, two, heads, dim = shape
    rows = np.stack([
        np.full((layers, two, heads, dim), t, np.float32) for t in tokens
    ], axis=2)  # [L, 2, len(tokens), H, D]
    return rows


def test_shared_prefix_dedup_and_lookup():
    mgr, shape = _manager(capacity_blocks=16)
    prefix = [1, 2, 3, 4, 5, 6, 7, 8]          # 2 blocks of 4
    a = prefix + [10, 11, 12, 13]               # +1 block
    b = prefix + [20, 21, 22, 23]               # +1 block, shares 2
    assert mgr.insert(a, _kv_for(a, shape)) == 3
    assert mgr.insert(b, _kv_for(b, shape)) == 1  # prefix blocks dedup'd
    stats = mgr.stats()
    assert stats["blocks_resident"] == 4
    assert stats["inserted_blocks"] == 4

    # Longest-match lookup, capped at len-1 so one token always prefills.
    lease = mgr.lookup(a + [99])
    assert lease is not None and lease.matched_tokens == 12
    kv = lease.kv()
    assert kv.shape[2] == 12
    np.testing.assert_array_equal(kv, _kv_for(a, shape))
    lease.release()

    # Whole-prompt coverage is capped one block short of the full prompt.
    lease = mgr.lookup(a)
    assert lease is not None and lease.matched_tokens == 8
    lease.release()

    # Re-inserting an existing chain adds nothing (pure dedup walk).
    assert mgr.insert(a, _kv_for(a, shape)) == 0
    assert mgr.stats()["hit_tokens"] == 20


def test_lru_eviction_refuses_ref_held_blocks():
    mgr, shape = _manager(capacity_blocks=3)
    a = [1, 2, 3, 4, 5, 6, 7, 8]    # 2 blocks
    b = [9, 10, 11, 12, 13, 14, 15, 16]
    assert mgr.insert(a, _kv_for(a, shape)) == 2
    lease = mgr.lookup(a + [99])     # pins both of a's blocks
    assert lease.matched_tokens == 8
    # b needs 2 blocks; only 1 slot is free and a is pinned: the tail drops.
    assert mgr.insert(b, _kv_for(b, shape)) == 1
    stats = mgr.stats()
    assert stats["evicted_blocks"] == 0
    assert stats["rejected_blocks"] == 1
    # a survives intact while leased.
    check = mgr.lookup(a + [99])
    assert check is not None and check.matched_tokens == 8
    check.release()
    lease.release()
    # Unpinned now: inserting a fresh chain evicts LRU (b's lone block first,
    # then a's leaf) instead of rejecting.
    c = [30, 31, 32, 33, 34, 35, 36, 37]
    assert mgr.insert(c, _kv_for(c, shape)) == 2
    stats = mgr.stats()
    assert stats["evicted_blocks"] == 2
    assert stats["blocks_resident"] == 3
    assert mgr.lookup(b + [99]) is None  # b was the LRU victim


def test_eviction_unwinds_chains_leaf_first():
    mgr, shape = _manager(capacity_blocks=2)
    a = [1, 2, 3, 4, 5, 6, 7, 8]    # 2 blocks: parent + leaf
    assert mgr.insert(a, _kv_for(a, shape)) == 2
    b = [9, 10, 11, 12, 13, 14, 15, 16]
    # Both of a's blocks must go (leaf, then its parent becomes a leaf).
    assert mgr.insert(b, _kv_for(b, shape)) == 2
    assert mgr.stats()["evicted_blocks"] == 2
    assert mgr.lookup(a + [99]) is None


def test_namespaces_isolate_adapters():
    from ray_tpu.llm.kvcache import RadixIndex

    mgr, shape = _manager(capacity_blocks=8)
    tokens = [1, 2, 3, 4, 5, 6, 7, 8]
    mgr.insert(tokens, _kv_for(tokens, shape), namespace=0)
    assert mgr.lookup(tokens + [9], namespace=1) is None  # other adapter
    with mgr.lookup(tokens + [9], namespace=0) as lease:  # leaksan: release the pin
        assert lease.matched_tokens == 8

    idx = RadixIndex(4)
    assert idx.chunks([1, 2, 3, 4, 5]) == [(1, 2, 3, 4)]
    assert idx.match([1, 2, 3, 4], namespace=3) == []


def test_concurrent_insert_lookup():
    mgr, shape = _manager(capacity_blocks=8, block_size=4)
    rng = np.random.default_rng(0)
    prefixes = [list(map(int, rng.integers(0, 50, 8))) for _ in range(4)]
    errors = []

    def worker(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(50):
                tokens = list(prefixes[int(r.integers(0, 4))])
                tokens += list(map(int, r.integers(50, 99, int(r.integers(0, 8)))))
                if r.random() < 0.5:
                    mgr.insert(tokens, _kv_for(tokens, shape))
                else:
                    lease = mgr.lookup(tokens + [99])
                    if lease is not None:
                        kv = lease.kv()
                        # leased rows always spell the looked-up prefix
                        np.testing.assert_array_equal(
                            kv, _kv_for(tokens[: lease.matched_tokens], shape)
                        )
                        lease.release()
        except Exception as e:  # pragma: no cover - surfaced via errors list
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    stats = mgr.stats()
    assert stats["blocks_resident"] <= 8
    # every lease released: nothing pinned, a full-capacity insert succeeds
    big = list(range(200, 232))
    assert mgr.insert(big, _kv_for(big, shape)) == 8


# -- engine integration ----------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _generate(engine, prompt, n, **sp):
    from ray_tpu.llm import SamplingParams

    out, done = [], threading.Event()

    def cb(tok, fin):
        out.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(max_tokens=n, **sp), cb)
    assert done.wait(180)
    return out


def test_cached_greedy_matches_uncached(tiny_model):
    """Token-exact equivalence: warm prefix-cache hits (suffix-only prefill)
    emit the same greedy tokens as a cache-disabled engine."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.kvcache import PrefixCacheManager

    cfg, model, params = tiny_model
    rng = np.random.default_rng(11)
    prefix = list(map(int, rng.integers(0, cfg.vocab_size, 40)))
    prompts = [prefix + list(map(int, rng.integers(0, cfg.vocab_size, k)))
               for k in (5, 9, 2)]

    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False)
    cached = DecodeEngine(
        cfg, params, num_slots=2, max_seq=128,
        prefix_cache=PrefixCacheManager(16, 8 << 20, name="equiv-test"),
    )
    try:
        expected = [_generate(plain, p, 6) for p in prompts]
        got_cold = _generate(cached, prompts[0], 6)
        assert cached.last_prefill["offset"] == 0
        cold_bucket = cached.last_prefill["bucket"]
        got_warm = [_generate(cached, p, 6) for p in prompts[1:]]
        # Suffix-only prefill actually happened: 2 shared blocks attached,
        # and the prefill bucket shrank to the suffix's bucket.
        assert cached.last_prefill["offset"] == 32
        assert cached.last_prefill["bucket"] < cold_bucket
        stats = cached.prefix_cache_stats()
        assert stats["hits"] == 2 and stats["hit_tokens"] == 64
        assert [got_cold] + got_warm == expected
        # Repeating a warm prompt is still deterministic.
        assert _generate(cached, prompts[1], 6) == expected[1]
    finally:
        plain.shutdown()
        cached.shutdown()


def test_pd_transfer_feeds_decode_cache(tiny_model):
    """A transferred prefix (submit_prefilled + token_ids) lands in the decode
    engine's pool and serves later direct submits suffix-only."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    rng = np.random.default_rng(5)
    prefix = list(map(int, rng.integers(0, cfg.vocab_size, 36)))
    p1 = prefix + [7, 8]
    p2 = prefix + [3]

    prefiller = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                             decode_loop=False, prefix_cache=False)
    decoder = DecodeEngine(cfg, params, num_slots=2, max_seq=128)
    plain = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                         prefix_cache=False)
    try:
        first_logits, kv, plen = prefiller.prefill_detached(p1)
        out, done = [], threading.Event()

        def cb(tok, fin):
            out.append(tok)
            if fin:
                done.set()

        decoder.submit_prefilled(kv, plen, first_logits,
                                 SamplingParams(max_tokens=6), cb, token_ids=p1)
        assert done.wait(180)
        assert out == _generate(plain, p1, 6)
        assert decoder.prefix_cache_stats()["inserted_blocks"] == 2
        # The transferred prefix now serves direct submits from cache.
        assert _generate(decoder, p2, 6) == _generate(plain, p2, 6)
        assert decoder.last_prefill["offset"] == 32
    finally:
        prefiller.shutdown()
        decoder.shutdown()
        plain.shutdown()


def test_prompt_overflow_raises(tiny_model):
    """Oversized prompts raise instead of silently truncating (submit and
    prefill_detached), and a tight generation budget shrinks max_tokens, not
    the prompt."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=32,
                          decode_loop=False, prefix_cache=False)
    try:
        with pytest.raises(ValueError, match="exceeds this engine"):
            engine.submit(list(range(32)), SamplingParams(), lambda *a: None)
        with pytest.raises(ValueError, match="exceeds this prefill engine"):
            engine.prefill_detached(list(range(40)))
        # max_seq - 1 tokens still fits (boundary).
        engine.submit(list(range(31)), SamplingParams(), lambda *a: None)
    finally:
        engine.shutdown()


def test_admission_queue_depth_cap(tiny_model):
    from ray_tpu.llm import DecodeEngine, EngineOverloadedError, SamplingParams

    cfg, model, params = tiny_model
    # decode_loop=False: nothing drains the queue, so the cap is exact.
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                          decode_loop=False, prefix_cache=False,
                          max_queue_depth=2)
    try:
        engine.submit([1, 2], SamplingParams(), lambda *a: None)
        engine.submit([3, 4], SamplingParams(), lambda *a: None)
        with pytest.raises(EngineOverloadedError, match="admission queue"):
            engine.submit([5, 6], SamplingParams(), lambda *a: None)
        with pytest.raises(EngineOverloadedError):
            engine.submit_prefilled(
                np.zeros((cfg.n_layers, 2, 16, cfg.n_kv_heads, cfg.head_dim),
                         np.float32),
                8, np.zeros((cfg.vocab_size,), np.float32),
                SamplingParams(), lambda *a: None,
            )
    finally:
        engine.shutdown()


# -- DP router ------------------------------------------------------------


class _FakeResponse:
    def __init__(self, value):
        self._value = value

    def __await__(self):
        async def _v():
            return self._value

        return _v().__await__()


class _FakeMethod:
    def __init__(self, calls, result):
        self._calls = calls
        self._result = result

    def remote(self, *args, **kwargs):
        self._calls.append((args, kwargs))
        return _FakeResponse(self._result)


class _FakeHandle:
    def __init__(self, calls):
        self.generate = _FakeMethod(calls, {"token_ids": [1], "dp_rank": 0})


def test_dp_router_forwards_full_sampling_surface():
    """DPRouter.__call__ must await coroutine request bodies and forward
    top_k / stop_token_id / lora, not just max_tokens + temperature."""
    import asyncio

    from ray_tpu.llm.dp_serve import DPRouter

    calls = []
    router = DPRouter(_FakeHandle(calls), assigner=None)

    class _Request:
        async def json(self):
            return {"prompt": "hi", "model": "m:tuned", "max_tokens": 7,
                    "temperature": 0.5, "top_k": 3, "stop_token_id": 9}

    out = asyncio.run(router(_Request()))
    assert out["dp_rank"] == 0
    (args, kwargs), = calls
    assert args == ("hi",)
    assert kwargs == {"max_tokens": 7, "temperature": 0.5, "top_k": 3,
                      "stop_token_id": 9, "lora": "tuned"}

    # Sync-json request objects (plain dicts of the body) keep working.
    calls.clear()

    class _SyncRequest:
        def json(self):
            return {"prompt": "yo", "max_tokens": 2}

    asyncio.run(router(_SyncRequest()))
    (args, kwargs), = calls
    assert args == ("yo",) and kwargs["max_tokens"] == 2


def test_dp_router_fingerprint_chain():
    """Chain hashes identify whole-block prefixes: equal prefixes share chain
    entries, divergent blocks fork, and partial blocks add nothing."""
    from ray_tpu.llm.dp_serve import DPRouter

    router = DPRouter(_FakeHandle([]), assigner=None)
    bs = router._block
    a = list(range(3 * bs + 2))
    b = list(range(2 * bs)) + [999] * bs
    ca, cb = router._chain(a), router._chain(b)
    assert len(ca) == 3 and len(cb) == 3
    assert ca[:2] == cb[:2] and ca[2] != cb[2]
    assert router._chain(a[: bs - 1]) == []

    # _record + longest-match bookkeeping (pure, no cluster needed).
    router._record("r1", ca)
    router._record("r2", cb)
    fps = router._fingerprints
    assert set(fps) == {"r1", "r2"}
    m = 0
    for h in cb:
        if h not in fps["r1"]:
            break
        m += 1
    assert m == 2  # r1 matches b's first two blocks only


def test_dp_cache_aware_routing_end_to_end(ray_start_regular):
    """Two requests sharing a whole-block prefix land on the SAME replica
    (longest-expected-match routing) and the router counts a cache-routed
    dispatch; output stays deterministic."""
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.dp_serve import build_dp_openai_app

    app = build_dp_openai_app(
        LLMConfig(model_id="test-tiny", num_slots=2), dp_size=2
    )
    handle = serve.run(app, name="dp-kv", route_prefix=None, _timeout_s=300)
    try:
        # ByteTokenizer: 40+ chars = 2+ whole 16-token blocks of prefix.
        prompt = "system: you are a poet who answers in rhyme. user: hi"
        a = handle.generate.remote(prompt, max_tokens=4).result(timeout_s=300)
        b = handle.generate.remote(prompt, max_tokens=4).result(timeout_s=300)
        assert a["token_ids"] == b["token_ids"]
        assert a["dp_rank"] == b["dp_rank"], "repeat prefix left its replica"
        stats = handle.routing_stats.remote().result(timeout_s=120)
        assert stats["cache_routed"] >= 1, stats
        assert stats["fingerprints"] >= 1
        # Short prompts (no whole block) still fan out via the balanced path.
        outs = [
            handle.generate.remote(f"p{i}", max_tokens=2).result(timeout_s=300)
            for i in range(4)
        ]
        assert all(len(o["token_ids"]) == 2 for o in outs)
        stats = handle.routing_stats.remote().result(timeout_s=120)
        assert stats["untracked"] >= 4, stats
    finally:
        serve.delete("dp-kv")
    # Graceful retirement (round 12): deleting the app runs each replica's
    # shutdown() hook, which hands the dp rank back to the assigner
    # EXPLICITLY — the lazy dead-actor reclamation is the backstop, not the
    # path — so the rank map empties promptly, not at the next exhaustion.
    import time as _time

    import ray_tpu

    assigner = ray_tpu.get_actor("DPRankAssigner-test-tiny", namespace="llm_dp")
    deadline = _time.monotonic() + 30
    held = None
    while _time.monotonic() < deadline:
        held = ray_tpu.get(assigner.ranks.remote())
        if held == {}:
            break
        _time.sleep(0.25)
    assert held == {}, f"dp ranks not released on app delete: {held}"


# -- error-path lease lifetime (leaklint/leaksan round 12) --------------------

def test_detached_prefill_releases_lease_when_attach_raises(tiny_model):
    """prefill_detached on a cache hit must release its lease even when
    materializing the cached rows raises: a leaked lease pins its chain
    against eviction for the engine's whole life (the detached path has no
    scheduler drain to back-stop it)."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.kvcache import PrefixCacheManager

    cfg, model, params = tiny_model
    mgr = PrefixCacheManager(16, 8 << 20, name="detached-leak-test")
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=128,
                          prefix_cache=mgr, decode_loop=False)
    try:
        rng = np.random.default_rng(3)
        prompt = list(map(int, rng.integers(0, cfg.vocab_size, 40)))
        engine.prefill_detached(prompt)          # warm: inserts 2 blocks
        assert mgr.stats()["blocks_resident"] > 0

        real_get = mgr._pool.get

        def poisoned_get(bid):
            raise RuntimeError("injected pool failure")

        mgr._pool.get = poisoned_get
        try:
            with pytest.raises(RuntimeError, match="injected pool failure"):
                engine.prefill_detached(prompt + [1, 2, 3])  # hit -> kv() raises
        finally:
            mgr._pool.get = real_get
        # The decisive assertion: the failed attach released its lease, so
        # nothing is pinned and the engine keeps serving.
        assert mgr.stats()["leases_active"] == 0
        first_logits, kv, n = engine.prefill_detached(prompt + [1, 2, 3])
        assert n == 43 and kv.shape[2] >= 43
        assert mgr.stats()["leases_active"] == 0
    finally:
        engine.shutdown()


def test_chunked_prefill_releases_lease_when_attach_raises(tiny_model):
    """The scheduler path: a cache-hit request whose leased-row
    materialization raises mid-attach must still release the lease (finally
    in _exec_chunk, scheduler drain as the backstop) and fail the caller's
    callback instead of hanging it."""
    from ray_tpu.llm import DecodeEngine, SamplingParams
    from ray_tpu.llm.kvcache import PrefixCacheManager

    cfg, model, params = tiny_model
    mgr = PrefixCacheManager(16, 8 << 20, name="chunk-leak-test")
    engine = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                          prefix_cache=mgr)
    rng = np.random.default_rng(5)
    prompt = list(map(int, rng.integers(0, cfg.vocab_size, 40)))
    try:
        assert _generate(engine, prompt, 4)  # warm the cache
        real_get = mgr._pool.get
        mgr._pool.get = lambda bid: (_ for _ in ()).throw(
            RuntimeError("injected pool failure")
        )
        done = threading.Event()
        tokens = []

        def cb(tok, fin):
            tokens.append(tok)
            if fin:
                done.set()

        try:
            engine.submit(prompt + [7], SamplingParams(max_tokens=4), cb)
            # stepper dies on the poisoned attach; the caller must be failed
            # (token=-1, finished=True), never left hanging
            assert done.wait(60), "callback never fired after attach failure"
        finally:
            mgr._pool.get = real_get
        assert tokens[-1] == -1
        assert mgr.stats()["leases_active"] == 0
        # a dead engine rejects new work loudly instead of enqueueing it
        with pytest.raises(RuntimeError, match="stepper died"):
            engine.submit([1, 2, 3], SamplingParams(max_tokens=2), cb)
    finally:
        engine.shutdown()


def test_shutdown_fails_queued_requests_and_releases_leases(tiny_model):
    """shutdown() must drain: requests admitted but never scheduled get
    their callbacks failed (no hung submitters) and queued leases release."""
    from ray_tpu.llm import DecodeEngine, SamplingParams

    cfg, model, params = tiny_model
    # decode_loop=False: nothing ever drains the queue except shutdown
    engine = DecodeEngine(cfg, params, num_slots=1, max_seq=64,
                          prefix_cache=False, decode_loop=False)
    results = []
    engine.submit([1, 2, 3], SamplingParams(max_tokens=2),
                  lambda tok, fin: results.append((tok, fin)))
    assert results == []
    engine.shutdown()
    assert results == [(-1, True)]
    # idempotent: a second shutdown neither raises nor double-fails
    engine.shutdown()
    assert results == [(-1, True)]
    with pytest.raises(RuntimeError, match="shut down"):
        engine.submit([4], SamplingParams(max_tokens=1),
                      lambda tok, fin: None)


def test_scheduler_drain_is_exception_safe():
    """One lease whose release raises must not leave the remaining drained
    requests leased or unreported."""
    from ray_tpu.llm.scheduler import Request, Scheduler

    sched = Scheduler(num_slots=1, buckets=(8, 16), max_seq=32,
                      token_budget=0, max_queue_depth=0)

    class _Lease:
        def __init__(self, blow_up):
            self.blow_up = blow_up
            self.released = False

        def release(self):
            if self.blow_up:
                raise RuntimeError("poisoned release")
            self.released = True

    reqs = [Request("prompt", prompt=[1, 2, 3], callback=lambda t, f: None)
            for _ in range(3)]
    leases = [_Lease(False), _Lease(True), _Lease(False)]
    for r, l in zip(reqs, leases):
        r.lease = l
        sched.submit(r)
    drained = sched.drain()
    assert len(drained) == 3
    assert leases[0].released and leases[2].released
    assert all(r.lease is None for r in drained)
    assert sched.queue_depth() == 0
