"""Post-mortem debugger + pushed-down task-event queries (round 5).

Parity: reference `python/ray/util/rpdb.py` (socket pdb, sessions advertised
via GCS, `ray debug` attaches) and GcsTaskManager server-side query filters.
"""

import socket
import time

import pytest

import ray_tpu
from ray_tpu._private import debugger
from ray_tpu._private.config import CONFIG


@pytest.fixture
def pm_cluster():
    ray_tpu.init(
        num_cpus=2,
        num_tpus=0,
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "RAY_TPU_POST_MORTEM": "1",
            "RAY_TPU_POST_MORTEM_WAIT_S": "60",
        },
    )
    yield
    ray_tpu.shutdown()
    CONFIG._reset()


def _read_until(sock, marker: bytes, timeout: float = 30.0) -> bytes:
    sock.settimeout(timeout)
    buf = b""
    while marker not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
    return buf


PROMPT = b"(ray_tpu-pdb) "


def test_post_mortem_breakpoint_roundtrip(pm_cluster):
    @ray_tpu.remote
    def boom():
        secret = 12345  # noqa: F841 - inspected via the debugger
        raise ValueError("park me")

    ref = boom.remote()

    # The worker parks the failing frame and advertises a session.
    from ray_tpu._private.worker import global_worker

    deadline = time.time() + 60
    sessions = []
    while time.time() < deadline:
        sessions = debugger.list_sessions(global_worker())
        if sessions:
            break
        time.sleep(0.2)
    assert sessions, "no post-mortem session advertised"
    s = sessions[0]
    assert "park me" in s["error"]
    assert s["name"] == "boom"

    # Drive pdb over the socket: inspect the raising frame, then continue.
    with socket.create_connection((s["ip"], s["port"]), timeout=30) as conn:
        banner = _read_until(conn, PROMPT)
        assert b"post-mortem" in banner and b"park me" in banner
        conn.sendall(b"p secret\n")
        out = _read_until(conn, PROMPT)
        assert b"12345" in out, out
        conn.sendall(b"c\n")

    # Releasing the debugger lets the original error propagate to the caller.
    with pytest.raises(ValueError, match="park me"):
        ray_tpu.get(ref, timeout=60)

    # The session deregisters once released.
    deadline = time.time() + 30
    while time.time() < deadline:
        if not debugger.list_sessions(global_worker()):
            break
        time.sleep(0.2)
    assert not debugger.list_sessions(global_worker())


def test_list_tasks_filters_push_down(pm_cluster):
    from ray_tpu.util import state

    @ray_tpu.remote
    def alpha():
        return 1

    @ray_tpu.remote
    def beta():
        return 2

    ray_tpu.get([alpha.remote() for _ in range(3)] + [beta.remote()],
                timeout=120)

    # Events flush on a cadence; poll for the filtered page.
    deadline = time.time() + 60
    rows = []
    while time.time() < deadline:
        rows = state.list_tasks(filters=[("name", "=", "alpha")], limit=100)
        if len({r["task_id"] for r in rows}) >= 3 and any(
            r.get("state") == "FINISHED" for r in rows
        ):
            break
        time.sleep(0.5)
    assert rows and all(r["name"] == "alpha" for r in rows)
    assert len({r["task_id"] for r in rows}) == 3

    # Pagination pushes down too: page sizes add up to the unpaged listing.
    all_alpha = state.list_tasks(filters=[("name", "=", "alpha")], limit=1000)
    page1 = state.list_tasks(filters=[("name", "=", "alpha")], limit=2)
    page2 = state.list_tasks(filters=[("name", "=", "alpha")], limit=2,
                             offset=2)
    assert [r["task_id"] for r in page1 + page2][:len(all_alpha)] == [
        r["task_id"] for r in all_alpha[:4]
    ]

    # Per-task drill-down rides the GCS index.
    tid = rows[0]["task_id"]
    events = state.get_task(tid)
    assert events and all(e["task_id"] == tid for e in events)
    states = [e["state"] for e in events]
    assert "FINISHED" in states

    # Comparison predicates evaluate server-side.
    t0 = min(e.get("time", 0.0) for e in events)
    recent = state.list_tasks(filters=[("time", ">=", t0)], limit=1000)
    assert recent
