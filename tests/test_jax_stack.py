"""TPU compute stack tests on the virtual 8-device CPU mesh.

Covers mesh construction, flash-attention kernel (interpret mode) vs reference, ring /
ulysses attention equivalence under shard_map, and a sharded FSDP+TP train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import mesh as mesh_lib


def test_create_mesh_shapes():
    m = mesh_lib.create_mesh({"dp": 2, "tp": 4})
    assert m.shape["dp"] == 2 and m.shape["tp"] == 4
    m2 = mesh_lib.create_mesh({"fsdp": -1})
    assert m2.shape["fsdp"] == 8


def test_logical_to_spec():
    spec = mesh_lib.logical_to_spec(("batch", "seq", "embed"))
    assert spec[0] == ("dp", "fsdp") or spec[0] in ("dp", ("dp", "fsdp"))
    # embed must not reuse axes already consumed by batch
    assert spec[2] is None or spec[2] not in ("dp",)


def test_flash_attention_matches_reference_interpret():
    from ray_tpu.ops.attention import _flash_forward, reference_attention

    B, S, H, D = 2, 256, 4, 64
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), jnp.float32)
        for i in range(3)
    )
    out, lse = _flash_forward(
        q, k, v, causal=True, scale=D**-0.5, block_q=128, block_k=128, interpret=True
    )
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_backward_matches_reference_interpret():
    """Pallas backward kernel parity, run in interpret mode on CPU.

    The dq accumulator block is revisited across the outer k-block grid axis
    (see _flash_backward), so this guards the refetch-on-revisit semantics the
    kernel relies on — a Pallas semantics change would corrupt gradients
    silently, TPU-only, without this check (round-2 advisor, medium)."""
    from ray_tpu.ops.attention import _flash_backward, _flash_forward, reference_attention

    B, S, H, D = 2, 256, 4, 64
    key = jax.random.PRNGKey(7)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), jnp.float32)
        for i in range(3)
    )
    scale = D**-0.5
    out, lse = _flash_forward(
        q, k, v, causal=True, scale=scale, block_q=128, block_k=128, interpret=True
    )
    g = jax.random.normal(jax.random.fold_in(key, 9), out.shape, jnp.float32)
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, g, causal=True, scale=scale,
        block_q=128, block_k=128, interpret=True,
    )

    def loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * g)

    rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=5e-4, rtol=5e-4)


def test_flash_backward_gqa_reduction_interpret():
    """GQA rep>1: full-head kernel grads reduced over the repeat axis must match
    reference grads w.r.t. the un-repeated k/v (round-2 advisor, medium)."""
    from ray_tpu.ops.attention import _flash_backward, _flash_forward, reference_attention

    B, S, H, Hkv, D = 1, 128, 4, 2, 32
    rep = H // Hkv
    key = jax.random.PRNGKey(11)
    q = jax.random.normal(key, (B, S, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, Hkv, D), jnp.float32)
    scale = D**-0.5
    k_full = jnp.repeat(k, rep, axis=2)
    v_full = jnp.repeat(v, rep, axis=2)
    out, lse = _flash_forward(
        q, k_full, v_full, causal=True, scale=scale, block_q=64, block_k=64,
        interpret=True,
    )
    g = jax.random.normal(jax.random.fold_in(key, 3), out.shape, jnp.float32)
    dq, dkf, dvf = _flash_backward(
        q, k_full, v_full, out, lse, g, causal=True, scale=scale,
        block_q=64, block_k=64, interpret=True,
    )
    dk = dkf.reshape(B, S, Hkv, rep, D).sum(axis=3)
    dv = dvf.reshape(B, S, Hkv, rep, D).sum(axis=3)

    def loss(q, k, v):
        kf = jnp.repeat(k, rep, axis=2)
        vf = jnp.repeat(v, rep, axis=2)
        return jnp.sum(reference_attention(q, kf, vf, causal=True) * g)

    rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=5e-4, rtol=5e-4)


def test_flash_attention_grad_path():
    from ray_tpu.ops.attention import flash_attention, reference_attention

    B, S, H, D = 1, 64, 2, 32
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), jnp.float32)
        for i in range(3)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_gqa_flash_matches_reference():
    from ray_tpu.ops.attention import flash_attention, reference_attention

    B, S, H, Hkv, D = 1, 32, 4, 2, 16
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, Hkv, D))
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, Hkv, D))
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=2e-5, rtol=2e-5,
    )


def test_ring_attention_matches_full():
    from ray_tpu.util.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.attention import reference_attention
    from ray_tpu.ops.ring_attention import ring_attention

    mesh = mesh_lib.create_mesh({"sp": 4})
    B, S, H, D = 2, 128, 4, 32
    key = jax.random.PRNGKey(3)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), jnp.float32)
        for i in range(3)
    )

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = ring(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_ulysses_attention_matches_full():
    from ray_tpu.util.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.attention import reference_attention
    from ray_tpu.ops.ring_attention import ulysses_attention

    mesh = mesh_lib.create_mesh({"sp": 4})
    B, S, H, D = 1, 128, 4, 32
    key = jax.random.PRNGKey(4)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D), jnp.float32)
        for i in range(3)
    )
    uly = shard_map(
        lambda q, k, v: ulysses_attention(
            q, k, v, "sp", attn_fn=lambda a, b, c: reference_attention(a, b, c, causal=True)
        ),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = uly(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_sharded_train_step_fsdp_tp():
    import optax

    from ray_tpu.models.transformer import Transformer, get_config
    from ray_tpu.parallel.spmd import build_train_step, init_state

    cfg = get_config("test-tiny")
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    optimizer = optax.adamw(1e-3)
    state, shardings = init_state(model, cfg, optimizer, mesh, sample_shape=(2, 32))

    # embedding [vocab, embed] should be sharded over fsdp on dim 1
    emb_sharding = state.params["embedding"].sharding
    assert "fsdp" in str(emb_sharding.spec)

    step_fn, batch_shardings = build_train_step(model, optimizer, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 32), 0, cfg.vocab_size)
    batch = {
        "tokens": jax.device_put(tokens, batch_shardings["tokens"]),
        "targets": jax.device_put(tokens, batch_shardings["targets"]),
    }
    with mesh:
        state2, metrics = step_fn(state, batch)
        loss1 = float(metrics["loss"])
        for _ in range(3):
            state2, metrics = step_fn(state2, batch)
    assert float(metrics["loss"]) < loss1  # loss decreases on a repeated batch
    assert int(metrics["step"]) == 4


def test_model_decode_with_kv_cache():
    from ray_tpu.models.transformer import Transformer, get_config, init_params

    cfg = get_config("test-tiny")
    model, params = init_params(cfg, batch=1, seq=16)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 16), 0, cfg.vocab_size)
    full_logits = model.apply(params, tokens)

    # Incremental decode must match the parallel forward.
    caches = [
        (
            jnp.zeros((1, 32, cfg.n_kv_heads, cfg.head_dim), jnp.float32),
            jnp.zeros((1, 32, cfg.n_kv_heads, cfg.head_dim), jnp.float32),
            0,
        )
        for _ in range(cfg.n_layers)
    ]
    outs = []
    for t in range(16):
        logits, caches = model.apply(
            params,
            tokens[:, t : t + 1],
            positions=jnp.array([[t]], jnp.int32),
            kv_caches=caches,
        )
        outs.append(logits[:, 0])
    inc = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(inc), np.asarray(full_logits), atol=2e-3, rtol=2e-3
    )


def test_ulysses_attention_gqa_with_small_kv_heads():
    """GQA where kv-heads (2) < sp axis (4): the repeat fallback must kick in."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.attention import reference_attention
    from ray_tpu.ops.ring_attention import ulysses_attention
    from ray_tpu.util.jax_compat import shard_map

    sp = 4
    mesh = mesh_lib.create_mesh({"sp": sp}, devices=jax.devices()[:sp])
    B, S, H, Hkv, D = 2, 16 * sp, 8, 2, 16
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(jax.random.fold_in(key, 0), (B, S, H, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, Hkv, D), jnp.float32)
    fn = jax.jit(
        shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, "sp",
                attn_fn=lambda a, b, c: reference_attention(a, b, c, causal=True),
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
        )
    )
    out = fn(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_moe_routing_capacity_and_balance():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.moe import top_k_routing

    T, E, k, C = 64, 4, 2, 40
    logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
    dispatch, combine, aux = top_k_routing(logits, k, C)
    assert dispatch.shape == (T, E, C)
    # each expert's slots hold at most one token each
    per_slot = np.asarray(dispatch).sum(axis=0)  # [E, C]
    assert per_slot.max() <= 1.0 + 1e-6
    # each kept token's combine weights sum to ~1
    kept = np.asarray(dispatch).sum(axis=(1, 2)) > 0
    combine_sums = np.asarray(combine).sum(axis=(1, 2))[kept]
    np.testing.assert_allclose(combine_sums, 1.0, atol=1e-5)
    assert float(aux) > 0


def test_moe_transformer_train_step_on_ep_mesh():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.transformer import Transformer, get_config
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.spmd import build_train_step, init_state

    cfg = get_config(
        "test-tiny", moe_experts=4, moe_top_k=2, scan_layers=True, remat=False,
    )
    model = Transformer(cfg)
    mesh = mesh_lib.create_mesh({"dp": 2, "ep": 4}, devices=jax.devices()[:8])
    optimizer = optax.adamw(1e-3)
    state, _ = init_state(model, cfg, optimizer, mesh, sample_shape=(4, 32))
    step_fn, shardings = build_train_step(model, optimizer, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, cfg.vocab_size)
    batch = {
        "tokens": jax.device_put(tokens, shardings["tokens"]),
        "targets": jax.device_put(tokens, shardings["targets"]),
    }
    with mesh:
        state, metrics = step_fn(state, batch)
        state, metrics2 = step_fn(state, batch)
    assert jnp.isfinite(metrics["loss"])
    assert metrics2["loss"] < metrics["loss"] + 1.0  # sane optimization step
