"""ray_tpu.data tests.

Shape parity with the reference suite (python/ray/data/tests/): construction, map
transforms, all-to-all shuffles, groupby aggregates, iteration incl. the JAX batch
path, splits, and file IO roundtrips.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _cluster(ray_start_regular):
    yield


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_and_schema():
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    assert ds.count() == 2
    assert set(ds.columns()) == {"a", "b"}
    assert ds.take_all()[1]["b"] == "y"


def test_map_batches_numpy():
    ds = rd.range(64).map_batches(lambda b: {"id": b["id"] * 2})
    out = ds.take_all()
    assert sorted(r["id"] for r in out) == [2 * i for i in range(64)]


def test_map_batches_batch_size_and_format():
    seen_sizes = []

    def f(batch):
        seen_sizes.append(len(batch["id"]))
        return batch

    ds = rd.range(100, parallelism=1).map_batches(f, batch_size=30).materialize()
    assert ds.count() == 100


def test_map_filter_flat_map():
    ds = rd.range(20).map(lambda r: {"v": r["id"] + 1})
    ds = ds.filter(lambda r: r["v"] % 2 == 0)
    ds = ds.flat_map(lambda r: [{"v": r["v"]}, {"v": -r["v"]}])
    vals = sorted(r["v"] for r in ds.take_all())
    evens = [i + 1 for i in range(20) if (i + 1) % 2 == 0]
    assert vals == sorted([v for e in evens for v in (e, -e)])


def test_add_drop_select_rename_columns():
    ds = rd.range(10).add_column("twice", lambda b: b["id"] * 2)
    assert set(ds.columns()) == {"id", "twice"}
    assert ds.select_columns(["twice"]).columns() == ["twice"]
    assert ds.drop_columns(["twice"]).columns() == ["id"]
    assert set(ds.rename_columns({"id": "idx"}).columns()) == {"idx", "twice"}


def test_limit_short_circuits():
    ds = rd.range(10_000, parallelism=8).limit(17)
    assert ds.count() == 17


def test_repartition():
    ds = rd.range(100, parallelism=4).repartition(7).materialize()
    assert ds.count() == 100
    assert ds.num_blocks() == 7


def test_random_shuffle_preserves_multiset():
    ds = rd.range(200, parallelism=4).random_shuffle(seed=7)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(200))
    first = [r["id"] for r in rd.range(200, parallelism=4).random_shuffle(seed=7).take(20)]
    assert first != list(range(20))


def test_sort():
    rng = np.random.default_rng(0)
    items = [{"k": int(v)} for v in rng.permutation(500)]
    ds = rd.from_items(items).sort("k")
    out = [r["k"] for r in ds.take_all()]
    assert out == sorted(out)
    out_desc = [r["k"] for r in rd.from_items(items).sort("k", descending=True).take(10)]
    assert out_desc == list(range(499, 489, -1))


def test_groupby_aggregate():
    items = [{"g": ["a", "b", "c"][i % 3], "v": i} for i in range(90)]
    ds = rd.from_items(items).groupby("g").aggregate(rd.Sum("v"), rd.Count(), rd.Mean("v"))
    rows = {r["g"]: r for r in ds.take_all()}
    for gi, g in enumerate(["a", "b", "c"]):
        vs = [i for i in range(90) if i % 3 == gi]
        assert rows[g]["sum(v)"] == sum(vs)
        assert rows[g]["count()"] == len(vs)
        assert rows[g]["mean(v)"] == pytest.approx(np.mean(vs))


def test_global_aggregates():
    ds = rd.range(100)
    assert ds.sum("id") == sum(range(100))
    assert ds.min("id") == 0
    assert ds.max("id") == 99
    assert ds.mean("id") == pytest.approx(49.5)
    assert ds.std("id") == pytest.approx(np.std(np.arange(100), ddof=1))


def test_union_zip():
    a = rd.range(10)
    b = rd.range(10)
    assert a.union(b).count() == 20
    z = rd.range(5).zip(rd.range(5).map_batches(lambda x: {"other": x["id"] + 10}))
    rows = z.take_all()
    assert all(r["other"] == r["id"] + 10 for r in rows)


def test_iter_batches_rebatching():
    ds = rd.range(100, parallelism=7)
    batches = list(ds.iter_batches(batch_size=32, drop_last=False))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 100
    assert all(s == 32 for s in sizes[:-1])


def test_iter_batches_local_shuffle():
    ds = rd.range(256, parallelism=2)
    flat = np.concatenate(
        [b["id"] for b in ds.iter_batches(batch_size=64, local_shuffle_buffer_size=100,
                                          local_shuffle_seed=3)]
    )
    assert sorted(flat.tolist()) == list(range(256))
    assert flat[:10].tolist() != list(range(10))


def test_iter_jax_batches():
    import jax.numpy as jnp

    ds = rd.range(64)
    batches = list(ds.iter_jax_batches(batch_size=16, dtypes={"id": jnp.float32}))
    assert len(batches) == 4
    assert all(b["id"].dtype == jnp.float32 for b in batches)
    total = sum(float(b["id"].sum()) for b in batches)
    assert total == sum(range(64))


def test_split_and_streaming_split():
    parts = rd.range(90).split(3)
    assert [p.count() for p in parts] == [30, 30, 30]
    its = rd.range(90, parallelism=6).streaming_split(3)
    counts = [sum(len(b["id"]) for b in it.iter_batches(batch_size=10)) for it in its]
    assert sum(counts) == 90


def test_split_at_indices_and_train_test():
    parts = rd.range(100).split_at_indices([10, 40])
    assert [p.count() for p in parts] == [10, 30, 60]
    train, test = rd.range(100).train_test_split(0.25)
    assert train.count() == 75 and test.count() == 25


def test_parquet_roundtrip(tmp_path):
    ds = rd.range(50).map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    files = ds.write_parquet(str(tmp_path / "out"))
    assert files
    back = rd.read_parquet(str(tmp_path / "out"))
    rows = back.take_all()
    assert len(rows) == 50
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_csv_json_roundtrip(tmp_path):
    ds = rd.from_items([{"a": i, "b": f"s{i}"} for i in range(20)])
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 20
    ds.write_json(str(tmp_path / "json"))
    back = rd.read_json(str(tmp_path / "json")).take_all()
    assert sorted(r["a"] for r in back) == list(range(20))


def test_tensor_columns_roundtrip():
    arr = np.arange(24, dtype=np.float32).reshape(6, 2, 2)
    ds = rd.from_numpy(arr, column="x").map_batches(lambda b: {"x": b["x"] + 1})
    out = ds.take_batch(6)
    np.testing.assert_allclose(out["x"], arr + 1)


def test_actor_pool_map_batches():
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(40, parallelism=4).map_batches(
        AddConst, fn_args=(100,), compute=rd.ActorPoolStrategy(size=2)
    )
    assert sorted(r["id"] for r in ds.take_all()) == [100 + i for i in range(40)]


def test_materialize_reuse():
    ds = rd.range(30).map_batches(lambda b: {"id": b["id"] * 3}).materialize()
    assert ds.count() == 30
    assert ds.count() == 30  # second pass hits cached bundles
    assert sorted(r["id"] for r in ds.take_all()) == [3 * i for i in range(30)]


def test_unique_and_random_sample():
    ds = rd.from_items([{"v": i % 5} for i in range(100)])
    assert ds.unique("v") == [0, 1, 2, 3, 4]
    sampled = rd.range(1000).random_sample(0.1, seed=0).count()
    assert 40 < sampled < 250


def test_shard():
    ds = rd.range(100, parallelism=10)
    s0 = ds.shard(2, 0).count()
    s1 = ds.shard(2, 1).count()
    assert s0 + s1 == 100


def test_sort_string_keys():
    items = [{"k": s} for s in ["pear", "apple", "fig", "banana", "kiwi", "date"]]
    out = [r["k"] for r in rd.from_items(items).sort("k").take_all()]
    assert out == sorted(out)


def test_sort_after_selective_filter():
    # Early bundles all empty after the filter; sort must still sort (regression).
    ds = rd.range(120, parallelism=12).filter(lambda r: r["id"] >= 110)
    out = [r["id"] for r in ds.sort("id", descending=True).take_all()]
    assert out == list(range(119, 109, -1))


def test_error_propagates_to_slow_consumer():
    import time

    def boom(batch):
        if batch["id"].max() >= 150:
            raise ValueError("boom")
        return batch

    ds = rd.range(200, parallelism=8).map_batches(boom)
    with pytest.raises(Exception):
        for b in ds.iter_batches(batch_size=10):
            time.sleep(0.05)  # slow consumer: error must still arrive, not hang


def test_abandoned_iterator_stops_executor():
    import threading
    import time

    before = threading.active_count()
    for _ in range(5):
        ds = rd.range(10_000, parallelism=8)
        next(iter(ds.iter_batches(batch_size=10)))
    time.sleep(1.0)
    assert threading.active_count() <= before + 2


def test_seeded_shuffle_differs_across_blocks():
    # Regression: every map task used the same permutation for its first block.
    ds = rd.range(400, parallelism=4).random_shuffle(seed=5)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(400))
    # Per-position deltas between block-sized chunks must not be constant.
    chunks = [ids[i * 100 : (i + 1) * 100] for i in range(4)]
    deltas = {tuple((b - a) for a, b in zip(chunks[0], c)) for c in chunks[1:]}
    assert all(len(set(d)) > 1 for d in deltas)


def test_shard_slices_read_tasks_not_output():
    ds = rd.range(100, parallelism=10)
    shard = ds.shard(5, 2)
    stage = shard._stages[0]
    tasks = stage.datasource.get_read_tasks(10)
    assert len(tasks) == 2  # 10 read tasks strided by 5
    total = sum(s.count() for s in (ds.shard(5, i) for i in range(5)))
    assert total == 100


def test_rows_to_block_unions_keys():
    ds = rd.from_items([{"id": i} for i in range(4)]).map(
        lambda r: {"id": r["id"]} if r["id"] % 2 == 0 else {"id": r["id"], "label": 1}
    )
    rows = ds.take_all()
    assert any("label" in r and r["label"] == 1 for r in rows)


def test_seeded_random_sample_uncorrelated_across_blocks():
    ds = rd.range(4000, parallelism=8).random_sample(0.5, seed=42)
    ids = np.array(sorted(r["id"] for r in ds.take_all()))
    # Correlated per-block masks would repeat every 500 ids; check block-relative
    # positions differ between two blocks.
    picks0 = set(ids[(ids >= 0) & (ids < 500)] % 500)
    picks1 = set(ids[(ids >= 500) & (ids < 1000)] % 500)
    assert picks0 != picks1


def test_abandoned_jax_iterator_stops_threads():
    import threading
    import time

    before = threading.active_count()
    for _ in range(4):
        it = rd.range(50_000, parallelism=8).iter_jax_batches(batch_size=16)
        next(it)
        del it
    import gc

    gc.collect()
    time.sleep(1.0)
    assert threading.active_count() <= before + 3


def test_dynamic_block_splitting(ray_start_regular):
    """Oversized transform outputs are split to target_max_block_size
    (reference: DataContext-driven dynamic block splitting)."""
    import numpy as np

    import ray_tpu.data as rdata
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    prev = ctx.target_max_block_size
    ctx.target_max_block_size = 64 * 1024  # 64KB
    try:
        # One input block ballooning to ~8MB through map_batches.
        ds = rdata.from_items([{"n": i} for i in range(8)]).map_batches(
            lambda b: {"big": np.ones((len(b["n"]), 128 * 1024), np.float64)},
            batch_size=8,
        )
        bundles = list(ds.materialize()._execute())
        sizes = []
        for bundle in bundles:
            for block in bundle.get_blocks():
                sizes.append(block.nbytes)
        assert len(sizes) >= 8  # one ~8MB output block split to ~1MB row slices
        # Every block respects the cap with slack for row granularity (1 row ~ 1MB).
        assert max(sizes) <= 2 * 1024 * 1024
        total_rows = sum(b.num_rows for bundle in bundles for b in bundle.get_blocks())
        assert total_rows == 8
    finally:
        ctx.target_max_block_size = prev


def test_block_split_helper_zero_copy_roundtrip():
    import numpy as np

    from ray_tpu.data.block import BlockAccessor, batch_to_block, split_block_by_bytes

    block = batch_to_block({"x": np.arange(1000, dtype=np.int64)})
    parts = split_block_by_bytes(block, block.nbytes // 4)
    assert 4 <= len(parts) <= 6
    assert sum(p.num_rows for p in parts) == 1000
    recon = np.concatenate(
        [BlockAccessor.for_block(p).to_batch_format("numpy")["x"] for p in parts]
    )
    np.testing.assert_array_equal(recon, np.arange(1000))


def test_split_blocks_pickle_small():
    """Split blocks must serialize at slice size, not parent-buffer size
    (regression: pickled Arrow slices carry the whole parent table)."""
    import pickle

    import numpy as np

    from ray_tpu.data.block import batch_to_block, split_block_by_bytes

    block = batch_to_block({"x": np.ones(1_000_000, np.float64)})  # ~8MB
    parts = split_block_by_bytes(block, block.nbytes // 8)
    assert len(parts) >= 8
    blob = pickle.dumps(parts[0], protocol=5)
    assert len(blob) < 2 * parts[0].nbytes, (len(blob), parts[0].nbytes)


def test_join_inner_and_left(ray_start_regular):
    import ray_tpu.data as rd

    left = rd.from_items([{"id": i, "x": i * 10} for i in range(8)])
    right = rd.from_items([{"id": i, "y": i * 100} for i in range(4, 12)])
    joined = left.join(right, on="id").sort("id").take_all()
    assert [r["id"] for r in joined] == [4, 5, 6, 7]
    assert all(r["y"] == r["id"] * 100 and r["x"] == r["id"] * 10 for r in joined)

    lj = left.join(right, on="id", how="left").sort("id").take_all()
    assert [r["id"] for r in lj] == list(range(8))
    assert lj[0]["y"] is None and lj[7]["y"] == 700

    # Multi-key join + non-key column collision gets the right suffix.
    l2 = rd.from_items([{"a": 1, "b": 2, "v": 7}])
    r2 = rd.from_items([{"a": 1, "b": 2, "v": 9}])
    out = l2.join(r2, on=["a", "b"]).take_all()
    assert out == [{"a": 1, "b": 2, "v": 7, "v_1": 9}]


def test_join_partitioned_matches_single_partition(ray_start_regular):
    import ray_tpu.data as rd

    left = rd.range(50).map(lambda r: {"id": r["id"] % 13, "x": r["id"]})
    right = rd.from_items([{"id": i, "tag": f"t{i}"} for i in range(13)])
    many = left.join(right, on="id", num_partitions=4).take_all()
    one = left.join(right, on="id", num_partitions=1).take_all()
    key = lambda r: (r["id"], r["x"])  # noqa: E731
    assert sorted(many, key=key) == sorted(one, key=key)
    assert len(many) == 50


def test_join_empty_copartitions_and_empty_sides(ray_start_regular):
    """Left/outer joins survive co-partitions where one side is empty
    (regression: empty side crashed the pyarrow join or silently dropped
    the other side's rows)."""
    import ray_tpu.data as rd

    left = rd.from_items([{"id": i, "x": i} for i in range(12)])
    right = rd.from_items([{"id": 0, "y": 99}])  # one key: most partitions empty
    lj = left.join(right, on="id", how="left", num_partitions=4).sort("id").take_all()
    assert len(lj) == 12
    assert lj[0]["y"] == 99 and all(r["y"] is None for r in lj[1:])

    rj = right.join(left, on="id", how="right", num_partitions=4).sort("id").take_all()
    assert len(rj) == 12

    empty = rd.from_items([{"id": 1, "z": 2}]).filter(lambda r: False)
    assert left.join(empty, on="id").take_all() == []
    assert len(left.join(empty, on="id", how="left").take_all()) == 12
