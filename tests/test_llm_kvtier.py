"""Hierarchical KV store tests (docs/kvcache.md tiering):

- tier mechanics: atomic spill commits (torn spills invisible, incl. a real
  SIGKILL mid-spill), content-addressed disk store with byte cap, device
  hot-tier promotion/demotion, eviction-while-leased refusal across tiers;
- token identity: greedy output identical for device-warm / host-warm /
  disk-warm / cross-replica-fetched prefixes vs a cold reference engine;
- multicast: 1 prefill -> N decode fanout token-identical to point-to-point
  with exactly ONE staging (D2H) pass on the writer, and dead subscribers
  unwinding the writer without wedging siblings;
- the lookup-contention fix: insert's block copies stage OUTSIDE the
  manager lock;
- leaksan lifetimes for spill handles, subscriptions, and fetch leases.

Runs under the leaksan guard (conftest LEAKSAN_SUITES).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest


def _kv_for(tokens, shape):
    layers, two, heads, dim = shape
    return np.stack([
        np.full((layers, two, heads, dim), t, np.float32) for t in tokens
    ], axis=2)


def _tiered(tmp_path, capacity_blocks, block_size=4, layers=2, heads=2,
            dim=3, device_blocks=0, spill=True, name="kvtier"):
    from ray_tpu.llm.kvcache import TieredPrefixCacheManager

    block_bytes = layers * 2 * block_size * heads * dim * 4
    mgr = TieredPrefixCacheManager(
        block_size, capacity_blocks * block_bytes, name=name,
        device_bytes=device_blocks * block_bytes,
        spill_dir=str(tmp_path / "spill") if spill else "",
        spill_bytes=64 * block_bytes,
    )
    return mgr, (layers, 2, heads, dim)


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    pytest.fail(f"timed out waiting for {msg}")


# -- spill atomicity ----------------------------------------------------------

def test_spill_commit_is_atomic_and_abort_invisible(tmp_path):
    from ray_tpu.llm.kvcache.tiers import DiskSpillStore

    store = DiskSpillStore(str(tmp_path))
    kv = np.arange(2 * 2 * 4 * 2 * 3, dtype=np.float32).reshape(2, 2, 4, 2, 3)
    key = store.key(7, [1, 2, 3, 4])
    assert store.get(key) is None
    assert store.put(key, kv)
    np.testing.assert_array_equal(store.get(key), kv)
    # Content addressing: a re-spill of a committed entry is a no-op.
    assert not store.put(key, kv)

    # An aborted (never-committed) spill is invisible and leaves no tmp.
    f = store.open_spill(store.key(7, [9, 9, 9, 9]))
    f.write(b"partial garbage")
    f.close()
    assert store.get(store.key(7, [9, 9, 9, 9])) is None
    assert not [n for n in os.listdir(str(tmp_path)) if n.endswith(".tmp")]


def test_sigkill_mid_spill_is_invisible_on_restart(tmp_path):
    """The crash-safety contract: a process killed between write and commit
    leaves nothing a restarted store can see — the chain is simply a miss,
    never corruption — while previously COMMITTED entries still load."""
    from ray_tpu.llm.kvcache.tiers import DiskSpillStore

    code = f"""
import os, signal
import numpy as np
from ray_tpu.llm.kvcache.tiers import DiskSpillStore
store = DiskSpillStore({str(tmp_path)!r})
kv = np.ones((2, 2, 4, 2, 3), np.float32)
store.put(store.key(0, [1, 2, 3, 4]), kv)          # committed: must survive
f = store.open_spill(store.key(0, [5, 6, 7, 8]))   # torn: must be invisible
f.write(b"partial spill bytes, never committed")
f._f.flush()
os.kill(os.getpid(), signal.SIGKILL)
"""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == -signal.SIGKILL, (proc.returncode, proc.stderr)
    store = DiskSpillStore(str(tmp_path))  # restart: sweeps tmp orphans
    np.testing.assert_array_equal(
        store.get(store.key(0, [1, 2, 3, 4])), np.ones((2, 2, 4, 2, 3)),
    )
    assert store.get(store.key(0, [5, 6, 7, 8])) is None
    assert not [n for n in os.listdir(str(tmp_path)) if n.endswith(".tmp")]


def test_disk_store_byte_cap_unlinks_oldest(tmp_path):
    from ray_tpu.llm.kvcache.tiers import DiskSpillStore

    kv = np.ones((2, 2, 4, 2, 3), np.float32)
    store = DiskSpillStore(str(tmp_path), capacity_bytes=3 * (kv.nbytes + 256))
    keys = [store.key(0, [i, i, i, i]) for i in range(6)]
    for i, key in enumerate(keys):
        store.put(key, kv)
        os.utime(store._path(key), (i, i))  # deterministic LRU order
        store._evict_over_cap()
    live = [k for k in keys if store.contains(k)]
    assert len(live) <= 3
    assert keys[-1] in live and keys[0] not in live


# -- tier roundtrip -----------------------------------------------------------

def test_tier_roundtrip_device_host_disk(tmp_path):
    mgr, shape = _tiered(tmp_path, capacity_blocks=3, device_blocks=8)
    try:
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        b = [9, 10, 11, 12, 13, 14, 15, 16]
        assert mgr.insert(a, _kv_for(a, shape)) == 2
        with mgr.lookup(a + [99]) as lease:
            assert lease.tier == "host"      # first hit: host, promotes
            np.testing.assert_array_equal(lease.kv(), _kv_for(a, shape))
        with mgr.lookup(a + [99]) as lease:
            assert lease.tier == "device"    # second hit: device-resident
            dev = mgr.device_kv(lease)
            assert dev is not None
            np.testing.assert_array_equal(np.asarray(dev), _kv_for(a, shape))
        # Evict a's chain (capacity 3) -> spill-on-evict instead of discard.
        assert mgr.insert(b, _kv_for(b, shape)) == 2
        _wait(lambda: mgr.stats()["tiers"]["spills"] >= 1, msg="async spill")
        with mgr.lookup(a + [99]) as lease:  # disk-warm: promoted back
            assert lease.tier == "disk"
            np.testing.assert_array_equal(lease.kv(), _kv_for(a, shape))
        tiers = mgr.stats()["tiers"]
        assert tiers["promotions_host"] >= 1
        assert tiers["promotions_device"] >= 2
        assert tiers["hits_device"] == 1 and tiers["hits_disk"] == 1
    finally:
        mgr.close()


def test_eviction_while_leased_refuses_across_tiers(tmp_path):
    """A leased chain can never be evicted — not to disk, not dropped from
    under an attach: the insert drops its own tail instead, exactly the
    flat-pool contract, and the spill tier sees nothing."""
    mgr, shape = _tiered(tmp_path, capacity_blocks=3, device_blocks=4)
    try:
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        b = [9, 10, 11, 12, 13, 14, 15, 16]
        assert mgr.insert(a, _kv_for(a, shape)) == 2
        lease = mgr.lookup(a + [99])
        assert lease.matched_tokens == 8
        assert mgr.insert(b, _kv_for(b, shape)) == 1  # tail dropped, no evict
        stats = mgr.stats()
        assert stats["evicted_blocks"] == 0
        assert stats["tiers"]["spills"] == 0 and stats["tiers"]["spill_queued"] == 0
        np.testing.assert_array_equal(lease.kv(), _kv_for(a, shape))
        lease.release()
        # Unpinned now: the same pressure spills instead of refusing.
        c = [30, 31, 32, 33, 34, 35, 36, 37]
        assert mgr.insert(c, _kv_for(c, shape)) == 2
        _wait(lambda: mgr.stats()["tiers"]["spills"] >= 1, msg="spill after release")
    finally:
        mgr.close()


# -- lookup-contention fix ----------------------------------------------------

def test_insert_stages_copies_outside_manager_lock(tmp_path, monkeypatch):
    """The small-fix regression: insert's block copies must run with the
    manager lock NOT held (lease pins make that safe), so a big insert
    cannot stall concurrent lookups for the duration of the memcpy."""
    from ray_tpu.llm.kvcache import PrefixCacheManager
    from ray_tpu.llm.kvcache.manager import PrefixCacheManager as MgrCls

    mgr, shape = _tiered(tmp_path, capacity_blocks=64, spill=False)
    locked_during_copy = []
    orig = MgrCls._stage_block
    staging = threading.Event()

    def probe(self, kv, i):
        locked_during_copy.append(self._lock.locked())
        staging.set()
        time.sleep(0.15)  # a "big" copy: ~0.6s total for 4 blocks
        return orig(self, kv, i)

    monkeypatch.setattr(MgrCls, "_stage_block", probe)
    a = list(range(16))
    warm = [100, 101, 102, 103]
    assert PrefixCacheManager.insert(mgr, warm, _kv_for(warm, shape)) == 1
    staging.clear()
    locked_during_copy.clear()

    lookup_s = []

    def inserter():
        PrefixCacheManager.insert(mgr, a, _kv_for(a, shape))

    t = threading.Thread(target=inserter)
    t.start()
    try:
        assert staging.wait(10)
        t0 = time.monotonic()
        lease = mgr.lookup(warm + [99])  # must NOT wait out the staging
        lookup_s.append(time.monotonic() - t0)
        assert lease is not None
        lease.release()
    finally:
        t.join(30)
    assert locked_during_copy and not any(locked_during_copy), (
        "block copies ran under the manager lock"
    )
    assert lookup_s[0] < 0.3, (
        f"lookup stalled {lookup_s[0]:.3f}s behind insert staging"
    )
    mgr.close()


# -- engine token identity across tiers --------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Transformer, get_config

    cfg = get_config("test-tiny", scan_layers=False, remat=False)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _generate(engine, prompt, n, **sp):
    from ray_tpu.llm import SamplingParams

    out, done = [], threading.Event()

    def cb(tok, fin):
        out.append(tok)
        if fin:
            done.set()

    engine.submit(prompt, SamplingParams(max_tokens=n, **sp), cb)
    assert done.wait(180)
    return out


def test_tiered_engine_token_identity_all_tiers(tiny_model, tmp_path):
    """The acceptance bar: greedy output is identical for device-warm,
    host-warm, and disk-warm prefixes vs a cache-disabled reference, and
    the flight recorder's cache-attach events carry the serving tier."""
    from ray_tpu.llm import DecodeEngine
    from ray_tpu.llm.kvcache import TieredPrefixCacheManager

    cfg, model, params = tiny_model
    rng = np.random.default_rng(11)
    prefix = list(map(int, rng.integers(0, cfg.vocab_size, 40)))
    p_a = prefix + [5, 6, 7]
    other = list(map(int, rng.integers(0, cfg.vocab_size, 40)))

    # Capacity of exactly 2 blocks: inserting `other` evicts (spills) p_a.
    block_bytes = cfg.n_layers * 2 * 16 * cfg.n_kv_heads * cfg.head_dim * 4
    mgr = TieredPrefixCacheManager(
        16, 2 * block_bytes, name="equiv-tier",
        device_bytes=4 * block_bytes, spill_dir=str(tmp_path / "sp"),
    )
    plain = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                         prefix_cache=False)
    tiered = DecodeEngine(cfg, params, num_slots=2, max_seq=128,
                          prefix_cache=mgr)
    try:
        ref_a = _generate(plain, p_a, 6)
        ref_other = _generate(plain, other, 6)

        cold = _generate(tiered, p_a, 6)
        host_warm = _generate(tiered, p_a, 6)
        assert tiered.last_attach["tier"] == "host"
        dev_warm = _generate(tiered, p_a, 6)
        assert tiered.last_attach["tier"] == "device"
        # Evict p_a's chain to disk, then hit it disk-warm.
        assert _generate(tiered, other, 6) == ref_other
        _wait(lambda: mgr.stats()["tiers"]["spills"] >= 2, msg="spill of p_a")
        disk_warm = _generate(tiered, p_a, 6)
        assert tiered.last_attach["tier"] == "disk"
        assert ref_a == cold == host_warm == dev_warm == disk_warm
        # The recorder's cache-attach events carried the tier field.
        recs = tiered._recorder.records()
        tiers_seen = [
            attrs["tier"]
            for r in recs for (name, _t0, _t1, attrs) in r["events"]
            if name == "cache-attach"
        ]
        assert tiers_seen.count("host") >= 1
        assert tiers_seen.count("device") >= 1
        assert tiers_seen.count("disk") >= 1
    finally:
        plain.shutdown()
        tiered.shutdown()


# -- multicast ---------------------------------------------------------------

def test_multicast_fanout_one_staging_pass():
    """1 -> N fanout moves each staged chunk once: the multicast group's
    stream_chunks_staged delta equals ONE point-to-point stream's, while N
    separate p2p streams pay N times that (the transfer-counter assertion
    behind 'exactly one D2H pass on the writer')."""
    from ray_tpu.experimental import tensor_transport as _tt
    from ray_tpu.experimental.device_channel import (
        DeviceChannel, MulticastDeviceChannel,
    )

    payload = {"kv": np.arange(60000, dtype=np.float32)}

    def staged_delta(fn):
        before = _tt.transport_stats()["stream_chunks_staged"]
        fn()
        return _tt.transport_stats()["stream_chunks_staged"] - before

    def run_multicast():
        mc = MulticastDeviceChannel.create(4, chunk_bytes=8192, num_slots=8)
        outs = [None] * 4
        threads = []
        for i in range(4):
            def reader(i=i):
                with mc.subscribe(i) as sub:
                    outs[i] = sub.recv(timeout=60)
            threads.append(threading.Thread(target=reader))
            threads[-1].start()
        mc.send(payload, timeout=60)
        for t in threads:
            t.join(60)
        assert mc.drain(30)
        mc.close()
        mc.destroy()
        for o in outs:
            np.testing.assert_array_equal(o["kv"], payload["kv"])

    def run_p2p(n):
        for _ in range(n):
            ch = DeviceChannel.create(same_node=True, chunk_bytes=8192,
                                      num_slots=8)
            got = [None]
            t = threading.Thread(
                target=lambda: got.__setitem__(0, ch.recv(timeout=60)))
            t.start()
            ch.send(payload, timeout=60)
            t.join(60)
            ch.close()
            ch.destroy()
            np.testing.assert_array_equal(got[0]["kv"], payload["kv"])

    mc_staged = staged_delta(run_multicast)
    one_p2p = staged_delta(lambda: run_p2p(1))
    four_p2p = staged_delta(lambda: run_p2p(4))
    assert mc_staged == one_p2p, (mc_staged, one_p2p)
    assert four_p2p == 4 * one_p2p, (four_p2p, one_p2p)


def test_multicast_dead_subscriber_unwinds_writer():
    """A subscriber that never reads stalls the ring; the writer's stall
    unwind detaches it MID-STREAM and the remaining subscribers still read
    a byte-identical stream (no tears, no wedge)."""
    from ray_tpu.experimental.device_channel import MulticastDeviceChannel

    payload = {"kv": np.arange(50000, dtype=np.float32)}
    mc = MulticastDeviceChannel.create(3, chunk_bytes=4096, num_slots=4)
    outs = [None] * 2
    threads = []
    for i in range(2):
        def reader(i=i):
            with mc.subscribe(i) as sub:
                outs[i] = sub.recv(timeout=60)
        threads.append(threading.Thread(target=reader))
        threads[-1].start()
    # Subscriber 2 is dead (never subscribes/reads): the ring fills, the
    # stall unwind detaches it, and the send completes for the others.
    t0 = time.monotonic()
    mc.send(payload, stall_timeout=0.5)
    for t in threads:
        t.join(60)
    assert mc.detached == {2}
    assert time.monotonic() - t0 < 30
    for o in outs:
        np.testing.assert_array_equal(o["kv"], payload["kv"])
    assert mc.drain(30)
    mc.close()
    mc.destroy()


def test_pd_multicast_group_token_identical_to_p2p():
    """1 prefill -> 2 decode replicas over the multicast group: both
    replicas' greedy output is token-identical to the raw point-to-point
    handoff, with ONE staging pass on the prefill writer."""
    import asyncio

    from ray_tpu.experimental import tensor_transport as _tt
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.pd_disagg import DecodeServer, PrefillServer

    cfg = LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128)
    pre = PrefillServer(cfg)
    decs = [DecodeServer(cfg), DecodeServer(cfg)]
    try:
        rng = np.random.default_rng(3)
        toks = list(map(int, rng.integers(0, 64, 30)))

        async def main():
            before = _tt.transport_stats()["stream_chunks_staged"]
            out = await pre.prefill_multicast(toks, 2)
            results = await asyncio.gather(*[
                d.generate_prefilled(
                    {"group": out["group"], "subscriber": i},
                    out["prompt_len"], out["first_logits"],
                    max_tokens=6, token_ids=toks,
                )
                for i, d in enumerate(decs)
            ])
            staged = _tt.transport_stats()["stream_chunks_staged"] - before
            fl, kv, plen = pre._engine.prefill_detached(toks)
            ref = await decs[0].generate_prefilled(
                kv, plen, fl, max_tokens=6, token_ids=toks)
            return results, ref, staged

        results, ref, staged = asyncio.run(main())
        assert results[0]["token_ids"] == results[1]["token_ids"]
        assert results[0]["token_ids"] == ref["token_ids"]
        # ONE pass over the payload chunks for the whole 2-reader group
        # (kv is CPU-host-resident here, so 1 chunk per stream write; on
        # accelerators these ARE the D2H slices).
        assert staged >= 1
        # p2p reference for the same payload costs the same again PER reader:
        before = _tt.transport_stats()["stream_chunks_staged"]
        from ray_tpu.experimental.device_channel import DeviceChannel

        ch = DeviceChannel.create(same_node=True)
        got = [None]
        t = threading.Thread(target=lambda: got.__setitem__(0, ch.recv(timeout=60)))
        t.start()
        fl, kv, plen = pre._engine.prefill_detached(toks)
        ch.send(kv, timeout=60)
        t.join(60)
        ch.close()
        ch.destroy()
        one = _tt.transport_stats()["stream_chunks_staged"] - before
        assert staged == one, (staged, one)
    finally:
        pre._engine.shutdown()
        for d in decs:
            d._engine.shutdown()


# -- cluster-wide prefix plane ------------------------------------------------

def test_dp_pick_reports_prefix_holder_for_remote_fetch():
    """Routing-decision unit: when the imbalance guard steers a request AWAY
    from the replica that computed its prefix, _pick surfaces that replica
    as the fetch source (holder) instead of silently recomputing."""
    from ray_tpu.llm.dp_serve import DPRouter

    class _Rep:
        def __init__(self, aid):
            self._actor_id = aid

    a, b = _Rep("A"), _Rep("B")

    class _FakeRouter:
        def replicas(self):
            return [a, b]

        def loads(self):
            return {"A": 0, "B": 100}  # B hot: imbalance guard rejects it

        def pick_replica(self, r):
            return r

        def pick(self, _):
            return a

    class _FakeGen:
        def _get_router(self):
            return _FakeRouter()

    class _FakeHandle:
        generate = _FakeGen()

    router = DPRouter(_FakeHandle(), assigner=None)
    chain = [101, 102, 103]
    router._record("B", chain)  # B computed this prefix earlier
    picked, _r, mode, holder = router._pick(chain)
    assert picked is a and mode == "balanced"
    assert holder is b, "the overloaded prefix holder must surface as source"
    # When the pick IS the holder there is nothing to fetch.
    router._record("A", chain)

    class _Even(_FakeRouter):
        def loads(self):
            return {"A": 0, "B": 0}

    _FakeGen._get_router = lambda self: _Even()
    picked, _r, mode, holder = router._pick(chain)
    assert mode == "cache_routed" and holder is None


def test_cross_replica_prefix_fetch_token_identity(ray_start_regular, tmp_path):
    """The transfer plane end-to-end over a real cluster data plane: replica
    S1 computes a prefix; S2 imports it over the DeviceChannel stream and
    serves it from ITS cache — token-identical to S1 and to a cold engine,
    with S2's insert accounted as remote."""
    import asyncio

    from ray_tpu.llm import DecodeEngine, LLMConfig, LLMServer
    from ray_tpu.llm.kvcache import TieredPrefixCacheManager

    from ray_tpu._private.config import CONFIG
    from ray_tpu.models.transformer import get_config

    del TieredPrefixCacheManager  # engines build their own from the flags
    mcfg = get_config("test-tiny", scan_layers=False, remat=False)
    block_bytes = mcfg.n_layers * 2 * 16 * mcfg.n_kv_heads * mcfg.head_dim * 4
    cfg_obj = LLMConfig(model_id="test-tiny", num_slots=2, max_seq=128)
    s1 = LLMServer(cfg_obj)
    # s2's engine builds a TIERED cache (flag-driven, the production path)
    # so the remote insert lands in the tier books.
    CONFIG._cache["llm_kv_device_bytes"] = 8 * block_bytes
    CONFIG._cache["llm_kv_spill_dir"] = str(tmp_path / "s2spill")
    try:
        s2 = LLMServer(cfg_obj)
    finally:
        CONFIG._cache["llm_kv_device_bytes"] = 0
        CONFIG._cache["llm_kv_spill_dir"] = ""
    plain = DecodeEngine(mcfg, s1._engine.params, num_slots=1, max_seq=128,
                         prefix_cache=False)
    try:
        rng = np.random.default_rng(21)
        toks = list(map(int, rng.integers(0, mcfg.vocab_size, 40)))

        async def main():
            warm = await s1.generate(toks, max_tokens=6)     # S1 computes
            desc = await s1.export_prefix(toks)
            assert desc is not None and desc["matched_tokens"] == 32
            inserted = await s2.import_prefix(desc, toks)
            assert inserted == 2, inserted
            got = await s2.generate(toks, max_tokens=6)      # served locally
            return warm, got

        warm, got = asyncio.run(main())
        ref = _generate(plain, toks, 6)
        assert warm["token_ids"] == got["token_ids"] == ref
        # S2's prefill was suffix-only off the imported prefix...
        assert s2._engine.last_prefill["offset"] == 32
        # ...and the tier books know it came from a peer, not a recompute.
        tiers = s2._engine.prefix_cache_stats()["tiers"]
        assert tiers["remote_inserts"] == 1
        # The export lease released once the send leg drained (leaksan's
        # kv_lease books also prove this at suite level).
        _wait(lambda: s1._engine.prefix_cache_stats()["leases_active"] == 0,
              msg="export lease release")
        assert s1._engine.prefix_cache_stats()["exports"] == 1
    finally:
        plain.shutdown()
        asyncio.run(s1.shutdown())
        asyncio.run(s2.shutdown())


# -- leaksan lifetimes --------------------------------------------------------

def test_leaksan_tracks_kvtier_lifetimes(tmp_path):
    """Planted-leak accounting for the three new lifetimes: each handle is
    live in the registry while held and balances on release."""
    from ray_tpu.devtools import leaksan
    from ray_tpu.experimental.device_channel import MulticastDeviceChannel
    from ray_tpu.llm.kvcache import PrefixCacheManager
    from ray_tpu.llm.kvcache.tiers import DiskSpillStore

    def live(kind):
        return leaksan.live_counts().get(kind, 0)

    store = DiskSpillStore(str(tmp_path))
    base = live("kv_spill_file")
    f = store.open_spill("deadbeef")
    assert live("kv_spill_file") == base + 1
    f.write(b"x")
    f.close()  # abort balances the books exactly like commit
    assert live("kv_spill_file") == base

    mc = MulticastDeviceChannel.create(2, chunk_bytes=4096)
    base = live("mc_subscription")
    sub = mc.subscribe(0)
    assert live("mc_subscription") == base + 1
    sub.unsubscribe()
    sub.unsubscribe()  # idempotent
    assert live("mc_subscription") == base
    mc.close()
    mc.destroy()

    mgr = PrefixCacheManager(4, 1 << 20, name="leaksan-fetch")
    tokens = [1, 2, 3, 4, 5, 6, 7, 8]
    kv = _kv_for(tokens, (2, 2, 2, 3))
    mgr.insert(tokens, kv)
    base = live("kv_lease")
    lease = mgr.lease_prefix(tokens)
    assert lease is not None and lease.matched_tokens == 8  # no len-1 cap
    assert live("kv_lease") == base + 1
    lease.release()
    assert live("kv_lease") == base
    assert mgr.stats()["exports"] == 1
