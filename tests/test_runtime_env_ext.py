"""conda + container (image_uri) runtime envs.

Shape parity with the reference suite (python/ray/tests/test_runtime_env_conda*.py,
test_runtime_env_container.py): validation, env-key derivation, builder behavior
against a fake conda binary, container command assembly, and cluster-level
failure clarity when the engine is absent. A fake `conda` on PATH doubles as the
real thing — its named env's python is a symlink to this interpreter, so the
worker actually boots through the resolved path.
"""

import json
import os
import stat
import sys

import pytest

import ray_tpu
from ray_tpu._private import runtime_env as renv_mod


def test_validate_conda_and_image_uri():
    assert renv_mod.validate({"conda": "myenv"})["conda"] == "myenv"
    spec = {"conda": {"dependencies": ["python=3.12", "cowsay"]}}
    assert renv_mod.validate(spec)["conda"] == spec["conda"]
    assert renv_mod.validate({"image_uri": "docker://img:1"})["image_uri"]
    with pytest.raises(ValueError, match="conda must be"):
        renv_mod.validate({"conda": 42})
    with pytest.raises(ValueError, match="either pip or conda"):
        renv_mod.validate({"pip": ["x"], "conda": "e"})
    with pytest.raises(ValueError, match="cannot be combined"):
        renv_mod.validate({"image_uri": "img", "pip": ["x"]})


def test_env_key_covers_dedicated_plugins():
    assert renv_mod.env_key({"env_vars": {"A": "1"}}) is None
    k_pip = renv_mod.env_key({"pip": {"packages": ["x"]}})
    k_conda = renv_mod.env_key({"conda": "myenv"})
    k_img = renv_mod.env_key({"image_uri": "docker://img:1"})
    assert len({k_pip, k_conda, k_img}) == 3 and None not in {k_pip, k_conda, k_img}


def _write_fake_conda(tmp_path, base_dir):
    """A shell script honoring the two invocations the builder makes."""
    script = tmp_path / "conda"
    script.write_text(f"""#!/bin/sh
if [ "$1" = "info" ]; then
    echo "{base_dir}"
    exit 0
fi
if [ "$1" = "env" ] && [ "$2" = "create" ]; then
    # args: env create -y -p <path> -f <yml>
    path="$5"
    mkdir -p "$path/bin"
    ln -s "{sys.executable}" "$path/bin/python"
    exit 0
fi
exit 1
""")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script)


def test_ensure_conda_env_named_and_spec(tmp_path):
    base = tmp_path / "conda_base"
    envp = base / "envs" / "myenv" / "bin"
    envp.mkdir(parents=True)
    (envp / "python").symlink_to(sys.executable)
    fake = _write_fake_conda(tmp_path, base)

    python = renv_mod.ensure_conda_env({"conda": "myenv"}, str(tmp_path / "cache"),
                                       conda_exe=fake)
    assert python == str(envp / "python")
    with pytest.raises(RuntimeError, match="not found"):
        renv_mod.ensure_conda_env({"conda": "nope"}, str(tmp_path / "cache"),
                                  conda_exe=fake)

    spec = {"conda": {"dependencies": ["python=3.12"]}}
    python2 = renv_mod.ensure_conda_env(spec, str(tmp_path / "cache"), conda_exe=fake)
    assert os.path.islink(python2) and os.path.exists(python2)
    # cached: second call resolves without rebuilding (script would still work,
    # but .ready short-circuits)
    assert renv_mod.ensure_conda_env(spec, str(tmp_path / "cache"),
                                     conda_exe="/nonexistent-after-cache") == python2


def test_ensure_conda_missing_binary(tmp_path, monkeypatch):
    monkeypatch.setenv("PATH", str(tmp_path))  # no conda anywhere
    with pytest.raises(RuntimeError, match="conda/mamba"):
        renv_mod.ensure_conda_env({"conda": "x"}, str(tmp_path))


def test_container_command_assembly():
    cmd = renv_mod.container_command(
        {"image_uri": "docker://repo/img:tag"},
        session_dir="/tmp/sess", env={"RAY_TPU_NODE_ID": "n1"}, engine="podman",
    )
    assert cmd[:3] == ["podman", "run", "--rm"]
    assert "--network=host" in cmd and "--ipc=host" in cmd
    assert "-v" in cmd and "/tmp/sess:/tmp/sess" in cmd
    assert "--env" in cmd and "RAY_TPU_NODE_ID=n1" in cmd
    assert cmd[-3:] == ["repo/img:tag", "python3", "-m"] or \
        cmd[-4:] == ["repo/img:tag", "python3", "-m",
                     "ray_tpu._private.default_worker"]


@pytest.fixture
def conda_cluster(tmp_path, monkeypatch):
    base = tmp_path / "conda_base"
    envp = base / "envs" / "clusterenv" / "bin"
    envp.mkdir(parents=True)
    # The env "python" is an exec wrapper around this interpreter that stamps
    # a marker env var — a symlink would lose the venv prefix (pyvenv.cfg is
    # resolved relative to argv0's location), while the marker proves the
    # conda-resolved path is what the raylet actually spawned.
    wrapper = envp / "python"
    wrapper.write_text(
        f"#!/bin/sh\nRAY_TPU_TEST_CONDA_ENV=clusterenv exec {sys.executable} \"$@\"\n"
    )
    wrapper.chmod(wrapper.stat().st_mode | stat.S_IEXEC)
    _write_fake_conda(tmp_path, base)
    monkeypatch.setenv("PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}")
    from tests.conftest import _WORKER_ENV

    ray_tpu.init(num_cpus=2, num_tpus=0, worker_env=_WORKER_ENV)
    yield str(wrapper)
    ray_tpu.shutdown()


def test_conda_named_env_actor_end_to_end(conda_cluster):
    """An actor with a conda runtime env boots through the env's interpreter
    (a wrapper around this one — the resolution path is what's under test)."""

    @ray_tpu.remote(runtime_env={"conda": "clusterenv"})
    class E:
        def marker(self):
            import os as _os

            return _os.environ.get("RAY_TPU_TEST_CONDA_ENV")

    a = E.remote()
    assert ray_tpu.get(a.marker.remote(), timeout=180) == "clusterenv"
    ray_tpu.kill(a)


def test_image_uri_fails_clearly_without_engine(conda_cluster, monkeypatch):
    """No podman/docker on the node: the task fails with a message naming the
    requirement instead of spawn-looping."""

    @ray_tpu.remote(runtime_env={"image_uri": "docker://img:1"})
    def in_container():
        return 1

    with pytest.raises(Exception, match="podman or docker"):
        ray_tpu.get(in_container.remote(), timeout=120)