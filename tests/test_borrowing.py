"""Sequenced borrow protocol: registration must never race the owner's release.

Round-1/2 carried a known race: borrow registration was a fire-and-forget
notify that could reorder against the owner's last release, freeing data a
borrower still held (reference sequences this in
`src/ray/core_worker/reference_counter.h:43`). Round 3 routes registration
through the task protocol (reply-borne, strictly ordered ahead of arg-pin
release). These tests inject a large delay into the legacy notify path to
prove the sequenced paths never depend on it, and exercise crash
reconciliation of dead borrowers.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def borrow_cluster(monkeypatch):
    """Cluster with the legacy borrow notify delayed 1500ms (fault injection)
    and a fast borrower audit. Any path that still depended on the async
    notify ordering would free borrowed objects under this delay."""
    monkeypatch.setenv("RAY_TPU_TEST_DELAY_BORROW_REPORT_MS", "1500")
    monkeypatch.setenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S", "1")
    from ray_tpu._private.config import CONFIG

    CONFIG._reset()
    ray_tpu.init(
        num_cpus=4, num_tpus=0,
        worker_env={
            "RAY_TPU_TEST_DELAY_BORROW_REPORT_MS": "1500",
            "RAY_TPU_BORROW_AUDIT_INTERVAL_S": "1",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        },
    )
    yield
    ray_tpu.shutdown()
    monkeypatch.delenv("RAY_TPU_TEST_DELAY_BORROW_REPORT_MS")
    monkeypatch.delenv("RAY_TPU_BORROW_AUDIT_INTERVAL_S")
    CONFIG._reset()


@ray_tpu.remote
class Holder:
    def __init__(self):
        self.ref = None

    def hold(self, box):
        self.ref = box[0]
        return True

    def read(self):
        return float(ray_tpu.get(self.ref).sum())

    def drop(self):
        self.ref = None
        return True


def test_borrowed_arg_survives_owner_drop(borrow_cluster):
    """Actor keeps a borrowed arg ref past the call; the owner drops its own
    ref immediately after. The reply-borne registration must already have
    counted the actor, so the put object survives without reconstruction
    (put objects have NO lineage — a premature free here is unrecoverable)."""
    h = Holder.remote()
    ref = ray_tpu.put(np.ones(200_000))
    assert ray_tpu.get(h.hold.remote([ref]), timeout=120)
    del ref  # owner's local count -> 0 while the (delayed) legacy notify path idles
    time.sleep(2.0)  # any mis-ordered free would land in this window
    assert ray_tpu.get(h.read.remote(), timeout=120) == 200_000.0
    assert ray_tpu.get(h.drop.remote(), timeout=60)


def test_actor_task_result_ref_survives_executor_release(borrow_cluster):
    """Actor returns a ref it owns inside its result (the VERDICT actor-task
    case): the executor's task-local refs die at completion, but the caller was
    pre-counted as sub-borrower before the reply left, so materializing the
    ref later still works. Actor-task results are not reconstructible."""

    @ray_tpu.remote
    class Maker:
        def make(self):
            return [ray_tpu.put(np.full(150_000, 3.0))]

    m = Maker.remote()
    box = ray_tpu.get(m.make.remote(), timeout=120)
    time.sleep(2.0)  # executor's locals are long dead; delayed notify path idles
    assert float(ray_tpu.get(box[0], timeout=120).sum()) == 450_000.0
    del box


def test_borrow_chain_through_two_actors(borrow_cluster):
    """Driver ref -> actor A -> actor B: the sub-borrow tree keeps the object
    alive after the driver and A both drop their refs."""
    a, b = Holder.remote(), Holder.remote()
    ref = ray_tpu.put(np.ones(120_000))
    assert ray_tpu.get(a.hold.remote([ref]), timeout=120)

    @ray_tpu.remote
    def forward(src, dst):
        # Runs inside a worker: the received ref is itself a borrow; handing
        # it to B extends the chain.
        return ray_tpu.get(dst.hold.remote([src[0]]))

    assert ray_tpu.get(forward.remote([ref], b), timeout=120)
    del ref
    assert ray_tpu.get(a.drop.remote(), timeout=60)
    time.sleep(2.0)
    assert ray_tpu.get(b.read.remote(), timeout=120) == 120_000.0


def test_intermediate_borrower_crash_grandchild_survives(borrow_cluster):
    """The VERDICT transitive hole: driver ref -> actor A -> grandchild actor
    C; A is SIGKILLed while C still borrows. Sub-borrower registrations are
    mirrored to the TRUE owner, so the audit dropping A must NOT free the
    object (put objects have no lineage — a premature free is unrecoverable,
    so a successful read proves no free and no reconstruction happened)."""
    from ray_tpu._private.worker import _global_worker

    @ray_tpu.remote
    class Middle:
        def __init__(self):
            self.ref = None

        def hold(self, box):
            self.ref = box[0]
            return True

        def forward(self, child):
            # Runs inside A: handing the borrowed ref onward makes C a
            # grandchild registered with A (and, mirrored, with the owner).
            return ray_tpu.get(child.hold.remote([self.ref]), timeout=60)

    a = Middle.remote()
    c = Holder.remote()
    ref = ray_tpu.put(np.ones(130_000))
    oid = ref.id
    rc = _global_worker.reference_counter
    assert ray_tpu.get(a.hold.remote([ref]), timeout=120)
    assert ray_tpu.get(a.forward.remote(c), timeout=120)
    # The mirror is async: wait until the owner's table lists BOTH A and C.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        keys = {k for k, oids in rc.borrower_snapshot().items() if oid in oids}
        if len(keys) >= 2:
            break
        time.sleep(0.2)
    assert len(keys) >= 2, f"grandchild never mirrored to the owner: {keys}"
    ray_tpu.kill(a)  # intermediate dies WITHOUT releasing
    del ref  # owner's local count -> 0: only borrower counts protect the data
    time.sleep(4.0)  # audit (1s) reconciles A; C's mirrored count must hold
    assert ray_tpu.get(c.read.remote(), timeout=120) == 130_000.0
    assert ray_tpu.get(c.drop.remote(), timeout=60)
    # After the grandchild releases, nothing holds the object: the owner's
    # table must fully drain (C's release lands at the owner even though its
    # borrow parent A is dead — the audit's holdings check reconciles it).
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and rc.num_borrows(oid) > 0:
        time.sleep(0.5)
    assert rc.num_borrows(oid) == 0, "borrower table leaked after release"


def test_put_embedded_ref_protected(borrow_cluster):
    """Refs embedded in put() payloads (not task args/results): the put object
    pins them for its lifetime (contained-in protection), so a reader can
    materialize the inner ref long after the owner dropped its own handle —
    even with the legacy notify path delayed 1500ms."""
    from ray_tpu._private.worker import _global_worker

    inner = ray_tpu.put(np.full(110_000, 2.0))
    inner_oid = inner.id
    outer = ray_tpu.put({"box": inner})
    del inner  # owner's only DIRECT handle dies; the put pin must hold
    time.sleep(2.0)  # any unprotected window would free inner here

    @ray_tpu.remote
    def read_inner(box):
        payload = ray_tpu.get(box[0])
        return float(ray_tpu.get(payload["box"]).sum())

    assert ray_tpu.get(read_inner.remote([outer]), timeout=120) == 220_000.0
    # Freeing the outer object releases the pin: inner must actually die
    # (protection, not a leak).
    del outer
    store = _global_worker.memory_store
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and store.get(inner_oid) is not None:
        _global_worker.reference_counter.drain_deferred()
        time.sleep(0.5)
    assert store.get(inner_oid) is None, "put-embedded pin leaked"


def test_crashed_borrower_reconciles(borrow_cluster):
    """A borrower killed without releasing must not pin the object forever:
    the owner's audit loop drops dead borrowers (reference: worker-failure
    interception in the reference counter)."""
    from ray_tpu._private.worker import _global_worker

    h = Holder.remote()
    ref = ray_tpu.put(np.ones(100_000))
    assert ray_tpu.get(h.hold.remote([ref]), timeout=120)
    oid = ref.id
    rc = _global_worker.reference_counter
    # the reply-borne registration has landed by now
    assert rc.num_borrows(oid) >= 1
    ray_tpu.kill(h)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and rc.num_borrows(oid) > 0:
        time.sleep(0.5)
    assert rc.num_borrows(oid) == 0, "dead borrower's count was never reconciled"
    # owner still holds its own ref: the object must still be readable
    assert float(ray_tpu.get(ref, timeout=60).sum()) == 100_000.0
