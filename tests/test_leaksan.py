"""leaksan: the runtime leak sanitizer catches planted leaks and stays
zero-cost when disabled (docs/raylint.md §leaksan)."""

import gc
import threading

import numpy as np
import pytest

from ray_tpu.devtools import leaksan


@pytest.fixture(autouse=True)
def _fresh_registry():
    leaksan.reset()
    leaksan.enable()
    yield
    leaksan.reset()
    leaksan.disable()


class _Handle:
    """A stand-in acquire/release-paired resource."""

    def __init__(self, detail=""):
        leaksan.track("test_handle", self, detail=detail)
        self.released = False

    def release(self):
        if not self.released:
            self.released = True
            leaksan.untrack("test_handle", self)


def test_live_counts_track_and_release():
    h = _Handle("h1")
    assert leaksan.live_counts().get("test_handle") == 1
    h.release()
    assert "test_handle" not in leaksan.live_counts()


def test_gc_without_release_counts_as_leak():
    # A handle collected WITHOUT release is the leak GC hides: an unreleased
    # SlotView never publishes its ack, an unreleased PrefixLease pins its
    # blocks forever — leaksan moves those to the `<kind>:gc` bucket.
    _Handle("dropped")
    gc.collect()
    counts = leaksan.live_counts()
    assert "test_handle" not in counts
    assert counts.get("test_handle:gc") == 1


def test_token_tracking_is_counted():
    leaksan.track("test_pin", token=("arena", b"obj1"))
    leaksan.track("test_pin", token=("arena", b"obj1"))
    assert leaksan.live_counts()["test_pin"] == 2
    leaksan.untrack("test_pin", token=("arena", b"obj1"))
    assert leaksan.live_counts()["test_pin"] == 1
    leaksan.untrack("test_pin", token=("arena", b"obj1"))
    assert "test_pin" not in leaksan.live_counts()
    # over-release never goes negative
    leaksan.untrack("test_pin", token=("arena", b"obj1"))
    assert "test_pin" not in leaksan.live_counts()


def test_disabled_tracks_nothing():
    leaksan.disable()
    leaksan.track("test_handle", token="t")
    assert leaksan.live_counts() == {}
    leaksan.enable()


def test_leak_report_carries_detail():
    h = _Handle("the-culprit")
    report = leaksan.leak_report()
    assert report["test_handle"] == ["the-culprit"]
    h.release()
    assert "test_handle" not in leaksan.leak_report()


def test_fixture_catches_planted_slot_view_leak():
    """The contract the gated suites run under: plant a deliberate leak of a
    REAL resource (an unreleased SlotView ring-slot lease) and assert the
    fixture's growth check reports it; release it and assert clean."""
    from ray_tpu.experimental.channel import Channel

    before = leaksan.snapshot()
    ch = Channel(capacity=1 << 13, num_readers=1, num_slots=2)
    try:
        ch.write({"x": np.arange(1024, dtype=np.int32)})  # tensor fast path
        view = ch.reader(0).read_view()
        growth = leaksan.check_growth(before, settle_s=0.2)
        assert "slot_view" in growth, growth
        assert "report" in growth and growth["report"].get("slot_view")
        view.release()
        assert leaksan.check_growth(before, settle_s=0.2) == {}
    finally:
        ch.close()
        ch.destroy()


def test_fixture_catches_planted_kv_lease_leak():
    from ray_tpu.llm.kvcache import PrefixCacheManager

    mgr = PrefixCacheManager(block_size=4, capacity_bytes=1 << 20, name="san")
    tokens = [1, 2, 3, 4, 5, 6, 7, 8]
    kv = np.zeros((2, 2, 8, 1, 4), np.float32)
    mgr.insert(tokens, kv)
    before = leaksan.snapshot()
    lease = mgr.lookup(tokens + [9])
    assert lease is not None
    growth = leaksan.check_growth(before, settle_s=0.2)
    assert "kv_lease" in growth, growth
    lease.release()
    assert leaksan.check_growth(before, settle_s=0.2) == {}
    assert mgr.stats()["leases_active"] == 0


def test_fixture_catches_planted_adapter_pin_leak():
    """The round-13 adapter plane is leaksan-covered from day one: an
    AdapterHandle acquired and never released grows the `adapter_pin` kind
    (and pins its device slot against eviction), releasing clears it."""
    import jax.numpy as jnp

    from ray_tpu.llm.adapters import AdapterCache

    cache = AdapterCache(
        n_layers=2, hidden=8, q_out=8, v_out=8, rank=2, dtype=jnp.float32,
        max_adapters=4, cache_slots=2, name="san-adapters",
    )
    cache.register("tuned", {0: {"q_A": np.zeros((8, 2), np.float32)}})
    before = leaksan.snapshot()
    handle = cache.acquire("tuned")
    growth = leaksan.check_growth(before, settle_s=0.2)
    assert "adapter_pin" in growth, growth
    assert cache.stats()["pinned"] == 1
    handle.release()
    assert leaksan.check_growth(before, settle_s=0.2) == {}
    assert cache.stats()["pinned"] == 0
    # base-model handles are pin-free by design: nothing to leak or track
    base = cache.acquire("")
    assert base.slot == 0 and base.uid == 0
    base.release()
    assert leaksan.check_growth(before, settle_s=0.2) == {}


def test_fixture_catches_planted_gcs_lease_and_peer_link_leak():
    """The round-14 replication plane is leaksan-covered: a primary lease
    token held past demotion grows `gcs_lease`, a replication link never
    closed grows `gcs_repl_peer`; releasing/closing clears both (the
    end-to-end demotion balance is asserted in test_gcs_repl.py)."""
    import asyncio

    from ray_tpu._private.gcs_replication import LeaseToken, PeerLink

    class _FakeConn:
        closed = False

        async def close(self):
            self.closed = True

    before = leaksan.snapshot()
    lease = LeaseToken(epoch=3)
    link = PeerLink(("127.0.0.1", 1), _FakeConn())
    growth = leaksan.check_growth(before, settle_s=0.2)
    assert "gcs_lease" in growth and "gcs_repl_peer" in growth, growth
    lease.release()
    lease.release()  # idempotent: double demotion must not underflow
    asyncio.run(link.close())
    assert leaksan.check_growth(before, settle_s=0.2) == {}


def test_fixture_catches_planted_profiler_capture_leak():
    """The round-18 compute-plane observatory is leaksan-covered: a
    ProfilerCapture started and never stopped grows the `profiler_capture`
    kind (and keeps jax.profiler tracing for the process's life);
    stop_capture clears it and is idempotent."""
    import tempfile

    from ray_tpu.util import xprof

    before = leaksan.snapshot()
    cap = xprof.start_capture(log_dir=tempfile.mkdtemp(prefix="leaksan_xprof_"))
    try:
        growth = leaksan.check_growth(before, settle_s=0.2)
        assert "profiler_capture" in growth, growth
    finally:
        cap.stop_capture()
    cap.stop_capture()  # idempotent: double stop must not underflow
    assert leaksan.check_growth(before, settle_s=0.2) == {}


def test_check_growth_waits_for_async_teardown():
    # growth that resolves within the settle window is not a leak: the
    # devobj stream pump releases on its own thread after the reader drains
    leaksan.track("test_handle", token="slow")
    before_clear = threading.Timer(
        0.3, lambda: leaksan.untrack("test_handle", token="slow")
    )
    before_clear.start()
    try:
        growth = leaksan.check_growth({"handles": {}, "threads": []},
                                      settle_s=3.0)
        assert growth == {}
    finally:
        before_clear.cancel()


def test_rpc_conns_reported_but_not_failed():
    # conns are cached per (process, peer) for the process lifetime by
    # design: the guard reports them but does not fail on their growth
    leaksan.track("rpc_conn", token="peer:1234")
    try:
        assert leaksan.check_growth({"handles": {}, "threads": []},
                                    settle_s=0.1) == {}
    finally:
        leaksan.untrack("rpc_conn", token="peer:1234")
